"""Headline benchmark: training throughput, images/sec/chip.

Measures the flagship Faster R-CNN FPN full train step (forward + backward +
optimizer) at COCO resolution on the available accelerator and reports
images/sec/chip against BASELINE.json's >=20 img/s/chip north star.
Synthetic pixels (no dataset download in this environment) — the compute
path is identical to real training; input pipeline is benchmarked
separately (see --loader and BASELINE.md's tunnel-bandwidth note).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"} (plus
diagnostics on stderr: per-step percentiles, analytic FLOPs/step, achieved
TFLOP/s and MFU when XLA cost analysis is available).

Flags (default invocation is the driver's headline r50 run):
  --config NAME   preset to bench (default r50_fpn_coco; r101_fpn_coco is
                  the north-star model)
  --loader        ALSO measure loader-fed throughput: real DetectionLoader
                  batches shipped host->device through the train loop's
                  device_prefetch.  Under the axon tunnel this measures the
                  ~10 MB/s tunnel, not the chip — see BASELINE.md.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

BASELINE_IMG_S_CHIP = 20.0
# The reference's GPU-era inference speed (~5 fps, Ren et al. / upstream
# README) — the --eval metric's vs_baseline denominator.  NOTE the two
# modes' vs_baseline fields are ratios against DIFFERENT anchors: train is
# "fraction of the >=20 img/s/chip north star", eval is "speedup over the
# reference's published inference fps".
BASELINE_EVAL_IMG_S = 5.0
# v5e peak bf16 matmul throughput, used for the MFU diagnostic.
V5E_PEAK_BF16_FLOPS = 197e12

# The detection-middle fast paths plus the r6 precision policy that the
# headline number is defined over.  Applied as bench DEFAULTS (user --set
# overrides win — A/B probes must be able to turn any of these off); the
# no-override invocation is asserted below to resolve to exactly the
# fast-path set, so preset drift can never silently re-benchmark a slow
# path.  That drift is how the r5 wins leaked out of the r5 headline:
# the preset gained topk_impl="hier"/assign_block/pallas-bwd defaults,
# but loss_impl stayed "dense" and fold_frozen_bn stayed off, and the
# headline run inherited whatever the preset happened to say.
HEADLINE_FASTPATH = (
    "model.rpn.loss_impl=compact",
    "model.backbone.fold_frozen_bn=true",
    "model.precision.policy=mixed",
)


def resolved_knobs(cfg) -> dict:
    """The perf-relevant knob set a bench run actually resolved to.

    Emitted into the BENCH artifact as the ``bench_knobs`` JSON line so
    every headline number carries its own provenance — a regression
    triages by diffing two artifacts' knob lines before anyone re-runs
    anything."""
    m = cfg.model
    return {
        "backbone_dtype": m.backbone.dtype,
        "precision_policy": m.precision.policy,
        "fold_frozen_bn": m.backbone.fold_frozen_bn,
        "stem_s2d": m.backbone.stem_s2d,
        "stem_pool_fold": m.backbone.stem_pool_fold,
        "c2_pad": m.backbone.c2_pad,
        "remat": m.backbone.remat,
        "topk_impl": m.rpn.topk_impl,
        "topk_block": m.rpn.topk_block,
        "assign_block": m.rpn.assign_block,
        "loss_impl": m.rpn.loss_impl,
        "packed_head": m.rpn.packed_head,
        "roi_align_impl": m.rcnn.roi_align_impl,
        "roi_align_bwd_impl": m.rcnn.roi_align_bwd_impl,
        "nms_impl": m.rpn.nms_impl,
        "fused_middle": m.rpn.fused_middle,
        "roi_block": m.rcnn.roi_block,
        "steps_per_call": cfg.train.steps_per_call,
        "accum_steps": cfg.train.accum_steps,
        "bucket_mb": cfg.train.bucket_mb,
        "per_device_batch": cfg.train.per_device_batch,
    }


def assert_headline_fastpath(cfg) -> None:
    """Hard-fail the NO-override invocation when any fast path resolved
    off.  Only the default (driver/headline) invocation is guarded —
    ``--set`` runs are A/B probes and may disable anything."""
    knobs = resolved_knobs(cfg)
    want = {
        "topk_impl": "hier",
        "loss_impl": "compact",
        "packed_head": True,
        "roi_align_bwd_impl": "pallas",
        "precision_policy": "mixed",
    }
    bad = {
        k: (knobs[k], v) for k, v in want.items() if knobs[k] != v
    }
    if knobs["assign_block"] <= 0:
        bad["assign_block"] = (knobs["assign_block"], "> 0")
    if cfg.model.backbone.name.startswith("resnet") and not knobs[
        "fold_frozen_bn"
    ]:
        bad["fold_frozen_bn"] = (False, True)
    if bad:
        raise SystemExit(
            "headline bench config drifted off the fast-path set: "
            + "; ".join(
                f"{k}={got!r} (want {need!r})"
                for k, (got, need) in sorted(bad.items())
            )
            + " — fix the preset/HEADLINE_FASTPATH or make this an "
            "explicit --set A/B probe"
        )


def _synthetic_batch(cfg, batch, image_size, k):
    from mx_rcnn_tpu.detection import Batch

    rng = np.random.RandomState(0)
    g = cfg.data.max_gt_boxes
    h, w = image_size
    n_gt = 8
    # K DISTINCT batches for the scan loop (a single batch broadcast K
    # times would let every post-warmup step re-read hot pixels/boxes and
    # slightly flatter cache locality vs real training).
    n = batch * k
    boxes = np.zeros((n, g, 4), np.float32)
    for b in range(n):
        x1 = rng.uniform(0, w - 64, n_gt)
        y1 = rng.uniform(0, h - 64, n_gt)
        bw = rng.uniform(16, 64, n_gt)
        bh = rng.uniform(16, 64, n_gt)
        boxes[b, :n_gt] = np.stack([x1, y1, x1 + bw, y1 + bh], axis=1)
    classes = np.zeros((n, g), np.int32)
    classes[:, :n_gt] = rng.randint(1, cfg.model.num_classes, (n, n_gt))
    valid = np.zeros((n, g), bool)
    valid[:, :n_gt] = True
    # uint8 pixels: the production loader ships raw letterboxed uint8 and
    # the step normalizes in-graph (graph.py::prep_images), so the timed
    # program must be that one.  Also 1/4 the device_put bytes.
    images = rng.randint(0, 256, (n, h, w, 3), dtype=np.uint8)
    masks = None
    if cfg.model.mask.enabled:
        # Box-relative gt masks, the loader's rasterized contract
        # (data/loader.py::GT_MASK_SIZE); blobby half-coverage shapes so
        # the mask loss sees both classes.
        from mx_rcnn_tpu.data.loader import GT_MASK_SIZE

        masks = np.zeros((n, g, GT_MASK_SIZE, GT_MASK_SIZE), np.float32)
        yy, xx = np.mgrid[0:GT_MASK_SIZE, 0:GT_MASK_SIZE]
        for b in range(n):
            cy = rng.uniform(0.3, 0.7, n_gt) * GT_MASK_SIZE
            cx = rng.uniform(0.3, 0.7, n_gt) * GT_MASK_SIZE
            r = rng.uniform(0.2, 0.45, n_gt) * GT_MASK_SIZE
            for j in range(n_gt):
                masks[b, j] = (
                    (yy - cy[j]) ** 2 + (xx - cx[j]) ** 2 <= r[j] ** 2
                ).astype(np.float32)
    data = Batch(
        images=images,
        image_hw=np.tile(
            np.asarray([[float(h), float(w)]], np.float32), (n, 1)
        ),
        gt_boxes=boxes,
        gt_classes=classes,
        gt_valid=valid,
        gt_masks=masks,
    )
    if k > 1:
        # Stacked (K, B, ...) layout consumed by the device-side lax.scan.
        data = Batch(*[
            None if f is None else f.reshape(k, batch, *f.shape[1:])
            for f in data
        ])
    return data


def _cost_analysis(step_fn, state, data, k, dt_per_call):
    """FLOPs/step + achieved TFLOP/s + MFU.

    Primary count: an analytic jaxpr walk over conv/dot primitives
    (mx_rcnn_tpu.utils.flops) — XLA's ``compiled.cost_analysis()`` was
    measured ~5x low for this program on the TPU runtime, so it is printed
    only as a secondary diagnostic when available."""
    try:
        from mx_rcnn_tpu.utils.flops import count_matmul_flops

        flops = count_matmul_flops(step_fn, state, data)
    except Exception as e:  # pragma: no cover
        print(f"analytic flop count failed: {e!r}", file=sys.stderr)
        return
    per_step = flops / k
    achieved = flops / dt_per_call
    print(
        f"analytic (conv+matmul jaxpr walk): {per_step/1e12:.2f} TFLOP/step, "
        f"achieved {achieved/1e12:.1f} TFLOP/s, "
        f"MFU {achieved/V5E_PEAK_BF16_FLOPS*100:.1f}% of v5e bf16 peak",
        file=sys.stderr,
    )
    try:
        from mx_rcnn_tpu.utils.hlo_profile import attribute_flops

        comps = attribute_flops(step_fn, state, data)
        total = sum(c["flops"] for c in comps.values()) or 1.0
        ranked = sorted(
            comps.items(), key=lambda kv: kv[1]["flops"], reverse=True
        )
        print(
            "per-component: " + ", ".join(
                f"{name} {c['flops']/k/1e9:.0f}GF ({c['flops']/total*100:.0f}%)"
                for name, c in ranked if c["flops"] / total >= 0.01
            ) + "  [full table: tools/mfu_report.py]",
            file=sys.stderr,
        )
    except Exception as e:  # pragma: no cover
        print(f"per-component attribution failed: {e!r}", file=sys.stderr)
    try:
        ca = step_fn.lower(state, data).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        xla_flops = float(ca.get("flops", 0.0))
        if xla_flops > 0:
            # cost_analysis counts the lax.scan body ONCE (no trip-count
            # multiply), i.e. it is already a per-step figure here; it
            # cross-checks the jaxpr walk (they agree to ~1%).
            print(
                f"(xla cost_analysis per-step cross-check: "
                f"{xla_flops/1e12:.2f} TFLOP/step)",
                file=sys.stderr,
            )
    except Exception:
        pass


def _loader_fed(cfg, step_fn, state, global_batch, n_steps=20):
    """Throughput with real loader batches shipped host->device through
    device_prefetch (the production train path).  Under the axon tunnel the
    host->device link (~10 MB/s measured) caps this at ~1 img/s at 1024² —
    the number documents the tunnel, not the chip; production PCIe moves
    the same batches at GB/s (BASELINE.md)."""
    import jax

    from mx_rcnn_tpu.data import DetectionLoader, SyntheticDataset
    from mx_rcnn_tpu.parallel.prefetch import PrefetchStats, device_prefetch
    from mx_rcnn_tpu.train.loop import _stacked_batches

    k = max(cfg.train.steps_per_call, 1)
    accum = max(cfg.train.accum_steps, 1)
    stack = max(k, accum)
    # uint8 synthetic pixels: same batch dtype as the main phase's program
    # (no recompile) and the production transfer size — 3 MB/image at the
    # recipe canvas instead of the f32 path's 12.
    roidb = SyntheticDataset(
        num_images=max(global_batch * 2, 8), image_hw=cfg.data.image_size,
        dtype="uint8",
    ).roidb()
    loader = DetectionLoader(
        roidb, cfg.data, batch_size=global_batch // accum, prefetch=False
    )
    host_it = iter(loader)
    if stack > 1:
        host_it = _stacked_batches(host_it, stack)
    stats = PrefetchStats()
    it = device_prefetch(
        host_it, mesh=None, depth=2, stacked=stack > 1, host_depth=1,
        stats=stats,
    )
    # Warm (program is already compiled from the synthetic phase).
    state, metrics = step_fn(state, next(it))
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    jax.device_get((metrics["loss"], leaf.ravel()[0]))
    stats.take()  # warmup stall is compile wait, not loader speed
    n_calls = max(n_steps // k, 2)
    t0 = time.perf_counter()
    for _ in range(n_calls):
        state, metrics = step_fn(state, next(it))
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    jax.device_get((metrics["loss"], leaf.ravel()[0]))
    dt = time.perf_counter() - t0
    n_steps_done = n_calls * k
    img_s = n_calls * k * global_batch / dt
    stall_s, _ = stats.take()
    # Tear the pipeline down promptly: closing the device_prefetch
    # generator closes the host prefetch thread, the stacking generator,
    # and the loader iterator under it — including input-service worker
    # processes when the run was configured with data.num_workers > 0.
    it.close()
    h, w = cfg.data.image_size
    platform = jax.default_backend()
    # Data-starvation stage line (satellite of the train_stage_ms
    # breakdown): ms/step the consumer blocked in next(loader) PAST the
    # prefetch double buffer.  ~0 means the step hides the loader; a
    # value near the step time means the run is input-bound and device
    # optimizations will not move the headline.
    print(
        json.dumps(
            {
                "metric": (
                    f"train_stage_ms[data_stall@{h}x{w},"
                    f"b{global_batch},{platform}]"
                ),
                "value": round(stall_s * 1e3 / n_steps_done, 3),
                "unit": "ms/step",
                "stalled_frac": round(stall_s / dt, 4),
            }
        )
    )
    print(
        f"loader-fed (host->device each step): {img_s:.2f} img/s "
        f"({n_steps_done} steps in {dt:.1f}s, "
        f"data stall {stall_s:.2f}s)",
        file=sys.stderr,
    )
    return img_s


def _eval_bench(cfg, image_size, on_accel):
    """Inference throughput: forward_inference at test.per_device_batch.

    The timed graph is the PRODUCTION one: uint8 images normalized
    in-graph (graph.py::prep_images), exactly what eval_cli runs on real
    loader batches.

    Timing method: N per-dispatch chained executions with ONE final fetch
    — each dispatch provably executes the full forward, nothing can be
    hoisted.  The chain rides the PARAMS (v_{i+1} = v_i + 1e-20 * f(v_i,
    images), f32 leaves, buffers donated) because uint8 images cannot
    absorb an infinitesimal perturbation; the r3 form chained through the
    float images.  A scan-with-perturbed-carry form measured 7x slower on
    the same graph (an XLA scan pathology with a 100 MB changing carry,
    r3 finding), so eval numbers use the per-dispatch chain; it agrees
    with the 0-carry scan form to ~3%."""
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.detection import Batch, TwoStageDetector, forward_inference
    from mx_rcnn_tpu.detection.graph import init_detector

    b = max(cfg.model.test.per_device_batch, 1) if on_accel else 1
    h, w = image_size
    model = TwoStageDetector(cfg=cfg.model)
    variables = init_detector(model, jax.random.PRNGKey(0), (h, w))
    rng = np.random.RandomState(0)
    g = cfg.data.max_gt_boxes
    stats = (cfg.data.pixel_mean, cfg.data.pixel_std)
    batch = Batch(
        images=jnp.asarray(rng.randint(0, 256, (b, h, w, 3), dtype=np.uint8)),
        image_hw=jnp.asarray([[float(h), float(w)]] * b, jnp.float32),
        gt_boxes=jnp.zeros((b, g, 4), jnp.float32),
        gt_classes=jnp.zeros((b, g), jnp.int32),
        gt_valid=jnp.zeros((b, g), bool),
    )

    # Params ride as a jit ARGUMENT (device buffers), not a closure: closed-
    # over arrays embed as HLO constants in the remote-compile request, and
    # VGG-16's ~0.5 GB fc6/fc7 blow the tunnel's request-size limit (413).
    variables = jax.device_put(variables)

    def run(v, imgs):
        dets = forward_inference(
            model, v, batch._replace(images=imgs), pixel_stats=stats
        )
        return jnp.sum(dets.boxes) + jnp.sum(dets.scores)

    def chain(v, im):
        eps = 1e-20 * run(v, im)
        return jax.tree_util.tree_map(lambda p: p + eps.astype(p.dtype), v)

    step = jax.jit(chain, donate_argnums=(0,))
    variables = step(variables, batch.images)
    jax.device_get(jax.tree_util.tree_leaves(variables)[0].ravel()[0])
    n = 10 if on_accel else 2
    t0 = time.perf_counter()
    for _ in range(n):
        variables = step(variables, batch.images)
    jax.device_get(jax.tree_util.tree_leaves(variables)[0].ravel()[0])
    dt = (time.perf_counter() - t0) / n
    print(
        f"eval: {dt * 1e3:.1f} ms/batch-of-{b} ({b / dt:.1f} img/s/chip)",
        file=sys.stderr,
    )
    return b / dt, b


def _stage_breakdown(cfg, model, state, image_size, batch, platform, on_accel):
    """One JSON line per train-step stage into the BENCH artifact.

    Same prefix-ablation stage list as tools/perf_breakdown.py (shared in
    mx_rcnn_tpu/utils/stage_bench.py) so future BENCH_r*.json files carry
    their own regression localization: a throughput drop shows up as a
    specific stage's delta growing, not as an unattributed headline number.
    Stage lines print BEFORE the headline metric line so "last JSON line =
    headline" keeps holding for existing consumers."""
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.detection import Batch
    from mx_rcnn_tpu.train.loop import FREEZE_PREFIXES
    from mx_rcnn_tpu.train.optim import frozen_mask
    from mx_rcnn_tpu.utils.stage_bench import time_train_stages, train_stage_fns

    h, w = image_size
    b = batch
    rng = np.random.RandomState(0)
    g = cfg.data.max_gt_boxes
    boxes = np.zeros((b, g, 4), np.float32)
    boxes[:, :8] = [100.0, 100.0, 300.0, 300.0]
    bt = Batch(
        images=jnp.asarray(rng.randn(b, h, w, 3), jnp.float32),
        image_hw=jnp.asarray([[float(h), float(w)]] * b, jnp.float32),
        gt_boxes=jnp.asarray(boxes),
        gt_classes=jnp.ones((b, g), jnp.int32),
        gt_valid=jnp.asarray(np.tile(np.arange(g)[None] < 8, (b, 1))),
    )
    params = state.params
    rest = state.model_state
    if cfg.model.backbone.freeze_stages > 0:
        mask = frozen_mask(
            params, FREEZE_PREFIXES.get(cfg.model.backbone.name, ())
        )

        def masked(p):
            return jax.tree_util.tree_map(
                lambda x, t: x if t else jax.lax.stop_gradient(x), p, mask
            )
    else:
        masked = None

    stages = train_stage_fns(
        model, params, rest, bt, jax.random.PRNGKey(1), masked=masked
    )
    results = time_train_stages(
        stages, params, steps=10 if on_accel else 2, calls=2
    )
    label = f"@{h}x{w},b{b},{platform}"
    prev = 0.0
    for name, dt in results:
        print(
            json.dumps(
                {
                    "metric": f"train_stage_ms[{name}{label}]",
                    "value": round(dt * 1e3, 3),
                    "unit": "ms/step",
                    "delta_ms": round((dt - prev) * 1e3, 3),
                }
            )
        )
        prev = dt


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="r50_fpn_coco")
    ap.add_argument("--loader", action="store_true")
    ap.add_argument(
        "--eval", action="store_true",
        help="bench forward_inference (proposals -> heads -> per-class NMS) "
        "instead of the train step",
    )
    ap.add_argument(
        "--set", dest="overrides", action="append", default=[],
        metavar="KEY.PATH=VALUE",
        help="config overrides for A/B probes (same syntax as train.py)",
    )
    ap.add_argument(
        "--breakdown", action=argparse.BooleanOptionalAction, default=None,
        help="ALSO emit one JSON line per train-step stage (the "
        "tools/perf_breakdown.py prefix ablation, shared via "
        "mx_rcnn_tpu/utils/stage_bench.py) so the BENCH artifact localizes "
        "regressions without a separate tool run.  Default: on for "
        "accelerators, off for the CPU fallback (each stage recompiles).",
    )
    args = ap.parse_args()
    if args.eval and args.loader:
        ap.error("--loader applies to the train bench only, not --eval")

    import jax

    # Persistent compile cache: repeat bench invocations (fresh processes)
    # skip the multi-minute XLA compile of the K-step scan program.
    # Repo-scoped path (not /tmp): safe on multi-user hosts.  Keyed by a
    # backend + host-feature fingerprint (utils/compile_cache.py): the old
    # un-keyed dir replayed XLA:CPU AOT blobs compiled on a DIFFERENT host
    # when the checkout migrated between machines — the MULTICHIP_r0*
    # "could lead to execution errors such as SIGILL" tails.
    import os

    from mx_rcnn_tpu.utils.compile_cache import configure_cache

    configure_cache(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
        min_compile_secs=10,
        # Bench artifacts are produced on whichever host holds the checkout
        # this round; when the LLVM-feature probe is unavailable, keep the
        # hosts' XLA:CPU blob caches strictly separate (MULTICHIP_r0*
        # foreign-blob SIGILL tails).
        strict_host=True,
    )

    from mx_rcnn_tpu.config import apply_overrides, get_config
    from mx_rcnn_tpu.train.loop import build_all

    platform = jax.default_backend()
    # Full recipe resolution on an accelerator: the preset's own landscape
    # canvas (COCO presets: 800x1344 per the 800-short/1333-max Detectron
    # rule; vgg16_voc07: 608x1024 per the 600/1000 VOC rule).  CPU fallback
    # shrinks the canvas so the bench finishes (labeled by vs_baseline).
    on_accel = platform in ("tpu", "gpu")
    cfg = get_config(args.config)
    image_size = cfg.data.image_size if on_accel else (256, 256)
    # 2 images per chip: the Detectron-recipe per-device batch (the
    # BASELINE north-star mAP presumes that recipe); measured +8% img/s
    # over batch 1 on a v5e.  lr scales linearly via build_all.
    batch = 2 if on_accel else 1

    # steps_per_call: the host-side loop is a lax.scan on device — one
    # dispatch per K steps.  Through the axon tunnel a single dispatch
    # costs ~25 ms (more than the step's device compute), so per-step
    # calling measures the tunnel, not the chip.
    k = 10 if on_accel else 1
    cfg = dataclasses.replace(
        cfg,
        data=dataclasses.replace(cfg.data, image_size=image_size, max_gt_boxes=32),
        train=dataclasses.replace(
            cfg.train, steps_per_call=k, per_device_batch=batch
        ),
    )
    # Fast-path headline preset (see HEADLINE_FASTPATH): bench defaults,
    # below user overrides in precedence.
    cfg = apply_overrides(cfg, list(HEADLINE_FASTPATH))
    if args.overrides:
        # Overrides win over the bench defaults above — and the locals the
        # synthetic batch / metric label derive from must follow them, or
        # an overridden canvas/batch would silently bench stale shapes.
        cfg = apply_overrides(cfg, args.overrides)
        image_size = cfg.data.image_size
        batch = cfg.train.per_device_batch
        k = max(cfg.train.steps_per_call, 1)
        if cfg.train.accum_steps > 1 and k > 1:
            # The plan forbids the combination; surface it as a CLI error
            # instead of a trace-time ValueError.
            ap.error("train.accum_steps and train.steps_per_call are "
                     "mutually exclusive (both stack the leading axis)")
    else:
        assert_headline_fastpath(cfg)
    # Leading-axis stack: K scanned optimizer steps OR N accumulated
    # microbatches (mutually exclusive; plan-validated).
    accum = max(cfg.train.accum_steps, 1)
    stack = max(k, accum)
    # Knob provenance line, FIRST json line of the artifact (the headline
    # metric stays the last — existing consumers key off that).
    print(json.dumps({"metric": "bench_knobs", "value": resolved_knobs(cfg)}))

    if args.eval:
        img_s, eb = _eval_bench(cfg, image_size, on_accel)
        name = args.config.replace("_coco", "")
        print(
            json.dumps(
                {
                    "metric": f"eval_images_per_sec_per_chip[{name}@{image_size[0]}x{image_size[1]},b{eb},{platform}]",
                    "value": round(img_s, 3),
                    "unit": "img/s/chip",
                    "vs_baseline": round(img_s / BASELINE_EVAL_IMG_S, 4),
                }
            )
        )
        return
    model, tx, state, step_fn, global_batch = build_all(cfg, mesh=None)
    data = _synthetic_batch(cfg, batch, image_size, stack)

    # Device-resident batch: the metric is the train step (fwd+bwd+update);
    # input delivery is measured separately (--loader) because the axon
    # tunnel's ~10 MB/s host->device link is not representative of
    # production PCIe (BASELINE.md).
    data = jax.device_put(data)

    def sync(s, m):
        # Under the axon tunnel block_until_ready returns at dispatch time,
        # not execution time — a device->host fetch of a value that depends
        # on the whole step is the only true barrier.  Fetch from the
        # UPDATED params (depends on forward+backward+optimizer) and the
        # loss; one fetch per timed window, so the tunnel round-trip is
        # counted once, not per step.
        leaf = jax.tree_util.tree_leaves(s.params)[0]
        jax.device_get((m["loss"], leaf.ravel()[0]))

    # Warmup (compile) + timed steps.
    for _ in range(2):
        state, metrics = step_fn(state, data)
    sync(state, metrics)
    n_calls = 6 if on_accel else 5
    # Images processed per call: K steps x batch, or batch x N
    # microbatches per accumulated step — `stack * batch` either way.
    n_steps = n_calls * stack
    t0 = time.perf_counter()
    for _ in range(n_calls):
        state, metrics = step_fn(state, data)
    sync(state, metrics)
    dt = time.perf_counter() - t0

    _cost_analysis(step_fn, state, data, stack, dt / n_calls)

    # Per-step percentiles (sync per step — includes one tunnel round-trip
    # per step, an upper bound) on stderr.
    from mx_rcnn_tpu.utils import StepTimer

    timer = StepTimer(warmup=2)
    for _ in range(8 if on_accel else 3):
        with timer:
            state, metrics = step_fn(state, data)
            sync(state, metrics)
    per_call = timer.summary()
    per_step = {key: v / stack if key != "steps" else v for key, v in per_call.items()}
    print(
        f"per-call (K={k} steps x N={accum} microbatches, synced upper "
        f"bound): {per_call}\n"
        f"per-step equivalent: {per_step}",
        file=sys.stderr,
    )

    if args.loader:
        _loader_fed(cfg, step_fn, state, global_batch)

    do_breakdown = args.breakdown if args.breakdown is not None else on_accel
    if do_breakdown:
        _stage_breakdown(
            cfg, model, state, image_size, batch, platform, on_accel
        )

    img_s = n_steps * batch / dt
    name = args.config.replace("_coco", "")
    print(
        json.dumps(
            {
                "metric": f"train_images_per_sec_per_chip[{name}@{image_size[0]}x{image_size[1]},b{batch},{platform}]",
                "value": round(img_s, 3),
                "unit": "img/s/chip",
                "vs_baseline": round(img_s / BASELINE_IMG_S_CHIP, 4),
                # Per-step wall-clock tail (StepTimer, synced upper bound):
                # mean/p50/p90/p99/max in ms — a throughput headline can
                # hide a straggler step; these cannot.
                "step_ms": {
                    key: round(v, 3)
                    for key, v in per_step.items() if key != "steps"
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
