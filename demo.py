#!/usr/bin/env python
"""Entry point — see mx_rcnn_tpu/cli/demo_cli.py (reference: demo driver)."""
from mx_rcnn_tpu.cli.demo_cli import main

if __name__ == "__main__":
    main()
