"""mx_rcnn_tpu: a TPU-native two-stage detection framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of the MXNet
Faster R-CNN codebase (reference: xuelanglv/mx-rcnn, a fork of
ijkguo/mx-rcnn).  Nothing here is a translation: the reference's
host-side custom ops (``rcnn/symbol/proposal.py``,
``rcnn/symbol/proposal_target.py``), Cython/CUDA kernels
(``rcnn/cython/``), and MXNet Module/KVStore runtime are replaced by a
single statically-shaped jitted train step, in-graph detection ops, and
``jax.sharding`` data parallelism over a device mesh.

Layers (bottom-up, see SURVEY.md section 8):
  geometry/  pure-JAX box math            (replaces rcnn/processing, rcnn/cython/bbox.pyx)
  ops/       static-shape detection ops   (replaces custom ops + gpu_nms + ROIPooling)
  models/    Flax backbones/necks/heads   (replaces rcnn/symbol)
  detection/ assembled train/test steps   (replaces symbol train/test graph variants)
  train/     optimizer/metrics/checkpoint (replaces rcnn/core module/metric/callback)
  parallel/  mesh + sharding              (replaces Module ctx slicing + KVStore)
  data/      datasets + static batching   (replaces rcnn/io, rcnn/dataset, rcnn/core/loader)
  evalutil/  VOC / COCO mAP evaluators    (replaces pascal_voc_eval + pycocotools eval)
  cli/       drivers                      (replaces train_end2end.py, test.py, demo.py)
"""

__version__ = "0.1.0"

import os as _os

# Runtime lock-order sanitizer (analysis/lockcheck.py), env-gated so the
# one variable activates it in every process of a run — chaos children,
# serve hosts, data workers — with no per-entry-point wiring.  Must
# install BEFORE any module creates its locks; package import is the
# earliest common point.  Unset (the default) this is one getenv.
if _os.environ.get("MX_RCNN_LOCKCHECK") == "1":
    from mx_rcnn_tpu.analysis import lockcheck as _lockcheck

    _lockcheck.install()
    del _lockcheck
del _os

import jax as _jax

# Sharding-invariant PRNG, unconditionally.  The legacy threefry lowering
# leaves its iota counter generation to the whims of the SPMD partitioner;
# inside a large partitioned step we have observed it produce *different
# bits for the same key* between a pure-DP and a spatially-partitioned
# compilation (and upstream jax made this mode the default in later
# releases for the same reason).  Every determinism contract in this repo —
# spatial-vs-DP metric parity, bit-exact chaos resume, double-compile
# determinism — sits on top of "same key => same bits", so opt in at import.
_jax.config.update("jax_threefry_partitionable", True)
del _jax
