"""tpulint: static analysis that proves the train/eval steps are TPU-clean.

The paper's premise is that the whole MXNet/CUDA execution path becomes a
single XLA computation with no hidden host round-trips.  This package is
the machinery that *checks* that claim instead of assuming it:

* :mod:`ast_lint` (layer 1) — repo-aware AST rules over the package source:
  host-sync casts on traced values, raw numpy inside jit-traced code,
  Python branching on tracer values, dict-ordering-dependent trace inputs,
  and MXU-emitting code outside any ``jax.named_scope``/flax-module scope
  (which would fall into hlo_profile's "other" bucket).  Pre-existing
  violations are frozen in a committed baseline file; new ones fail.

* :mod:`jaxpr_checks` (layer 2) — abstractly trace the *actual* jitted
  train/eval/proposal steps under ``JAX_PLATFORMS=cpu`` and machine-verify
  the TPU invariants: zero f64/i64 in the traced programs, a
  ``jax.transfer_guard("disallow")``-clean steady-state step, double-trace
  determinism (the recompilation guard), buffer donation actually applied
  to the train state, and >=99% of conv/dot FLOPs attributed to a named
  component by :mod:`mx_rcnn_tpu.utils.hlo_profile`.

* :mod:`fleetlint` (layer 3) — concurrency + contract lint for the
  threaded host-side plane (``serve/ obs/ ctrl/ data/ tools/``):
  lock-acquisition-order cycles, bare acquires, undaemonized threads,
  unlocked shared writes from thread targets, blocking calls under
  locks (FL001–FL005), plus the serve typed-error vocabulary, the
  journal-kind/metric registry and the cfg-knob docs contracts
  (FL010–FL012).  Own ratchet baseline (``fleetlint_baseline.json``).

* :mod:`lockcheck` (runtime twin of layer 3) — opt-in instrumented
  ``threading.Lock/RLock`` (env ``MX_RCNN_LOCKCHECK=1``) that enforces
  the acquisition-order graph and the no-blocking-under-lock rule at
  runtime, deterministically, without needing a real deadlock.

``tools/tpulint.py`` and ``tools/fleetlint.py`` are the CLIs (writing
``artifacts/tpulint_report.json`` / ``artifacts/fleetlint_report.json``);
``tests/test_tpulint.py`` and ``tests/test_fleetlint.py`` run the layers
as tier-1 tests.  See ``docs/static_analysis.md`` for the rule sets and
extension guide.
"""

from mx_rcnn_tpu.analysis.ast_lint import (
    Finding,
    RULES,
    TRACED_PREFIXES,
    lint_paths,
    lint_source,
    traced_files,
)
from mx_rcnn_tpu.analysis.baseline import (
    collect_counts,
    load_baseline,
    new_findings,
    write_baseline,
)
from mx_rcnn_tpu.analysis.jaxpr_checks import (
    UPCAST_ALLOWLIST,
    CheckResult,
    build_programs,
    run_jaxpr_checks,
)

# Layer 3 + its runtime twin, as submodules: fleetlint deliberately
# reuses the names Finding/RULES/lint_paths for its own rule family, so
# the flat namespace stays tpulint's and layer 3 is reached as
# ``analysis.fleetlint.*`` / ``analysis.lockcheck.*``.
from mx_rcnn_tpu.analysis import fleetlint, lockcheck

__all__ = [
    "fleetlint",
    "lockcheck",
    "Finding",
    "RULES",
    "TRACED_PREFIXES",
    "lint_paths",
    "lint_source",
    "traced_files",
    "collect_counts",
    "load_baseline",
    "new_findings",
    "write_baseline",
    "UPCAST_ALLOWLIST",
    "CheckResult",
    "build_programs",
    "run_jaxpr_checks",
]
