"""tpulint: static analysis that proves the train/eval steps are TPU-clean.

The paper's premise is that the whole MXNet/CUDA execution path becomes a
single XLA computation with no hidden host round-trips.  This package is
the machinery that *checks* that claim instead of assuming it:

* :mod:`ast_lint` (layer 1) — repo-aware AST rules over the package source:
  host-sync casts on traced values, raw numpy inside jit-traced code,
  Python branching on tracer values, dict-ordering-dependent trace inputs,
  and MXU-emitting code outside any ``jax.named_scope``/flax-module scope
  (which would fall into hlo_profile's "other" bucket).  Pre-existing
  violations are frozen in a committed baseline file; new ones fail.

* :mod:`jaxpr_checks` (layer 2) — abstractly trace the *actual* jitted
  train/eval/proposal steps under ``JAX_PLATFORMS=cpu`` and machine-verify
  the TPU invariants: zero f64/i64 in the traced programs, a
  ``jax.transfer_guard("disallow")``-clean steady-state step, double-trace
  determinism (the recompilation guard), buffer donation actually applied
  to the train state, and >=99% of conv/dot FLOPs attributed to a named
  component by :mod:`mx_rcnn_tpu.utils.hlo_profile`.

``tools/tpulint.py`` is the CLI (writes ``artifacts/tpulint_report.json``);
``tests/test_tpulint.py`` runs both layers as tier-1 tests.  See
``docs/static_analysis.md`` for the rule set and extension guide.
"""

from mx_rcnn_tpu.analysis.ast_lint import (
    Finding,
    RULES,
    TRACED_PREFIXES,
    lint_paths,
    lint_source,
    traced_files,
)
from mx_rcnn_tpu.analysis.baseline import (
    collect_counts,
    load_baseline,
    new_findings,
    write_baseline,
)
from mx_rcnn_tpu.analysis.jaxpr_checks import (
    UPCAST_ALLOWLIST,
    CheckResult,
    build_programs,
    run_jaxpr_checks,
)

__all__ = [
    "Finding",
    "RULES",
    "TRACED_PREFIXES",
    "lint_paths",
    "lint_source",
    "traced_files",
    "collect_counts",
    "load_baseline",
    "new_findings",
    "write_baseline",
    "UPCAST_ALLOWLIST",
    "CheckResult",
    "build_programs",
    "run_jaxpr_checks",
]
