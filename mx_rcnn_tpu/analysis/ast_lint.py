"""Layer 1: repo-aware AST lint over the jit-traced package source.

The rules only fire inside *traced* modules — the files whose code is
reachable from the jitted step functions (``TRACED_PREFIXES``).  Host-side
code (data loading, evaluation, CLIs) legitimately calls ``float()`` on
device scalars it already fetched; the same call inside ``detection/graph``
would be a silent per-step device->host sync, which is exactly the failure
mode the reference repo's CustomOp sandwich had and this repo exists to
eliminate.

Static analysis cannot prove a value is a tracer, so each rule is a
*reviewed* heuristic: pre-existing findings are frozen in the committed
baseline (``tpulint_baseline.json``) after human review, and only NEW
findings fail ``tools/tpulint.py --check``.  The baseline keys on
(rule, path, stripped source line) with a count, so moving a line is free
but adding another occurrence of a frozen pattern still fails.

Rules
-----
TPU001 host-cast        float()/int()/bool() on a non-literal, ``.item()``
                        / ``.tolist()``, and ``np.asarray``/``np.array`` —
                        each forces a device sync on a traced value.
TPU002 numpy-call       any other ``np.*`` computation in traced code
                        (numpy silently pulls tracers to host or bakes
                        trace-time constants).
TPU003 tracer-branch    Python ``if``/``while``/``assert`` whose test
                        calls ``jnp.*``/``jax.nn.*``/``lax.*`` — branching
                        on a tracer raises at trace time or, worse, bakes
                        one branch in silently via a concrete aval.
TPU004 dict-order       iterating ``.items()/.keys()/.values()`` without
                        ``sorted()`` in traced code — trace order (and so
                        the compiled program hash) then depends on dict
                        insertion history; the recompilation guard
                        (layer 2) can only catch in-process instances.
TPU005 unscoped-mxu     conv/dot-emitting calls in a plain function with
                        no enclosing ``jax.named_scope`` and no flax
                        module scope — their FLOPs land in hlo_profile's
                        "other" bucket, breaking per-component MFU
                        attribution.
TPU007 host-in-trace    any import of ``mx_rcnn_tpu.obs`` or
                        ``mx_rcnn_tpu.ctrl`` in traced code.  The
                        observability and control planes are host-side by
                        contract (journal writes, HTTP endpoint, wall
                        clocks, fleet mutation): an emit/span/counter or
                        autoscaler call inside a jitted module would at
                        best bake trace-time values and at worst sync or
                        do I/O per step.  (TPU006 is the dynamic bf16
                        upcast walk in tools/tpulint.py.)
TPU008 no-interpret     a ``pallas_call(...)`` without an explicit
                        ``interpret=`` keyword.  Every Pallas kernel in
                        this repo must declare its CPU fallback posture
                        at the call site (threaded from graph.py's
                        ``_pallas_interpret()`` gate): an implicit
                        default means the kernel silently fails to lower
                        off-TPU, and the CI interpret-mode parity suites
                        (test_roi_align, test_fused_middle) can't reach
                        it.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
from typing import Iterable, Optional

# Modules whose code is reachable from the jitted step functions
# (forward_train / forward_inference / forward_proposals / make_train_step).
# Paths are repo-root-relative with "/" separators; a trailing "/" marks a
# package prefix.
TRACED_PREFIXES: tuple[str, ...] = (
    "mx_rcnn_tpu/detection/",
    "mx_rcnn_tpu/models/",
    "mx_rcnn_tpu/geometry/",
    "mx_rcnn_tpu/ops/",
    "mx_rcnn_tpu/parallel/step.py",
    "mx_rcnn_tpu/train/state.py",
    "mx_rcnn_tpu/train/optim.py",
)

RULES: dict[str, str] = {
    "TPU001": "host-sync cast (float/int/bool/.item/.tolist/np.asarray) "
              "in jit-traced code",
    "TPU002": "raw numpy computation in jit-traced code",
    "TPU003": "Python branch on a jnp/lax expression (tracer branching)",
    "TPU004": "unsorted dict iteration in jit-traced code "
              "(trace-order nondeterminism)",
    "TPU005": "MXU-emitting op outside any jax.named_scope / flax module "
              "(unattributable FLOPs)",
    "TPU007": "mx_rcnn_tpu.obs/ctrl imported in jit-traced code (the "
              "observability and control planes are host-side only)",
    "TPU008": "pallas_call without an explicit interpret= kwarg (every "
              "kernel must declare its CPU-fallback posture at the call "
              "site)",
}

# Host-only top-level packages TPU007 fences out of traced code.
_HOST_ONLY_PKGS: tuple[str, ...] = ("obs", "ctrl")
_HOST_ONLY_MODULES: tuple[str, ...] = tuple(
    f"mx_rcnn_tpu.{p}" for p in _HOST_ONLY_PKGS
)

# TPU001: numpy calls that materialize/cast an array on host.
_HOST_CAST_NP = {"asarray", "array"}
# TPU002 allowlist: attribute uses of numpy that are constants/dtypes, not
# computations (np.float32 as a dtype argument, np.pi, np.inf, ...).
# Includes the dtype-introspection calls (issubdtype/iinfo/finfo): static
# host dispatch on an aval's dtype, never a computation on traced values.
_NP_CONST_ATTRS = {
    "float32", "float16", "bfloat16", "int32", "int8", "uint8", "bool_",
    "pi", "inf", "nan", "newaxis", "ndarray", "dtype", "integer",
    "floating", "inexact", "issubdtype", "iinfo", "finfo",
}
# TPU005: calls that emit MXU (conv/dot) work.
_MXU_CALL_NAMES = {
    "conv_general_dilated", "dot_general", "dot", "matmul", "einsum",
    "tensordot", "conv", "conv_transpose",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str        # repo-root-relative, "/" separators
    line: int
    col: int
    snippet: str     # stripped source line (fingerprint material)
    message: str

    def fingerprint(self) -> str:
        """Stable id for the baseline: survives line moves, not edits."""
        key = f"{self.rule}:{self.path}:{self.snippet}"
        return hashlib.sha1(key.encode()).hexdigest()[:12]

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{RULES[self.rule]}\n    {self.snippet}"
        )


def is_traced_path(rel_path: str) -> bool:
    p = rel_path.replace(os.sep, "/")
    return any(
        p.startswith(pref) if pref.endswith("/") else p == pref
        for pref in TRACED_PREFIXES
    )


def _attr_root(node: ast.expr) -> Optional[str]:
    """Leftmost name of an attribute chain (``np.linalg.norm`` -> "np")."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_literal(node: ast.expr) -> bool:
    """Constant-foldable at trace time — casts of these never sync."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_literal(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_literal(node.left) and _is_literal(node.right)
    return False


class _ImportTracker:
    """Module aliases seen in the file (``import numpy as np`` -> np)."""

    def __init__(self) -> None:
        self.numpy: set[str] = set()
        self.jnp: set[str] = set()
        self.lax: set[str] = set()
        self.jax: set[str] = set()

    def visit_import(self, node: ast.Import) -> None:
        for a in node.names:
            alias = a.asname or a.name.split(".")[0]
            if a.name == "numpy":
                self.numpy.add(alias)
            elif a.name in ("jax.numpy",):
                self.jnp.add(a.asname or "jnp")
            elif a.name == "jax":
                self.jax.add(alias)

    def visit_import_from(self, node: ast.ImportFrom) -> None:
        if node.module == "jax":
            for a in node.names:
                if a.name == "numpy":
                    self.jnp.add(a.asname or "numpy")
                elif a.name == "lax":
                    self.lax.add(a.asname or "lax")
        elif node.module == "jax.numpy":
            pass  # from jax.numpy import X — X calls are rule-invisible


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, lines: list[str]) -> None:
        self.path = path
        self.lines = lines
        self.imports = _ImportTracker()
        self.findings: list[Finding] = []
        # Lexical context stacks.
        self._scope_depth = 0          # inside `with jax.named_scope(...)`
        self._class_stack: list[ast.ClassDef] = []
        self._branch_depth = 0         # inside an if/while/assert test expr

    # -- helpers ----------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, message: str = "") -> None:
        line = getattr(node, "lineno", 1)
        snippet = (
            self.lines[line - 1].strip() if line - 1 < len(self.lines) else ""
        )
        self.findings.append(
            Finding(
                rule=rule,
                path=self.path,
                line=line,
                col=getattr(node, "col_offset", 0),
                snippet=snippet,
                message=message or RULES[rule],
            )
        )

    def _in_flax_module(self) -> bool:
        """Flax modules name-scope their ops for free — TPU005 exempts
        them.  Heuristic: any enclosing class whose bases mention Module."""
        for cls in self._class_stack:
            for base in cls.bases:
                name = base.attr if isinstance(base, ast.Attribute) else (
                    base.id if isinstance(base, ast.Name) else ""
                )
                if "Module" in name:
                    return True
        return False

    def _is_named_scope_with(self, node: ast.With) -> bool:
        for item in node.items:
            call = item.context_expr
            if (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "named_scope"
            ):
                return True
        return False

    # -- structure --------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        self.imports.visit_import(node)
        for a in node.names:
            if any(
                a.name == mod or a.name.startswith(mod + ".")
                for mod in _HOST_ONLY_MODULES
            ):
                self._emit("TPU007", node)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        self.imports.visit_import_from(node)
        mod = node.module or ""
        if any(
            mod == m or mod.startswith(m + ".") for m in _HOST_ONLY_MODULES
        ):
            self._emit("TPU007", node)
        elif mod == "mx_rcnn_tpu" and any(
            a.name in _HOST_ONLY_PKGS for a in node.names
        ):
            self._emit("TPU007", node)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_With(self, node: ast.With) -> None:
        if self._is_named_scope_with(node):
            self._scope_depth += 1
            self.generic_visit(node)
            self._scope_depth -= 1
        else:
            self.generic_visit(node)

    # -- TPU003: tracer branching ----------------------------------------

    def _check_branch_test(self, test: ast.expr) -> None:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                root = _attr_root(sub.func)
                if root in self.imports.jnp or root in self.imports.lax:
                    self._emit("TPU003", test)
                    return

    def visit_If(self, node: ast.If) -> None:
        self._check_branch_test(node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch_test(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_branch_test(node.test)
        self.generic_visit(node)

    # -- TPU004: dict-order iteration ------------------------------------

    def _check_dict_iter(self, it: ast.expr) -> None:
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Attribute)
            and it.func.attr in ("items", "keys", "values")
            and not it.args
        ):
            self._emit("TPU004", it)

    def visit_For(self, node: ast.For) -> None:
        self._check_dict_iter(node.iter)
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        self._check_dict_iter(node.iter)
        self.generic_visit(node)

    # -- calls: TPU001 / TPU002 / TPU005 ---------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # sorted(x.items()) is the sanctioned form — don't descend into the
        # sorted() argument with the TPU004 comprehension check (handled in
        # _check_dict_iter callers, which only see raw loop iterables).
        if isinstance(func, ast.Name):
            if (
                func.id in ("float", "int", "bool")
                and len(node.args) == 1
                and not _is_literal(node.args[0])
            ):
                self._emit("TPU001", node)
        elif isinstance(func, ast.Attribute):
            root = _attr_root(func)
            if func.attr in ("item", "tolist") and not node.args:
                self._emit("TPU001", node)
            elif root in self.imports.numpy:
                if func.attr in _HOST_CAST_NP:
                    self._emit("TPU001", node)
                elif func.attr not in _NP_CONST_ATTRS:
                    self._emit("TPU002", node)
            if (
                func.attr in _MXU_CALL_NAMES
                and root in (
                    self.imports.jnp | self.imports.lax | self.imports.jax
                )
                and self._scope_depth == 0
                and not self._in_flax_module()
            ):
                self._emit("TPU005", node)
            # TPU008: pallas_call must state its interpret posture.
            if func.attr == "pallas_call" and not any(
                kw.arg == "interpret" for kw in node.keywords
            ):
                self._emit("TPU008", node)
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        # a @ b is a dot_general like any other (TPU005).
        if (
            isinstance(node.op, ast.MatMult)
            and self._scope_depth == 0
            and not self._in_flax_module()
        ):
            self._emit("TPU005", node)
        self.generic_visit(node)


def lint_source(src: str, path: str) -> list[Finding]:
    """Lint one file's source; ``path`` (repo-relative) decides traced-ness.

    Returns [] for non-traced paths — the rules only mean anything where
    code runs under trace.
    """
    if not is_traced_path(path):
        return []
    tree = ast.parse(src, filename=path)
    linter = _Linter(path.replace(os.sep, "/"), src.splitlines())
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.col))


def traced_files(repo_root: str) -> list[str]:
    """All repo-relative python files under the traced prefixes."""
    out = []
    for pref in TRACED_PREFIXES:
        full = os.path.join(repo_root, pref)
        if pref.endswith("/"):
            for dirpath, _dirnames, filenames in os.walk(full):
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, name), repo_root
                        )
                        out.append(rel.replace(os.sep, "/"))
        elif os.path.exists(full):
            out.append(pref)
    return sorted(set(out))


def lint_paths(
    repo_root: str, paths: Optional[Iterable[str]] = None
) -> list[Finding]:
    """Lint the given repo-relative paths (default: every traced file)."""
    findings: list[Finding] = []
    for rel in paths if paths is not None else traced_files(repo_root):
        with open(os.path.join(repo_root, rel)) as f:
            findings.extend(lint_source(f.read(), rel))
    return findings
