"""Baseline / suppression file for the AST lint layer.

The repo predates tpulint, so layer 1 finds violations that were reviewed
and found harmless (host-side constant math in traced files, Pallas kernel
bodies whose FLOPs are attributed by the caller's scope, ...).  Freezing
them in a committed file turns the lint into a ratchet: the frozen set can
only shrink, and any NEW finding — a new fingerprint, or more occurrences
of a frozen one — fails ``tools/tpulint.py --check``.

Format (``tpulint_baseline.json``): human-auditable JSON —

    {"version": 1,
     "suppressions": {
        "<fingerprint>": {"rule": ..., "path": ..., "snippet": ...,
                          "count": N}}}

The fingerprint is sha1(rule:path:stripped-line)[:12] (ast_lint.Finding),
so reformatting or moving a line does not churn the file, while editing
the line re-opens the finding for review.  Regenerate with
``python tools/tpulint.py --write-baseline`` (then review the diff — a
baseline refresh is a statement that every new entry was human-judged
acceptable).

Layer 2 (jaxpr invariants) has NO suppression mechanism by design: the
traced-program invariants must hold outright.
"""

from __future__ import annotations

import json
from typing import Iterable

from mx_rcnn_tpu.analysis.ast_lint import Finding

BASELINE_VERSION = 1


def collect_counts(findings: Iterable[Finding]) -> dict[str, int]:
    counts: dict[str, int] = {}
    for f in findings:
        fp = f.fingerprint()
        counts[fp] = counts.get(fp, 0) + 1
    return counts


def load_baseline(path: str) -> dict:
    """Load a baseline file; missing file = empty baseline (everything is
    a new finding)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return {"version": BASELINE_VERSION, "suppressions": {}}
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: baseline version {data.get('version')!r} != "
            f"{BASELINE_VERSION}; regenerate with --write-baseline"
        )
    return data


def new_findings(
    findings: Iterable[Finding], baseline: dict
) -> list[Finding]:
    """Findings beyond the baseline's per-fingerprint counts.

    Occurrence semantics: a baseline count of N suppresses the first N
    occurrences of that fingerprint; the N+1'th is new.  Order within a
    fingerprint follows (path, line) so the reported "new" one is the
    last-added in source order.
    """
    budget = {
        fp: entry.get("count", 1)
        for fp, entry in baseline.get("suppressions", {}).items()
    }
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            out.append(f)
    return out


def write_baseline(path: str, findings: Iterable[Finding]) -> dict:
    """Freeze the given findings as the new baseline; returns the data."""
    entries: dict[str, dict] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        fp = f.fingerprint()
        if fp in entries:
            entries[fp]["count"] += 1
        else:
            entries[fp] = {
                "rule": f.rule,
                "path": f.path,
                "snippet": f.snippet,
                "count": 1,
            }
    data = {"version": BASELINE_VERSION, "suppressions": entries}
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data
