"""fleetlint (layer 3): concurrency + contract lint for the host-side plane.

tpulint (:mod:`ast_lint` + :mod:`jaxpr_checks`) proves the *traced* half
of the repo is TPU-clean; this module covers the other half — the
threaded serving control plane in ``serve/``, ``obs/``, ``ctrl/``,
``data/`` and ``tools/`` — whose worst bugs are concurrency bugs that no
jaxpr can show.  Same discipline as tpulint: AST rules with stable IDs,
a committed fingerprint baseline that only ratchets down
(``fleetlint_baseline.json``), ``tools/fleetlint.py --check`` as the CLI
and ``tests/test_fleetlint.py`` as the tier-1 gate.

Concurrency rules (per file):

* FL001 — lock-acquisition-order cycle.  Builds the order graph from
  ``with <lock>:`` nesting plus a one-level call-graph closure
  (``with self._a: self.m()`` where ``m`` acquires ``self._b`` adds the
  edge ``a -> b``), then flags every edge that participates in a cycle.
* FL002 — bare ``.acquire()`` on a lock without a ``try/finally``
  ``.release()`` in the same function.
* FL003 — ``threading.Thread`` without an explicit ``daemon=`` and with
  no visible ``.join()``/stop path for the created thread.
* FL004 — attribute written from a thread-target method outside any
  lock, but read from another method also outside any lock, in a class
  that owns locks (i.e. the class has a locking discipline and this
  attribute escaped it).
* FL005 — blocking call while a lock is held: ``urlopen``, bare
  ``.get()``/``.result()``/``.wait()``/``.join()`` without a timeout,
  and weight-push calls (``.swap_weights()``/``.swap()``).

Contract rules (repo-level, :func:`contract_findings`):

* FL010 — ``raise``/``except`` in ``serve/`` outside the typed-error
  vocabulary, and the RPC status map in ``serve/rpc.py`` must be total
  over the serve error vocabulary in both directions.
* FL011 — every literal journal kind passed to ``obs.emit`` must have a
  template in ``obs/events.py``; every metric name created via
  ``obs.counter/gauge/histogram`` must be listed in the
  ``docs/observability.md`` inventory; every metric
  ``tools/obs_report.py`` consumes must actually be produced somewhere.
* FL012 — every ``cfg.<section>.<knob>`` read (serve/ctrl/obs/data/
  fabric) must exist as a field on the matching dataclass in
  ``config.py`` and appear in a docs table.

The runtime twin of FL001/FL005 is :mod:`mx_rcnn_tpu.analysis.lockcheck`.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
from typing import Iterable, Optional

__all__ = [
    "FLEET_PREFIXES",
    "RULES",
    "Finding",
    "fleet_files",
    "lint_source",
    "lint_paths",
    "contract_findings",
]

# Repo-relative prefixes the concurrency rules run over (trailing "/" =
# subtree).  The contract rules additionally scan train/ for journal
# kinds — training emits into the same journal.
FLEET_PREFIXES = (
    "mx_rcnn_tpu/serve/",
    "mx_rcnn_tpu/obs/",
    "mx_rcnn_tpu/ctrl/",
    "mx_rcnn_tpu/data/",
    "tools/",
)
CONTRACT_EXTRA_PREFIXES = ("mx_rcnn_tpu/train/",)

RULES = {
    "FL001": "lock-acquisition-order cycle (deadlock by interleaving)",
    "FL002": "bare .acquire() without a try/finally .release()",
    "FL003": "threading.Thread without explicit daemon= or a join()/stop "
             "path",
    "FL004": "attribute written from a thread target outside any lock "
             "but read elsewhere outside any lock",
    "FL005": "blocking call while a lock is held",
    "FL010": "raise/except outside the serve typed-error vocabulary, or "
             "RPC status map not total over it",
    "FL011": "journal kind missing from obs/events.py, or metric name "
             "missing from the registry docs / never produced",
    "FL012": "cfg knob read that is missing from config.py or "
             "undocumented",
}

_LOCKISH_RE = re.compile(r"(?:^|_)(lock|mutex|mu|cond|cv)\d*$", re.I)
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}

# FL010 vocabularies. Typed serve errors + the builtins that express
# programming/usage errors (they surface as 500s on purpose).
RAISE_ALLOW = frozenset({
    "ServeError", "Overloaded", "EngineUnavailable", "DeadlineExceeded",
    "QuotaExceeded", "HostUnreachable",
    "ValueError", "TypeError", "KeyError", "RuntimeError", "TimeoutError",
    "NotImplementedError", "AssertionError", "OSError", "StopIteration",
    "_error",  # serve handler-local typed-error factory
})
EXCEPT_ALLOW = RAISE_ALLOW | frozenset({
    "Exception", "BaseException", "Empty", "Full", "HTTPError",
    "URLError", "ConnectionError", "ConnectionRefusedError",
    "ConnectionResetError", "BrokenPipeError", "InterruptedError",
    "BlockingIOError", "AttributeError", "IndexError", "OverflowError",
    "ZeroDivisionError", "FileNotFoundError", "JSONDecodeError",
})

# FL005: attribute calls that are blocking regardless of arguments.
_ALWAYS_BLOCKING_ATTRS = {"urlopen", "swap_weights", "swap"}
# FL005: attribute calls that block when called with no timeout.
_TIMEOUT_BLOCKING_ATTRS = {"get", "result", "wait", "join"}

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_METRIC_NAME_RE = re.compile(
    r"^(serve|data|fleet|obs|slo|ctrl|train|gateway|gossip|rpc)"
    r"_[a-z0-9_]+$"
)
_CFG_SECTIONS = {"serve", "ctrl", "obs", "data", "fabric"}
_CFG_CLASS_BY_SECTION = {
    "serve": "ServeConfig", "ctrl": "CtrlConfig", "obs": "ObsConfig",
    "data": "DataConfig", "fabric": "FabricConfig",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    snippet: str
    message: str

    def fingerprint(self) -> str:
        # Deliberately excludes the line number: moving code around does
        # not create "new" findings, editing the flagged line does.
        key = f"{self.rule}:{self.path}:{self.snippet}"
        return hashlib.sha1(key.encode()).hexdigest()[:12]

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.message}\n    {self.snippet}"
        )


def is_fleet_path(rel_path: str) -> bool:
    p = rel_path.replace(os.sep, "/")
    return any(
        p.startswith(pref) if pref.endswith("/") else p == pref
        for pref in FLEET_PREFIXES
    )


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


def _attr_name(node: ast.expr) -> Optional[str]:
    """'EngineUnavailable' for both ``Name`` and ``x.EngineUnavailable``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_lockish_expr(expr: ast.expr, class_locks: set[str]) -> bool:
    """Does this with-item / receiver look like a lock?"""
    if isinstance(expr, ast.Attribute):
        return expr.attr in class_locks or bool(
            _LOCKISH_RE.search(expr.attr)
        )
    if isinstance(expr, ast.Name):
        return bool(_LOCKISH_RE.search(expr.id))
    return False


def _is_lock_factory_call(value: ast.expr) -> bool:
    """``threading.Lock()`` / ``Lock()`` / ``threading.Condition(...)``."""
    if not isinstance(value, ast.Call):
        return False
    name = _attr_name(value.func)
    return name in _LOCK_FACTORIES


class _FnInfo:
    """Per-function facts collected during the walk."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.acquires: set[str] = set()        # lock keys acquired via with
        self.calls_under: list = []            # (held_key, method, node)
        self.acquire_calls: list = []          # (recv_key, node)  bare .acquire
        self.finally_releases: set[str] = set()
        self.writes_nolock: dict[str, ast.AST] = {}  # self.attr = .. no lock
        self.reads_nolock: set[str] = set()


class _FileLint(ast.NodeVisitor):
    """One pass over one file: FL001–FL005 (+ FL010 raise/except in
    serve/)."""

    def __init__(self, path: str, src_lines: list[str]) -> None:
        self.path = path
        self.src_lines = src_lines
        self.findings: list[Finding] = []
        self.in_serve = path.startswith("mx_rcnn_tpu/serve/")
        self._class: list[str] = []            # class name stack
        self._class_locks: list[set[str]] = []  # lock attr names per class
        self._fns: list[dict[str, _FnInfo]] = []  # per-class method infos
        self._thread_targets: list[set[str]] = []  # per-class target methods
        self._edges: list[dict] = []           # per-class {(A,B): node}
        self._fn: list[_FnInfo] = []           # function stack
        self._held: list[str] = []             # lock keys held (lexically)
        self._src = "\n".join(src_lines)

    # -- helpers ---------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, message: str = "") -> None:
        line = getattr(node, "lineno", 1)
        snippet = ""
        if 0 < line <= len(self.src_lines):
            snippet = self.src_lines[line - 1].strip()
        self.findings.append(Finding(
            rule=rule, path=self.path, line=line,
            col=getattr(node, "col_offset", 0), snippet=snippet,
            message=message or RULES[rule],
        ))

    def _lock_key(self, expr: ast.expr) -> str:
        owner = self._class[-1] if self._class else "<module>"
        return f"{owner}.{_unparse(expr)}"

    def _cur_class_locks(self) -> set[str]:
        return self._class_locks[-1] if self._class_locks else set()

    # -- scopes ----------------------------------------------------------

    def _prescan_class_locks(self, node: ast.ClassDef) -> set[str]:
        locks: set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and _is_lock_factory_call(
                sub.value
            ):
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        locks.add(tgt.attr)
        return locks

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class.append(node.name)
        self._class_locks.append(self._prescan_class_locks(node))
        self._fns.append({})
        self._thread_targets.append(set())
        self._edges.append({})
        self.generic_visit(node)
        self._finish_class()
        self._class.pop()
        self._class_locks.pop()
        self._fns.pop()
        self._thread_targets.pop()
        self._edges.pop()

    def _finish_class(self) -> None:
        fns = self._fns[-1]
        edges = self._edges[-1]
        # One-level call closure: held A, call self.m(), m acquires B.
        for info in fns.values():
            for held_key, meth, call_node in info.calls_under:
                callee = fns.get(meth)
                if callee is None:
                    continue
                for b in callee.acquires:
                    if b != held_key and (held_key, b) not in edges:
                        edges[(held_key, b)] = call_node
        # Cycle detection: flag every edge whose reverse is reachable.
        adj: dict[str, set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        def reachable(src: str, dst: str) -> bool:
            stack, seen = [src], set()
            while stack:
                n = stack.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(adj.get(n, ()))
            return False

        for (a, b), node in sorted(
            edges.items(), key=lambda kv: getattr(kv[1], "lineno", 0)
        ):
            if reachable(b, a):
                self._emit(
                    "FL001", node,
                    f"lock-order cycle: {a} -> {b} inverts an existing "
                    f"{b} ->* {a} ordering",
                )
        # FL004: unlocked writes from thread targets vs unlocked reads.
        if not self._cur_class_locks():
            return
        targets = self._thread_targets[-1]
        for meth in sorted(targets):
            info = fns.get(meth)
            if info is None:
                continue
            for attr, wnode in sorted(info.writes_nolock.items()):
                if _LOCKISH_RE.search(attr):
                    continue
                for other_name, other in fns.items():
                    if other_name in (meth, "__init__"):
                        continue
                    if attr in other.reads_nolock:
                        self._emit(
                            "FL004", wnode,
                            f"self.{attr} written in thread target "
                            f"{meth}() without a lock but read in "
                            f"{other_name}() without a lock",
                        )
                        break

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn.append(_FnInfo(node.name))
        held_before = list(self._held)
        self._held = []  # lock scopes don't cross function boundaries
        self.generic_visit(node)
        self._held = held_before
        info = self._fn.pop()
        if self._fns:
            self._fns[-1][node.name] = info
        # FL002 resolution: every bare acquire needs a finally release.
        for recv_key, call_node in info.acquire_calls:
            if recv_key not in info.finally_releases:
                self._emit(
                    "FL002", call_node,
                    f"{recv_key}.acquire() without try/finally "
                    f"{recv_key}.release()",
                )

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    # -- the rules -------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        keys = []
        for item in node.items:
            expr = item.context_expr
            if _is_lockish_expr(expr, self._cur_class_locks()):
                key = self._lock_key(expr)
                if self._held and self._held[-1] != key and self._edges:
                    edge = (self._held[-1], key)
                    self._edges[-1].setdefault(edge, node)
                if self._fn:
                    self._fn[-1].acquires.add(key)
                keys.append(key)
                self._held.append(key)
            else:
                self.visit(expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in keys:
            self._held.pop()

    def visit_Try(self, node: ast.Try) -> None:
        if self._fn:
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "release"):
                        self._fn[-1].finally_releases.add(
                            self._lock_key(sub.func.value)
                        )
        self.generic_visit(node)

    def _check_thread_ctor(self, node: ast.Call) -> None:
        kwargs = {kw.arg for kw in node.keywords if kw.arg}
        # Record thread-target methods for FL004 regardless of daemon=.
        for kw in node.keywords:
            if (kw.arg == "target"
                    and isinstance(kw.value, ast.Attribute)
                    and isinstance(kw.value.value, ast.Name)
                    and kw.value.value.id == "self"
                    and self._thread_targets):
                self._thread_targets[-1].add(kw.value.attr)
        if "daemon" in kwargs:
            return
        # No explicit daemon=: require a visible join()/stop path for
        # whatever name the thread is bound to.
        parent = getattr(node, "_fl_parent", None)
        bound: Optional[str] = None
        if isinstance(parent, ast.Assign) and parent.targets:
            tgt = parent.targets[0]
            if isinstance(tgt, ast.Name):
                bound = tgt.id
            elif isinstance(tgt, ast.Attribute):
                bound = tgt.attr
        if bound and (
            f"{bound}.join(" in self._src or f"{bound}.daemon" in self._src
        ):
            return
        self._emit("FL003", node)

    def _has_timeout(self, node: ast.Call) -> bool:
        if node.args:
            return True
        return any(kw.arg == "timeout" for kw in node.keywords)

    def _check_blocking_under_lock(self, node: ast.Call) -> None:
        func = node.func
        name = _attr_name(func)
        if name == "urlopen":
            self._emit(
                "FL005", node,
                f"urlopen while holding {self._held[-1]}",
            )
            return
        if not isinstance(func, ast.Attribute):
            return
        if name in _ALWAYS_BLOCKING_ATTRS:
            self._emit(
                "FL005", node,
                f".{name}() while holding {self._held[-1]}",
            )
            return
        if name in _TIMEOUT_BLOCKING_ATTRS and not self._has_timeout(node):
            recv_key = self._lock_key(func.value)
            if name == "wait" and recv_key in self._held:
                return  # Condition.wait on the held condition: releases it
            if name == "get" and not (
                isinstance(func.value, (ast.Name, ast.Attribute))
            ):
                return
            self._emit(
                "FL005", node,
                f".{name}() with no timeout while holding "
                f"{self._held[-1]}",
            )

    def visit_Call(self, node: ast.Call) -> None:
        name = _attr_name(node.func)
        if name == "Thread":
            self._check_thread_ctor(node)
        if name == "acquire" and isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if _is_lockish_expr(recv, self._cur_class_locks()) and self._fn:
                self._fn[-1].acquire_calls.append(
                    (self._lock_key(recv), node)
                )
        if self._held:
            self._check_blocking_under_lock(node)
            # One-level closure input: self.m() under a held lock.
            if (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and self._fn):
                self._fn[-1].calls_under.append(
                    (self._held[-1], node.func.attr, node)
                )
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            node.value._fl_parent = node  # type: ignore[attr-defined]
        self._record_write(node, node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node, [node.target])
        self.generic_visit(node)

    def _record_write(self, node: ast.AST, targets: list) -> None:
        if self._held or not self._fn:
            return
        for tgt in targets:
            base = tgt
            if isinstance(base, ast.Subscript):
                base = base.value
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"):
                self._fn[-1].writes_nolock.setdefault(base.attr, node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (not self._held and self._fn
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            self._fn[-1].reads_nolock.add(node.attr)
        self.generic_visit(node)

    # -- FL010 (serve/ only) --------------------------------------------

    def visit_Raise(self, node: ast.Raise) -> None:
        if self.in_serve and node.exc is not None:
            exc = node.exc
            if isinstance(exc, ast.Call):
                name = _attr_name(exc.func)
                # `raise _ERROR_TYPES.get(...)(msg)` and other dynamic
                # constructors are out of static reach — skip those.
                if name is not None and not isinstance(exc.func, ast.Call):
                    if name not in RAISE_ALLOW:
                        self._emit(
                            "FL010", node,
                            f"raise {name}(...) is outside the serve "
                            f"typed-error vocabulary",
                        )
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self.in_serve and node.type is not None:
            types = (node.type.elts
                     if isinstance(node.type, ast.Tuple) else [node.type])
            for t in types:
                name = _attr_name(t)
                if name is not None and name not in EXCEPT_ALLOW:
                    self._emit(
                        "FL010", node,
                        f"except {name} is outside the serve typed-error "
                        f"vocabulary",
                    )
        self.generic_visit(node)


def lint_source(src: str, path: str) -> list[Finding]:
    """Concurrency-lint one file; ``path`` decides scoping.  Returns []
    for paths outside the fleet prefixes."""
    if not is_fleet_path(path):
        return []
    tree = ast.parse(src, filename=path)
    linter = _FileLint(path.replace(os.sep, "/"), src.splitlines())
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.col))


def fleet_files(repo_root: str) -> list[str]:
    """All repo-relative python files under the fleet prefixes."""
    out = []
    for pref in FLEET_PREFIXES:
        full = os.path.join(repo_root, pref)
        if not os.path.isdir(full):
            continue
        for dirpath, _dirnames, filenames in os.walk(full):
            for name in sorted(filenames):
                if name.endswith(".py"):
                    rel = os.path.relpath(
                        os.path.join(dirpath, name), repo_root
                    )
                    out.append(rel.replace(os.sep, "/"))
    return out


# -- contract checks (repo-level) ---------------------------------------------


def _read_sources(
    repo_root: str,
    rel_paths: Iterable[str],
    overlay: Optional[dict] = None,
) -> dict[str, str]:
    srcs: dict[str, str] = {}
    for rel in rel_paths:
        if overlay and rel in overlay:
            srcs[rel] = overlay[rel]
            continue
        full = os.path.join(repo_root, rel)
        if os.path.exists(full):
            with open(full) as f:
                srcs[rel] = f.read()
    if overlay:
        for rel, src in overlay.items():
            srcs.setdefault(rel, src)
    return srcs


def _mk(rule: str, path: str, line: int, snippet: str,
        message: str) -> Finding:
    return Finding(rule=rule, path=path, line=line, col=0,
                   snippet=snippet, message=message)


def _line_at(src: str, line: int) -> str:
    lines = src.splitlines()
    if 0 < line <= len(lines):
        return lines[line - 1].strip()
    return ""


def _events_kinds(events_src: str) -> set[str]:
    """Keys of the EVENTS dict literal in obs/events.py."""
    kinds: set[str] = set()
    tree = ast.parse(events_src)
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tgt = (node.targets[0] if isinstance(node, ast.Assign)
                   else node.target)
            if (isinstance(tgt, ast.Name) and tgt.id == "EVENTS"
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                        k.value, str
                    ):
                        kinds.add(k.value)
    return kinds


def _serve_error_vocab(engine_src: str) -> set[str]:
    """Names of ServeError subclasses defined in serve/engine.py."""
    out: set[str] = set()
    for node in ast.walk(ast.parse(engine_src)):
        if isinstance(node, ast.ClassDef):
            for base in node.bases:
                if _attr_name(base) == "ServeError":
                    out.add(node.name)
    return out


def _dict_literal_keys(src: str, var_name: str) -> tuple[set[str], int]:
    """(string keys, line) of a module-level dict literal assignment."""
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Name) and tgt.id == var_name
                        and isinstance(node.value, ast.Dict)):
                    keys = {
                        k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    }
                    return keys, node.lineno
    return set(), 1


def _config_fields(config_src: str) -> dict[str, set[str]]:
    """section -> annotated field names, from config.py dataclasses."""
    by_class: dict[str, set[str]] = {}
    for node in ast.walk(ast.parse(config_src)):
        if isinstance(node, ast.ClassDef):
            fields = {
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
            by_class[node.name] = fields
    return {
        section: by_class.get(cls, set())
        for section, cls in _CFG_CLASS_BY_SECTION.items()
    }


def contract_findings(
    repo_root: str, overlay: Optional[dict] = None
) -> list[Finding]:
    """FL010/FL011/FL012 over the whole plane.  ``overlay`` maps
    repo-relative paths to source text that replaces (or extends) what is
    on disk — used by tests to seed violations without touching files."""
    findings: list[Finding] = []
    scan_paths = fleet_files(repo_root)
    for pref in CONTRACT_EXTRA_PREFIXES:
        full = os.path.join(repo_root, pref)
        if os.path.isdir(full):
            for dirpath, _d, names in os.walk(full):
                for name in sorted(names):
                    if name.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, name), repo_root
                        )
                        scan_paths.append(rel.replace(os.sep, "/"))
    srcs = _read_sources(repo_root, scan_paths, overlay)

    aux = _read_sources(repo_root, (
        "mx_rcnn_tpu/obs/events.py",
        "mx_rcnn_tpu/serve/engine.py",
        "mx_rcnn_tpu/serve/rpc.py",
        "mx_rcnn_tpu/config.py",
        "tools/obs_report.py",
    ), overlay)
    docs = _read_sources(repo_root, (
        "docs/observability.md", "docs/static_analysis.md",
        "docs/serving.md", "docs/data_plane.md", "docs/fabric.md",
        "README.md",
    ), overlay)
    registry_docs = docs.get("docs/observability.md", "")
    all_docs = "\n".join(docs.values())

    # FL010 — status-map totality, both directions.
    vocab = _serve_error_vocab(aux.get("mx_rcnn_tpu/serve/engine.py", ""))
    rpc_src = aux.get("mx_rcnn_tpu/serve/rpc.py", "")
    for var in ("_ERROR_STATUS", "_ERROR_TYPES"):
        keys, line = _dict_literal_keys(rpc_src, var)
        if not keys:
            continue
        missing = vocab - keys
        extra = keys - vocab
        if missing:
            findings.append(_mk(
                "FL010", "mx_rcnn_tpu/serve/rpc.py", line,
                _line_at(rpc_src, line),
                f"{var} is missing typed error(s) {sorted(missing)} — "
                f"they would degrade to generic 500s on the wire",
            ))
        if extra:
            findings.append(_mk(
                "FL010", "mx_rcnn_tpu/serve/rpc.py", line,
                _line_at(rpc_src, line),
                f"{var} maps unknown error name(s) {sorted(extra)} not "
                f"defined in serve/engine.py",
            ))

    # FL011 — journal kinds + metric registry.
    kinds = _events_kinds(aux.get("mx_rcnn_tpu/obs/events.py", ""))
    produced_metrics: dict[str, tuple[str, int]] = {}
    for rel, src in sorted(srcs.items()):
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _attr_name(node.func)
            if name == "emit" and len(node.args) >= 2:
                kind_arg = node.args[1]
                if (isinstance(kind_arg, ast.Constant)
                        and isinstance(kind_arg.value, str)
                        and kind_arg.value not in kinds):
                    findings.append(_mk(
                        "FL011", rel, node.lineno,
                        _line_at(src, node.lineno),
                        f"journal kind {kind_arg.value!r} has no "
                        f"template in obs/events.py EVENTS",
                    ))
            elif name in _METRIC_FACTORIES and node.args:
                name_arg = node.args[0]
                if (isinstance(name_arg, ast.Constant)
                        and isinstance(name_arg.value, str)
                        and _METRIC_NAME_RE.match(name_arg.value)):
                    produced_metrics.setdefault(
                        name_arg.value, (rel, node.lineno)
                    )
    for metric, (rel, line) in sorted(produced_metrics.items()):
        if metric not in registry_docs:
            findings.append(_mk(
                "FL011", rel, line, _line_at(srcs.get(rel, ""), line),
                f"metric {metric!r} is not listed in the "
                f"docs/observability.md inventory",
            ))
    # Consumed direction: what obs_report reads must be produced.  A
    # literal counts as a consumed metric name when it matches the
    # naming convention with at least two underscores (separates real
    # series like serve_cache_size from dict keys like obs_dir) and is
    # not a journal kind.
    report_src = aux.get("tools/obs_report.py", "")
    if report_src:
        for node in ast.walk(ast.parse(report_src)):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _METRIC_NAME_RE.match(node.value)
                    and node.value.count("_") >= 2
                    and node.value not in kinds
                    and node.value not in produced_metrics):
                findings.append(_mk(
                    "FL011", "tools/obs_report.py", node.lineno,
                    _line_at(report_src, node.lineno),
                    f"obs_report consumes metric {node.value!r} that "
                    f"nothing produces",
                ))

    # FL012 — cfg knob reads vs config.py fields vs docs.
    fields = _config_fields(aux.get("mx_rcnn_tpu/config.py", ""))
    seen_knobs: set[tuple[str, str]] = set()
    for rel, src in sorted(srcs.items()):
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Attribute)
                    and node.value.attr in _CFG_SECTIONS):
                continue
            root = node.value.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if not (isinstance(root, ast.Name)
                    and ("cfg" in root.id.lower()
                         or root.id.lower() == "config")):
                continue
            section, knob = node.value.attr, node.attr
            if fields.get(section) is not None and fields[section] and \
                    knob not in fields[section]:
                findings.append(_mk(
                    "FL012", rel, node.lineno,
                    _line_at(src, node.lineno),
                    f"cfg.{section}.{knob} is not a field of "
                    f"{_CFG_CLASS_BY_SECTION[section]} in config.py",
                ))
                continue
            if (section, knob) in seen_knobs:
                continue
            seen_knobs.add((section, knob))
            if f"{section}.{knob}" not in all_docs:
                findings.append(_mk(
                    "FL012", rel, node.lineno,
                    _line_at(src, node.lineno),
                    f"cfg.{section}.{knob} is read here but documented "
                    f"in no docs table",
                ))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col))


def lint_paths(
    repo_root: str,
    paths: Optional[Iterable[str]] = None,
    contracts: bool = True,
    overlay: Optional[dict] = None,
) -> list[Finding]:
    """Concurrency-lint the given repo-relative paths (default: every
    fleet file) plus, by default, the repo-level contract checks."""
    findings: list[Finding] = []
    rels = list(paths) if paths is not None else fleet_files(repo_root)
    srcs = _read_sources(repo_root, rels, overlay)
    for rel in rels:
        if rel in srcs:
            findings.extend(lint_source(srcs[rel], rel))
    if contracts:
        findings.extend(contract_findings(repo_root, overlay))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col))
