"""Layer 2: machine-checked TPU invariants on the real jitted steps.

Where layer 1 pattern-matches source, this layer traces the *actual*
programs — the train step ``make_train_step`` builds (donation, scan,
freeze masks and all), the eval step, and the RPN proposal-dump step —
and asserts properties of the traced/lowered artifact itself.  Everything
runs under ``JAX_PLATFORMS=cpu`` via abstract tracing + one tiny executed
step, so CI needs no accelerator; the invariants are about the program,
not the backend.

Invariants (no suppression mechanism — these must hold outright):

* ``no_x64``        — no float64/int64 aval anywhere in the traced
                      train/eval/proposal jaxprs (an x64 leak doubles
                      HBM/ICI bytes and falls off the TPU fast path).
* ``transfer_guard`` — one steady-state train step and one eval step
                      execute cleanly under
                      ``jax.transfer_guard("disallow")``: zero implicit
                      host transfers in the hot path.
* ``trace_deterministic`` — lowering the train step twice yields
                      byte-identical StableHLO: the trace is a pure
                      function of (code, shapes), not of dict ordering or
                      object identity — the in-process half of the
                      recompilation guard (utils/compile_cache.py's probe
                      is the cross-process half).
* ``donation``      — the lowered train step carries input-output
                      aliasing for the train state's buffers (donation
                      actually applied; params update in place in HBM).
* ``flop_attribution`` — >=99% of the train step's conv/dot FLOPs land in
                      a named component (utils/hlo_profile.py), so the
                      per-component MFU report has no silent "other"
                      bucket.
* ``no_f32_upcast``  — (TPU006) a bf16-mixed variant of the train step
                      (``model.backbone.dtype=bfloat16`` +
                      ``model.precision.policy=mixed``) carries no
                      bf16->f32 ``convert_element_type`` outside the
                      accumulation allowlist (:data:`UPCAST_ALLOWLIST`)
                      or the backward pass.  This is the un-rot guard
                      for the r6 mixed-precision win: one stray
                      ``.astype(jnp.float32)`` on a head output or a
                      score lane silently re-materializes the (B, ~268k)
                      detection middle in f32, and nothing else would
                      notice.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

ATTRIBUTION_MIN_PCT = 99.0


@dataclasses.dataclass
class CheckResult:
    name: str
    ok: bool
    detail: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Programs:
    """The traced surfaces under test, built once and shared by checks."""

    config_name: str
    state: Any
    train_batch: Any
    train_step: Callable
    eval_variables: Any
    eval_batch: Any
    eval_step: Callable
    proposal_step: Callable


def build_programs(config_name: str = "tiny_synthetic") -> Programs:
    """Build the real train/eval/proposal steps for ``config_name``.

    ``tiny_synthetic`` is the hermetic CPU-sized preset the test suite
    already jits; any preset works for trace-only checks but the
    transfer-guard check executes one step.
    """
    import jax

    from bench import _synthetic_batch
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.detection.graph import forward_proposals
    from mx_rcnn_tpu.parallel.step import eval_variables, make_eval_step
    from mx_rcnn_tpu.train.loop import build_all

    cfg = get_config(config_name)
    model, _tx, state, train_step, _gb = build_all(cfg, mesh=None)
    k = max(cfg.train.steps_per_call, 1)
    train_batch = _synthetic_batch(
        cfg, cfg.train.per_device_batch, cfg.data.image_size, k
    )
    pixel_stats = (cfg.data.pixel_mean, cfg.data.pixel_std)
    eval_step = make_eval_step(model, mesh=None, pixel_stats=pixel_stats)
    eval_batch = _synthetic_batch(
        cfg, cfg.train.per_device_batch, cfg.data.image_size, 1
    )
    proposal_step = jax.jit(
        lambda variables, batch: forward_proposals(
            model, variables, batch, pixel_stats=pixel_stats
        )
    )
    return Programs(
        config_name=config_name,
        state=state,
        train_batch=train_batch,
        train_step=train_step,
        eval_variables=eval_variables(state),
        eval_batch=eval_batch,
        eval_step=eval_step,
        proposal_step=proposal_step,
    )


# ---------------------------------------------------------------------------
# Jaxpr walking


def _walk_avals(jaxpr, seen: set) -> None:
    for v in list(jaxpr.invars) + list(jaxpr.constvars) + list(jaxpr.outvars):
        dt = getattr(getattr(v, "aval", None), "dtype", None)
        if dt is not None:
            seen.add(str(dt))
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None:
                seen.add(str(dt))
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr", "body_jaxpr",
                    "cond_jaxpr"):
            sub = eqn.params.get(key)
            if sub is not None:
                _walk_avals(sub.jaxpr if hasattr(sub, "jaxpr") else sub, seen)
        for br in eqn.params.get("branches", ()):
            _walk_avals(br.jaxpr, seen)


def jaxpr_dtypes(fn, *args) -> set[str]:
    """Every aval dtype appearing in ``fn(*args)``'s traced jaxpr."""
    import jax

    closed = jax.make_jaxpr(fn, static_argnums=())(*args)
    seen: set[str] = set()
    _walk_avals(closed.jaxpr, seen)
    for c in closed.consts:
        dt = getattr(c, "dtype", None)
        if dt is not None:
            seen.add(str(dt))
    return seen


# ---------------------------------------------------------------------------
# Checks


def check_no_x64(programs: Programs) -> CheckResult:
    bad: dict[str, set[str]] = {}
    surfaces = {
        "train": (programs.train_step, programs.state, programs.train_batch),
        "eval": (programs.eval_step, programs.eval_variables,
                 programs.eval_batch),
        "proposals": (programs.proposal_step, programs.eval_variables,
                      programs.eval_batch),
    }
    for name, (fn, *args) in surfaces.items():
        wide = {
            d for d in jaxpr_dtypes(fn, *args) if d in ("float64", "int64")
        }
        if wide:
            bad[name] = wide
    if bad:
        return CheckResult(
            "no_x64", False,
            "64-bit avals in traced programs: "
            + "; ".join(f"{k}: {sorted(v)}" for k, v in sorted(bad.items())),
        )
    return CheckResult(
        "no_x64", True,
        "train/eval/proposal jaxprs carry no float64/int64 avals",
    )


def check_transfer_guard(programs: Programs) -> CheckResult:
    """Execute one steady-state train step + eval step + proposal step
    under ``transfer_guard("disallow")``.

    The first call of each compiled program is run OUTSIDE the guard:
    trace-time constant transfers (e.g. the pixel-stat constants) are
    expected and happen once per compile, not per step.  Steady state must
    be implicit-transfer-free.
    """
    import jax
    import jax.numpy as jnp

    # The train step donates its input state, and the eval variables alias
    # the state's param buffers — execute on deep copies so the shared
    # Programs (reused by other checks / test fixtures) stays live.
    state = jax.tree_util.tree_map(jnp.copy, programs.state)
    train_batch = jax.device_put(programs.train_batch)
    eval_vars = jax.tree_util.tree_map(jnp.copy, programs.eval_variables)
    eval_batch = jax.device_put(programs.eval_batch)

    # Warm-up/compile round (guard off).
    state2, _ = programs.train_step(state, train_batch)
    programs.eval_step(eval_vars, eval_batch)
    programs.proposal_step(eval_vars, eval_batch)
    try:
        with jax.transfer_guard("disallow"):
            _state3, metrics = programs.train_step(state2, train_batch)
            dets = programs.eval_step(eval_vars, eval_batch)
            props = programs.proposal_step(eval_vars, eval_batch)
            jax.block_until_ready((metrics, dets.valid, props.valid))
    except Exception as e:  # jaxlib raises backend-specific error types
        return CheckResult(
            "transfer_guard", False,
            f"implicit transfer in steady-state step: {type(e).__name__}: "
            f"{str(e)[:300]}",
        )
    return CheckResult(
        "transfer_guard", True,
        "steady-state train/eval/proposal steps execute under "
        "transfer_guard('disallow')",
    )


def check_trace_deterministic(programs: Programs) -> CheckResult:
    import hashlib

    def lower_hash() -> str:
        txt = programs.train_step.lower(
            programs.state, programs.train_batch
        ).as_text()
        return hashlib.sha256(txt.encode()).hexdigest()

    h1, h2 = lower_hash(), lower_hash()
    if h1 != h2:
        return CheckResult(
            "trace_deterministic", False,
            f"two lowerings of the train step differ ({h1[:12]} vs "
            f"{h2[:12]}) — trace depends on dict order / object identity "
            "and will recompile per process",
        )
    return CheckResult(
        "trace_deterministic", True,
        f"double-lower StableHLO hash stable ({h1[:12]})",
    )


def check_donation(programs: Programs) -> CheckResult:
    import jax

    txt = programs.train_step.lower(
        programs.state, programs.train_batch
    ).as_text()
    aliased = txt.count("tf.aliasing_output")
    param_leaves = len(jax.tree_util.tree_leaves(programs.state.params))
    if aliased < param_leaves:
        return CheckResult(
            "donation", False,
            f"only {aliased} aliased inputs in the lowered train step for "
            f"{param_leaves} param leaves — state donation not applied "
            "(params would double-buffer in HBM)",
        )
    return CheckResult(
        "donation", True,
        f"{aliased} donated input buffers cover the train state "
        f"({param_leaves} param leaves)",
    )


def check_flop_attribution(programs: Programs) -> CheckResult:
    from mx_rcnn_tpu.utils.hlo_profile import attribute_flops

    acc = attribute_flops(
        programs.train_step, programs.state, programs.train_batch
    )
    total = sum(v["flops"] for v in acc.values())
    if not total:
        return CheckResult(
            "flop_attribution", False, "no conv/dot FLOPs found in the "
            "train step trace (attribution walk broken?)",
        )
    other = acc.get("other", {"flops": 0.0})["flops"]
    pct = 100.0 * (total - other) / total
    if pct < ATTRIBUTION_MIN_PCT:
        return CheckResult(
            "flop_attribution", False,
            f"only {pct:.2f}% of train-step MXU FLOPs attributed to a "
            f"named component (need >={ATTRIBUTION_MIN_PCT}%); 'other' "
            f"holds {other / 1e9:.2f} GFLOP — tag the emitting code with "
            "jax.named_scope or extend hlo_profile.COMPONENT_PATTERNS",
        )
    return CheckResult(
        "flop_attribution", True,
        f"{pct:.2f}% of train-step MXU FLOPs attributed "
        f"({len([c for c in acc if c != 'other'])} components)",
    )


# ---------------------------------------------------------------------------
# TPU006: no accidental f32 upcast on the bf16 hot path


# Name-stack tokens under which a bf16->f32 convert is an ACCUMULATION
# entry, not a leak: losses, sampling/assignment (IoU vs f32 gt boxes),
# proposal decode (f32 anchors/coords — see utils/precision.py's box-
# coordinate note), ROI Align (f32 bilinear weights from f32 roi coords
# and an f32 per-bin sample accumulator, downcast ONCE to the feature
# dtype on exit — ops/roi_align.py), the guardian finiteness reduction,
# and the optimizer.  The backward pass is allowed wholesale via its
# "transpose(...)" stack frames: jax.grad of an f32 param used in bf16
# compute accumulates the gradient back to f32 through the transpose of
# the param cast — that convert IS the f32-master-gradient contract, not
# a leak.
UPCAST_ALLOWLIST = (
    "rpn_loss",
    "rcnn_loss",
    "mask_loss",
    "guardian",
    "optimizer",
    "proposals",
    # The fused Pallas middle runs decode/clip/NMS in f32 in-kernel (box
    # coordinates are f32 by contract) — its named scope covers the f32
    # staging of bf16 scores/deltas into the kernel operand block.
    "fused_middle",
    "sample_rois",
    "assign_anchors",
    "roi_align",
)

_BF16_OVERRIDES = (
    "model.backbone.dtype=bfloat16",
    "model.precision.policy=mixed",
)


@functools.lru_cache(maxsize=2)
def _bf16_train_jaxpr(config_name: str):
    """Traced jaxpr of the train step under the bf16 "mixed" policy.

    The shared ``Programs`` trace the preset as-is — for tiny_synthetic
    (f32 backbone) the mixed policy degenerates to all-f32 and an upcast
    scan would be vacuous — so TPU006 traces its own bf16 variant.
    Memoized: the trace is the expensive part and both the CLI and the
    test suite call this."""
    import jax

    from bench import _synthetic_batch
    from mx_rcnn_tpu.config import apply_overrides, get_config
    from mx_rcnn_tpu.train.loop import build_all

    cfg = apply_overrides(get_config(config_name), list(_BF16_OVERRIDES))
    _model, _tx, state, train_step, _gb = build_all(cfg, mesh=None)
    k = max(cfg.train.steps_per_call, 1)
    batch = _synthetic_batch(
        cfg, cfg.train.per_device_batch, cfg.data.image_size, k
    )
    return jax.make_jaxpr(train_step)(state, batch)


def _walk_upcasts(jaxpr, prefix: str, bad: list[str], total: list[int]) -> None:
    for eqn in jaxpr.eqns:
        stack = str(getattr(eqn.source_info, "name_stack", "") or "")
        full = "/".join(s for s in (prefix, stack) if s)
        if eqn.primitive.name == "convert_element_type":
            in_dt = str(getattr(eqn.invars[0].aval, "dtype", ""))
            out_dt = str(getattr(eqn.outvars[0].aval, "dtype", ""))
            if in_dt == "bfloat16" and out_dt == "float32":
                total[0] += 1
                if "transpose(" not in full and not any(
                    tok in full for tok in UPCAST_ALLOWLIST
                ):
                    bad.append(full or "<no name stack>")
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr", "body_jaxpr",
                    "cond_jaxpr"):
            sub = eqn.params.get(key)
            if sub is not None:
                _walk_upcasts(
                    sub.jaxpr if hasattr(sub, "jaxpr") else sub,
                    full, bad, total,
                )
        for br in eqn.params.get("branches", ()):
            _walk_upcasts(br.jaxpr, full, bad, total)


def check_no_f32_upcast(programs: Programs) -> CheckResult:
    """TPU006: every bf16->f32 convert in the bf16-mixed train step sits
    under an allowlisted accumulation scope or the backward pass."""
    closed = _bf16_train_jaxpr(programs.config_name)
    bad: list[str] = []
    total = [0]
    _walk_upcasts(closed.jaxpr, "", bad, total)
    if bad:
        sample = sorted(set(bad))[:8]
        return CheckResult(
            "no_f32_upcast", False,
            f"{len(bad)} bf16->f32 convert(s) outside the accumulation "
            f"allowlist {UPCAST_ALLOWLIST} in the bf16-mixed train step; "
            "name stacks: " + "; ".join(s[:90] for s in sample),
        )
    return CheckResult(
        "no_f32_upcast", True,
        f"all {total[0]} bf16->f32 converts in the bf16-mixed train step "
        "sit under allowlisted accumulation scopes or the backward pass",
    )


ALL_CHECKS = (
    check_no_x64,
    check_trace_deterministic,
    check_donation,
    check_flop_attribution,
    check_no_f32_upcast,
    check_transfer_guard,   # last: the only one that executes the programs
)


def run_jaxpr_checks(
    config_name: str = "tiny_synthetic",
    programs: Optional[Programs] = None,
) -> list[CheckResult]:
    """Run every layer-2 invariant; returns one CheckResult per check.

    A check that *errors* (as opposed to failing its assertion) is
    reported as failed with the exception — a broken checker must never
    read as a passing invariant.
    """
    if programs is None:
        programs = build_programs(config_name)
    results = []
    for check in ALL_CHECKS:
        try:
            results.append(check(programs))
        except Exception as e:
            results.append(
                CheckResult(
                    check.__name__.removeprefix("check_"), False,
                    f"checker raised {type(e).__name__}: {str(e)[:300]}",
                )
            )
    return results
