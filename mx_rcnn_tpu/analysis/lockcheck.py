"""lockcheck: opt-in runtime lock-order sanitizer for the host-side plane.

The static half of fleetlint (:mod:`mx_rcnn_tpu.analysis.fleetlint`)
proves lock-acquisition order from the AST; this module proves it at
runtime, the way TSan's deadlock detector does: every
``threading.Lock``/``threading.RLock`` created by repo code is replaced
by an instrumented wrapper that

* tracks the per-thread *held set* (which locks this thread currently
  holds, in acquisition order),
* maintains a global acquisition-order graph keyed by the lock's
  *creation site* (``file:line``), so the discipline is enforced across
  instances — two replicas' per-replica locks created on the same line
  are one node, exactly like a striped lock class in a real detector,
* raises :class:`LockOrderViolation` the moment an acquisition would
  close a cycle in that graph (deterministically, from a single thread's
  nesting — no real contention or timing needed), and
* raises :class:`HeldLockBlockedCall` when a registered blocking call
  (``urllib.request.urlopen``, or any :func:`blocking_region`) runs
  while a non-exempt instrumented lock is held.

Activation is the env knob ``MX_RCNN_LOCKCHECK=1`` checked by
:func:`maybe_install` (hooked from ``mx_rcnn_tpu/__init__.py`` so the
variable alone activates it in any child process — chaos children,
serve hosts, data workers).  When the variable is unset the module is a
zero-cost no-op: nothing is patched, ``threading.Lock`` is the original
C implementation bit-for-bit (``tests/test_fleetlint.py`` asserts the
identity).

Deliberate coarse sections — the fleet/gateway ``_swap_lock``, which
serializes weight rolls *by design* while doing device or network work —
are marked with :func:`allow_blocking`, which exempts that one lock from
the blocked-call check (never from the order check).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Optional

__all__ = [
    "LockOrderViolation",
    "HeldLockBlockedCall",
    "install",
    "uninstall",
    "maybe_install",
    "enabled",
    "allow_blocking",
    "blocking_region",
    "reset",
    "order_graph",
]

ENV_KNOB = "MX_RCNN_LOCKCHECK"

# Originals, captured at import time — the instrumented wrappers and the
# sanitizer's own internal bookkeeping always use these, never the
# patched names (the sanitizer must not sanitize itself).
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Only locks created from these trees are instrumented; everything else
# (threading.py internals, queue.Queue mutexes, jax/numpy machinery)
# gets the real lock.  Allowlist, not denylist: a lock we fail to
# instrument costs coverage, a lock we wrongly instrument can break the
# stdlib.
_INSTRUMENT_DIRS = (
    os.path.join(_REPO_ROOT, "mx_rcnn_tpu") + os.sep,
    os.path.join(_REPO_ROOT, "tools") + os.sep,
    os.path.join(_REPO_ROOT, "tests") + os.sep,
)


class LockOrderViolation(RuntimeError):
    """Acquiring this lock would close a cycle in the global
    acquisition-order graph — two code paths take the same pair of locks
    in opposite orders, which is a deadlock waiting for the right
    interleaving."""


class HeldLockBlockedCall(RuntimeError):
    """A registered blocking call (network I/O, unbounded wait) ran while
    an instrumented lock was held — every other thread that wants that
    lock now waits on the network."""


class _State:
    """All sanitizer state, guarded by a REAL (uninstrumented) lock."""

    def __init__(self) -> None:
        self.mu = _REAL_LOCK()
        # site -> set of successor sites: edge A->B means "B was acquired
        # while A was held" somewhere, ever, in this process.
        self.edges: dict[str, set[str]] = {}
        # Sites marked blocking-exempt (via allow_blocking).
        self.exempt_sites: set[str] = set()
        self.violations = 0

    def reachable(self, src: str, dst: str) -> bool:
        """DFS: is dst reachable from src over recorded edges?"""
        stack, seen = [src], set()
        while stack:
            node = stack.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            stack.extend(self.edges.get(node, ()))
        return False


_state = _State()
_tls = threading.local()
_installed = False
_real_urlopen: Optional[Any] = None


def _held() -> list:
    """This thread's held instrumented locks, acquisition order."""
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _creation_site() -> Optional[str]:
    """repo-relative file:line of the frame that called Lock()/RLock(),
    or None when the caller is outside the instrumented trees."""
    frame = sys._getframe(2)  # caller of the patched factory
    fname = frame.f_code.co_filename
    try:
        fname = os.path.abspath(fname)
    except (OSError, ValueError):
        return None
    for root in _INSTRUMENT_DIRS:
        if fname.startswith(root):
            rel = os.path.relpath(fname, _REPO_ROOT)
            return f"{rel}:{frame.f_lineno}"
    return None


def _emit(kind: str, payload: dict) -> None:
    """Journal the violation so chaos runs can fail on it — best-effort,
    the raise is the real signal."""
    try:
        from mx_rcnn_tpu import obs

        obs.emit("lockcheck", kind, payload)
    except Exception:
        pass


def _record_acquire(lock: "_CheckedLock") -> None:
    """Called AFTER the underlying acquire succeeded, while the caller is
    about to enter the critical section."""
    if not _installed:
        return  # leftover wrapper after uninstall(): pure pass-through
    held = _held()
    site = lock._lc_site
    if held:
        prev_site = held[-1]._lc_site
        if prev_site != site:
            cycle = False
            # The emit below can itself acquire instrumented locks
            # (obs counters), re-entering this function — so never
            # report or raise while holding the state mutex.
            with _state.mu:
                succ = _state.edges.setdefault(prev_site, set())
                if site not in succ:
                    # New edge prev->site: a cycle exists iff prev is
                    # already reachable FROM site.
                    if _state.reachable(site, prev_site):
                        _state.violations += 1
                        cycle = True
                    else:
                        succ.add(site)
            if cycle:
                held_sites = [h._lc_site for h in held]
                _emit("lock_order_violation", {
                    "edge": [prev_site, site],
                    "held": held_sites,
                    "thread": threading.current_thread().name,
                })
                raise LockOrderViolation(
                    f"lock-order cycle: acquiring {site} while "
                    f"holding {held_sites} inverts an existing "
                    f"{site} -> {prev_site} ordering"
                )
    held.append(lock)


def _record_release(lock: "_CheckedLock") -> None:
    held = _held()
    # Releases can be out of acquisition order (rare but legal); remove
    # the most recent entry for this lock.
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            del held[i]
            return


def check_blocking(what: str) -> None:
    """Raise :class:`HeldLockBlockedCall` if this thread holds any
    non-exempt instrumented lock.  No-op when the sanitizer is off."""
    if not _installed:
        return
    held = [
        h for h in getattr(_tls, "held", ()) or ()
        if not h._lc_allow_blocking
    ]
    if held:
        sites = [h._lc_site for h in held]
        with _state.mu:
            _state.violations += 1
        _emit("held_lock_blocked_call", {
            "call": what,
            "held": sites,
            "thread": threading.current_thread().name,
        })
        raise HeldLockBlockedCall(
            f"blocking call {what!r} while holding lock(s) {sites}"
        )


class blocking_region:
    """Context manager marking a region as a blocking call for the
    sanitizer (e.g. a device sync, a subprocess wait).  Zero-cost when
    lockcheck is not installed."""

    def __init__(self, what: str) -> None:
        self.what = what

    def __enter__(self) -> "blocking_region":
        check_blocking(self.what)
        return self

    def __exit__(self, *exc: Any) -> None:
        return None


class _CheckedLock:
    """Instrumented threading.Lock: same surface, plus order tracking."""

    _lc_reentrant = False

    def __init__(self, site: str) -> None:
        self._lc_inner = _REAL_LOCK()
        self._lc_site = site
        self._lc_allow_blocking = False

    # threading.Condition duck-types on these three when handed a lock.
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lc_inner.acquire(blocking, timeout)
        if ok:
            try:
                _record_acquire(self)
            except LockOrderViolation:
                self._lc_inner.release()
                raise
        return ok

    def release(self) -> None:
        _record_release(self)
        self._lc_inner.release()

    def locked(self) -> bool:
        return self._lc_inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<lockcheck.Lock site={self._lc_site}>"


class _CheckedRLock:
    """Instrumented threading.RLock: reentrant re-acquisition by the
    owning thread adds no graph edge (not an ordering event)."""

    _lc_reentrant = True

    def __init__(self, site: str) -> None:
        self._lc_inner = _REAL_RLOCK()
        self._lc_site = site
        self._lc_allow_blocking = False
        self._lc_owner: Optional[int] = None
        self._lc_depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._lc_owner == me:
            # Pure reentrancy: no new hold, no edge, never a violation.
            ok = self._lc_inner.acquire(blocking, timeout)
            if ok:
                self._lc_depth += 1
            return ok
        ok = self._lc_inner.acquire(blocking, timeout)
        if ok:
            try:
                _record_acquire(self)
            except LockOrderViolation:
                self._lc_inner.release()
                raise
            self._lc_owner = me
            self._lc_depth = 1
        return ok

    def release(self) -> None:
        if self._lc_owner == threading.get_ident() and self._lc_depth > 1:
            self._lc_depth -= 1
            self._lc_inner.release()
            return
        self._lc_owner = None
        self._lc_depth = 0
        _record_release(self)
        self._lc_inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # threading.Condition uses these when present (RLock protocol).
    def _is_owned(self) -> bool:
        return self._lc_owner == threading.get_ident()

    def _release_save(self):
        state = (self._lc_depth, self._lc_owner)
        while self._lc_depth:
            self.release()
        return state

    def _acquire_restore(self, state) -> None:
        depth, _ = state
        for _ in range(depth):
            self.acquire()

    def __repr__(self) -> str:
        return f"<lockcheck.RLock site={self._lc_site}>"


def _lock_factory():
    site = _creation_site()
    if site is None:
        return _REAL_LOCK()
    return _CheckedLock(site)


def _rlock_factory():
    site = _creation_site()
    if site is None:
        return _REAL_RLOCK()
    return _CheckedRLock(site)


def _checked_urlopen(*args: Any, **kwargs: Any):
    url = args[0] if args else kwargs.get("url", "?")
    check_blocking(f"urlopen({getattr(url, 'full_url', url)!r})")
    return _real_urlopen(*args, **kwargs)  # type: ignore[misc]


def enabled() -> bool:
    """True iff the sanitizer is currently installed."""
    return _installed


def install() -> None:
    """Patch ``threading.Lock``/``RLock`` and ``urllib.request.urlopen``.
    Idempotent.  Locks created BEFORE install stay uninstrumented."""
    global _installed, _real_urlopen
    if _installed:
        return
    import urllib.request

    _real_urlopen = urllib.request.urlopen
    threading.Lock = _lock_factory  # type: ignore[assignment]
    threading.RLock = _rlock_factory  # type: ignore[assignment]
    urllib.request.urlopen = _checked_urlopen
    _installed = True


def uninstall() -> None:
    """Restore the real primitives and drop all recorded state."""
    global _installed, _real_urlopen
    if not _installed:
        return
    import urllib.request

    threading.Lock = _REAL_LOCK  # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
    if _real_urlopen is not None:
        urllib.request.urlopen = _real_urlopen
    _real_urlopen = None
    _installed = False
    reset()


def reset() -> None:
    """Forget the recorded order graph (between test cases)."""
    with _state.mu:
        _state.edges.clear()
        _state.exempt_sites.clear()
        _state.violations = 0


def maybe_install() -> bool:
    """Install iff ``MX_RCNN_LOCKCHECK=1`` in the environment.  The
    no-op path is one getenv — safe to call from package import."""
    if os.environ.get(ENV_KNOB) == "1":
        install()
        return True
    return False


def allow_blocking(lock: Any) -> Any:
    """Mark one lock as deliberately held across blocking work (a coarse
    serialization lock, by design).  Exempts it from the blocked-call
    check only — order checking still applies.  No-op on real
    (uninstrumented) locks, so call sites never need to gate on the env
    knob."""
    try:
        lock._lc_allow_blocking = True
        with _state.mu:
            _state.exempt_sites.add(lock._lc_site)
    except AttributeError:
        pass  # real _thread.lock: attributes are read-only, nothing to mark
    return lock


def order_graph() -> dict[str, list[str]]:
    """Snapshot of the recorded acquisition-order edges (for tests and
    reports)."""
    with _state.mu:
        return {k: sorted(v) for k, v in _state.edges.items()}


def violation_count() -> int:
    with _state.mu:
        return _state.violations
