"""Command-line drivers (the reference's L7 layer, SURVEY.md §3.1).

One module per driver, mirroring the reference's entry points:

=======================  ==========================================
reference                here
=======================  ==========================================
``train_end2end.py``     :mod:`mx_rcnn_tpu.cli.train_cli`
``train_alternate.py``   :mod:`mx_rcnn_tpu.cli.alternate_cli`
``test.py``              :mod:`mx_rcnn_tpu.cli.eval_cli`
``demo.py``              :mod:`mx_rcnn_tpu.cli.demo_cli`
``rcnn/tools/reeval.py`` :mod:`mx_rcnn_tpu.cli.reeval_cli`
``rcnn/tools/test_rpn``  ``eval_cli --proposals`` (proposal dump)
=======================  ==========================================

Thin repo-root scripts (``train.py``, ``test.py``, ``demo.py``,
``train_alternate.py``, ``reeval.py``) call these mains, so the user-facing
commands match the reference verbatim.
"""

from mx_rcnn_tpu.cli.common import config_from_args, setup_logging

__all__ = ["config_from_args", "setup_logging"]
