"""4-step alternate training (Ren et al. 2015) driver.

Parity with ``train_alternate.py`` (SURVEY.md §4.2).  The reference runs
four separate processes over four separate symbol graphs
(``rcnn/tools/train_rpn.py`` / ``test_rpn.py`` / ``train_rcnn.py``) and
merges the two resulting param files with ``combine_model``.  Here every
phase reuses the SAME jitted train graph and the SAME loop — phases differ
only in loss weights (rpn vs rcnn) and freeze prefixes, and "combine" is a
no-op because all parameters already live in one pytree:

  1. train RPN          (rcnn loss off;   box head frozen)
  2. dump proposals     (forward_proposals over the train split → pkl)
  3. train Fast R-CNN   (rpn loss off;    rpn head frozen — its frozen
                         weights generate the in-graph proposals, which is
                         exactly "train on phase-1's proposals")
  4. retrain RPN        (rcnn loss off;   shared conv + box head frozen)
  5. dump proposals again
  6. retrain Fast R-CNN (rpn loss off;    shared conv + rpn head frozen)

Proposal dumps are written for artifact parity (the reference's rpn pkl);
training itself consumes proposals in-graph from the frozen RPN, which keeps
every phase a single statically-shaped jitted step.

Two schedules are offered:

- default (in-graph): the rcnn phases keep the frozen RPN in the graph and
  sample from its live proposals.  Deviation from the reference: phases
  continue from the previous phase's weights (an in-graph frozen RPN only
  matches the trunk it was trained on), so ``--pretrained`` seeds phase 1
  only.
- ``--external-proposals``: the reference-faithful Ren et al. schedule.
  Each rcnn phase consumes the PRECOMPUTED pkl dumped by the preceding rpn
  phase (Fast R-CNN mode — the RPN drops out of the graph), which makes
  per-phase re-initialization safe: rcnn1 restarts from the ImageNet seed
  exactly as the reference's ``train_rcnn.py`` does.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import os

from mx_rcnn_tpu.cli.common import add_config_args, config_from_args, setup_logging
from mx_rcnn_tpu.config import Config

log = logging.getLogger("mx_rcnn_tpu.alternate")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    add_config_args(p, default="vgg16_voc07")
    p.add_argument(
        "--phase-steps", type=int, default=None,
        help="steps per phase (default: schedule total_steps per phase)",
    )
    p.add_argument(
        "--no-proposal-dump", action="store_true",
        help="skip the pkl artifact dumps between phases",
    )
    p.add_argument(
        "--pretrained", default=None, metavar="PTH",
        help="torchvision backbone .pth. Default schedule: seeds phase 1 "
        "only (see module docstring); with --external-proposals it also "
        "re-seeds the rcnn1 phase, as the reference does",
    )
    p.add_argument(
        "--strict-resume", action="store_true",
        help="fail (instead of warn) when a phase's config drifts from "
        "the workdir's recorded config.json",
    )
    p.add_argument(
        "--external-proposals", action="store_true",
        help="reference-faithful schedule: rcnn phases train on the pkl "
        "dumped by the preceding rpn phase (Fast R-CNN mode, RPN out of "
        "the graph) instead of in-graph frozen-RPN proposals",
    )
    return p.parse_args(argv)


def _phase_cfg(cfg: Config, name: str, rpn_on: bool, rcnn_on: bool) -> Config:
    model = dataclasses.replace(
        cfg.model,
        rpn=dataclasses.replace(cfg.model.rpn, loss_weight=1.0 if rpn_on else 0.0),
        rcnn=dataclasses.replace(cfg.model.rcnn, loss_weight=1.0 if rcnn_on else 0.0),
    )
    return dataclasses.replace(cfg, name=f"{cfg.name}_{name}", model=model)


def alternate_train(
    cfg: Config,
    mesh=None,
    phase_steps=None,
    workdir=None,
    dump_proposals_pkl: bool = True,
    num_phases: int = 4,
    pretrained=None,
    external_proposals: bool = False,
    strict_resume: bool = False,
):
    """Run the 6-step schedule; returns the final combined TrainState.

    ``num_phases`` < 4 truncates the schedule (tests exercise the phase
    transition without paying for four full compiles).
    ``external_proposals``: reference-faithful mode — rcnn phases train on
    the preceding rpn phase's pkl dump (see module docstring).
    """
    import jax

    from mx_rcnn_tpu.cli.eval_cli import dump_proposals
    from mx_rcnn_tpu.train.loop import train

    workdir = workdir or cfg.workdir
    # Backbone trunk freeze prefixes come from the shared-conv set; the
    # conv1/res2-style early freeze stays active in every phase via
    # build_all's default behavior.
    shared_conv = ("backbone", "fpn")

    phases = [
        ("rpn1", dict(rpn=True, rcnn=False), ("box_head",), None),
        ("rcnn1", dict(rpn=False, rcnn=True), ("rpn",), "proposals_rpn1.pkl"),
        ("rpn2", dict(rpn=True, rcnn=False), shared_conv + ("box_head",), None),
        ("rcnn2", dict(rpn=False, rcnn=True), shared_conv + ("rpn",), "proposals_rpn2.pkl"),
    ]
    if external_proposals and not dump_proposals_pkl:
        raise ValueError("--external-proposals requires the proposal dumps")
    state = None
    for name, losses, freeze, dump_before in phases[:num_phases]:
        pcfg = _phase_cfg(cfg, name, losses["rpn"], losses["rcnn"])
        proposals_path = None
        if dump_before and dump_proposals_pkl and state is not None:
            path = os.path.join(workdir, cfg.name, dump_before)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            dump_proposals(cfg, path, state=state)
            if external_proposals:
                proposals_path = path
        # Reference-faithful mode: rcnn1 restarts from the ImageNet seed
        # and trains on the dumped pkl (Fast R-CNN, RPN out of the graph) —
        # safe because the proposals are precomputed, exactly like
        # rcnn/tools/train_rcnn.py.  rcnn2 keeps rpn2's weights (its trunk
        # is frozen-shared by then, per the 4-step schedule).
        reseed = external_proposals and name == "rcnn1"
        if reseed and not pretrained:
            # Hermetic/synthetic runs may legitimately lack a .pth, but the
            # reference schedule presumes the ImageNet seed — be loud.
            log.warning(
                "--external-proposals without --pretrained: rcnn1 restarts "
                "from RANDOM init (the reference re-seeds it from ImageNet)"
            )
        log.info(
            "=== alternate phase %s (freeze: %s%s) ===",
            name, ",".join(freeze),
            ", external proposals" if proposals_path else "",
        )
        state = train(
            pcfg,
            mesh=mesh,
            total_steps=phase_steps,
            workdir=workdir,
            state=(
                jax.device_get(state)
                if state is not None and not reseed
                else None
            ),
            extra_freeze=tuple(freeze),
            # ImageNet seed applies to fresh states only: phase 1, and the
            # re-seeded rcnn1 of the reference-faithful schedule.
            pretrained=pretrained if (state is None or reseed) else None,
            proposals_path=proposals_path,
            strict_resume=strict_resume,
        )
    # combine_model parity: nothing to merge — one pytree holds RPN + RCNN.
    # Save the combined result under the BASE config name so eval/demo find
    # it at the same path an end-to-end run would use (the reference's
    # combine_model writes the merged `final` param file).
    from mx_rcnn_tpu.train.checkpoint import save_checkpoint

    state = jax.device_get(state)
    save_checkpoint(f"{workdir}/{cfg.name}/ckpt", state, wait=True)
    return state


def main(argv=None):
    args = parse_args(argv)
    setup_logging(args.verbose)
    cfg = config_from_args(args)

    import jax

    from mx_rcnn_tpu.parallel import make_mesh

    mesh = (
        make_mesh(model_parallel=cfg.train.spatial_partition)
        if jax.device_count() > 1
        else None
    )
    state = alternate_train(
        cfg,
        mesh=mesh,
        phase_steps=args.phase_steps,
        workdir=cfg.workdir,
        dump_proposals_pkl=not args.no_proposal_dump,
        pretrained=args.pretrained,
        external_proposals=args.external_proposals,
        strict_resume=args.strict_resume,
    )
    from mx_rcnn_tpu.cli.eval_cli import run_eval

    return run_eval(cfg, state=state)


def cli(argv=None) -> int:
    """Console-script entry point ([project.scripts]).  ``main`` returns
    its result dict for programmatic callers; returning that from a
    console script would make ``sys.exit`` treat the truthy dict as a
    FAILURE exit status, so discard it and return 0 explicitly.

    A preemption mid-phase exits with RESUMABLE_EXIT_CODE after the
    emergency checkpoint lands (see train_cli.cli)."""
    from mx_rcnn_tpu.train.preemption import RESUMABLE_EXIT_CODE, Preempted

    try:
        main(argv)
    except Preempted as p:
        log.warning(
            "preempted at step %d (checkpoint: %s); exiting %d",
            p.step, p.ckpt_dir, RESUMABLE_EXIT_CODE,
        )
        return RESUMABLE_EXIT_CODE
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(cli())
