"""Shared argparse plumbing for all drivers.

Replaces the reference's per-driver ``parse_args`` + ``generate_config``
pattern (``train_end2end.py::parse_args`` mutating ``rcnn/config.py``'s
global): every driver here takes ``--config <preset>`` plus dotted
``--set section.field=value`` overrides and gets back one frozen Config.
"""

from __future__ import annotations

import argparse
import logging
import sys

from mx_rcnn_tpu.config import Config, apply_overrides, available_configs, get_config


def setup_logging(verbose: bool = False) -> None:
    logging.basicConfig(
        level=logging.DEBUG if verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        stream=sys.stderr,
        force=True,
    )


def add_config_args(p: argparse.ArgumentParser, default: str = "r50_fpn_coco") -> None:
    p.add_argument(
        "--config",
        default=default,
        choices=available_configs(),
        help="experiment preset (reference: --network + --dataset pair)",
    )
    p.add_argument(
        "--set",
        dest="overrides",
        action="append",
        default=[],
        metavar="KEY.PATH=VALUE",
        help="dotted config override, e.g. --set data.root=/data/coco "
        "--set train.schedule.total_steps=1000 (repeatable)",
    )
    p.add_argument("--workdir", default=None, help="run directory (ckpts, dumps)")
    p.add_argument("-v", "--verbose", action="store_true")


def config_from_args(args: argparse.Namespace) -> Config:
    cfg = get_config(args.config)
    if args.overrides:
        cfg = apply_overrides(cfg, args.overrides)
    if getattr(args, "workdir", None):
        import dataclasses

        cfg = dataclasses.replace(cfg, workdir=args.workdir)
    return cfg


def default_use_07_metric(cfg: Config) -> bool:
    """The VOC metric auto-default shared by eval and reeval: the 11-point
    AP for VOC2007 test splits (the reference evaluates VOC07 with
    use_07_metric=True), the area metric everywhere else."""
    return cfg.data.dataset == "voc" and cfg.data.val_split.startswith("2007")


def submission_imageset(cfg: Config) -> str:
    """The imageset token for comp4 det filenames: VOC splits are
    "<year>_<imageset>" so the filename takes the imageset part
    ("comp4_det_test_<cls>.txt"); other datasets use the split verbatim."""
    split = cfg.data.val_split
    return split.split("_")[-1] if cfg.data.dataset == "voc" else split
