"""Single-image demo: checkpoint → detections → visualization.

Parity with ``demo.py`` (SURVEY.md §4.4): load an image, run the jitted
inference graph, print detections, draw labeled boxes to an output file
(``rcnn/core/tester.py::vis_all_detection`` equivalent, headless).
"""

from __future__ import annotations

import argparse
import logging

import numpy as np

from mx_rcnn_tpu.cli.common import add_config_args, config_from_args, setup_logging
from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.evalutil.vis import draw_detections

log = logging.getLogger("mx_rcnn_tpu.demo")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    add_config_args(p)
    p.add_argument("image", help="input image path")
    p.add_argument("--ckpt", default=None, help="checkpoint dir (default: workdir)")
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--out", default=None, help="output visualization path (png)")
    p.add_argument("--threshold", type=float, default=0.5, help="vis score cutoff")
    p.add_argument(
        "--random-params", action="store_true",
        help="skip checkpoint load (smoke-test the graph with random weights)",
    )
    return p.parse_args(argv)


def detect_image(cfg: Config, variables, image: np.ndarray,
                 mask_threshold: float = 0.0):
    """Run inference on one RGB uint8/float image; detections in original
    image coordinates (the reference's ``im_detect`` + unscale).

    Masks are pasted to image resolution only for detections scoring at
    least ``mask_threshold`` (others get None — pasting is the expensive
    part and the demo discards sub-threshold entries anyway)."""
    import jax

    from mx_rcnn_tpu.data.transforms import letterbox, normalize_image
    from mx_rcnn_tpu.detection import Batch, TwoStageDetector, forward_inference

    model = TwoStageDetector(cfg=cfg.model)
    h, w = image.shape[:2]
    canvas, _, scale, (nh, nw) = letterbox(
        image.astype(np.float32),
        np.zeros((0, 4), np.float32),
        cfg.data.image_size,
        cfg.data.short_side,
        cfg.data.max_side,
    )
    canvas = normalize_image(canvas, cfg.data.pixel_mean, cfg.data.pixel_std)
    g = cfg.data.max_gt_boxes
    batch = Batch(
        images=canvas[None],
        image_hw=np.array([[nh, nw]], np.float32),
        gt_boxes=np.zeros((1, g, 4), np.float32),
        gt_classes=np.zeros((1, g), np.int32),
        gt_valid=np.zeros((1, g), bool),
    )
    infer = jax.jit(lambda v, b: forward_inference(model, v, b))
    dets = jax.device_get(infer(variables, batch))
    from mx_rcnn_tpu.evalutil.postprocess import unletterbox_detections

    d = unletterbox_detections(
        dets.boxes[0], dets.scores[0], dets.classes[0], dets.valid[0],
        scale, h, w,
        masks=dets.masks[0] if dets.masks is not None else None,
        mask_threshold=mask_threshold,
    )
    return d["boxes"], d["scores"], d["classes"], d.get("masks")


def load_demo_image(path: str) -> np.ndarray:
    """Read one RGB image or raise SystemExit with a one-line diagnosis.

    A missing path, a directory, or bytes PIL cannot decode are operator
    errors, not bugs — the CLI reports them cleanly (nonzero exit, no
    traceback) instead of dumping PIL internals."""
    import os

    from PIL import Image, UnidentifiedImageError

    if not os.path.exists(path):
        raise SystemExit(f"error: input image not found: {path}")
    try:
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))
    except UnidentifiedImageError:
        raise SystemExit(
            f"error: {path} is not a decodable image (corrupt or "
            "unsupported format)"
        ) from None
    except OSError as e:
        raise SystemExit(f"error: could not read image {path}: {e}") from None


def main(argv=None):
    args = parse_args(argv)
    setup_logging(args.verbose)
    cfg = config_from_args(args)

    image = load_demo_image(args.image)

    import jax

    from mx_rcnn_tpu.parallel.step import eval_variables

    if args.random_params:
        from mx_rcnn_tpu.detection import TwoStageDetector, init_detector

        variables = init_detector(
            TwoStageDetector(cfg=cfg.model), jax.random.PRNGKey(0), cfg.data.image_size
        )
    else:
        from mx_rcnn_tpu.cli.eval_cli import _restored_state

        variables = jax.device_put(
            eval_variables(_restored_state(cfg, args.ckpt, args.step))
        )

    # The demo serves through the same engine production traffic uses
    # (docs/serving.md): warmup-compiled programs, watchdog, typed errors.
    from mx_rcnn_tpu.serve import ServeError, build_engine

    try:
        with build_engine(cfg, variables) as engine:
            result = engine.infer(image)
    except ServeError as e:
        raise SystemExit(f"error: inference failed: {e}") from None
    log.info(
        "served at level %r in %.3fs", result["level"], result["latency_s"]
    )
    boxes, scores, classes = (
        result["boxes"], result["scores"], result["classes"],
    )
    masks = result.get("masks")
    class_names = None
    if cfg.data.dataset == "voc":
        from mx_rcnn_tpu.data.datasets import VOC_CLASSES

        class_names = ("__background__",) + VOC_CLASSES
    for box, score, cls in zip(boxes, scores, classes):
        if score >= args.threshold:
            name = class_names[int(cls)] if class_names else str(int(cls))
            log.info("%s %.3f [%.1f %.1f %.1f %.1f]", name, score, *box)
    out = args.out or (args.image.rsplit(".", 1)[0] + "_det.png")
    n = draw_detections(
        image, boxes, scores, classes, class_names, out, args.threshold,
        masks=masks,
    )
    log.info("drew %d detections -> %s", n, out)
    return boxes, scores, classes, masks


def cli(argv=None) -> int:
    """Console-script entry point ([project.scripts]).  ``main`` returns
    its result dict for programmatic callers; returning that from a
    console script would make ``sys.exit`` treat the truthy dict as a
    FAILURE exit status, so discard it and return 0 explicitly."""
    main(argv)
    return 0


if __name__ == "__main__":
    main()
