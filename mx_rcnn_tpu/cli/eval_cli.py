"""Evaluation driver.

Parity with ``test.py`` → ``rcnn/core/tester.py::pred_eval`` (SURVEY.md
§4.3): restore checkpoint, run the jitted inference graph over the val
split, score with the dataset evaluator (COCO mAP@[.5:.95] or VOC AP).
``--proposals`` runs the RPN-only path and dumps proposals instead
(``rcnn/tools/test_rpn.py`` parity).
"""

from __future__ import annotations

import argparse
import logging
import pickle
from typing import Optional

from mx_rcnn_tpu.cli.common import (
    add_config_args,
    config_from_args,
    setup_logging,
    submission_imageset,
)
from mx_rcnn_tpu.config import Config

log = logging.getLogger("mx_rcnn_tpu.eval")

# Mirrored from train.preemption.RESUMABLE_EXIT_CODE without importing it
# at module scope (parse_args must not drag in jax).
_RESUMABLE_CODE = 75


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    add_config_args(p)
    p.add_argument("--ckpt", default=None, help="checkpoint dir (default: workdir)")
    p.add_argument("--step", type=int, default=None, help="checkpoint step")
    p.add_argument(
        "--dump", default=None, help="write raw detections here (reeval input)"
    )
    p.add_argument(
        "--dump-coco", default=None, metavar="RESULTS.JSON",
        help="also write a COCO results json in ORIGINAL (sparse 91-space) "
        "category ids — the format the COCO server and stock pycocotools "
        "loadRes score (reference coco.py evaluate_detections parity)",
    )
    p.add_argument(
        "--dump-voc", default=None, metavar="DIR",
        help="also write VOC comp4 per-class det files into DIR "
        "(reference pascal_voc.py det-file-writer parity)",
    )
    p.add_argument(
        "--proposals",
        default=None,
        metavar="OUT.PKL",
        help="dump RPN proposals per image instead of evaluating (test_rpn parity)",
    )
    p.add_argument(
        "--from-proposals",
        default=None,
        metavar="IN.PKL",
        help="score this external proposal pkl instead of running the RPN "
        "(Fast R-CNN testing; reference: test_rcnn --has_rpn false)",
    )
    p.add_argument(
        "--proposals-split",
        choices=("train", "val"),
        default=None,
        help="which split --proposals dumps (default val; train: the Fast "
        "R-CNN training input; reference rpn.generate over TRAIN.dataset)",
    )
    p.add_argument(
        "--use-07-metric",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="VOC 11-point AP metric (default: on for VOC2007 test splits, "
        "matching the reference's use_07_metric choice; off otherwise)",
    )
    p.add_argument(
        "--vis", type=int, default=0, metavar="N",
        help="draw the first N evaluated images with detections into "
        "<workdir>/<config>/vis (reference pred_eval vis=True parity)",
    )
    p.add_argument(
        "--resumable", action="store_true",
        help="preemption-safe evaluation: per-shard detection checkpoints "
        "under --shard-dir, SIGTERM flushes the in-flight shard and exits "
        f"{_RESUMABLE_CODE} for the supervisor to re-run with --resume",
    )
    p.add_argument(
        "--shard-dir", default=None, metavar="DIR",
        help="where shard files + manifest live (implies --resumable; "
        "default <workdir>/<config>/eval_shards)",
    )
    p.add_argument(
        "--shard-size", type=int, default=8, metavar="N",
        help="eval batches per shard checkpoint (default 8)",
    )
    p.add_argument(
        "--shard-retries", type=int, default=1, metavar="N",
        help="retries per failed shard before giving up (default 1)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="skip shards already on disk (schedule fingerprint checked)",
    )
    p.add_argument(
        "--limit", type=int, default=None, metavar="N",
        help="evaluate only the first N images (smoke/chaos runs)",
    )
    return p.parse_args(argv)


def _eval_loader(
    cfg: Config,
    batch_size: int = 1,
    with_masks: bool = False,
    proposals_path: Optional[str] = None,
    limit: Optional[int] = None,
):
    from mx_rcnn_tpu.data import DetectionLoader, build_dataset, load_proposals

    import jax

    proposals = load_proposals(proposals_path) if proposals_path else None
    dataset = build_dataset(cfg.data, train=False)
    roidb = dataset.roidb()
    if limit is not None:
        # Smoke/chaos runs: evaluate a prefix of the split.  The metric
        # roidb is sliced identically so absent images don't score as
        # misses.
        roidb = roidb[:limit]
    loader = DetectionLoader(
        roidb, cfg.data, batch_size=batch_size, train=False,
        with_masks=with_masks,
        proposals=proposals,
        num_proposals=cfg.model.rpn.test_post_nms_top_n,
        # Eval keeps the full roidb everywhere; rank/world shard each
        # global batch for lockstep multi-host iteration (loader docs).
        rank=jax.process_index(),
        world=jax.process_count(),
        # Same rot-tolerance contract as training: unreadable images are
        # quarantined + blank-substituted, never a crashed eval.
        num_classes=cfg.model.num_classes,
        quarantine_path=(
            f"{cfg.workdir}/{cfg.name}/quarantine.jsonl"
            if cfg.workdir else None
        ),
    )
    return dataset, roidb, loader


def _restored_state(cfg: Config, ckpt_dir: Optional[str], step: Optional[int]):
    import jax

    from mx_rcnn_tpu.train.checkpoint import restore_checkpoint
    from mx_rcnn_tpu.train.loop import build_all

    # restore_checkpoint only needs the target's tree structure and
    # shapes/dtypes, so build it under eval_shape: no parameter is ever
    # materialized on device just to be thrown away (the eager init cost
    # minutes of cold-start through the TPU tunnel).
    def make_state():
        _, _, state, _, _ = build_all(cfg, mesh=None)
        return state

    abstract = jax.eval_shape(make_state)
    ckpt = ckpt_dir or f"{cfg.workdir}/{cfg.name}/ckpt"
    return restore_checkpoint(ckpt, abstract, step=step)


def run_eval(
    cfg: Config,
    state=None,
    ckpt_dir: Optional[str] = None,
    step: Optional[int] = None,
    dump_path: Optional[str] = None,
    use_07_metric: Optional[bool] = None,
    vis_count: int = 0,
    proposals_path: Optional[str] = None,
    coco_results_path: Optional[str] = None,
    voc_dets_dir: Optional[str] = None,
    shard_dir: Optional[str] = None,
    shard_size: int = 8,
    resume: bool = False,
    shard_retries: int = 1,
    limit: Optional[int] = None,
) -> dict:
    """Evaluate a state (or a restored checkpoint) on the config's val split.

    ``use_07_metric`` None = auto: the 11-point metric for VOC2007 test
    splits (the reference evaluates VOC07 with use_07_metric=True), the
    area metric otherwise.

    ``proposals_path``: score an external proposal pkl instead of running
    the RPN (reference ``test_rcnn --has_rpn false`` Fast R-CNN testing).

    ``shard_dir`` switches to preemption-safe sharded evaluation
    (docs/serving.md): per-shard detection checkpoints, ``resume`` skipping
    completed shards, SIGTERM/SIGINT draining the in-flight shard and
    raising ``Preempted`` (the CLI maps it to exit 75).  Single-process
    only."""
    import jax

    from mx_rcnn_tpu.cli.common import default_use_07_metric

    if use_07_metric is None:
        use_07_metric = default_use_07_metric(cfg)

    from mx_rcnn_tpu.detection import TwoStageDetector
    from mx_rcnn_tpu.evalutil import pred_eval
    from mx_rcnn_tpu.parallel import make_mesh, replicated
    from mx_rcnn_tpu.parallel.step import eval_variables, make_eval_step

    if state is None:
        state = _restored_state(cfg, ckpt_dir, step)
    state = jax.device_get(state)
    # ALL visible chips evaluate in data parallel, test.per_device_batch
    # images per chip per step (the reference's test path is strictly
    # single-device, one image at a time).  Multi-host runs shard each
    # GLOBAL batch by process rank in the loader (lockstep schedule from
    # the full roidb), assemble global arrays via shard_batch, and gather
    # the tiny Detections to every host so each computes the full metric
    # (artifacts are written by process 0 only — see pred_eval).
    mesh = make_mesh() if jax.device_count() > 1 else None
    multiproc = jax.process_count() > 1
    model = TwoStageDetector(cfg=cfg.model)
    eval_step = make_eval_step(
        model, mesh=mesh, gather_outputs=multiproc,
        pixel_stats=(cfg.data.pixel_mean, cfg.data.pixel_std),
    )
    # Pin the inference params on device ONCE.  Feeding the numpy pytree
    # into the jitted step would re-upload every parameter on every call —
    # ~100 MB/step through the TPU tunnel, turning an ~90 ms eval step into
    # ~10 s (measured; the r1 CLI had exactly this bug).
    variables = eval_variables(state)
    variables = (
        jax.device_put(variables, replicated(mesh))
        if mesh is not None
        else jax.device_put(variables)
    )
    per_chip = max(cfg.model.test.per_device_batch, 1)
    dataset, roidb, loader = _eval_loader(
        cfg,
        batch_size=(mesh.size if mesh is not None else 1) * per_chip,
        proposals_path=proposals_path,
        limit=limit,
    )
    style = "voc" if cfg.data.dataset == "voc" else "coco"
    class_names = None
    if cfg.data.dataset == "voc":
        from mx_rcnn_tpu.data.datasets import VOC_CLASSES

        class_names = ("__background__",) + VOC_CLASSES
    elif voc_dets_dir:
        # comp4 files are per-class-NAME; non-VOC datasets use their own.
        class_names = tuple(getattr(dataset, "classes", ()))
    if voc_dets_dir and len(class_names or ()) <= 1:
        # write_submission_artifacts raises the same complaint, but only
        # AFTER pred_eval's full inference pass (and only on the artifact-
        # writing process) — minutes of eval discarded by an error that is
        # knowable right here.  Fail up-front, on every host.
        raise ValueError(
            "--dump-voc needs foreground class names; the dataset "
            f"exposes {tuple(class_names or ())!r} — comp4 det files "
            "are per-class-NAME"
        )
    # COCO submissions must carry the ORIGINAL sparse category ids; only
    # the real CocoDataset has the mapping (synthetic/custom ids are
    # already dense → identity).
    label_to_cat = (
        getattr(dataset, "label_to_cat", None) if coco_results_path else None
    )
    import contextlib

    from mx_rcnn_tpu.train.preemption import PreemptionGuard

    # The guard turns SIGTERM/SIGINT into a shard-boundary drain; without
    # sharding there is no safe boundary to drain to, so don't install it.
    guard_cm = PreemptionGuard() if shard_dir else contextlib.nullcontext()
    with guard_cm as guard:
        metrics = pred_eval(
            eval_step,
            variables,
            loader,
            roidb,
            cfg.model.num_classes,
            style=style,
            class_names=class_names,
            use_07_metric=use_07_metric,
            dump_path=dump_path,
            vis_dir=f"{cfg.workdir}/{cfg.name}/vis" if vis_count > 0 else None,
            vis_count=vis_count,
            mesh=mesh,
            coco_results_path=coco_results_path,
            label_to_cat=label_to_cat,
            voc_dets_dir=voc_dets_dir,
            voc_imageset=submission_imageset(cfg),
            shard_dir=shard_dir,
            shard_size=shard_size,
            resume=resume,
            shard_retries=shard_retries,
            guard=guard,
        )
    for k, v in sorted(metrics.items()):
        log.info("%s = %.4f", k, v)
    return metrics


def dump_proposals(
    cfg: Config,
    out_path: str,
    state=None,
    ckpt_dir: Optional[str] = None,
    step: Optional[int] = None,
    train_split: bool = True,
    use_train_counts: Optional[bool] = None,
) -> dict:
    """Run the RPN over a split and dump per-image proposal boxes+scores.

    The alternate-training bridge: phase N's RPN writes the proposal roidb
    consumed by phase N+1's Fast R-CNN training (SURVEY.md §4.2 steps 2/5).

    ``use_train_counts`` (default: follows ``train_split``): generate the
    TRAIN-config proposal counts (pre/post-NMS top-n, e.g. 2000) instead of
    the test counts (e.g. 300) — proposals destined for Fast R-CNN
    *training* must match the reference's TRAIN.RPN_POST_NMS_TOP_N pool,
    not the test pool.

    Runs batched over every visible chip (the same loader/mesh machinery
    as ``run_eval``, ``test.per_device_batch`` images per chip per step):
    a COCO train-split dump is minutes, not the hours the old
    one-image-one-chip loop took (VERDICT r2 #7).
    """
    import dataclasses

    import jax
    import numpy as np

    from mx_rcnn_tpu.data import DetectionLoader, build_dataset
    from mx_rcnn_tpu.detection import TwoStageDetector, forward_proposals
    from mx_rcnn_tpu.evalutil.pred_eval import device_eval_batches
    from mx_rcnn_tpu.parallel import make_mesh, replicated
    from mx_rcnn_tpu.parallel.step import eval_variables, make_sharded_infer

    if state is None:
        state = _restored_state(cfg, ckpt_dir, step)
    state = jax.device_get(state)
    if use_train_counts is None:
        use_train_counts = train_split
    if use_train_counts:
        # forward_proposals runs the test-config proposal path; give it the
        # train counts so the dumped pool matches what training samples.
        cfg = dataclasses.replace(
            cfg,
            model=dataclasses.replace(
                cfg.model,
                rpn=dataclasses.replace(
                    cfg.model.rpn,
                    test_pre_nms_top_n=cfg.model.rpn.train_pre_nms_top_n,
                    test_post_nms_top_n=cfg.model.rpn.train_post_nms_top_n,
                ),
            ),
        )
    model = TwoStageDetector(cfg=cfg.model)
    mesh = make_mesh() if jax.device_count() > 1 else None
    multiproc = jax.process_count() > 1
    # Device-resident params: see run_eval — numpy params re-upload per call.
    variables = eval_variables(state)
    variables = (
        jax.device_put(variables, replicated(mesh))
        if mesh is not None
        else jax.device_put(variables)
    )
    stats = (cfg.data.pixel_mean, cfg.data.pixel_std)
    prop_step = make_sharded_infer(
        lambda v, b: forward_proposals(model, v, b, pixel_stats=stats),
        mesh, gather_outputs=multiproc,
    )

    per_chip = max(cfg.model.test.per_device_batch, 1)
    data_cfg = cfg.data
    split = data_cfg.train_split if train_split else data_cfg.val_split
    roidb = build_dataset(dataclasses.replace(data_cfg, val_split=split), train=False).roidb()
    loader = DetectionLoader(
        roidb, data_cfg,
        batch_size=(mesh.size if mesh is not None else 1) * per_chip,
        train=False,
        rank=jax.process_index(),
        world=jax.process_count(),
    )
    out: dict[str, dict] = {}
    for batch, recs in device_eval_batches(loader, mesh):
        props = jax.device_get(prop_step(variables, batch))
        for i, rec in enumerate(recs):
            scale = loader.record_scale(rec)
            valid = np.asarray(props.valid[i])
            out[rec.image_id] = {
                "boxes": np.asarray(props.rois[i])[valid] / scale,
                "scores": np.asarray(props.scores[i])[valid],
            }
    from mx_rcnn_tpu.parallel.distributed import is_primary

    if is_primary():
        with open(out_path, "wb") as f:
            pickle.dump(out, f)
        log.info("wrote %d images' proposals to %s", len(out), out_path)
    return out


def main(argv=None) -> dict:
    args = parse_args(argv)
    setup_logging(args.verbose)
    cfg = config_from_args(args)
    if args.proposals and args.from_proposals:
        raise SystemExit(
            "--proposals (dump) and --from-proposals (score) are exclusive"
        )
    if args.proposals_split and not args.proposals:
        raise SystemExit("--proposals-split only applies with --proposals")
    if args.proposals:
        if args.resumable or args.shard_dir or args.resume:
            raise SystemExit("--proposals does not support sharded/resumable mode")
        return dump_proposals(
            cfg, args.proposals, ckpt_dir=args.ckpt, step=args.step,
            train_split=args.proposals_split == "train",
        )
    if args.resume and not (args.resumable or args.shard_dir):
        raise SystemExit("--resume requires --resumable (or --shard-dir)")
    shard_dir = args.shard_dir
    if args.resumable and not shard_dir:
        shard_dir = f"{cfg.workdir}/{cfg.name}/eval_shards"
    return run_eval(
        cfg,
        ckpt_dir=args.ckpt,
        step=args.step,
        dump_path=args.dump,
        use_07_metric=args.use_07_metric,
        vis_count=args.vis,
        proposals_path=args.from_proposals,
        coco_results_path=args.dump_coco,
        voc_dets_dir=args.dump_voc,
        shard_dir=shard_dir,
        shard_size=args.shard_size,
        resume=args.resume,
        shard_retries=args.shard_retries,
        limit=args.limit,
    )


def cli(argv=None) -> int:
    """Console-script entry point ([project.scripts]).  ``main`` returns
    its result dict for programmatic callers; returning that from a
    console script would make ``sys.exit`` treat the truthy dict as a
    FAILURE exit status, so discard it and return 0 explicitly.

    A preemption during --resumable eval exits with the distinct
    RESUMABLE_EXIT_CODE after the in-flight shard lands, so supervisors
    can tell "requeue with --resume" from a real failure."""
    from mx_rcnn_tpu.train.preemption import RESUMABLE_EXIT_CODE, Preempted

    try:
        main(argv)
    except Preempted as p:
        log.warning(
            "eval preempted after shard %d (shards in %s); exiting %d — "
            "requeue with --resume", p.step, p.ckpt_dir, RESUMABLE_EXIT_CODE,
        )
        return RESUMABLE_EXIT_CODE
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(cli())
