"""Re-score cached detections without a model.

Parity with ``rcnn/tools/reeval.py``: load a detection dump written by
``eval_cli --dump``, re-run the dataset evaluator.  Useful for trying eval
variants (07-metric vs area AP) without re-running inference.
"""

from __future__ import annotations

import argparse
import logging

from mx_rcnn_tpu.cli.common import add_config_args, config_from_args, setup_logging

log = logging.getLogger("mx_rcnn_tpu.reeval")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    add_config_args(p)
    p.add_argument("detections", help="dump file from eval_cli --dump")
    p.add_argument(
        "--use-07-metric",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="VOC 11-point AP (default: auto — on for VOC2007 test splits)",
    )
    p.add_argument(
        "--dump-coco", default=None, metavar="RESULTS.JSON",
        help="export the cached detections as a COCO results json in "
        "ORIGINAL sparse category ids (submission format) — no model run",
    )
    p.add_argument(
        "--dump-voc", default=None, metavar="DIR",
        help="export the cached detections as VOC comp4 det files",
    )
    return p.parse_args(argv)


def main(argv=None) -> dict:
    args = parse_args(argv)
    setup_logging(args.verbose)
    cfg = config_from_args(args)

    from mx_rcnn_tpu.data import build_dataset
    from mx_rcnn_tpu.evalutil import evaluate_detections, load_detections

    per_image = load_detections(args.detections)
    dataset = build_dataset(cfg.data, train=False)
    roidb = dataset.roidb()
    if args.dump_coco or args.dump_voc:
        from mx_rcnn_tpu.cli.common import submission_imageset
        from mx_rcnn_tpu.evalutil.submission import write_submission_artifacts

        write_submission_artifacts(
            per_image,
            coco_results_path=args.dump_coco,
            label_to_cat=getattr(dataset, "label_to_cat", None),
            voc_dets_dir=args.dump_voc,
            class_names=tuple(getattr(dataset, "classes", ())),
            voc_imageset=submission_imageset(cfg),
        )
    from mx_rcnn_tpu.cli.common import default_use_07_metric

    use_07 = args.use_07_metric
    if use_07 is None:
        use_07 = default_use_07_metric(cfg)
    style = "voc" if cfg.data.dataset == "voc" else "coco"
    class_names = None
    if cfg.data.dataset == "voc":
        from mx_rcnn_tpu.data.datasets import VOC_CLASSES

        class_names = ("__background__",) + VOC_CLASSES
    metrics = evaluate_detections(
        per_image, roidb, cfg.model.num_classes, style, class_names,
        use_07_metric=use_07,
    )
    for k, v in sorted(metrics.items()):
        log.info("%s = %.4f", k, v)
    return metrics


def cli(argv=None) -> int:
    """Console-script entry point ([project.scripts]).  ``main`` returns
    its result dict for programmatic callers; returning that from a
    console script would make ``sys.exit`` treat the truthy dict as a
    FAILURE exit status, so discard it and return 0 explicitly."""
    main(argv)
    return 0


if __name__ == "__main__":
    main()
