"""End-to-end training driver.

Parity with ``train_end2end.py`` (SURVEY.md §3.1/§4.1): config + overrides →
mesh → train loop with metrics/checkpoints, optional resume, optional final
evaluation pass.  The kvstore/ctx-list plumbing of the reference is replaced
by the device mesh (all visible chips by default).
"""

from __future__ import annotations

import argparse
import logging

from mx_rcnn_tpu.cli.common import add_config_args, config_from_args, setup_logging

log = logging.getLogger("mx_rcnn_tpu.train")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    add_config_args(p)
    p.add_argument("--resume", action="store_true", help="resume from workdir ckpt")
    p.add_argument(
        "--strict-resume", action="store_true",
        help="fail (instead of warn) when the resumed config drifts from "
        "the workdir's recorded config.json",
    )
    p.add_argument(
        "--steps", type=int, default=None, help="override schedule total_steps"
    )
    p.add_argument(
        "--no-eval", action="store_true", help="skip the final evaluation pass"
    )
    p.add_argument(
        "--profile", default=None, metavar="DIR",
        help="write a jax.profiler trace of steps 10-15 to DIR",
    )
    p.add_argument(
        "--pretrained", default=None, metavar="PTH",
        help="torchvision-style ResNet .pth to seed the backbone "
        "(reference: --pretrained imagenet params)",
    )
    p.add_argument(
        "--proposals", default=None, metavar="PKL",
        help="train the box head on this external proposal pkl (from "
        "test.py --proposals) instead of in-graph RPN proposals — Fast "
        "R-CNN mode (reference: train_rcnn.py/ROIIter).  Pair with --set "
        "model.rpn.loss_weight=0 to drop the RPN from the graph entirely",
    )
    return p.parse_args(argv)


def main(argv=None) -> dict:
    args = parse_args(argv)
    setup_logging(args.verbose)
    cfg = config_from_args(args)

    import jax

    from mx_rcnn_tpu.parallel import initialize, make_mesh
    from mx_rcnn_tpu.train.loop import train

    initialize()  # multi-host runtime (no-op single-process)
    mesh = (
        make_mesh(model_parallel=cfg.train.spatial_partition)
        if jax.device_count() > 1
        else None
    )
    n_dev = mesh.size if mesh is not None else 1
    log.info(
        "config=%s devices=%d backend=%s", cfg.name, n_dev, jax.default_backend()
    )
    state = train(
        cfg,
        mesh=mesh,
        total_steps=args.steps,
        workdir=cfg.workdir,
        resume=args.resume,
        profile_dir=args.profile,
        pretrained=args.pretrained,
        proposals_path=args.proposals,
        strict_resume=args.strict_resume,
    )
    metrics: dict = {"final_step": int(jax.device_get(state.step))}
    if not args.no_eval:
        from mx_rcnn_tpu.cli.eval_cli import run_eval

        metrics.update(run_eval(cfg, state=state))
    return metrics


def cli(argv=None) -> int:
    """Console-script entry point ([project.scripts]).  ``main`` returns
    its result dict for programmatic callers; returning that from a
    console script would make ``sys.exit`` treat the truthy dict as a
    FAILURE exit status, so discard it and return 0 explicitly.

    A preemption (SIGTERM/SIGINT mid-run) exits with the distinct
    RESUMABLE_EXIT_CODE after the emergency checkpoint lands, so
    schedulers can tell "requeue with --resume" from a real failure."""
    from mx_rcnn_tpu.train.preemption import RESUMABLE_EXIT_CODE, Preempted

    try:
        main(argv)
    except Preempted as p:
        log.warning(
            "preempted at step %d (checkpoint: %s); exiting %d — requeue "
            "with --resume", p.step, p.ckpt_dir, RESUMABLE_EXIT_CODE,
        )
        return RESUMABLE_EXIT_CODE
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(cli())
