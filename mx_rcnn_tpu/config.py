"""Immutable experiment configuration.

Replaces the reference's mutable global config singleton
(``rcnn/config.py``: one module-level easydict mutated by every CLI via
``generate_config(network, dataset)``) with frozen dataclasses passed
explicitly.  Nothing here is global; a config is constructed once (from a
preset plus CLI overrides) and threaded through the program.

The numeric defaults preserve the reference's semantics where parity
matters (anchor geometry, NMS thresholds, fg/bg sampling quotas, bbox
normalization stds) and upgrade to the FPN-era Detectron recipe where the
BASELINE north star requires it (>=37 COCO mAP needs FPN + ROIAlign + the
modern 1x schedule, not the 2017 C4 recipe).

Presets mirror BASELINE.json's five configs — see :func:`get_config`.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True)
class AnchorConfig:
    """Anchor geometry (reference: config.ANCHOR_SCALES / ANCHOR_RATIOS)."""

    # Scales are in units of the stride at each level.  The reference's C4
    # single-level setup uses base_size 16 with scales (8, 16, 32); FPN uses
    # one scale (8) per level with strides (4..64) covering the same range.
    scales: tuple[float, ...] = (8.0, 16.0, 32.0)
    ratios: tuple[float, ...] = (0.5, 1.0, 2.0)

    def num_anchors(self) -> int:
        return len(self.scales) * len(self.ratios)


@dataclass(frozen=True)
class BackboneConfig:
    name: str = "resnet50"  # resnet50 | resnet101 | vgg16
    # Stages to freeze, counted like the reference's fixed_param_prefix
    # (conv1 + res2 frozen for ResNet; conv1_/conv2_ for VGG).
    freeze_stages: int = 2
    # Frozen BatchNorm everywhere (reference: use_global_stats=True).
    norm: str = "frozen_bn"  # frozen_bn | bn | gn
    # Compute dtype for conv/matmul (params stay float32).
    dtype: str = "bfloat16"
    # Rematerialize backbone activations on the backward pass
    # (jax.checkpoint per residual block / conv group): trades ~1/3 more
    # backbone FLOPs for O(depth) less HBM — enables bigger canvases or
    # per-chip batches than stored activations would allow.
    remat: bool = False
    # Execute the 7x7/2 RGB stem in space-to-depth form (exact rewrite,
    # 4x denser MXU contraction — models/resnet.py::StemConv).  ResNet only.
    stem_s2d: bool = False
    # Execute the stem's 3x3/2 max-pool as strided slices + elementwise max
    # instead of a reduce_window over the worst-laid-out tensor in the net
    # (models/resnet.py::_maxpool3x3s2_slices; exact, -inf padding both
    # forms; falls back on odd stem-output dims).  ResNet only.
    stem_pool_fold: bool = False
    # Zero-pad C2's 64-wide contractions to the MXU's 128 lanes (exact —
    # padded channels are zero; params keep canonical shapes).  ResNet
    # only; self-limiting to C2, the one sub-128-channel stage.
    c2_pad: bool = False
    # Fold frozen-BN affines into the conv weights: conv(x, W*s) + t, the
    # same math with the multiply riding the existing f32->bf16 weight
    # cast instead of a per-activation multiply-add (measured +1.4 ms
    # across an R101 trunk — FrozenBN does NOT all fuse into the convs).
    # ResNet + frozen_bn only; no-op otherwise.  Param tree unchanged.
    fold_frozen_bn: bool = False


@dataclass(frozen=True)
class FPNConfig:
    enabled: bool = True
    channels: int = 256
    min_level: int = 2
    max_level: int = 6  # P6 by max-pool of P5 (RPN only)


@dataclass(frozen=True)
class RPNConfig:
    """RPN head + proposal generation (reference: config.TRAIN/TEST RPN_*)."""

    channels: int = 256  # hidden conv (VGG uses 512 in the reference)
    # Anchor labeling (rcnn/io/rpn.py::assign_anchor semantics).
    batch_size: int = 256
    fg_fraction: float = 0.5
    positive_iou: float = 0.7
    negative_iou: float = 0.3
    allowed_border: float = 0.0
    # Proposal generation (rcnn/symbol/proposal.py semantics).
    train_pre_nms_top_n: int = 2000
    train_post_nms_top_n: int = 1000
    test_pre_nms_top_n: int = 1000
    test_post_nms_top_n: int = 1000
    nms_threshold: float = 0.7
    min_size: float = 0.0
    loss_weight: float = 1.0
    # Pre-NMS top-k selection over the anchor scores.  "hier" — the
    # default — is the blocked two-stage exact reduction
    # (ops/topk.py::hierarchical_top_k): per-tile partial top-k then a
    # merge of survivors, BIT-IDENTICAL to lax.top_k including the
    # snapped-score index-stable tie-breaks (proof in the module
    # docstring, asserted in tests/test_ops.py), but the sort shrinks
    # from the full 268k-anchor operand to ``topk_block``-wide tiles.
    # "exact" = the global lax.top_k (one full sort network — the
    # oracle).  "approx" = lax.approx_max_k (the TPU PartialReduce op)
    # at ``topk_recall`` expected recall of the true top-k: the
    # k'th-ranked RPN scores are deep in the sigmoid tail, so the
    # ~(1-recall) swapped candidates are low-objectness boxes
    # NMS/top-post would drop anyway — a first-class A/B'able training
    # option (measured +1.1 img/s over "exact" in r4b), opt-in because
    # it is the one impl that changes proposals.  Off TPU,
    # approx_max_k lowers to a full sort (exact), so CPU tests and
    # goldens see identical numbers for ALL three impls.
    topk_impl: str = "hier"
    topk_recall: float = 0.95
    # Tile width for the "hier" reduction (also routes the anchor
    # subsampling top_k's in ops/sampling.py::_select_random).  Any
    # value gives the same bits; power-of-two multiples of the 128-lane
    # VPU width keep the batched per-tile sort layout-friendly.  <= 0
    # falls back to the global sort.
    topk_block: int = 32768
    # Anchor-axis tile for assign_anchors' IoU/argmax reductions
    # (ops/sampling.py::_per_anchor_stats_blocked): the (A, G) IoU
    # matrix (34 MB at the recipe canvas) never materializes — each
    # tile's IoU is computed and reduced in one VMEM-resident fusion.
    # Bit-identical to the dense pass (f32 max is exactly associative);
    # <= 0 restores the single-pass dense form.
    assign_block: int = 16384
    # RPN loss reduction domain.  "dense" (default) reduces BCE/smooth-l1
    # over the full (B, A) anchor axis with masks — the historical form,
    # bit-identical to pre-fast-path builds.  "compact" gathers the
    # Q = fg_quota + batch_size sampled rows (AnchorTargets.sel_*) and
    # reduces only those: the same loss up to summation order (the
    # masked-out terms are exact zeros), so metrics match to f32
    # round-off, not bitwise — opt-in for A/B.
    loss_impl: str = "dense"
    # Sweep bound for the proposal NMS fixed point (ops/nms.py).  0 =
    # iterate to convergence (exact greedy NMS, the default).  > 0 caps
    # the batched per-level lane at that many sweeps: any cap >= N is
    # still exact and score-sorted RPN boxes converge in a handful of
    # sweeps, so a cap like 16 bounds the worst lane's data-dependent
    # latency while matching exact NMS on everything but adversarial
    # box soups.
    nms_sweep_cap: int = 0
    # Run the weight-shared head over all FPN levels as ONE packed
    # computation (models/heads.py::RPNHead.packed) instead of five
    # sequential small-spatial convs (the P2 apply alone measured
    # 6.6 ms/step).  Exact — identical per-level outputs; the packing is
    # sliced away before anything downstream.  No-op for single-level
    # (C4) models; disabled automatically under spatial partitioning
    # (parallel/step.py::mesh_safe_model_cfg — the packed canvas would
    # concatenate across height shards).
    packed_head: bool = True
    # Proposal-NMS backend.  "xla" (default) runs the batched while-loop
    # fixed point (ops/nms.py::nms_mask — the oracle).  "pallas" routes
    # the keep-mask through ops/pallas/nms.py::nms_mask_pallas, the
    # VMEM-resident greedy sweep — bit-identical keep bits (parity suite
    # tests/test_pallas.py / test_fused_middle.py); falls back to "xla"
    # off-TPU unless MX_RCNN_PALLAS_INTERPRET=1 forces interpret mode.
    nms_impl: str = "xla"
    # Fuse the proposal middle — decode -> clip -> snap -> min-size ->
    # greedy NMS — into ONE Pallas kernel per proposal call
    # (ops/pallas/middle.py): the per-level score/box tiles stay in VMEM
    # across the whole chain instead of round-tripping HBM between
    # ops/proposals.py, ops/topk.py and ops/nms.py as a string of small
    # XLA programs.  Bit-identical to the dense path (the kernel
    # replicates decode_boxes/clip_boxes/snap/iou_matrix to the bit and
    # greedy NMS in top-k positional order provably equals the
    # argsort-order oracle — docs/performance.md).  Default-off; same
    # fallback discipline as nms_impl.
    fused_middle: bool = False


@dataclass(frozen=True)
class RCNNConfig:
    """Second-stage sampling/head (reference: ProposalTarget + heads)."""

    roi_batch_size: int = 512  # reference BATCH_ROIS (128 C4 / 512 FPN)
    fg_fraction: float = 0.25
    fg_iou: float = 0.5
    bg_iou_hi: float = 0.5
    bg_iou_lo: float = 0.0
    # 1/std of the reference's TRAIN.BBOX_STDS (0.1, 0.1, 0.2, 0.2).
    bbox_weights: tuple[float, float, float, float] = (10.0, 10.0, 5.0, 5.0)
    pooled_size: int = 7
    sampling_ratio: int = 2
    hidden_dim: int = 1024  # 2-fc box head width (VGG fc6/fc7 use 4096)
    # Class-agnostic box regression (False = per-class, reference default).
    class_agnostic: bool = False
    loss_weight: float = 1.0
    # ROIAlign backend: "pallas" (default — one batch-folded windowed-DMA
    # kernel launch per step; measured 83.1 -> 77.6 ms/step on the full
    # R50-FPN train step once the whole batch rides one grid) or "xla"
    # (flattened-pyramid gather — the oracle, the backward, and the
    # automatic fallback off-TPU or on unsupported layouts).
    roi_align_impl: str = "pallas"
    # Backward for the pallas forward: "pallas" (default — the windowed-DMA
    # scatter-accumulate kernel ops/pallas/roi_align.py::_bwd_kernel, the
    # r3 default previously selected only via env) or "xla" (autodiff
    # through the flattened gather — the A/B and debugging escape hatch).
    # The MX_RCNN_POOL_BWD env var still overrides at trace time.
    roi_align_bwd_impl: str = "pallas"
    # ROI-axis tile for sample_rois' IoU/argmax reductions
    # (ops/sampling.py::_per_row_stats_blocked, the same machinery as
    # rpn.assign_block): the (R+G, G) IoU matrix never materializes —
    # each ROI tile's IoU is computed and reduced in one VMEM-resident
    # fusion.  Bit-identical to the dense pass (elementwise IoU is
    # tiling-independent and the per-row max/argmax never cross tiles);
    # <= 0 (default) restores the single-pass dense form.
    roi_block: int = 0


@dataclass(frozen=True)
class MaskConfig:
    enabled: bool = False
    pooled_size: int = 14
    channels: int = 256
    num_convs: int = 4
    resolution: int = 28
    loss_weight: float = 1.0


@dataclass(frozen=True)
class TestConfig:
    """Inference-time postprocessing (reference: config.TEST + pred_eval)."""

    # Eval images per chip per call (reference: strictly 1).  >1 amortizes
    # per-dispatch overhead and fills the MXU better at eval time — batch 8
    # measured ~3.5x batch-1 throughput (PARITY.md) — so 8 is the default
    # and only deliberately tiny presets (tiny_synthetic's hermetic CPU
    # programs) drop back to 1.
    per_device_batch: int = 8
    score_threshold: float = 0.05
    nms_threshold: float = 0.5  # per-class NMS (reference uses 0.3 for VOC)
    max_detections: int = 100
    # Postprocess NMS structure.  "per_class" replays the reference's
    # per-class loop exactly (one NMS fixed point per foreground class,
    # vmapped — C-1 passes of per_class_k boxes per image).  "fused" —
    # the default — takes the global top-``fused_top_k`` (roi, class)
    # candidates by score and runs ONE class-offset NMS over them
    # (ops/nms.py::batched_nms); per-class results are identical whenever
    # no class overflows the per-class cap and the union of
    # above-threshold candidates fits ``fused_top_k`` (tested), which
    # real images satisfy — only the pre-NMS candidate cap moves from
    # per-class (2*max_detections each) to global.  When the global cap
    # DOES bind, the dropped candidates are the score-ranked-worst
    # pre-NMS; under heavy suppression one of them could have survived
    # its class NMS into the final set, so binding-cap outputs can
    # differ (use "per_class" for exact reference replay there).  TPU
    # rationale: 80 vmapped while-loops run
    # every class lane until the slowest converges; one fused pass
    # converges once.  Measured (BASELINE.md): R50-FPN eval batch-8
    # 82.1 -> 94.9 img/s/chip.
    nms_mode: str = "fused"
    fused_top_k: int = 1000
    # Sweep bound for the postprocess NMS fixed points (same semantics
    # as RPNConfig.nms_sweep_cap; 0 = exact convergence, the default).
    nms_sweep_cap: int = 0


@dataclass(frozen=True)
class PrecisionConfig:
    """End-to-end mixed-precision policy (utils/precision.py resolves it).

    ``policy`` names the whole-graph dtype contract:

    - ``"mixed"`` (default): heads compute AND emit in the backbone's
      compute dtype — with a bfloat16 backbone nothing f32-sized crosses
      the model/detection boundary (the (B, ~268k) RPN logit and
      (B, ~268k, 4) delta materializations were the last ones).  Losses,
      metrics, the guardian reduction, and the optimizer still accumulate
      in float32 (the explicit upcast allowlist tpulint TPU006 enforces),
      and box *coordinates* stay float32 throughout — only scores/logits
      ride bf16.  With a float32 backbone (tiny_synthetic) this resolves
      to all-f32 and is bit-identical to historical graphs.
    - ``"widen"``: heads compute in the backbone dtype but cast outputs
      to float32 — exactly the pre-r6 graphs, kept as the A/B and
      bisection escape hatch.
    - ``"float32"``: force everything float32 regardless of the backbone
      dtype knob.

    ``accum`` is the accumulation dtype for losses/metrics/reductions;
    anything other than float32 voids the TPU006 contract and the NaN
    guardian's assumptions — it exists for experiments, not recipes.
    """

    policy: str = "mixed"  # mixed | widen | float32
    accum: str = "float32"


@dataclass(frozen=True)
class ModelConfig:
    num_classes: int = 81  # includes background at index 0 (COCO: 80 + 1)
    backbone: BackboneConfig = field(default_factory=BackboneConfig)
    fpn: FPNConfig = field(default_factory=FPNConfig)
    anchors: AnchorConfig = field(default_factory=AnchorConfig)
    rpn: RPNConfig = field(default_factory=RPNConfig)
    rcnn: RCNNConfig = field(default_factory=RCNNConfig)
    mask: MaskConfig = field(default_factory=MaskConfig)
    test: TestConfig = field(default_factory=TestConfig)
    precision: PrecisionConfig = field(default_factory=PrecisionConfig)


@dataclass(frozen=True)
class DataConfig:
    dataset: str = "coco"  # coco | voc | synthetic
    root: str = "data"
    train_split: str = "train2017"
    val_split: str = "val2017"
    # Static LANDSCAPE canvas (H, W), H <= W; portrait images letterbox
    # into its transpose (data/transforms.py::oriented_canvas — batches
    # are single-orientation under aspect_grouping, so each orientation is
    # one compiled program).  The reference resizes short side to
    # SCALES[0] capped at MAX_SIZE and re-binds executors per shape; two
    # static canvases are the TPU-native equivalent that preserves the
    # full short/max rule: 800x1344 fits every 800-short/1333-max resize
    # (1344 = 42*32 for FPN stride divisibility) at ~1.03x the pixels of
    # the old square 1024^2 canvas, which silently clamped most images
    # below the Detectron recipe resolution.
    image_size: tuple[int, int] = (800, 1344)
    short_side: int = 800
    max_side: int = 1333
    max_gt_boxes: int = 100
    flip: bool = True
    # Reference pixel means (BGR 123.68/116.78/103.94 order-swapped); we use
    # RGB ImageNet mean/std.
    pixel_mean: tuple[float, float, float] = (123.675, 116.28, 103.53)
    pixel_std: tuple[float, float, float] = (58.395, 57.12, 57.375)
    aspect_grouping: bool = True
    # Host-side normalization (the reference's rcnn/io/image.py::transform
    # order).  Default OFF: the loader ships uint8 letterboxed pixels (1/4
    # the host->device bytes and device_prefetch HBM of float32) and the
    # (x - mean) / std runs in-graph, fused into the first conv's input
    # (detection/graph.py::prep_images).  True restores float32 host
    # normalization (the fused C++ path); in-memory float synthetic images
    # always normalize on host regardless.
    normalize_on_host: bool = False
    # VOC only: promote "difficult" objects to real gt instead of keeping
    # them as flagged ignore regions (reference:
    # ``rcnn/dataset/pascal_voc.py`` config.USE_DIFFICULT knob).
    use_diff: bool = False
    # Parsed-roidb pickle cache directory (reference: imdb.gt_roidb caches
    # under data/cache/<name>_gt_roidb.pkl).  "" disables; entries are
    # invalidated by the annotation source's mtime.  Also roots the
    # checksummed tensor cache (data/cache.py): decoded+letterboxed pixels
    # memoized under <cache_dir>/tensors/<transform-fingerprint>/ with
    # per-blob CRCs — corrupt blobs are quarantined and rebuilt, never
    # served.
    cache_dir: str = ""
    # Process input service (data/service.py): decode/augment workers as
    # independent failure domains with deterministic reassignment — the
    # yielded schedule is bit-identical for any worker count and after any
    # worker death.  0 (default) keeps the in-process thread pool.
    num_workers: int = 0
    # Per-worker-slot respawn budget after a death/wedge; exhausting every
    # slot degrades to in-process synchronous assembly (run completes).
    worker_respawns: int = 2
    # Zero-copy shm transport for the input service (data/shm_ring.py):
    # each worker ships assembled batches through a CRC-stamped
    # shared-memory ring instead of pickling tensors through the result
    # queue; bounded slots are the backpressure.  Only active when
    # num_workers > 0.  shm_transport=False restores the pickle path.
    shm_transport: bool = True
    # Ring slots per worker.  Each slot holds one batch; more slots buy
    # pipelining headroom at slots*slot_bytes shm per worker.
    shm_slots: int = 4
    # Slot size override in MiB.  0 (default) auto-sizes from the batch
    # shape (canvas, max_gt_boxes, masks/proposals if on) with headroom;
    # a batch that still overflows its slot falls back to pickle for that
    # batch only.
    shm_slot_mb: int = 0


@dataclass(frozen=True)
class ScheduleConfig:
    """MultiFactor-style LR schedule (reference: lr_scheduler in drivers).

    ``decay_steps``/``total_steps`` are denominated at a global batch of
    ``reference_batch`` images; ``build_all`` rescales them by
    ``reference_batch / global_batch`` alongside the linear lr scaling, so
    a preset trains the same number of EPOCHS at any pod size (the
    reference's drivers likewise scale lr by ``len(ctx) * kv.num_workers``
    while keeping epoch-denominated schedules).  ``reference_batch = 0``
    disables both rescalings' step side (steps are absolute; lr still
    scales by global_batch/16) — used by the tiny test preset whose golden
    numbers pin absolute step counts.  ``warmup_steps`` stays absolute
    (warmup guards the first optimizer steps, however large the batch).
    """

    base_lr: float = 0.02  # for global batch `reference_batch`; scaled linearly
    warmup_steps: int = 500
    warmup_factor: float = 1.0 / 3.0
    # Steps at which lr is multiplied by `factor` (in units of train steps
    # at reference_batch).
    decay_steps: tuple[int, ...] = (60000, 80000)
    factor: float = 0.1
    total_steps: int = 90000
    reference_batch: int = 16


@dataclass(frozen=True)
class TrainConfig:
    per_device_batch: int = 1  # reference: 1 image per GPU
    # Chips per image sharing the spatial (height) axis — the mesh's model
    # axis.  1 = pure data parallelism (reference parity).  >1 partitions
    # the backbone convs spatially (XLA halo exchange) for resolutions one
    # chip can't hold; devices must be divisible by it.
    spatial_partition: int = 1
    # Train steps executed per host->device call: >1 moves the step loop
    # onto the device as a lax.scan over a (K, B, ...) stacked batch,
    # amortizing per-call dispatch latency (large under remote/tunneled
    # runtimes — measured ~25 ms/call through the axon tunnel) K-fold.
    # Logging/checkpoint cadence quantizes to K.
    steps_per_call: int = 1
    # Microbatches accumulated per optimizer step (parallel/plan.py): >1
    # scans N microbatches with f32 gradient accumulators and applies ONE
    # update — the global batch multiplies by N without more chips (the
    # large-minibatch lever when the target batch exceeds device memory).
    # Mutually exclusive with steps_per_call>1 and spatial_partition>1.
    # 1 is bit-identical to the plain step.
    accum_steps: int = 1
    # Bucketed gradient all-reduce (parallel/step.py::_bucketed_pmean):
    # > 0 splits the single per-step grads pmean into per-bucket pmeans
    # of ~bucket_mb MiB, grouped in reverse parameter order (the order
    # backward frames complete) so each bucket's DCN/ICI time can hide
    # under the remaining backward compute instead of serializing after
    # it.  Exact: each leaf rides exactly one pmean either way, so the
    # reduction is bitwise identical to the single fused pmean
    # (tests/test_fused_middle.py asserts it).  0 (default) keeps the
    # single-pmean trace — PR 3's bit-exact resume proofs carry over
    # literally.
    bucket_mb: int = 0
    momentum: float = 0.9
    weight_decay: float = 1e-4
    grad_clip: float = 35.0  # reference: clip_gradient=5 per-example scale
    schedule: ScheduleConfig = field(default_factory=ScheduleConfig)
    checkpoint_every: int = 5000
    log_every: int = 20
    seed: int = 0
    # NaN guardian (train/guardian.py): rollback-and-skip retries allowed
    # before a non-finite metric becomes a hard TrainingDiverged error.
    # 0 = detect-and-raise immediately (no rollback).
    guardian_rollbacks: int = 2
    # Loss-spike early warning: interval mean this many sigma above the
    # trailing-window mean logs loudly (no rollback — just visibility).
    guardian_spike_z: float = 8.0


@dataclass(frozen=True)
class ObsConfig:
    """Observability plane (mx_rcnn_tpu/obs/): typed journal, metrics
    registry + /metrics endpoint, span tracing, flight recorder.  All
    host-side — tpulint TPU007 keeps obs out of traced modules, so none
    of these knobs can change a compiled program."""

    # Master switch for the DURABLE surfaces (journal/spans/flight files
    # under <workdir>/<name>/obs).  Off, events still derive their log
    # lines and feed the in-memory flight ring — zero filesystem traffic.
    enabled: bool = False
    # Override the artifact directory ("" = <workdir>/<name>/obs).
    dir: str = ""
    # /metrics + /healthz + /statusz HTTP port: -1 = no endpoint,
    # 0 = ephemeral (logged + readable via obs.metrics_port()).
    metrics_port: int = -1
    # Per-step train spans + per-request serving spans -> spans.jsonl
    # (Chrome-trace lines; tools/obs_report.py wraps them loadable).
    spans: bool = True
    # Flight-recorder ring size (most-recent events+spans kept for the
    # postmortem dump).
    flight_size: int = 512
    # Seconds between metrics_flush journal events (0 = only at close).
    flush_s: float = 0.0


@dataclass(frozen=True)
class DeployConfig:
    """Continuous deployment (ctrl/deploy.py): shadow canaries,
    parity-gated promotion, burn-triggered automatic rollback.  All
    knobs read as ``cfg.ctrl.deploy.*`` (docs/deployment.md has the
    full table)."""

    # Master switch: serving entrypoints that honour it (tools/soak.py
    # --deploy, tools/deploy_watch.py) run a Deployer next to the fleet.
    enabled: bool = False
    # Seconds between checkpoint-directory scans.
    poll_s: float = 2.0
    # Fraction of accepted live submissions mirrored to the shadow
    # replica (deterministic every-Nth sampling, N = round(1/rate)).
    mirror_rate: float = 0.25
    # Minimum mirrored live/shadow pairs before the gate may rule.
    min_mirrored: int = 8
    # Maximum seconds a candidate may sit in shadow before the gate
    # rules on whatever evidence it has.
    shadow_window_s: float = 30.0
    # Golden-set mAP gate: allowed absolute mAP regression of the
    # shadow vs the live generation on the golden set.
    map_drop: float = 0.005
    # Shadow-scoped SLO (dedicated SLOEngine over the shadow's private
    # metrics window): targets + burn windows scaled to the shadow
    # phase, not the live 5min/1h pair.
    availability_target: float = 0.95
    latency_target: float = 0.95
    latency_threshold_s: float = 30.0
    burn_fast_s: float = 5.0
    burn_slow_s: float = 15.0
    burn_factor: float = 2.0
    # Post-promote watch: a live burn alert inside this window triggers
    # automatic rollback to the previous generation's retained leaves.
    watch_window_s: float = 60.0


@dataclass(frozen=True)
class CtrlConfig:
    """Closed-loop control plane (mx_rcnn_tpu/ctrl/): SLO burn-rate
    alerting and the SLO-driven autoscaler.  Host-side by construction —
    tpulint TPU007 keeps ctrl (like obs) out of traced modules, so none
    of these knobs can change a compiled program."""

    # Master switch: serving entrypoints that honour it (tools/soak.py)
    # run the autoscaler + SLO engine next to the fleet.
    enabled: bool = False
    # Autoscaler fleet bounds and pressure thresholds
    # (ctrl/autoscale.py).  Load is mean inflight+queue per routable
    # replica; shed_high is sheds/second over the evaluation window.
    min_replicas: int = 1
    max_replicas: int = 8
    load_high: float = 4.0
    load_low: float = 0.5
    shed_high: float = 0.0
    # Windowed p99 (seconds) that counts as pressure; 0 disables the
    # latency signal.
    p99_high_s: float = 0.0
    # Scale-down hysteresis (mirrors serve/degrade.py HysteresisPlanner:
    # scale-UP is immediate, scale-DOWN needs this many consecutive
    # comfortable evaluations) + per-direction cooldowns.
    down_dwell: int = 3
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 15.0
    # Seconds between autoscaler/SLO evaluations.
    period_s: float = 1.0
    # Default SLOs (ctrl/slo.py): availability over fleet request
    # outcomes, and a latency SLO ("latency_target" of requests under
    # "latency_threshold_s").
    availability_target: float = 0.99
    latency_target: float = 0.99
    latency_threshold_s: float = 30.0
    # Multi-window burn-rate alerting: alert when the burn over BOTH
    # windows exceeds burn_factor x the budget rate.
    burn_fast_s: float = 300.0
    burn_slow_s: float = 3600.0
    burn_factor: float = 2.0
    # Continuous deployment (ctrl/deploy.py): shadow canary + promote +
    # rollback knobs, read as cfg.ctrl.deploy.* (docs/deployment.md).
    deploy: DeployConfig = field(default_factory=DeployConfig)


@dataclass(frozen=True)
class TenancyConfig:
    """Multi-tenant admission (serve/tenancy.py), read as
    cfg.serve.tenancy.* — the knob table lives in docs/serving.md.

    Host-side only: tenancy never reaches a traced module, so no knob
    here can change a compiled program."""

    # Master switch.  Off keeps every admission path and metric series
    # bit-identical to the single-tenant build.
    enabled: bool = False
    # Compact tenant table: "name:weight=4,rate=50,burst=20,priority=0;
    # name2:..." (serve/tenancy.py::parse_table).  A string (not nested
    # config) so `--set serve.tenancy.table=...` works through
    # apply_overrides' scalar coercion.
    table: str = ""
    # Where unknown/absent wire tokens land (never a 500); shares this
    # tenant's bucket and label.
    default_tenant: str = "default"
    # Burn-governor degrade action: a tenant-scoped SLO burn alert
    # multiplies that tenant's admitted rate by this factor until the
    # alert clears (serve/tenancy.py::QuotaGovernor).
    tighten_factor: float = 0.25


@dataclass(frozen=True)
class ServeConfig:
    """Serving-engine defaults consumed by serve/engine.py::build_engine
    and serve/fleet.py::build_fleet (explicit kwargs still win)."""

    # Static micro-batch slots per device call.  1 keeps the
    # one-request-per-call path; >1 enables cross-request packing.
    batch_size: int = 1
    # Continuous batching (serve/batcher.py): pack pending requests from
    # different callers into every bucket slot of each device call,
    # deadline-aware.  De-interleaved responses are bitwise identical to
    # the unpacked path (docs/serving.md).  Only meaningful when
    # batch_size > 1.
    pack: bool = True
    # How long (seconds) the worker lingers for stragglers to top off a
    # partially-filled batch before launching it.  0 launches whatever is
    # packable immediately — lowest latency, occupancy rides on queue
    # depth.
    pack_window_s: float = 0.0
    # Serving-side fused-middle override (detection/graph.py): "inherit"
    # keeps model.rpn.fused_middle / model.rpn.nms_impl as-is; "on"
    # forces fused_middle=True + nms_impl="pallas" for every serving
    # program (full/small/reduced/proposals and the q8 levels); "off"
    # forces the dense XLA chain.  Same off-TPU fallback and
    # MX_RCNN_PALLAS_INTERPRET contract as training — off-TPU without
    # interpret mode the override silently serves the dense chain.
    fused_middle: str = "inherit"
    # Content-addressed result cache (serve/result_cache.py): max cached
    # responses per router (LRU).  0 (default) disables the cache AND
    # in-flight coalescing — duplicate-heavy serving surfaces opt in
    # (tools/loadgen.py defaults its fleets to 256); chaos/fault drills
    # keep it off so every request exercises a real replica.
    result_cache_capacity: int = 0
    # Multi-tenant admission: per-tenant token-bucket quotas +
    # weighted-fair pack shares, read as cfg.serve.tenancy.*
    # (docs/serving.md tenancy section).
    tenancy: TenancyConfig = field(default_factory=TenancyConfig)


@dataclass(frozen=True)
class FabricConfig:
    """Cross-host serving fabric (serve/rpc.py, serve/gossip.py,
    serve/gateway.py): one RPC surface per host, health gossip between
    hosts, and a pod-wide gateway.  All host-side, stdlib-HTTP only."""

    # RPC bind port for this host's fabric endpoint: -1 = fabric off,
    # 0 = ephemeral (tools/serve_host.py logs the bound port).
    rpc_port: int = -1
    # Seconds between gossip rounds (self-refresh + peer exchange).
    gossip_period_s: float = 0.5
    # A peer silent this long is SUSPECT; this much longer total, DEAD.
    suspect_after_s: float = 1.5
    dead_after_s: float = 4.0
    # Gateway: seconds before a pending request gets a duplicate on a
    # second host (None-like <=0 disables cross-host hedging), total
    # attempt budget per request, consecutive request failures that
    # quarantine a host, and the quarantined-host probe period.
    hedge_after_s: float = 0.0
    max_attempts: int = 2
    quarantine_failures: int = 2
    probe_interval_s: float = 0.5


@dataclass(frozen=True)
class Config:
    name: str = "faster_rcnn_r50_fpn_coco"
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    ctrl: CtrlConfig = field(default_factory=CtrlConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    fabric: FabricConfig = field(default_factory=FabricConfig)
    workdir: str = "runs"


def _replace(cfg: Any, **kw: Any) -> Any:
    return dataclasses.replace(cfg, **kw)


def _backbone(name: str) -> BackboneConfig:
    """Preset backbone defaults.  ResNet presets run the TPU layout forms
    by default — space-to-depth stem, slice-max stem pool, C2 lane padding
    — all exact rewrites (parity-tested in tests/test_models.py), so mAP
    and checkpoints are unaffected; only the compiled program changes.
    VGG has no strided RGB stem to rewrite and keeps the dense forms."""
    if name.startswith("resnet"):
        return BackboneConfig(
            name=name, stem_s2d=True, stem_pool_fold=True, c2_pad=True
        )
    return BackboneConfig(name=name)


def _c4_model(num_classes: int, backbone: str) -> ModelConfig:
    """Classic C4 recipe: single-level stride-16 features, anchor scales
    (8, 16, 32), ROIAlign on C4, conv5-as-head replaced by a 2-fc head."""
    return ModelConfig(
        num_classes=num_classes,
        backbone=_backbone(backbone),
        fpn=FPNConfig(enabled=False),
        anchors=AnchorConfig(scales=(8.0, 16.0, 32.0)),
        rpn=RPNConfig(
            channels=512,
            train_pre_nms_top_n=6000,
            train_post_nms_top_n=2000,
            test_pre_nms_top_n=6000,
            test_post_nms_top_n=300,
        ),
        rcnn=RCNNConfig(roi_batch_size=128),
    )


def _fpn_model(num_classes: int, backbone: str, mask: bool = False) -> ModelConfig:
    return ModelConfig(
        num_classes=num_classes,
        backbone=_backbone(backbone),
        fpn=FPNConfig(enabled=True),
        anchors=AnchorConfig(scales=(8.0,)),
        rpn=RPNConfig(),
        rcnn=RCNNConfig(),
        mask=MaskConfig(enabled=mask),
    )


_PRESETS: dict[str, Any] = {}


def _register(name: str, fn) -> None:
    _PRESETS[name] = fn


# The five BASELINE.json configs.
def _vgg16_voc07_model() -> ModelConfig:
    m = _c4_model(21, "vgg16")
    # Override only the VOC-specific test fields so the C4 recipe's other
    # test defaults (e.g. per_device_batch) carry through.
    return _replace(
        m,
        rcnn=RCNNConfig(roi_batch_size=128, hidden_dim=4096),
        test=_replace(m.test, nms_threshold=0.3),
    )


_register(
    "vgg16_voc07",
    lambda: Config(
        name="vgg16_voc07",
        model=_vgg16_voc07_model(),
        data=DataConfig(
            dataset="voc",
            train_split="2007_trainval",
            val_split="2007_test",
            image_size=(608, 1024),
            short_side=600,
            max_side=1000,
            aspect_grouping=True,
        ),
        train=TrainConfig(
            schedule=ScheduleConfig(
                base_lr=0.001, decay_steps=(50000,), total_steps=70000,
                warmup_steps=100,
            ),
        ),
    ),
)
_register(
    "r50_coco",
    lambda: Config(
        name="r50_coco",
        model=_c4_model(81, "resnet50"),
        data=DataConfig(dataset="coco"),
        train=TrainConfig(per_device_batch=2),
    ),
)
_register(
    "r101_coco",
    lambda: Config(
        name="r101_coco",
        model=_c4_model(81, "resnet101"),
        data=DataConfig(dataset="coco"),
        train=TrainConfig(per_device_batch=2),
    ),
)
_register(
    "r101_fpn_coco",
    lambda: Config(
        name="r101_fpn_coco",
        model=_fpn_model(81, "resnet101"),
        data=DataConfig(dataset="coco"),
        train=TrainConfig(per_device_batch=2),
    ),
)
_register(
    "mask_r50_fpn_coco",
    lambda: Config(
        name="mask_r50_fpn_coco",
        model=_fpn_model(81, "resnet50", mask=True),
        data=DataConfig(dataset="coco"),
        train=TrainConfig(per_device_batch=2),
    ),
)
# Default/flagship and test presets.
_register(
    "r50_fpn_coco",
    lambda: Config(
        name="r50_fpn_coco",
        model=_fpn_model(81, "resnet50"),
        data=DataConfig(dataset="coco"),
        train=TrainConfig(per_device_batch=2),
    ),
)
_register(
    "tiny_synthetic",
    lambda: Config(
        name="tiny_synthetic",
        model=_replace(
            _fpn_model(5, "resnet50"),
            # float32 + nothing frozen for the hermetic CPU programs; the
            # TPU layout forms stay ON so every tiny-preset test exercises
            # the production execution paths (exact rewrites — only
            # intra-conv summation order can differ).
            backbone=_replace(
                _backbone("resnet50"), freeze_stages=0, dtype="float32"
            ),
            rpn=RPNConfig(
                batch_size=64,
                train_pre_nms_top_n=200,
                train_post_nms_top_n=64,
                test_pre_nms_top_n=200,
                test_post_nms_top_n=64,
            ),
            rcnn=RCNNConfig(roi_batch_size=32, hidden_dim=128),
            # Batch 1 keeps the hermetic CPU test programs small.
            test=TestConfig(per_device_batch=1),
        ),
        data=DataConfig(
            dataset="synthetic",
            image_size=(128, 128),
            short_side=128,
            max_side=128,
            max_gt_boxes=8,
        ),
        train=TrainConfig(
            schedule=ScheduleConfig(
                base_lr=0.01, warmup_steps=10, decay_steps=(400,),
                total_steps=500,
                # Absolute steps: the golden overfit numbers pin this
                # preset's exact step count on the 8-device fake mesh.
                reference_batch=0,
            ),
            checkpoint_every=250,
        ),
    ),
)


def available_configs() -> list[str]:
    return sorted(_PRESETS)


def get_config(name: str, **overrides: Any) -> Config:
    """Build a preset config; kwargs replace top-level Config fields.

    Replaces the reference's ``generate_config(network, dataset)`` mutator:
    instead of mutating a global, returns a frozen Config.
    """
    if name not in _PRESETS:
        raise KeyError(f"unknown config {name!r}; available: {available_configs()}")
    cfg = _PRESETS[name]()
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def _coerce(text: str, current: Any) -> Any:
    """Parse ``text`` to the type of ``current`` (the existing field value)."""
    if isinstance(current, bool):
        if text.lower() in ("1", "true", "yes"):
            return True
        if text.lower() in ("0", "false", "no"):
            return False
        raise ValueError(f"expected bool, got {text!r}")
    if isinstance(current, tuple):
        parts = [p for p in text.replace("(", "").replace(")", "").split(",") if p]
        elem = current[0] if current else float("nan")
        return tuple(type(elem)(p) if current else float(p) for p in parts)
    if isinstance(current, int) and not isinstance(current, bool):
        return int(text)
    if isinstance(current, float):
        return float(text)
    return text


def apply_overrides(cfg: Config, assignments: list[str]) -> Config:
    """Apply CLI ``dotted.path=value`` overrides to a frozen config tree.

    The functional replacement for the reference CLIs' ad-hoc mutation of the
    global easydict (e.g. ``config.TRAIN.BATCH_IMAGES = args.batch``): each
    assignment rebuilds the dataclass spine from the leaf up.
    """
    for item in assignments:
        if "=" not in item:
            raise ValueError(f"override {item!r} is not of the form key.path=value")
        path, text = item.split("=", 1)
        keys = path.strip().split(".")
        # Collect the chain of dataclass nodes down to the leaf's parent.
        nodes = [cfg]
        for k in keys[:-1]:
            nodes.append(getattr(nodes[-1], k))
        leaf = getattr(nodes[-1], keys[-1])
        if dataclasses.is_dataclass(leaf):
            raise ValueError(f"{path} is a config section, not a field")
        new_val = _coerce(text.strip(), leaf)
        for node, k in zip(reversed(nodes), reversed(keys)):
            new_val = dataclasses.replace(node, **{k: new_val})
        cfg = new_val
    return cfg
