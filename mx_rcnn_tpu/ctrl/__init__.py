"""Closed-loop control plane: SLOs, burn-rate alerts, autoscaling.

The observability plane (``mx_rcnn_tpu.obs``) *watches* the serving
stack; this package *acts* on what it sees:

* ``ctrl/slo.py`` — declarative :class:`SLO` objects evaluated over
  metrics ``Registry`` snapshots, with SRE-style multi-window burn-rate
  alerting journaled as typed events and the remaining error budget
  exported on ``/metrics``.
* ``ctrl/autoscale.py`` — an :class:`Autoscaler` policy loop that turns
  queue-depth / shed-rate / windowed-p99 pressure into
  ``FleetRouter.add_replica()`` / ``retire_replica()`` calls, with
  scale-down hysteresis mirroring ``serve/degrade.HysteresisPlanner``.
* ``ctrl/deploy.py`` — a :class:`Deployer` that watches the checkpoint
  directory, stages candidates as shadow canaries behind a
  parity + shadow-SLO gate, promotes through the one-at-a-time weight
  roll, and rolls back automatically on a post-promote burn alert
  (docs/deployment.md).

Everything here is host-side control logic: tpulint's TPU007 rule bans
``mx_rcnn_tpu.ctrl`` imports from jit-traced modules, exactly as it
does for ``mx_rcnn_tpu.obs``.  Knobs live under ``cfg.ctrl``
(:class:`mx_rcnn_tpu.config.CtrlConfig`); see docs/autoscaling.md.
"""

from mx_rcnn_tpu.ctrl.autoscale import (
    Autoscaler,
    ScalePolicy,
    ScaleSignals,
    desired_action,
)
from mx_rcnn_tpu.ctrl.deploy import (
    Deployer,
    ShadowVerdict,
    build_deployer,
)
from mx_rcnn_tpu.ctrl.slo import (
    SLO,
    SLOEngine,
    default_slos,
    good_total,
    merged_percentile,
    tenant_slos,
)


def build_controller(cfg, fleet):
    """(SLOEngine, Autoscaler) pair wired from ``cfg.ctrl`` — neither
    loop started; callers pick the period (``cfg.ctrl.period_s``)."""
    ctrl = cfg.ctrl
    engine = SLOEngine(
        default_slos(ctrl),
        fast_s=ctrl.burn_fast_s,
        slow_s=ctrl.burn_slow_s,
        burn_factor=ctrl.burn_factor,
    )
    scaler = Autoscaler(fleet, ScalePolicy.from_config(ctrl))
    return engine, scaler


__all__ = [
    "SLO",
    "SLOEngine",
    "default_slos",
    "good_total",
    "merged_percentile",
    "tenant_slos",
    "Autoscaler",
    "ScalePolicy",
    "ScaleSignals",
    "desired_action",
    "build_controller",
    "Deployer",
    "ShadowVerdict",
    "build_deployer",
]
