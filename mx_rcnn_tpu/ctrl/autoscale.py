"""SLO-driven autoscaler: queue/p99/shed pressure -> fleet resizes.

The policy layer is pure (:func:`desired_action` over immutable
:class:`ScaleSignals` — unit-testable without a fleet); the
:class:`Autoscaler` loop reads signals from ``FleetRouter.stats()`` and
the metrics registry, then drives the dynamic-fleet API:
``fleet.add_replica()`` on pressure, ``fleet.retire_replica(rid)`` when
the fleet has been comfortable long enough.

Asymmetry is deliberate and mirrors ``serve/degrade.py``'s
``HysteresisPlanner``: **scale-up is immediate** (pressure is never
absorbed — one evaluation over threshold adds capacity, gated only by a
cooldown so a build-in-progress isn't doubled), while **scale-down
needs dwell** (``down_dwell`` consecutive comfortable evaluations plus
a cooldown), so the fleet never flaps around a load edge.

Every resize decision is journaled (``fleet_scale_up`` /
``fleet_scale_down`` typed events) WITH its input signals, so
``tools/obs_report.py`` can reconstruct *why* the fleet resized from
the journal alone.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Optional

from mx_rcnn_tpu import obs
from mx_rcnn_tpu.ctrl.slo import merged_percentile
from mx_rcnn_tpu.obs.metrics import Registry, SnapshotWindow
from mx_rcnn_tpu.serve.router import DEGRADED, QUARANTINED, READY

log = logging.getLogger("mx_rcnn_tpu.ctrl")

__all__ = ["ScaleSignals", "ScalePolicy", "desired_action", "Autoscaler"]


@dataclasses.dataclass(frozen=True)
class ScaleSignals:
    """One evaluation's inputs, all read at the same instant."""

    routable: int          # replicas a request can land on now
    building: int          # quarantined slots with capacity imminent
    mean_load: float       # mean inflight+queue per routable replica
    queue_depth: int       # total queued across routable replicas
    shed_rate: float       # fleet sheds per second over the window
    p99_s: Optional[float]  # windowed p99 latency (None = no data)
    # Pod-wide mean load across live hosts, aggregated from gossip
    # (serve/gossip.py GossipNode.aggregate()).  None on a single-host
    # deployment — every decision then reads local signals only.
    pod_mean_load: Optional[float] = None

    def as_payload(self) -> dict:
        p = dataclasses.asdict(self)
        p["mean_load"] = round(p["mean_load"], 3)
        p["shed_rate"] = round(p["shed_rate"], 3)
        if p["p99_s"] is not None:
            p["p99_s"] = round(p["p99_s"], 4)
        if p["pod_mean_load"] is not None:
            p["pod_mean_load"] = round(p["pod_mean_load"], 3)
        return p


@dataclasses.dataclass(frozen=True)
class ScalePolicy:
    """Thresholds + hysteresis knobs (cfg.ctrl.* — docs/autoscaling.md)."""

    min_replicas: int = 1
    max_replicas: int = 8
    load_high: float = 4.0
    load_low: float = 0.5
    shed_high: float = 0.0      # sheds/s strictly above this is pressure
    p99_high_s: float = 0.0     # 0 disables the latency signal
    down_dwell: int = 3
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 15.0

    @classmethod
    def from_config(cls, ctrl_cfg) -> "ScalePolicy":
        return cls(
            min_replicas=ctrl_cfg.min_replicas,
            max_replicas=ctrl_cfg.max_replicas,
            load_high=ctrl_cfg.load_high,
            load_low=ctrl_cfg.load_low,
            shed_high=ctrl_cfg.shed_high,
            p99_high_s=ctrl_cfg.p99_high_s,
            down_dwell=ctrl_cfg.down_dwell,
            up_cooldown_s=ctrl_cfg.up_cooldown_s,
            down_cooldown_s=ctrl_cfg.down_cooldown_s,
        )


def desired_action(sig: ScaleSignals,
                   pol: ScalePolicy) -> tuple[str, str]:
    """("up"|"down"|"hold", reason).  Pure — dwell/cooldown gating is
    the loop's job; this only reads the instant."""
    size = sig.routable + sig.building
    pressure = []
    if sig.mean_load > pol.load_high:
        pressure.append(
            f"mean load {sig.mean_load:.2f} > {pol.load_high:g}"
        )
    if sig.shed_rate > pol.shed_high:
        pressure.append(
            f"shed rate {sig.shed_rate:.2f}/s > {pol.shed_high:g}/s"
        )
    if pol.p99_high_s > 0 and sig.p99_s is not None \
            and sig.p99_s > pol.p99_high_s:
        pressure.append(f"p99 {sig.p99_s:.3f}s > {pol.p99_high_s:g}s")
    if sig.pod_mean_load is not None \
            and sig.pod_mean_load > pol.load_high:
        pressure.append(
            f"pod mean load {sig.pod_mean_load:.2f} > {pol.load_high:g}"
        )
    if pressure:
        if size >= pol.max_replicas:
            return "hold", (
                f"pressure ({'; '.join(pressure)}) but at "
                f"max_replicas={pol.max_replicas}"
            )
        return "up", "; ".join(pressure)
    comfortable = (
        sig.mean_load < pol.load_low
        and sig.shed_rate <= pol.shed_high
        and (
            pol.p99_high_s <= 0 or sig.p99_s is None
            or sig.p99_s <= pol.p99_high_s
        )
        # A host never scales down while the pod as a whole is hot:
        # gossip says peers are loaded, so this host's comfort is
        # about to end (the gateway rebalances toward it).
        and (
            sig.pod_mean_load is None
            or sig.pod_mean_load < pol.load_low
        )
    )
    if comfortable and sig.building == 0 \
            and sig.routable > pol.min_replicas:
        return "down", (
            f"mean load {sig.mean_load:.2f} < {pol.load_low:g}, "
            f"no shed"
        )
    return "hold", "within band"


class Autoscaler:
    """Policy loop over one fleet.  ``step()`` is one evaluation (tests
    drive it directly with a fake clock); ``start(period_s)`` runs it on
    a daemon thread."""

    def __init__(
        self,
        fleet,
        policy: ScalePolicy = ScalePolicy(),
        *,
        registry: Optional[Registry] = None,
        p99_window_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        pod_view: Optional[Callable[[], dict]] = None,
    ) -> None:
        self.fleet = fleet
        self.policy = policy
        # ``pod_view`` returns a gossip aggregate dict (serve/gossip.py
        # GossipNode.aggregate) so a host scales on POD pressure, not
        # just its own — None keeps single-host behaviour bit-for-bit.
        self.pod_view = pod_view
        self._clock = clock
        self._registry = registry if registry is not None else obs.registry()
        self._window = SnapshotWindow(
            self._registry, horizon_s=max(p99_window_s * 4, 120.0)
        )
        self.p99_window_s = p99_window_s
        self._lock = threading.Lock()
        self._down_streak = 0
        self._last_up = -float("inf")
        self._last_down = -float("inf")
        self._last_shed: Optional[tuple[float, int]] = None
        self.decisions: list[dict] = []  # resize timeline (BENCH_soak)
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals -----------------------------------------------------------

    def signals(self, now: Optional[float] = None) -> ScaleSignals:
        now = self._clock() if now is None else now
        stats = self.fleet.stats()
        routable = building = 0
        load = queue = 0
        for rep in stats["replica"]:
            if rep["state"] in (READY, DEGRADED):
                routable += 1
                eng = rep.get("engine") or {}
                q = int(eng.get("queue_depth", 0))
                load += rep["inflight"] + q
                queue += q
            elif rep["state"] == QUARANTINED:
                building += 1
        shed = int(stats.get("shed", 0))
        with self._lock:
            last = self._last_shed
            self._last_shed = (now, shed)
        shed_rate = 0.0
        if last is not None and now > last[0]:
            shed_rate = max(0, shed - last[1]) / (now - last[0])
        _, delta = self._window.delta_over(self.p99_window_s)
        p99 = merged_percentile(delta, 0.99) if delta else None
        if p99 is not None and p99 == float("inf"):
            p99 = None  # beyond the last bucket: no usable estimate
        pod_mean = None
        if self.pod_view is not None:
            try:
                agg = self.pod_view() or {}
                if int(agg.get("hosts", 0)) > 1:
                    pod_mean = float(agg.get("mean_load", 0.0))
            except Exception:  # noqa: BLE001 - gossip is advisory
                log.exception("autoscaler: pod_view failed")
        return ScaleSignals(
            routable=routable,
            building=building,
            mean_load=load / routable if routable else 0.0,
            queue_depth=queue,
            shed_rate=shed_rate,
            p99_s=p99,
            pod_mean_load=pod_mean,
        )

    # -- one evaluation ----------------------------------------------------

    def step(self, now: Optional[float] = None) -> dict:
        now = self._clock() if now is None else now
        self._window.observe(now)
        sig = self.signals(now)
        pol = self.policy
        action, reason = desired_action(sig, pol)
        size = sig.routable + sig.building
        rec = {
            "t": now, "action": action, "reason": reason, "size": size,
            "signals": sig.as_payload(),
        }
        if action == "up":
            with self._lock:
                self._down_streak = 0
                in_cooldown = now - self._last_up < pol.up_cooldown_s
                if not in_cooldown:
                    self._last_up = now
            if in_cooldown:
                rec["action"] = "hold"
                rec["reason"] = f"up-cooldown ({reason})"
            else:
                try:
                    rid = self.fleet.add_replica()
                except Exception as e:  # noqa: BLE001 - keep looping
                    log.exception("autoscaler: add_replica failed")
                    rec["action"], rec["error"] = "hold", str(e)
                else:
                    rec.update(replica=rid, target=size + 1)
                    obs.emit("ctrl", "fleet_scale_up", {
                        "size": size, "target": size + 1,
                        "reason": reason, "replica": rid,
                        "signals": sig.as_payload(),
                    }, logger=log)
                    obs.counter(
                        "ctrl_scale_decisions_total", "fleet resizes"
                    ).inc(direction="up")
        elif action == "down":
            with self._lock:
                self._down_streak += 1
                streak = self._down_streak
                ready = (
                    streak >= pol.down_dwell
                    and now - self._last_down >= pol.down_cooldown_s
                    and now - self._last_up >= pol.down_cooldown_s
                )
                if ready:
                    self._down_streak = 0
                    self._last_down = now
            rec["dwell"] = streak
            if not ready:
                rec["action"] = "hold"
                rec["reason"] = (
                    f"down-dwell {streak}/{pol.down_dwell} ({reason})"
                )
            else:
                victim = self._pick_victim()
                if victim is None:
                    rec["action"] = "hold"
                    rec["reason"] = "no retirable replica"
                else:
                    obs.emit("ctrl", "fleet_scale_down", {
                        "size": size, "target": size - 1,
                        "dwell": streak or pol.down_dwell,
                        "reason": reason, "replica": victim,
                        "signals": sig.as_payload(),
                    }, logger=log)
                    obs.counter(
                        "ctrl_scale_decisions_total", "fleet resizes"
                    ).inc(direction="down")
                    try:
                        clean = self.fleet.retire_replica(
                            victim, reason="autoscaler scale-down"
                        )
                    except Exception as e:  # noqa: BLE001 - keep looping
                        log.exception("autoscaler: retire failed")
                        rec["error"] = str(e)
                    else:
                        rec.update(
                            replica=victim, target=size - 1, clean=clean
                        )
        else:
            with self._lock:
                self._down_streak = 0
        self._registry.gauge(
            "ctrl_fleet_size", "replicas in rotation or building"
        ).set(size)
        if rec["action"] in ("up", "down"):
            with self._lock:
                self.decisions.append(rec)
        return rec

    def _pick_victim(self) -> Optional[int]:
        """Newest (highest-rid) routable replica — deterministic, and
        the one whose device slot was claimed last."""
        rids = [
            rep["rid"] for rep in self.fleet.stats()["replica"]
            if rep["state"] in (READY, DEGRADED)
        ]
        if len(rids) <= self.policy.min_replicas:
            return None
        return max(rids)

    def resize_timeline(self) -> list[dict]:
        with self._lock:
            return list(self.decisions)

    # -- loop --------------------------------------------------------------

    def start(self, period_s: float = 1.0) -> "Autoscaler":
        if self._thread is not None:
            return self

        def loop() -> None:
            while not self._stop_event.wait(period_s):
                try:
                    self.step()
                except Exception:
                    log.exception("autoscaler step failed")

        self._thread = threading.Thread(
            target=loop, name="ctrl-autoscale", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_event.set()
        if self._thread is not None:
            # A retire drain can hold a step for its full timeout.
            self._thread.join(90.0)
            self._thread = None
