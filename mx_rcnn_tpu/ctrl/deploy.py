"""Continuous deployment: shadow canaries, parity-gated promotion, and
burn-triggered automatic rollback (ROADMAP item 4's "self-updating
service" step — docs/deployment.md).

The :class:`Deployer` closes the loop between the training and serving
halves:

* **Watch** — polls a checkpoint directory for new steps.  A candidate
  must pass ``train/checkpoint.py``'s manifest verification (file-level
  size+CRC digests — truncated/tampered checkpoints are rejected before
  a single byte is deserialized) and then the same restore-fallback walk
  the trainer trusts, plus an end-to-end param-tree CRC check.
* **Shadow** — the candidate is staged on a spare out-of-rotation
  replica (``FleetRouter.build_spare_engine`` — fresh never-reused rid,
  invisible to routing and supervision) while live traffic is mirrored
  to it at ``cfg.ctrl.deploy.mirror_rate`` through the router's mirror
  hook.  Shadow responses never reach callers by construction: the hook
  only ever sees a copy of the input.
* **Gate** — live/shadow pairs whose degrade levels match must agree
  BITWISE over the comparable payload (the result-cache sanitization
  discipline: everything except the volatile per-serving stamps and the
  producer's generation tag); pairs whose levels differ — and any
  bitwise divergence — are arbitrated by mAP-on-a-golden-set
  (evalutil's voc_eval, like the q8n parity gate).  A dedicated
  :class:`~mx_rcnn_tpu.ctrl.slo.SLOEngine` over the shadow's PRIVATE
  metrics registry must hold, and a minimum mirrored-request count must
  be reached, before promotion.
* **Promote** — the existing one-at-a-time ``swap_weights`` roll, with
  the generation pinned to the shadow's number (unique, never reused —
  a rejected candidate's generation can never reappear in a served
  response's tag).
* **Watch window / rollback** — after promotion, a burn alert from the
  LIVE SLO engine inside ``watch_window_s`` triggers automatic
  rollback: the previous generation's retained tree (depth-2 history in
  fleet/gateway) is re-published under a NEW, HIGHER generation number.
  Monotonic ``health.record_swap`` and generation-keyed
  ``result_cache.invalidate_below`` both require that the number never
  moves backwards; only the weights roll back, never the counter.

Every decision is a typed journal event (deploy_candidate,
deploy_shadow_start, deploy_shadow_verdict, deploy_promote,
deploy_reject, deploy_rollback, deploy_resume), so ``tools/obs_report``
replays the whole deployment history from artifacts alone, and a
restarted Deployer reconstructs its state from the journal
(:meth:`Deployer.recover`): killed after a promote verdict but before
the roll completed it resumes the roll; killed mid-shadow it safely
abandons the candidate.

Host-side only (tpulint TPU007): nothing here may be imported from
jit-traced modules.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from .. import obs
from ..obs.metrics import Registry
from ..serve.result_cache import _VOLATILE_FIELDS
from .slo import SLOEngine, default_slos

log = logging.getLogger("mx_rcnn_tpu.ctrl")

__all__ = [
    "PARITY_EXCLUDED_FIELDS", "ShadowVerdict", "Deployer",
    "build_deployer", "comparable_payload", "payloads_equal", "golden_map",
]

# Fields excluded from the bitwise live/shadow comparison: the volatile
# per-serving stamps the result cache strips before insert
# (serve/result_cache.py), plus the tags that differ between live and
# shadow BY CONSTRUCTION — the producer's generation and the cache's
# coalesced marker.  Everything else must match bit for bit.
PARITY_EXCLUDED_FIELDS = tuple(_VOLATILE_FIELDS) + ("generation", "coalesced")


def comparable_payload(res: dict) -> dict:
    """The parity-comparable subset of one response payload."""
    return {
        k: v for k, v in res.items() if k not in PARITY_EXCLUDED_FIELDS
    }


def payloads_equal(a: dict, b: dict) -> bool:
    """Bitwise equality over the comparable payload."""
    ca, cb = comparable_payload(a), comparable_payload(b)
    if set(ca) != set(cb):
        return False
    for k, va in ca.items():
        vb = cb[k]
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            if not np.array_equal(np.asarray(va), np.asarray(vb)):
                return False
        elif va != vb:
            return False
    return True


def golden_map(infer: Callable[[object], dict], golden: dict,
               iou_threshold: float = 0.5) -> Optional[float]:
    """mAP of ``infer`` over a golden set.

    ``golden`` is ``{"images": [arrays], "gt": {class_idx: {image_id:
    {"boxes": (m,4), "difficult": (m,)}}}}`` — image ids are the string
    indices into ``images``.  Returns None when the set is unusable
    (empty, or every inference failed)."""
    from ..evalutil.voc_eval import voc_eval

    images = golden.get("images") or []
    gt = golden.get("gt") or {}
    if not images or not gt:
        return None
    per_class: dict[int, dict[str, np.ndarray]] = {
        int(c): {} for c in gt
    }
    ran = 0
    for i, image in enumerate(images):
        try:
            res = infer(image)
        except Exception:  # noqa: BLE001 - a dead side scores 0, not a crash
            continue
        ran += 1
        boxes = np.asarray(res.get("boxes", ())).reshape(-1, 4)
        scores = np.asarray(res.get("scores", ())).reshape(-1)
        classes = np.asarray(res.get("classes", ())).reshape(-1)
        n = min(len(boxes), len(scores), len(classes))
        for c in per_class:
            keep = classes[:n] == c
            rows = np.concatenate(
                [boxes[:n][keep], scores[:n][keep][:, None]], axis=1
            ) if keep.any() else np.zeros((0, 5))
            per_class[c][str(i)] = rows
    if ran == 0:
        return None
    aps = []
    for c, dets in per_class.items():
        class_gt = {str(k): v for k, v in gt[c].items()}
        ap, _, _ = voc_eval(dets, class_gt, iou_threshold)
        aps.append(ap)
    return float(np.mean(aps)) if aps else None


@dataclasses.dataclass
class ShadowVerdict:
    """The shadow gate's ruling plus the evidence it ruled on."""

    step: int
    generation: int
    promote: bool
    reason: str
    mirrored: int = 0
    compared: int = 0
    mismatched: int = 0
    level_mismatch: int = 0
    shadow_failures: int = 0
    map_live: Optional[float] = None
    map_shadow: Optional[float] = None
    map_ok: Optional[bool] = None
    slo_ok: bool = True
    slo_verdicts: list = dataclasses.field(default_factory=list)

    def payload(self) -> dict:
        out = dataclasses.asdict(self)
        out["verdict"] = "promote" if self.promote else "reject"
        return out


class _ShadowState:
    """One candidate's in-flight shadow bookkeeping (own lock — never
    nested with the Deployer's or any router lock)."""

    def __init__(self, step: int, generation: int, engine,
                 slo: SLOEngine, registry: Registry) -> None:
        self.step = step
        self.generation = generation
        self.engine = engine
        self.slo = slo
        self.registry = registry
        self.lock = threading.Lock()
        self.mirrored = 0
        self.compared = 0
        self.mismatched = 0
        self.level_mismatch = 0
        self.shadow_failures = 0
        self.closed = False


class Deployer:
    """Watch → shadow → gate → promote → watch-window → rollback.

    ``router`` is a FleetRouter or GatewayRouter (detected via
    ``accepts_wire_leaves``).  ``loader(step)`` returns the raw
    checkpoint tree (default: ``checkpoint.restore_raw``);
    ``to_variables(tree)`` maps it to the serving tree (default:
    identity, or the tree's ``"variables"``/``"params"`` entry when
    present).  ``shadow_engine_factory()`` builds the out-of-rotation
    canary engine (default: ``router.build_spare_engine`` — fleets
    only).  ``live_slo`` is the LIVE SLOEngine whose burn alerts drive
    the post-promote watch."""

    def __init__(
        self,
        router,
        ckpt_dir: str,
        *,
        poll_s: float = 2.0,
        mirror_rate: float = 0.25,
        min_mirrored: int = 8,
        shadow_window_s: float = 30.0,
        map_drop: float = 0.005,
        watch_window_s: float = 60.0,
        mirror_timeout_s: float = 30.0,
        slos: Optional[Sequence] = None,
        slo_fast_s: float = 5.0,
        slo_slow_s: float = 15.0,
        slo_burn_factor: float = 2.0,
        availability_target: float = 0.95,
        latency_target: float = 0.95,
        latency_threshold_s: float = 30.0,
        golden: Optional[dict] = None,
        live_slo: Optional[SLOEngine] = None,
        loader: Optional[Callable[[int], object]] = None,
        to_variables: Optional[Callable[[object], object]] = None,
        shadow_engine_factory: Optional[Callable[[], object]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._router = router
        self._is_gateway = bool(getattr(router, "accepts_wire_leaves", False))
        self.ckpt_dir = ckpt_dir
        self.poll_s = float(poll_s)
        self.mirror_rate = float(mirror_rate)
        self.min_mirrored = int(min_mirrored)
        self.shadow_window_s = float(shadow_window_s)
        self.map_drop = float(map_drop)
        self.watch_window_s = float(watch_window_s)
        self.mirror_timeout_s = float(mirror_timeout_s)
        self.availability_target = float(availability_target)
        self.latency_target = float(latency_target)
        self.latency_threshold_s = float(latency_threshold_s)
        self._slos = tuple(slos) if slos is not None else None
        self.slo_fast_s = float(slo_fast_s)
        self.slo_slow_s = float(slo_slow_s)
        self.slo_burn_factor = float(slo_burn_factor)
        self.golden = golden
        self.live_slo = live_slo
        self._loader = loader
        self._to_variables = to_variables
        self._shadow_factory = shadow_engine_factory
        self._clock = clock
        self._lock = threading.Lock()
        self._shadow: Optional[_ShadowState] = None
        self._watch: Optional[dict] = None
        self._decided: dict[int, str] = {}   # step -> outcome
        self._deployed_step: Optional[int] = None
        self._next_gen = 1                   # never reused, never rewound
        self.history: list[dict] = []
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_candidates = obs.counter(
            "ctrl_deploy_candidates_total",
            "deploy candidates by final outcome",
        )
        self._m_mirrored = obs.counter(
            "ctrl_deploy_mirrored_total",
            "live submissions mirrored to the shadow replica",
        )
        self._m_rollbacks = obs.counter(
            "ctrl_deploy_rollbacks_total",
            "burn-triggered automatic rollbacks",
        )

    # -- candidate plumbing ------------------------------------------------

    def _load(self, step: int):
        if self._loader is not None:
            return self._loader(step)
        from ..train import checkpoint
        return checkpoint.restore_raw(self.ckpt_dir, step=step)

    def _variables_of(self, tree):
        if self._to_variables is not None:
            return self._to_variables(tree)
        if isinstance(tree, dict):
            for key in ("variables", "params"):
                if key in tree:
                    return tree[key] if key == "variables" else \
                        {"params": tree["params"]}
        return tree

    def _spare_engine(self):
        if self._shadow_factory is not None:
            return self._shadow_factory()
        factory = getattr(self._router, "build_spare_engine", None)
        if factory is None:
            raise RuntimeError(
                "router has no build_spare_engine; pass "
                "shadow_engine_factory explicitly"
            )
        return factory()

    def _reserve_generation(self) -> int:
        """A unique, strictly-increasing generation for the next shadow.
        Rejected candidates burn their number — it can never reappear in
        a served response's generation tag."""
        with self._lock:
            gen = max(self._next_gen, self._router.generation + 1)
            self._next_gen = gen + 1
            return gen

    # -- journal -----------------------------------------------------------

    def _record(self, kind: str, payload: dict) -> None:
        obs.emit("ctrl", kind, payload, logger=log)
        self.history.append(dict(payload, kind=kind, t=self._clock()))

    # -- mirror ------------------------------------------------------------

    def _on_mirror(self, image, live_req) -> None:
        """Router mirror hook: pair one live request with a shadow
        inference, off the caller's path (fresh daemon thread)."""
        sh = self._shadow
        if sh is None or sh.closed:
            return
        self._m_mirrored.inc()
        threading.Thread(
            target=self._mirror_pair, args=(sh, image, live_req),
            name="deploy-mirror", daemon=True,
        ).start()

    def _mirror_pair(self, sh: _ShadowState, image, live_req) -> None:
        t0 = self._clock()
        shadow_res = None
        try:
            shadow_res = sh.engine.infer(image, timeout=self.mirror_timeout_s)
            sh.registry.counter(
                "fleet_requests_total", "shadow requests by outcome"
            ).inc(outcome="completed")
            sh.registry.histogram(
                "serve_request_latency_seconds", "shadow request latency"
            ).observe(
                self._clock() - t0,
                level=str(shadow_res.get("level", "full")),
            )
        except Exception:  # noqa: BLE001 - a failing canary is evidence
            sh.registry.counter(
                "fleet_requests_total", "shadow requests by outcome"
            ).inc(outcome="failed")
        live_res = None
        try:
            live_res = live_req.result(timeout=self.mirror_timeout_s)
        except Exception:  # noqa: BLE001 - live failure isn't the canary's
            pass
        with sh.lock:
            sh.mirrored += 1
            if shadow_res is None:
                sh.shadow_failures += 1
            elif live_res is not None:
                if live_res.get("level") == shadow_res.get("level"):
                    sh.compared += 1
                    if not payloads_equal(live_res, shadow_res):
                        sh.mismatched += 1
                else:
                    sh.level_mismatch += 1
        sh.slo.observe()

    # -- shadow phase ------------------------------------------------------

    def _shadow_phase(self, step: int, variables) -> ShadowVerdict:
        generation = self._reserve_generation()
        engine = self._spare_engine()
        engine.start()
        try:
            engine.swap_weights(variables, generation=generation)
        except Exception as e:  # noqa: BLE001 - unload-able candidate
            try:
                engine.stop(drain=False)
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
            return ShadowVerdict(
                step=step, generation=generation, promote=False,
                reason=f"shadow_swap_failed: {e}",
            )
        registry = Registry()
        slo = SLOEngine(
            self._slos if self._slos is not None else default_slos(self),
            registry=registry,
            fast_s=self.slo_fast_s, slow_s=self.slo_slow_s,
            burn_factor=self.slo_burn_factor, clock=self._clock,
        )
        slo.observe()
        sh = _ShadowState(step, generation, engine, slo, registry)
        with self._lock:
            self._shadow = sh
        self._record("deploy_shadow_start", {
            "step": step, "generation": generation,
            "mirror_rate": self.mirror_rate,
        })
        self._router.set_mirror(self._on_mirror, self.mirror_rate)
        deadline = self._clock() + self.shadow_window_s
        try:
            while self._clock() < deadline:
                with sh.lock:
                    enough = (
                        sh.mirrored >= self.min_mirrored
                        and sh.compared + sh.level_mismatch > 0
                    )
                if enough or self._stop_event.wait(0.05):
                    break
        finally:
            self._router.clear_mirror()
        # Let in-flight mirror pairs land before ruling.
        settle = self._clock() + min(2.0, self.mirror_timeout_s)
        while self._clock() < settle:
            with sh.lock:
                if sh.mirrored >= self.min_mirrored or sh.closed:
                    break
            if self._stop_event.wait(0.02):
                break
        slo.observe()
        map_live = map_shadow = None
        if self.golden:
            map_shadow = golden_map(
                lambda img: engine.infer(img, timeout=self.mirror_timeout_s),
                self.golden,
            )
            map_live = golden_map(
                lambda img: self._router.infer(
                    img, timeout=self.mirror_timeout_s
                ),
                self.golden,
            )
        with sh.lock:
            sh.closed = True
            mirrored, compared = sh.mirrored, sh.compared
            mismatched, level_mm = sh.mismatched, sh.level_mismatch
            failures = sh.shadow_failures
        with self._lock:
            self._shadow = None
        try:
            engine.stop(drain=False)
        except Exception:  # noqa: BLE001 - best-effort teardown
            log.exception("deploy: stopping shadow engine failed")
        slo_verdicts = slo.verdicts()
        burn_started = any(a.get("event") == "start" for a in slo.alerts)
        slo_ok = (
            not burn_started
            and all(v.get("held", False) for v in slo_verdicts)
        )
        map_ok = None
        if map_live is not None and map_shadow is not None:
            map_ok = map_shadow >= map_live - self.map_drop
        # The gate: bitwise parity wherever degrade levels matched; any
        # divergence (bitwise or level) must be redeemed by an explicit
        # golden-set mAP pass; the shadow-scoped SLO must hold; and the
        # evidence must be big enough to mean something.
        enough = (
            mirrored >= self.min_mirrored and compared + level_mm > 0
        )
        parity_ok = (
            failures == 0
            and (mismatched == 0 or map_ok is True)
            and (level_mm == 0 or map_ok is True)
            and (map_ok is not False)
        )
        if not enough:
            promote, reason = False, "insufficient_mirrored"
        elif not parity_ok:
            promote, reason = False, "parity"
        elif not slo_ok:
            promote, reason = False, "shadow_slo"
        else:
            promote, reason = True, "ok"
        return ShadowVerdict(
            step=step, generation=generation, promote=promote,
            reason=reason, mirrored=mirrored, compared=compared,
            mismatched=mismatched, level_mismatch=level_mm,
            shadow_failures=failures, map_live=map_live,
            map_shadow=map_shadow, map_ok=map_ok, slo_ok=slo_ok,
            slo_verdicts=slo_verdicts,
        )

    # -- promote / rollback ------------------------------------------------

    def _swap_router(self, variables, generation: int) -> int:
        if self._is_gateway:
            return self._router.swap_weights(
                variables=variables, generation=generation
            )
        return self._router.swap_weights(variables, generation=generation)

    def _promote(self, step: int, variables, generation: int) -> int:
        from_gen = self._router.generation
        target = max(generation, from_gen + 1)
        rolled = self._swap_router(variables, target)
        with self._lock:
            self._deployed_step = step
            self._decided[step] = "promoted"
            self._next_gen = max(self._next_gen, rolled + 1)
            self._watch = {
                "step": step,
                "generation": rolled,
                "deadline": self._clock() + self.watch_window_s,
                "alerts_seen": (
                    len(self.live_slo.alerts)
                    if self.live_slo is not None else 0
                ),
            }
        self._m_candidates.inc(outcome="promoted")
        self._record("deploy_promote", {
            "step": step, "generation": rolled,
            "from_generation": from_gen,
            "watch_window_s": self.watch_window_s,
        })
        return rolled

    def _reject(self, step: int, reason: str,
                outcome: str = "rejected") -> None:
        with self._lock:
            self._decided[step] = outcome
        self._m_candidates.inc(outcome=outcome)
        self._record("deploy_reject", {"step": step, "reason": reason})

    def check_watch(self) -> Optional[dict]:
        """One post-promote watch evaluation: a NEW live burn alert
        inside the window triggers rollback.  Returns the rollback
        record when one happened."""
        with self._lock:
            w = self._watch
        if w is None:
            return None
        burn = None
        if self.live_slo is not None:
            alerts = list(self.live_slo.alerts)[w["alerts_seen"]:]
            burn = next(
                (a for a in alerts if a.get("event") == "start"), None
            )
        if burn is not None:
            return self.rollback(burn, watch=w)
        if self._clock() >= w["deadline"]:
            with self._lock:
                if self._watch is w:
                    self._watch = None
        return None

    def rollback(self, burn: Optional[dict] = None,
                 watch: Optional[dict] = None) -> Optional[dict]:
        """Re-publish the previous generation's retained tree under a
        NEW, HIGHER generation number.  ``health.record_swap`` refuses a
        backwards generation and the result cache invalidates strictly
        below — the number must keep climbing even though the weights go
        back."""
        if watch is None:
            with self._lock:
                watch = self._watch
        prev = (
            self._router.previous_leaves() if self._is_gateway
            else self._router.previous_weights()
        )
        if prev is None:
            log.error("deploy: rollback requested but no retained history")
            with self._lock:
                self._watch = None
            return None
        prev_gen, tree = prev
        from_gen = self._router.generation
        with self._lock:
            target = max(self._next_gen, from_gen + 1)
            self._next_gen = target + 1
        if self._is_gateway:
            rolled = self._router.swap_weights(
                leaves=tree, generation=target
            )
        else:
            rolled = self._router.swap_weights(tree, generation=target)
        step = watch.get("step") if watch else None
        with self._lock:
            self._watch = None
            if step is not None:
                self._decided[step] = "rolled_back"
            if self._deployed_step == step:
                self._deployed_step = None
        self._m_rollbacks.inc()
        record = {
            "step": step,
            "from_generation": from_gen,
            "to_generation": rolled,
            "restored_generation": prev_gen,
            "slo": None if burn is None else burn.get("slo"),
            "burn_fast": None if burn is None else burn.get("burn_fast"),
        }
        self._record("deploy_rollback", record)
        return record

    # -- the loop ----------------------------------------------------------

    def offer(self, step: int) -> dict:
        """Run one candidate through the full pipeline synchronously.
        Returns the decision record."""
        from ..train import checkpoint
        ok, reason = checkpoint.verify_manifest(self.ckpt_dir, step)
        self._record("deploy_candidate", {
            "step": step, "valid": ok, "reason": reason,
        })
        if not ok:
            self._reject(step, reason, outcome="invalid")
            return {"step": step, "outcome": "invalid", "reason": reason}
        try:
            tree = self._load(step)
        except Exception as e:  # noqa: BLE001 - unrestorable candidate
            self._reject(step, f"restore_failed: {e}", outcome="invalid")
            return {"step": step, "outcome": "invalid",
                    "reason": "restore_failed"}
        manifest = checkpoint.read_manifest(self.ckpt_dir, step)
        if manifest is not None and "tree_crc" in manifest and \
                checkpoint.tree_crc(tree) != manifest["tree_crc"]:
            self._reject(step, "tree_crc_mismatch", outcome="invalid")
            return {"step": step, "outcome": "invalid",
                    "reason": "tree_crc_mismatch"}
        variables = self._variables_of(tree)
        verdict = self._shadow_phase(step, variables)
        self._record("deploy_shadow_verdict", verdict.payload())
        if not verdict.promote:
            self._reject(step, verdict.reason)
            return {"step": step, "outcome": "rejected",
                    "reason": verdict.reason, "verdict": verdict}
        generation = self._promote(step, variables, verdict.generation)
        return {"step": step, "outcome": "promoted",
                "generation": generation, "verdict": verdict}

    def pending_candidates(self) -> list[int]:
        """Undecided steps on disk, oldest first."""
        from ..train import checkpoint
        steps = checkpoint.all_steps(self.ckpt_dir)
        with self._lock:
            decided = set(self._decided)
            deployed = self._deployed_step
        return [
            s for s in steps
            if s not in decided and (deployed is None or s > deployed)
        ]

    def step_once(self) -> list[dict]:
        """One control tick: watch-window check, then every pending
        candidate in order (the chaos/soak drivers call this directly
        for determinism; the background loop calls it on ``poll_s``)."""
        out = []
        rb = self.check_watch()
        if rb is not None:
            out.append({"outcome": "rolled_back", **rb})
        with self._lock:
            busy = self._watch is not None
        if not busy:
            for step in self.pending_candidates():
                out.append(self.offer(step))
                with self._lock:
                    if self._watch is not None:
                        break  # promote armed a watch; candidates wait
        return out

    def start(self, recover: bool = True) -> "Deployer":
        if recover:
            try:
                self.recover()
            except Exception:  # noqa: BLE001 - recovery is best-effort
                log.exception("deploy: journal recovery failed")
        self._thread = threading.Thread(
            target=self._loop, name="ctrl-deploy", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_event.wait(self.poll_s):
            try:
                self.step_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("deploy: control tick failed")

    def stop(self) -> None:
        self._stop_event.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self._router.clear_mirror()

    # -- crash recovery ----------------------------------------------------

    def recover(self, records: Optional[Sequence[dict]] = None) -> dict:
        """Reconstruct decisions from the journal and resolve any
        candidate caught mid-flight.

        * verdict said PROMOTE but no ``deploy_promote`` landed → the
          roll may have died half-way: RESUME it (reload the candidate,
          re-roll under a fresh generation ≥ the recorded one).
        * shadow started but no verdict → the evidence died with the
          process: ABANDON the candidate (journaled as a reject).
        * promote landed, watch window unresolved → re-arm a full watch
          window (conservative: a burn that fired while we were dead
          still triggers rollback via the live engine's next alerts).
        """
        if records is None:
            d = obs.out_dir()
            path = os.path.join(d, "journal.jsonl") if d else None
            records = (
                obs.read_journal(path)
                if path and os.path.exists(path) else []
            )
        per_step: dict[int, dict] = {}
        max_gen = 0
        for rec in records:
            kind = rec.get("kind", "")
            if not kind.startswith("deploy_"):
                continue
            payload = rec.get("payload") or {}
            step = payload.get("step")
            gen = payload.get("generation") or 0
            max_gen = max(max_gen, int(gen), int(
                payload.get("to_generation") or 0
            ))
            if step is None:
                continue
            st = per_step.setdefault(int(step), {})
            st[kind] = payload
            st["last"] = kind
        summary = {"resumed": [], "abandoned": [], "rearmed": [],
                   "decided": []}
        with self._lock:
            self._next_gen = max(self._next_gen, max_gen + 1)
        for step in sorted(per_step):
            st = per_step[step]
            if "deploy_rollback" in st:
                with self._lock:
                    self._decided[step] = "rolled_back"
                summary["decided"].append(step)
                continue
            if "deploy_reject" in st:
                with self._lock:
                    self._decided[step] = "rejected"
                summary["decided"].append(step)
                continue
            if "deploy_promote" in st:
                with self._lock:
                    self._decided[step] = "promoted"
                    self._deployed_step = step
                summary["decided"].append(step)
                # The watch window's elapsed time died with the old
                # process — re-arm a full one.
                promoted_gen = int(st["deploy_promote"].get(
                    "generation", 0
                ))
                if self._router.generation >= promoted_gen and \
                        self.watch_window_s > 0:
                    with self._lock:
                        self._watch = {
                            "step": step, "generation": promoted_gen,
                            "deadline": (
                                self._clock() + self.watch_window_s
                            ),
                            "alerts_seen": (
                                len(self.live_slo.alerts)
                                if self.live_slo is not None else 0
                            ),
                        }
                    summary["rearmed"].append(step)
                continue
            verdict = st.get("deploy_shadow_verdict")
            if verdict is not None and verdict.get("verdict") == "promote":
                # Killed between verdict and a completed roll: resume.
                self._record("deploy_resume", {
                    "step": step, "action": "resume_promote",
                    "generation": verdict.get("generation"),
                })
                try:
                    tree = self._load(step)
                    variables = self._variables_of(tree)
                    self._promote(
                        step, variables,
                        int(verdict.get("generation") or 0),
                    )
                    summary["resumed"].append(step)
                except Exception as e:  # noqa: BLE001 - then reject it
                    log.exception("deploy: resume of step %d failed", step)
                    self._reject(step, f"resume_failed: {e}")
                    summary["abandoned"].append(step)
                continue
            if verdict is not None:
                with self._lock:
                    self._decided[step] = "rejected"
                summary["decided"].append(step)
                continue
            if "deploy_shadow_start" in st:
                # Killed mid-shadow: the mirrored evidence is gone;
                # abandon deterministically (the step stays decided —
                # a re-offer would need a new checkpoint step).
                self._record("deploy_resume", {
                    "step": step, "action": "abandon",
                    "generation": st["deploy_shadow_start"].get(
                        "generation"
                    ),
                })
                self._reject(step, "crash_mid_shadow")
                summary["abandoned"].append(step)
        return summary


def build_deployer(cfg, router, **overrides) -> Deployer:
    """Wire a Deployer from ``cfg.ctrl.deploy`` (tools/soak.py --deploy,
    tools/deploy_watch.py).  Keyword overrides win over config."""
    dc = cfg.ctrl.deploy
    kw = dict(
        poll_s=dc.poll_s,
        mirror_rate=dc.mirror_rate,
        min_mirrored=dc.min_mirrored,
        shadow_window_s=dc.shadow_window_s,
        map_drop=dc.map_drop,
        watch_window_s=dc.watch_window_s,
        slo_fast_s=dc.burn_fast_s,
        slo_slow_s=dc.burn_slow_s,
        slo_burn_factor=dc.burn_factor,
        availability_target=dc.availability_target,
        latency_target=dc.latency_target,
        latency_threshold_s=dc.latency_threshold_s,
    )
    ckpt_dir = overrides.pop("ckpt_dir")
    kw.update(overrides)
    return Deployer(router, ckpt_dir, **kw)
