"""Declarative SLOs + multi-window burn-rate alerting over the obs plane.

An :class:`SLO` names a good-event fraction target over the metrics the
serving stack already exports (obs/metrics.py):

* ``availability`` — completed / (completed + failed + shed) from the
  fleet's ``fleet_requests_total{outcome=...}`` counters.  A shed
  request counts against availability: the fleet refused a user.
* ``latency`` — requests served under ``threshold_s`` as a fraction of
  all served, from the ``serve_request_latency_seconds`` histogram
  buckets, optionally restricted to one degrade level
  (``level="full"``) so "p99 of full-quality responses" is its own SLO.

The :class:`SLOEngine` evaluates them over ``Registry`` snapshots — fed
live (one :meth:`SLOEngine.observe` per control period) or replayed
from a journal's ``metrics_flush`` records (:meth:`SLOEngine.replay`),
so a post-hoc report computes the exact same burn rates the live loop
saw.  Alerting is the SRE multi-window burn-rate rule: page when the
error-budget burn exceeds ``burn_factor`` over BOTH a fast and a slow
window (fast catches the step change, slow filters blips); the alert
clears when the fast window recovers.  Transitions are journaled as
typed ``slo_burn_start`` / ``slo_burn_stop`` events and the remaining
budget is exported as an ``slo_error_budget_remaining{slo=...}`` gauge
on ``/metrics``.

Host-side only — tpulint TPU007 fences ``mx_rcnn_tpu.ctrl`` out of
traced modules exactly like ``mx_rcnn_tpu.obs``.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Optional, Sequence

from mx_rcnn_tpu import obs
from mx_rcnn_tpu.obs.metrics import (
    Registry,
    SnapshotWindow,
    parse_labels,
    percentile_from_counts,
    snapshot_delta,
)

log = logging.getLogger("mx_rcnn_tpu.ctrl")

__all__ = ["SLO", "SLOEngine", "default_slos", "good_total",
           "merged_percentile", "tenant_slos"]

AVAILABILITY_METRIC = "fleet_requests_total"
LATENCY_METRIC = "serve_request_latency_seconds"


@dataclasses.dataclass(frozen=True)
class SLO:
    """One objective: ``target`` fraction of events must be good."""

    name: str
    target: float                       # good fraction in (0, 1)
    kind: str = "availability"          # "availability" | "latency"
    threshold_s: Optional[float] = None  # latency: good = under this
    level: Optional[str] = None          # latency: one degrade level only
    # Tenant-scoped SLO (serve/tenancy.py): only events labeled
    # tenant=<this> count.  The label set is bounded by the configured
    # tenant table, so per-tenant SLOs can't explode either.
    tenant: Optional[str] = None

    def __post_init__(self) -> None:
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1)")
        if self.kind not in ("availability", "latency"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.kind == "latency" and self.threshold_s is None:
            raise ValueError("latency SLO needs threshold_s")


def good_total(slo: SLO, snapshot: dict) -> tuple[float, float]:
    """(good, total) events for ``slo`` in one snapshot — cumulative or
    a :func:`~mx_rcnn_tpu.obs.metrics.snapshot_delta` window (histogram
    summaries carry raw bucket counts either way)."""
    if slo.kind == "availability":
        series = snapshot.get(AVAILABILITY_METRIC, {})
        good = total = 0.0
        for label, v in series.items():
            if isinstance(v, dict):
                continue
            labels = parse_labels(label)
            if slo.tenant is not None and \
                    labels.get("tenant") != slo.tenant:
                continue
            if labels.get("outcome") == "quota":
                # The tenant's own budget talking (a contractual 429 +
                # Retry-After), not the fleet refusing a user: quota
                # rejections burn neither the fleet-wide budget nor the
                # capped tenant's own (docs/autoscaling.md).
                continue
            total += v
            if labels.get("outcome") == "completed":
                good += v
        return good, total
    good = total = 0.0
    for label, summ in snapshot.get(LATENCY_METRIC, {}).items():
        if not isinstance(summ, dict):
            continue
        labels = parse_labels(label)
        if slo.level is not None and labels.get("level") != slo.level:
            continue
        if slo.tenant is not None and labels.get("tenant") != slo.tenant:
            continue
        le = summ.get("le") or []
        counts = summ.get("buckets") or []
        total += summ.get("count", 0)
        good += sum(
            c for b, c in zip(le, counts) if b <= slo.threshold_s
        )
    return good, total


def merged_percentile(
    snapshot: dict, q: float,
    name: str = LATENCY_METRIC,
    level: Optional[str] = None,
) -> Optional[float]:
    """Quantile over a histogram family with all label series merged
    (optionally filtered to one degrade level) — the autoscaler's
    windowed-p99 pressure signal."""
    merged: Optional[list[float]] = None
    le: list[float] = []
    for label, summ in snapshot.get(name, {}).items():
        if not isinstance(summ, dict):
            continue
        if level is not None and parse_labels(label).get("level") != level:
            continue
        counts = summ.get("buckets") or []
        if merged is None:
            merged = [0.0] * len(counts)
            le = summ.get("le") or []
        if len(counts) == len(merged):
            merged = [m + c for m, c in zip(merged, counts)]
    if merged is None:
        return None
    return percentile_from_counts(le, merged, q)


def default_slos(ctrl_cfg) -> tuple[SLO, ...]:
    """The stock pair driven by ``cfg.ctrl``: availability + latency."""
    return (
        SLO("availability", target=ctrl_cfg.availability_target),
        SLO(
            "latency", target=ctrl_cfg.latency_target, kind="latency",
            threshold_s=ctrl_cfg.latency_threshold_s,
        ),
    )


def tenant_slos(ctrl_cfg, tenants: Sequence[str]) -> tuple[SLO, ...]:
    """The :func:`default_slos` pair instantiated per tenant, over the
    tenant-labeled series (serve/tenancy.py).  SLO names embed the
    tenant (``availability[victim]``) so the budget gauge, burn alerts,
    and verdict table attribute blame by name alone."""
    out: list[SLO] = []
    for t in tenants:
        out.append(SLO(
            f"availability[{t}]", target=ctrl_cfg.availability_target,
            tenant=t,
        ))
        out.append(SLO(
            f"latency[{t}]", target=ctrl_cfg.latency_target,
            kind="latency", threshold_s=ctrl_cfg.latency_threshold_s,
            tenant=t,
        ))
    return tuple(out)


class SLOEngine:
    """Evaluate SLOs over snapshots; journal burn alerts; export budget.

    One clock rules the window: pass a consistent ``t`` to
    :meth:`observe` (the built-in loop uses ``time.monotonic``; journal
    replay uses the records' wall ``ts``).  Thread-safe.
    """

    def __init__(
        self,
        slos: Sequence[SLO],
        *,
        registry: Optional[Registry] = None,
        fast_s: float = 300.0,
        slow_s: float = 3600.0,
        burn_factor: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        on_alert: Optional[Callable[[str, SLO, dict], None]] = None,
    ) -> None:
        if fast_s <= 0 or slow_s < fast_s:
            raise ValueError("need 0 < fast_s <= slow_s")
        self.slos = tuple(slos)
        # Alert hook: called as on_alert("start"|"stop", slo, payload)
        # on every burn transition.  serve/tenancy.py::QuotaGovernor
        # attaches here so a tenant-scoped burn tightens only that
        # tenant's quota instead of shedding the fleet.
        self.on_alert = on_alert
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.burn_factor = float(burn_factor)
        self._registry = registry if registry is not None else obs.registry()
        self._clock = clock
        self._window = SnapshotWindow(
            self._registry, horizon_s=self.slow_s * 1.2 + 60.0
        )
        self._lock = threading.Lock()
        self._baseline: Optional[dict] = None
        self._active: dict[str, float] = {}   # slo name -> alert start t
        self._worst: dict[str, float] = {}
        self._states: dict[str, dict] = {}
        self.alerts: list[dict] = []          # start/stop transitions
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- evaluation --------------------------------------------------------

    def _burn(self, slo: SLO, delta: dict) -> tuple[float, float]:
        """(burn rate, total events) over one windowed delta."""
        good, total = good_total(slo, delta)
        if total <= 0:
            return 0.0, 0.0
        bad_frac = (total - good) / total
        return bad_frac / (1.0 - slo.target), total

    def observe(self, t: Optional[float] = None,
                snapshot: Optional[dict] = None) -> dict:
        """One evaluation: record a snapshot, update burn/budget per
        SLO, fire/clear alerts.  Returns {slo name: state dict}."""
        t = self._clock() if t is None else float(t)
        snap = self._window.observe(t, snapshot)
        with self._lock:
            if self._baseline is None:
                self._baseline = snap
            baseline = self._baseline
        cum = snapshot_delta(baseline, snap)
        _, fast = self._window.delta_over(self.fast_s)
        _, slow = self._window.delta_over(self.slow_s)
        states = {}
        for slo in self.slos:
            good, total = good_total(slo, cum)
            bad_frac = (total - good) / total if total > 0 else 0.0
            budget = 1.0 - bad_frac / (1.0 - slo.target)
            burn_fast, n_fast = self._burn(slo, fast)
            burn_slow, _ = self._burn(slo, slow)
            firing = (
                n_fast > 0
                and burn_fast > self.burn_factor
                and burn_slow > self.burn_factor
            )
            with self._lock:
                self._worst[slo.name] = max(
                    self._worst.get(slo.name, 0.0), burn_fast
                )
                active_since = self._active.get(slo.name)
                start = firing and active_since is None
                # Clear on fast-window recovery (the slow window keeps
                # "burning" long after the incident ends — standard
                # multi-window reset).
                stop = (
                    active_since is not None
                    and burn_fast <= self.burn_factor
                )
                if start:
                    self._active[slo.name] = t
                elif stop:
                    del self._active[slo.name]
            if start:
                payload = {
                    "slo": slo.name, "burn_fast": burn_fast,
                    "fast_s": self.fast_s, "burn_slow": burn_slow,
                    "slow_s": self.slow_s, "budget_remaining": budget,
                }
                if slo.tenant is not None:
                    payload["tenant"] = slo.tenant
                obs.emit("ctrl", "slo_burn_start", payload, logger=log)
                obs.counter(
                    "slo_burn_alerts_total", "burn-rate alert starts"
                ).inc(slo=slo.name)
                with self._lock:
                    self.alerts.append(dict(payload, event="start", t=t))
                self._fire_alert("start", slo, payload)
            elif stop:
                payload = {
                    "slo": slo.name, "active_s": t - active_since,
                    "budget_remaining": budget,
                }
                if slo.tenant is not None:
                    payload["tenant"] = slo.tenant
                obs.emit("ctrl", "slo_burn_stop", payload, logger=log)
                with self._lock:
                    self.alerts.append(dict(payload, event="stop", t=t))
                self._fire_alert("stop", slo, payload)
            self._registry.gauge(
                "slo_error_budget_remaining",
                "fraction of the SLO error budget left (negative = "
                "violated)",
            ).set(budget, slo=slo.name)
            states[slo.name] = {
                "good": good, "total": total,
                "budget_remaining": budget,
                "burn_fast": burn_fast, "burn_slow": burn_slow,
                "firing": start or (active_since is not None and not stop),
            }
        with self._lock:
            self._states = states
        return states

    def _fire_alert(self, event: str, slo: SLO, payload: dict) -> None:
        if self.on_alert is None:
            return
        try:
            self.on_alert(event, slo, payload)
        except Exception:  # noqa: BLE001 - a hook must not stop evaluation
            log.exception("slo on_alert hook failed")

    def replay(self, records: Sequence[dict]) -> dict:
        """Feed every ``metrics_flush`` journal record through
        :meth:`observe` (on the records' wall clock) — synthetic-journal
        tests and post-hoc reports use the live code path."""
        states: dict = {}
        for rec in records:
            if rec.get("kind") != "metrics_flush":
                continue
            snap = (rec.get("payload") or {}).get("snapshot")
            if isinstance(snap, dict):
                states = self.observe(t=rec.get("ts", 0.0), snapshot=snap)
        return states

    def verdicts(self) -> list[dict]:
        """Final per-SLO verdicts for the soak's BENCH record: held
        means the whole-run error fraction stayed inside budget."""
        with self._lock:
            states = dict(self._states)
            worst = dict(self._worst)
            alerts = list(self.alerts)
        out = []
        for slo in self.slos:
            st = states.get(slo.name, {})
            budget = st.get("budget_remaining", 1.0)
            out.append({
                "slo": slo.name,
                "kind": slo.kind,
                "target": slo.target,
                "threshold_s": slo.threshold_s,
                "level": slo.level,
                "tenant": slo.tenant,
                "good": st.get("good", 0.0),
                "total": st.get("total", 0.0),
                "budget_remaining": round(budget, 6),
                "worst_burn_fast": round(worst.get(slo.name, 0.0), 3),
                "burn_alerts": sum(
                    1 for a in alerts
                    if a["slo"] == slo.name and a["event"] == "start"
                ),
                "held": budget >= 0.0,
            })
        return out

    # -- loop --------------------------------------------------------------

    def start(self, period_s: float = 1.0) -> "SLOEngine":
        if self._thread is not None:
            return self

        def loop() -> None:
            while not self._stop_event.wait(period_s):
                try:
                    self.observe()
                except Exception:
                    log.exception("slo evaluation failed")

        self._thread = threading.Thread(
            target=loop, name="ctrl-slo", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self.observe()  # final evaluation so verdicts cover the tail
