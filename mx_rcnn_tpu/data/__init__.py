"""Datasets and input pipeline.

Replaces the reference's L2+L4 stack (SURVEY.md §3.4/§3.6):
``rcnn/dataset/`` (IMDB/PascalVOC/coco roidb builders),
``rcnn/utils/load_data.py`` (load/filter/merge roidb),
``rcnn/io/image.py`` (resize/transform/tensor_vstack) and
``rcnn/core/loader.py`` (AnchorLoader/ROIIter DataIters).

Two deliberate departures, both TPU-motivated:
  * images are letterboxed into ONE static canvas per config instead of
    variable short-side shapes — no executor re-binding (there is no
    executor), no shape buckets, one compiled program;
  * anchor labeling is NOT done on host (the reference's assign_anchor in
    the loader) — it runs in-graph in forward_train; the loader only ships
    pixels and padded gt boxes.
"""

from mx_rcnn_tpu.data.batch import Batch
from mx_rcnn_tpu.data.cache import (
    TensorCache,
    quarantine_append,
    quarantine_read,
)
from mx_rcnn_tpu.data.datasets import (
    CocoDataset,
    SyntheticDataset,
    VocDataset,
    build_dataset,
)
from mx_rcnn_tpu.data.loader import DetectionLoader, load_image, load_proposals
from mx_rcnn_tpu.data.roidb import filter_roidb, merge_roidb
from mx_rcnn_tpu.data.service import (
    InputService,
    InputServiceDead,
    InputServiceError,
)
from mx_rcnn_tpu.data.transforms import letterbox, normalize_image

__all__ = [
    "Batch",
    "CocoDataset",
    "DetectionLoader",
    "InputService",
    "InputServiceDead",
    "InputServiceError",
    "SyntheticDataset",
    "TensorCache",
    "VocDataset",
    "build_dataset",
    "filter_roidb",
    "load_image",
    "load_proposals",
    "letterbox",
    "merge_roidb",
    "normalize_image",
    "quarantine_append",
    "quarantine_read",
]
