"""The Batch pytree, defined jax-free.

``Batch`` is the contract between the input pipeline and the jitted
detection graph.  It lives here — not in ``detection/graph.py`` where it
historically sat — so the input-service worker processes
(``data/service.py``) can unpickle batches without importing the model
stack (flax, optax, the Pallas kernels): a spawn worker pays the jax
import (``mx_rcnn_tpu/__init__`` needs it for the threefry flag) but
never traces, never initializes a backend, and never loads the detector.
``detection/graph.py`` re-exports the class, so every historical import
path keeps working and pickles exchange freely between parent and
workers.

Fields are numpy arrays on the host side; ``device_prefetch`` /
``shard_batch`` turn them into device arrays without changing the
structure (NamedTuple = pytree).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional


class Batch(NamedTuple):
    """One statically-shaped training/eval batch (data/ produces these)."""

    # (B, H, W, 3): uint8 raw letterboxed pixels (default — normalized
    # in-graph, see graph.py::prep_images) or float32 already
    # host-normalized (synthetic in-memory data, data.normalize_on_host).
    images: Any
    image_hw: Any     # (B, 2) float32 true (unpadded) height, width
    gt_boxes: Any     # (B, G, 4)
    gt_classes: Any   # (B, G) int32, 0 = background/padding
    gt_valid: Any     # (B, G) bool
    gt_masks: Optional[Any] = None  # (B, G, Hm, Wm) float32 in [0,1]
    # COCO crowd / VOC difficult regions: never fg, and anchors/rois covering
    # them are excluded from bg sampling.  Disjoint from gt_valid slots.
    gt_ignore: Optional[Any] = None  # (B, G) bool
    # Externally supplied proposals in letterboxed-image coords, score-desc,
    # padded (Fast R-CNN mode — the reference's ROIIter/train_rcnn path,
    # ``rcnn/core/loader.py::ROIIter``).  None = in-graph RPN proposals.
    ext_rois: Optional[Any] = None   # (B, R, 4)
    ext_valid: Optional[Any] = None  # (B, R) bool
