"""Checksummed tensor cache + crash-safe quarantine records.

Two host-side durability primitives for the input pipeline
(docs/robustness.md "Input service"):

**Quarantine journal** — ``quarantine_append`` writes ONE ``O_APPEND``
``os.write`` per record, so a record is either fully present or absent:
concurrent writers (loader threads, service workers via their own loader,
the cache layer) interleave at line granularity and a crash mid-append
can leave at most one torn final line, which ``quarantine_read``
tolerates (skips unparseable lines instead of dying on them).  Records
carry a wall-clock + monotonic timestamp and a ``reason`` category
(``io`` | ``annotation`` | ``cache_checksum`` | ``cache_truncated``) so
chaos scenarios can assert on journal contents.

**TensorCache** — memoizes decoded+letterboxed pixel tensors on disk
(optionally staged through a RAM LRU) keyed like the compile-cache
fingerprints (utils/compile_cache.py): the key hashes the record's
source identity (path+size+mtime, or the pixel bytes for in-memory
synthetic arrays), the flip flag, and the transform fingerprint (canvas
/ short / max / normalization), so a config change or a re-decoded file
can never alias a stale entry.  Every blob carries a CRC32 of its
payload and is written atomically (tmp + ``os.replace``).  Integrity
contract: a corrupt or truncated blob is **detected, quarantined to the
journal, deleted, and rebuilt from source — never served**; the
``cache_corrupt`` chaos scenario proves the end-to-end run is bitwise
identical to a cache-less one.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import tempfile
import threading
import time
import zlib
from collections import OrderedDict
from typing import Optional

import numpy as np

from mx_rcnn_tpu import obs

log = logging.getLogger("mx_rcnn_tpu")

# -- quarantine journal -------------------------------------------------------


def quarantine_append(path: str, record: dict) -> None:
    """Append one JSON record crash-safely.

    A single ``write(2)`` on an ``O_APPEND`` fd is atomic with respect to
    other appenders for this size class, and a crash mid-call tears at
    most this one line — earlier records are never damaged (contrast the
    old buffered ``open(path, "a").write`` which could flush half-lines).
    Timestamps: ``ts`` (epoch seconds, human/cross-run) and ``ts_mono_ns``
    (monotonic, for in-run ordering asserts — never goes backwards when
    the wall clock steps).
    """
    rec = dict(record)
    rec.setdefault("ts", round(time.time(), 3))
    rec.setdefault("ts_mono_ns", time.monotonic_ns())
    line = (json.dumps(rec) + "\n").encode()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line)
    finally:
        os.close(fd)


def quarantine_read(path: str) -> list[dict]:
    """All parseable records; a torn (crash-truncated) trailing line or a
    corrupt interior line is skipped, not fatal."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path, "rb") as f:
        for line in f:
            try:
                out.append(json.loads(line.decode("utf-8", "replace")))
            except ValueError:
                continue
    return out


# -- tensor cache -------------------------------------------------------------

# Blob layout: MAGIC, u32 header length, JSON header, raw payload bytes.
# The header carries dtype/shape to rebuild the array and crc32/nbytes to
# validate the payload before anything is served.
_MAGIC = b"MXTC1\n"
_VERSION = 1


def transform_fingerprint(cfg) -> str:
    """Hash of every knob that changes cached pixel bytes (DataConfig).

    Same doctrine as compile_cache: the fingerprint IS the namespace, so
    changing the letterbox geometry or normalization can never serve a
    stale tensor — it lands in a different cache directory.
    """
    sig = {
        "v": _VERSION,
        "image_size": list(cfg.image_size),
        "short_side": cfg.short_side,
        "max_side": cfg.max_side,
        "normalize_on_host": bool(cfg.normalize_on_host),
        "pixel_mean": list(cfg.pixel_mean),
        "pixel_std": list(cfg.pixel_std),
    }
    return hashlib.sha1(
        json.dumps(sig, sort_keys=True).encode()
    ).hexdigest()[:16]


def record_source_signature(rec) -> str:
    """Identity of a record's SOURCE pixels.

    On-disk images: path + size + mtime_ns (a re-decoded/replaced file
    invalidates naturally).  In-memory arrays (synthetic datasets): CRC of
    the raw bytes — content-addressed, stable across runs of the same
    deterministic generator.
    """
    if rec.image_array is not None:
        arr = np.ascontiguousarray(rec.image_array)
        return f"mem:{arr.dtype}:{arr.shape}:{zlib.crc32(arr.view(np.uint8).ravel())}"
    try:
        st = os.stat(rec.image_path)
        return f"file:{rec.image_path}:{st.st_size}:{st.st_mtime_ns}"
    except OSError:
        # Unreadable now — key on the path alone; the load itself will
        # fail and quarantine, nothing gets cached for this record.
        return f"file:{rec.image_path}:?"


class TensorCache:
    """RAM+disk cache of decoded+letterboxed pixel tensors.

    ``get`` returns ``(pixels, th, tw)`` or None (miss OR quarantined
    corruption — callers rebuild from source either way and ``put`` the
    result back).  Returned arrays are marked read-only: entries are
    shared across batches, and ``np.stack`` in assembly copies them into
    each batch anyway.
    """

    def __init__(
        self,
        root: str,
        cfg,
        ram_bytes: int = 256 << 20,
        quarantine_path: Optional[str] = None,
    ) -> None:
        self.dir = os.path.join(root, "tensors", transform_fingerprint(cfg))
        os.makedirs(self.dir, exist_ok=True)
        self.quarantine_path = quarantine_path
        self._ram_budget = max(int(ram_bytes), 0)
        self._ram: OrderedDict[str, tuple] = OrderedDict()
        self._ram_bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.corrupt = 0

    # -- keys --------------------------------------------------------------

    def key(self, rec, flip: bool) -> str:
        raw = f"{rec.image_id}|{record_source_signature(rec)}|flip={int(flip)}"
        return hashlib.sha1(raw.encode()).hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.blob")

    # -- blob io -----------------------------------------------------------

    @staticmethod
    def _encode(pixels: np.ndarray, th: int, tw: int) -> bytes:
        arr = np.ascontiguousarray(pixels)
        payload = arr.tobytes()
        header = json.dumps({
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "th": int(th),
            "tw": int(tw),
            "crc32": zlib.crc32(payload),
            "nbytes": len(payload),
        }).encode()
        return _MAGIC + struct.pack("<I", len(header)) + header + payload

    @staticmethod
    def _decode(blob: bytes) -> tuple:
        """(pixels, th, tw) or raises ValueError(category-prefixed)."""
        if len(blob) < len(_MAGIC) + 4 or not blob.startswith(_MAGIC):
            raise ValueError("cache_truncated: bad magic/short blob")
        off = len(_MAGIC)
        (hlen,) = struct.unpack_from("<I", blob, off)
        off += 4
        if len(blob) < off + hlen:
            raise ValueError("cache_truncated: header clipped")
        try:
            header = json.loads(blob[off:off + hlen])
        except ValueError as e:
            raise ValueError(f"cache_truncated: header unparseable ({e})")
        payload = blob[off + hlen:]
        if len(payload) != header["nbytes"]:
            raise ValueError(
                f"cache_truncated: payload {len(payload)} != "
                f"{header['nbytes']} bytes"
            )
        if zlib.crc32(payload) != header["crc32"]:
            raise ValueError("cache_checksum: payload crc mismatch")
        arr = np.frombuffer(payload, dtype=np.dtype(header["dtype"]))
        arr = arr.reshape(header["shape"])  # frombuffer views are read-only
        return arr, header["th"], header["tw"]

    # -- public api --------------------------------------------------------

    def get(self, key: str, image_id: str = "?"):
        with self._lock:
            hit = self._ram.get(key)
            if hit is not None:
                self._ram.move_to_end(key)
                self.hits += 1
                return hit
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            self.misses += 1
            return None
        try:
            value = self._decode(blob)
        except ValueError as e:
            self._quarantine_blob(key, image_id, e)
            return None
        self.hits += 1
        self._ram_put(key, value)
        return value

    def put(self, key: str, pixels: np.ndarray, th: int, tw: int) -> None:
        arr = np.ascontiguousarray(pixels)
        arr.flags.writeable = False
        value = (arr, int(th), int(tw))
        self._ram_put(key, value)
        path = self._path(key)
        blob = self._encode(arr, th, tw)
        # Atomic publish: a reader sees the old blob, the new blob, or no
        # blob — never a half-written one (a torn write would in any case
        # be caught by the crc and rebuilt, but why make readers pay).
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # -- internals ---------------------------------------------------------

    def _ram_put(self, key: str, value: tuple) -> None:
        if not self._ram_budget:
            return
        arr = value[0]
        with self._lock:
            if key in self._ram:
                self._ram.move_to_end(key)
                return
            self._ram[key] = value
            self._ram_bytes += arr.nbytes
            while self._ram_bytes > self._ram_budget and len(self._ram) > 1:
                _, (old, _, _) = self._ram.popitem(last=False)
                self._ram_bytes -= old.nbytes

    def _quarantine_blob(
        self, key: str, image_id: str, error: ValueError
    ) -> None:
        """Corrupt blob: journal it, delete it, let the caller rebuild.
        The blob is NEVER served — detection happens before any bytes
        reach assembly."""
        self.corrupt += 1
        reason = str(error).split(":", 1)[0]
        if reason not in ("cache_checksum", "cache_truncated"):
            reason = "cache_checksum"
        path = self._path(key)
        obs.emit("data", "cache_quarantine", {
            "image_id": image_id, "error": str(error), "path": path,
            "reason": reason,
        }, logger=log)
        obs.counter(
            "cache_quarantines_total", "corrupt tensor blobs quarantined"
        ).inc(reason=reason)
        if self.quarantine_path:
            quarantine_append(self.quarantine_path, {
                "image_id": image_id,
                "path": path,
                "reason": reason,
                "error": f"{type(error).__name__}: {error}",
                "retries": 0,
            })
        try:
            os.unlink(path)
        except OSError:
            pass
