"""Dataset readers producing roidb records.

Replaces ``rcnn/dataset/pascal_voc.py`` (XML parsing → gt_roidb),
``rcnn/dataset/coco.py`` (pycocotools-backed roidb with the 80↔91 category
id mapping) and adds a synthetic dataset for hermetic tests/benchmarks (the
reference has no equivalent — its only test was retraining on real data,
SURVEY.md §5).

No pycocotools dependency: COCO annotation JSON is indexed directly (the
eval side has its own mAP implementation in ``evalutil``).
"""

from __future__ import annotations

import json
import os
import xml.etree.ElementTree as ET
from typing import Optional, Sequence

import numpy as np

from mx_rcnn_tpu.config import DataConfig
from mx_rcnn_tpu.data.roidb import RoiRecord

VOC_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


class SyntheticDataset:
    """Deterministic images with geometric objects on noise background.

    Class c ∈ 1..num_classes-1 is a filled axis-aligned shape with a
    class-specific intensity pattern, so a detector can genuinely learn it —
    used by the overfit integration test (SURVEY.md §5(c)) and by bench.py
    (no dataset download in this environment).
    """

    name = "synthetic"

    def __init__(
        self,
        num_images: int = 64,
        image_hw: tuple[int, int] = (128, 128),
        num_classes: int = 5,
        max_objects: int = 4,
        seed: int = 0,
        dtype: str = "float32",
        palette: str = "classic",
    ) -> None:
        """``dtype="uint8"`` rounds the rendered pixels to uint8 — the
        loader then ships them raw and normalizes in-graph, exactly like a
        disk-backed dataset (float32 keeps the historical golden pixels).

        ``palette`` picks the class appearance model.  "classic" is the
        historical linear color ramp — bit-stable (the overfit goldens
        were recorded on it) but saturating above class ~8, so an
        80-class set is mostly indistinguishable.  "wheel" assigns every
        class a distinct golden-ratio hue plus a (stripe period,
        orientation, value-band) texture combo, all in-gamut — use it for
        many-class runs (tools/soak.py) where absolute AP should measure
        the DETECTOR, not the renderer's color collisions."""
        if palette not in ("classic", "wheel"):
            raise ValueError(f"palette must be 'classic' or 'wheel', got {palette!r}")
        self.num_images = num_images
        self.image_hw = image_hw
        self.num_classes = num_classes  # incl. background 0
        self.max_objects = max_objects
        self.seed = seed
        self.dtype = dtype
        self.palette = palette
        self.classes = ("__background__",) + tuple(
            f"shape{c}" for c in range(1, num_classes)
        )

    @staticmethod
    def class_style(cls: int) -> tuple[np.ndarray, int, int]:
        """Deterministic distinct (color, stripe period, orientation) for
        the "wheel" palette.  Hue walks the golden-ratio sequence (low
        discrepancy — 80 classes stay well separated on the wheel); the
        texture tuple (period 3..8, orientation of 4, value band of 2)
        is injective over 48 classes, so any hue near-collision still
        differs in texture."""
        import colorsys

        hue = (cls * 0.61803398875) % 1.0
        sat = 0.6 + 0.35 * (cls % 2)
        val = (160.0 + 80.0 * ((cls // 24) % 2)) / 255.0
        r, g, b = colorsys.hsv_to_rgb(hue, sat, val)
        color = np.asarray([r, g, b], np.float32) * 255.0
        period = 3 + cls % 6
        orient = (cls // 6) % 4
        return color, period, orient

    def _render(self, idx: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        rng = np.random.RandomState(self.seed * 100003 + idx)
        h, w = self.image_hw
        img = rng.uniform(0, 40, size=(h, w, 3)).astype(np.float32)
        n = rng.randint(1, self.max_objects + 1)
        boxes, classes = [], []
        for _ in range(n):
            cls = rng.randint(1, self.num_classes)
            bw = rng.randint(h // 8, h // 2)
            bh = rng.randint(h // 8, h // 2)
            x1 = rng.randint(0, w - bw)
            y1 = rng.randint(0, h - bh)
            # Class-specific color + texture: stripes along an axis whose
            # period encodes the class.
            yy, xx = np.mgrid[y1 : y1 + bh, x1 : x1 + bw]
            if self.palette == "wheel":
                color, period, orient = self.class_style(cls)
                coord = (xx, yy, xx + yy, xx - yy)[orient]
                stripe = ((coord // period) % 2).astype(np.float32)
                img[y1 : y1 + bh, x1 : x1 + bw] = (
                    color * (0.55 + 0.45 * stripe[..., None])
                )
            else:
                stripe = ((xx // (cls + 1) + yy // (cls + 1)) % 2).astype(
                    np.float32
                )
                color = np.array(
                    [80 + 40 * cls, 255 - 35 * cls, 120 + 25 * (cls % 3)],
                    np.float32,
                )
                img[y1 : y1 + bh, x1 : x1 + bw] = (
                    color * (0.6 + 0.4 * stripe[..., None])
                )
            boxes.append([x1, y1, x1 + bw - 1, y1 + bh - 1])
            classes.append(cls)
        if self.dtype == "uint8":
            img = np.clip(np.round(img), 0, 255).astype(np.uint8)
        return img, np.asarray(boxes, np.float32), np.asarray(classes, np.int32)

    def roidb(self) -> list[RoiRecord]:
        out = []
        h, w = self.image_hw
        for i in range(self.num_images):
            img, boxes, classes = self._render(i)
            # Instance masks: an octagon inset in each box (mask != box, so
            # mask-head tests get real signal, COCO polygon format).
            masks = []
            for (x1, y1, x2, y2) in boxes:
                bw, bh = x2 - x1, y2 - y1
                cx, cy = (x1 + x2) / 2, (y1 + y2) / 2
                poly = []
                for dx, dy in ((-.5, -.25), (-.25, -.5), (.25, -.5), (.5, -.25),
                               (.5, .25), (.25, .5), (-.25, .5), (-.5, .25)):
                    poly += [cx + dx * bw, cy + dy * bh]
                masks.append([poly])
            out.append(
                RoiRecord(
                    image_id=str(i),
                    image_path="",
                    height=h,
                    width=w,
                    boxes=boxes,
                    gt_classes=classes,
                    masks=masks,
                    image_array=img,
                )
            )
        return out


class CocoDataset:
    """COCO detection annotations without pycocotools.

    Builds the contiguous-id mapping (91 sparse category ids → 1..80) the
    same way ``rcnn/dataset/coco.py`` does via pycocotools, and keeps
    segmentation polygons/RLE for the mask head.
    """

    name = "coco"

    def __init__(self, root: str, split: str = "train2017") -> None:
        self.root = root
        self.split = split
        ann = os.path.join(root, "annotations", f"instances_{split}.json")
        with open(ann) as f:
            d = json.load(f)
        cats = sorted(d["categories"], key=lambda c: c["id"])
        self.classes = ("__background__",) + tuple(c["name"] for c in cats)
        self.cat_to_label = {c["id"]: i + 1 for i, c in enumerate(cats)}
        self.label_to_cat = {v: k for k, v in self.cat_to_label.items()}
        self._images = {im["id"]: im for im in d["images"]}
        self._anns: dict[int, list] = {}
        for a in d["annotations"]:
            self._anns.setdefault(a["image_id"], []).append(a)

    def roidb(self) -> list[RoiRecord]:
        out = []
        for img_id, im in self._images.items():
            # Crowd annotations are KEPT and flagged (the reference drops
            # them — ``rcnn/dataset/coco.py`` skips iscrowd — silently
            # training anchors inside crowds as negatives and scoring
            # crowd-overlapping detections as false positives).  Non-crowd
            # first so gt-slot truncation sheds crowds before real objects.
            anns = sorted(
                self._anns.get(img_id, []), key=lambda a: bool(a.get("iscrowd", 0))
            )
            boxes, classes, masks, crowd = [], [], [], []
            for a in anns:
                x, y, bw, bh = a["bbox"]
                x2, y2 = x + max(bw - 1, 0), y + max(bh - 1, 0)
                if bw < 1 or bh < 1:
                    continue
                boxes.append([x, y, x2, y2])
                classes.append(self.cat_to_label[a["category_id"]])
                masks.append(a.get("segmentation"))
                crowd.append(bool(a.get("iscrowd", 0)))
            out.append(
                RoiRecord(
                    image_id=str(img_id),
                    image_path=os.path.join(
                        self.root, self.split, im["file_name"]
                    ),
                    height=im["height"],
                    width=im["width"],
                    boxes=np.asarray(boxes, np.float32).reshape(-1, 4),
                    gt_classes=np.asarray(classes, np.int32),
                    masks=masks or None,
                    ignore=np.asarray(crowd, bool),
                )
            )
        return out


class VocDataset:
    """PASCAL VOC (reference: ``rcnn/dataset/pascal_voc.py``).

    ``split`` is "<year>_<imageset>" e.g. "2007_trainval"; expects the
    standard VOCdevkit layout under ``root``.
    """

    name = "voc"

    def __init__(
        self, root: str, split: str = "2007_trainval", use_diff: bool = False
    ) -> None:
        self.root = root
        year, imageset = split.split("_")
        self.year, self.imageset = year, imageset
        self.devkit = os.path.join(root, f"VOC{year}")
        self.use_diff = use_diff
        self.classes = ("__background__",) + VOC_CLASSES
        self._cls_index = {c: i for i, c in enumerate(self.classes)}
        index_file = os.path.join(
            self.devkit, "ImageSets", "Main", f"{imageset}.txt"
        )
        with open(index_file) as f:
            self.image_index = [line.strip() for line in f if line.strip()]

    def _parse(self, idx: str) -> RoiRecord:
        tree = ET.parse(os.path.join(self.devkit, "Annotations", f"{idx}.xml"))
        size = tree.find("size")
        h = int(size.find("height").text)
        w = int(size.find("width").text)
        # Difficult objects are KEPT and flagged (unless use_diff promotes
        # them to normal gt): training excludes them from negatives, and
        # ``voc_eval``'s difficult-ignore matching needs them present in the
        # gt — the reference keeps them for eval via the raw XML
        # (``rcnn/dataset/pascal_voc_eval.py::voc_eval``) while its roidb
        # drops them; one flagged roidb serves both here.  Non-difficult
        # first so gt-slot truncation sheds them before real objects.
        objs = []
        for obj in tree.findall("object"):
            name = obj.find("name").text.lower().strip()
            if name not in self._cls_index:
                continue
            difficult = bool(int(obj.find("difficult").text or 0))
            objs.append((difficult and not self.use_diff, name, obj))
        objs.sort(key=lambda t: t[0])
        boxes, classes, ignore = [], [], []
        for ign, name, obj in objs:
            bb = obj.find("bndbox")
            # VOC is 1-based pixel coords.
            boxes.append(
                [
                    float(bb.find("xmin").text) - 1,
                    float(bb.find("ymin").text) - 1,
                    float(bb.find("xmax").text) - 1,
                    float(bb.find("ymax").text) - 1,
                ]
            )
            classes.append(self._cls_index[name])
            ignore.append(ign)
        return RoiRecord(
            image_id=idx,
            image_path=os.path.join(self.devkit, "JPEGImages", f"{idx}.jpg"),
            height=h,
            width=w,
            boxes=np.asarray(boxes, np.float32).reshape(-1, 4),
            gt_classes=np.asarray(classes, np.int32),
            ignore=np.asarray(ignore, bool),
        )

    def roidb(self) -> list[RoiRecord]:
        return [self._parse(i) for i in self.image_index]


# Bump when roidb PARSING changes (crowd ordering, box conventions, new
# RoiRecord fields): the fingerprint only sees the annotation files, so a
# parser fix must invalidate existing caches itself.
_CACHE_VERSION = 2


class _CachedRoidb:
    """Lazy parsed-roidb pickle cache (reference:
    ``rcnn/dataset/imdb.py::gt_roidb`` caches
    ``data/cache/<name>_gt_roidb.pkl``).  On a cache hit the underlying
    dataset is never constructed — the win is skipping the multi-hundred-MB
    COCO annotation json parse, which happens in the constructor.  Entries
    are keyed by the annotation source's mtime, so edited annotations
    re-parse.  Attribute access (``classes`` etc.) constructs on demand."""

    def __init__(self, factory, name: str, cache_dir: str, split: str,
                 root: str, fingerprint) -> None:
        self._factory = factory
        self._name = name
        self._cache_dir = cache_dir
        self._split = split
        self._root = root
        self._fingerprint = fingerprint  # () -> Optional[str]
        self._ds = None

    def _dataset(self):
        if self._ds is None:
            self._ds = self._factory()
        return self._ds

    def __getattr__(self, name):
        return getattr(self._dataset(), name)

    def roidb(self) -> list[RoiRecord]:
        import hashlib
        import pickle

        fp = self._fingerprint()
        if fp is None:
            return self._dataset().roidb()
        # Key carries the dataset ROOT too: a relocated/second dataset copy
        # must not hit a cache whose RoiRecord.image_path points elsewhere.
        key = hashlib.sha1(
            f"v{_CACHE_VERSION}|{os.path.abspath(self._root)}|{fp}".encode()
        ).hexdigest()[:16]
        path = os.path.join(
            self._cache_dir,
            f"{self._name}_{self._split}_{key}_gt_roidb.pkl",
        )
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    return pickle.load(f)
            except Exception:
                # Corrupt or stale-format entry: self-heal by re-parsing
                # (the rewrite below replaces the poisoned file).
                pass
        roidb = self._dataset().roidb()
        os.makedirs(self._cache_dir, exist_ok=True)
        # Unique tmp per writer: concurrent multi-host startups over a
        # shared cache_dir must not interleave into one file (pids collide
        # across containers, so a uuid, not getpid).
        import uuid

        tmp = f"{path}.{uuid.uuid4().hex}.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(roidb, f)
        os.replace(tmp, path)
        return roidb


def _mtime_fingerprint(path: str):
    """mtime_ns+size of one file, or None if unreadable (→ cache bypass).
    Nanosecond mtime plus size closes the same-second-edit window and the
    replaced-with-older-copy case a bare integer-second mtime misses."""
    try:
        st = os.stat(path)
        return f"{st.st_mtime_ns}:{st.st_size}"
    except OSError:
        return None


def _voc_fingerprint(devkit: str, index_file: str):
    """ImageSets txt mtime + the NEWEST Annotations xml mtime (plus file
    count and total size): editing any annotation invalidates (a
    directory's own mtime only changes on add/remove, not edits); the
    count/size terms catch an annotation replaced with an older copy,
    which a max-mtime alone would miss."""
    base = _mtime_fingerprint(index_file)
    if base is None:
        return None
    newest = count = total = 0
    try:
        with os.scandir(os.path.join(devkit, "Annotations")) as it:
            for e in it:
                if e.name.endswith(".xml"):
                    st = e.stat()
                    newest = max(newest, st.st_mtime_ns)
                    count += 1
                    total += st.st_size
    except OSError:
        return None
    return f"{base}|{newest}:{count}:{total}"


def build_dataset(cfg: DataConfig, split: Optional[str] = None, train: bool = True):
    split = split or (cfg.train_split if train else cfg.val_split)
    if cfg.dataset == "synthetic":
        return SyntheticDataset(image_hw=cfg.image_size)
    if cfg.dataset == "coco":
        factory = lambda: CocoDataset(cfg.root, split)  # noqa: E731
        name = "coco"
        ann = os.path.join(cfg.root, "annotations", f"instances_{split}.json")
        fingerprint = lambda: _mtime_fingerprint(ann)  # noqa: E731
    elif cfg.dataset == "voc":
        factory = lambda: VocDataset(  # noqa: E731
            cfg.root, split, use_diff=cfg.use_diff
        )
        name = "voc"
        year, imageset = split.split("_")
        devkit = os.path.join(cfg.root, f"VOC{year}")
        index = os.path.join(devkit, "ImageSets", "Main", f"{imageset}.txt")
        # use_diff changes the PARSE (difficult promoted to real gt), so it
        # must key the roidb cache alongside the annotation fingerprint.
        fingerprint = lambda: (  # noqa: E731
            None
            if (fp := _voc_fingerprint(devkit, index)) is None
            else f"{fp}|diff{int(cfg.use_diff)}"
        )
    else:
        raise ValueError(f"unknown dataset {cfg.dataset!r}")
    if cfg.cache_dir:
        return _CachedRoidb(
            factory, name, cfg.cache_dir, split, cfg.root, fingerprint
        )
    return factory()
