"""Batch assembly: roidb → statically-shaped Batch pytrees.

Replaces ``rcnn/core/loader.py::AnchorLoader`` minus the anchor labeling
(in-graph now).  Keeps the reference's load-time behaviors: epoch shuffle,
aspect-ratio grouping (``ASPECT_GROUPING`` — portrait/landscape batched
together so letterbox padding is minimized), flip augmentation, and
per-host sharding for data parallelism — every host derives the SAME
global batch schedule from the full roidb and decodes only its rank's
rows of each global batch (lockstep by construction; the reference
instead slices batches across ``ctx`` GPUs inside one process).  A
one-deep background prefetch thread overlaps host decode with device
compute (the reference relied on MXNet's threaded DataIter for the same).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Iterable, Iterator, Optional

import numpy as np

from mx_rcnn_tpu.config import DataConfig
from mx_rcnn_tpu.data.batch import Batch
from mx_rcnn_tpu.data.cache import TensorCache, quarantine_append
from mx_rcnn_tpu.data.roidb import RoiRecord
from mx_rcnn_tpu.data.transforms import (
    flip_boxes,
    letterbox,
    letterbox_uint8,
    normalize_image,
    oriented_canvas,
    resize_scale,
)

try:
    import cv2
except Exception:  # pragma: no cover
    cv2 = None

log = logging.getLogger("mx_rcnn_tpu")

# tools/chaos.py fault hook: comma-separated GLOBAL batch indices whose
# images are replaced with NaN before yielding (training only) — exercises
# the guardian's detect/rollback path end-to-end without touching the
# model or the schedule.
CHAOS_NAN_ENV = "MX_RCNN_CHAOS_NAN_STEPS"

# tools/chaos.py fault hook: comma-separated image_ids whose pixel load
# RAISES (as a corrupt/unreadable file would) — drives the retry +
# quarantine + blank-substitution path against real loaders, including
# in-memory synthetic records that can't otherwise fail.  Active for
# training AND eval (the eval_corrupt chaos scenario).
CHAOS_BAD_IMAGES_ENV = "MX_RCNN_CHAOS_BAD_IMAGES"

# Box-relative resolution at which gt instance masks are rasterized on host;
# the device crops these to the mask head's target size per sampled roi.
GT_MASK_SIZE = 112


def load_proposals(path: str) -> dict:
    """Load and validate a proposal pkl (``test.py --proposals`` format:
    image_id → {"boxes": (n, 4) original-image coords, "scores": (n,)}).
    Fails fast on schema problems instead of mid-epoch in the loader."""
    import pickle

    with open(path, "rb") as f:
        props = pickle.load(f)
    if not isinstance(props, dict) or not props:
        raise ValueError(f"{path}: expected a non-empty image_id->dict map")
    for key, p in props.items():
        boxes = np.asarray(p.get("boxes", None))
        scores = np.asarray(p.get("scores", None))
        if boxes.ndim != 2 or boxes.shape[1] != 4 or scores.shape != boxes.shape[:1]:
            raise ValueError(
                f"{path}: image {key!r} needs boxes (n, 4) + scores (n,), "
                f"got {boxes.shape} / {scores.shape}"
            )
        break  # spot-check one entry; full arrays validate lazily per image
    return props


def annotation_error(rec: RoiRecord, num_classes: Optional[int] = None) -> Optional[str]:
    """Why this record's annotations are unusable, or None if they're fine.

    Mirrors the image-quarantine contract for the OTHER way a dataset rots
    in place: a truncated/corrupt annotation record (malformed box arrays,
    non-finite or inverted coordinates, out-of-range class ids) used to
    crash mid-epoch deep inside ``_example``; now it is detected up front
    and the record is quarantined + blank-substituted instead.
    """
    boxes = np.asarray(rec.boxes)
    if boxes.ndim != 2 or boxes.shape[1] != 4:
        return f"boxes shape {boxes.shape} is not (n, 4)"
    if boxes.dtype.kind not in "fiu" or not np.isfinite(
        boxes.astype(np.float64, copy=False)
    ).all():
        return "non-finite or non-numeric box coordinates"
    if (boxes[:, 2] < boxes[:, 0]).any() or (boxes[:, 3] < boxes[:, 1]).any():
        return "inverted box (x2 < x1 or y2 < y1)"
    cls = np.asarray(rec.gt_classes)
    if cls.shape != (len(boxes),):
        return f"gt_classes shape {cls.shape} does not match {len(boxes)} boxes"
    if len(cls) and cls.min() < 1:
        return "class id < 1 (foreground labels are 1-based)"
    if num_classes is not None and len(cls) and cls.max() >= num_classes:
        return f"class id {int(cls.max())} >= num_classes {num_classes}"
    if rec.ignore is not None and np.asarray(rec.ignore).shape != (len(boxes),):
        return "ignore flags do not match the box count"
    return None


def load_image(rec: RoiRecord) -> np.ndarray:
    """uint8 RGB from disk (float32 for in-memory synthetic images)."""
    if rec.image_array is not None:
        return rec.image_array
    if cv2 is None:  # pragma: no cover
        from PIL import Image

        return np.asarray(Image.open(rec.image_path).convert("RGB"), np.uint8)
    img = cv2.imread(rec.image_path, cv2.IMREAD_COLOR)
    if img is None:
        raise FileNotFoundError(rec.image_path)
    return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)


def _rasterize_mask(seg, box: np.ndarray) -> np.ndarray:
    """Polygon/RLE segmentation → (GT_MASK_SIZE,)*2 box-relative float mask."""
    out = np.zeros((GT_MASK_SIZE, GT_MASK_SIZE), np.float32)
    if seg is None or cv2 is None:
        return out
    x1, y1, x2, y2 = box
    bw, bh = max(x2 - x1 + 1, 1.0), max(y2 - y1 + 1, 1.0)
    if isinstance(seg, list):  # polygons in image coords
        polys = []
        for p in seg:
            pts = np.asarray(p, np.float32).reshape(-1, 2)
            pts[:, 0] = (pts[:, 0] - x1) / bw * GT_MASK_SIZE
            pts[:, 1] = (pts[:, 1] - y1) / bh * GT_MASK_SIZE
            polys.append(pts.round().astype(np.int32))
        cv2.fillPoly(out, polys, 1.0)
    elif isinstance(seg, dict):  # uncompressed RLE {"counts": [...], "size": [h, w]}
        h, w = seg["size"]
        counts = seg["counts"]
        if isinstance(counts, list):
            flat = np.zeros(h * w, np.uint8)
            pos, val = 0, 0
            for c in counts:
                flat[pos : pos + c] = val
                pos += c
                val = 1 - val
            full = flat.reshape((w, h)).T.astype(np.float32)
            crop = full[
                int(max(y1, 0)) : int(y2) + 1, int(max(x1, 0)) : int(x2) + 1
            ]
            if crop.size:
                out = cv2.resize(crop, (GT_MASK_SIZE, GT_MASK_SIZE))
    return out


class DetectionLoader:
    """Iterable over statically-shaped Batches.

    train=True: infinite, shuffled per epoch, flip augmentation.
    train=False: one pass in roidb order, no flip, yields (batch, records)
    so eval can map detections back to image ids and scales.
    """

    def __init__(
        self,
        roidb: list[RoiRecord],
        cfg: DataConfig,
        batch_size: int,
        train: bool = True,
        seed: int = 0,
        rank: int = 0,
        world: int = 1,
        with_masks: bool = False,
        prefetch: bool = True,
        num_workers: Optional[int] = None,
        proposals: Optional[dict] = None,
        num_proposals: int = 1000,
        run_length: int = 1,
        quarantine_path: Optional[str] = None,
        io_retries: int = 2,
        num_classes: Optional[int] = None,
        service_workers: Optional[int] = None,
        worker_respawns: Optional[int] = None,
        quarantine_announced: Optional[Iterable[str]] = None,
    ) -> None:
        """``proposals``: image_id → {"boxes": (n, 4) ORIGINAL-image coords,
        "scores": (n,)} (the ``test.py --proposals`` pkl format) — shipped
        per batch as score-ordered, letterbox-scaled, padded ext_rois for
        Fast R-CNN training/testing (reference ``ROIIter``).  Boxes are
        truncated/padded to the static ``num_proposals``.

        ``run_length``: emit training batches in runs of this many
        consecutive SAME-CANVAS batches (steps_per_call stacking needs K
        identically-shaped batches per device call).  Irrelevant for
        square canvases — every batch shares the shape anyway.

        ``num_classes``: when given, annotation validation additionally
        rejects class ids outside ``[1, num_classes)``."""
        # I/O hardening (docs/robustness.md): a record whose pixels cannot
        # be loaded after bounded retries is quarantined — recorded to
        # ``quarantine_path`` and substituted with a black canvas whose gt
        # slots are all invalid — instead of killing the run.  The batch
        # SCHEDULE never depends on load success (it is derived from the
        # roidb alone), so substitution is schedule-deterministic and
        # multi-host ranks stay in lockstep: shapes and collectives are
        # unchanged, only local pixel content differs.
        self.quarantine_path = quarantine_path
        self.io_retries = max(int(io_retries), 0)
        self._quarantine_lock = threading.Lock()
        # Pre-announced ids (an input-service worker rebuilding this loader
        # from the parent's payload): suppress duplicate journal lines for
        # records the parent already quarantined at construction.
        self._quarantined: set[str] = set(quarantine_announced or ())
        # Annotation hardening (same contract as pixels): a corrupt or
        # truncated annotation record is detected HERE — before the first
        # epoch touches it — quarantined, and blank-substituted at assembly.
        # The record stays in the roidb, so the schedule (and therefore
        # every host's collectives) is identical to a clean run.
        self._bad_annotations: dict[str, str] = {}
        for r in roidb:
            why = annotation_error(r, num_classes)
            if why is not None and r.image_id not in self._bad_annotations:
                self._bad_annotations[r.image_id] = why
                self._quarantine(r, ValueError(why), reason="annotation")
        # The flag decides the Batch pytree structure (gt_ignore present or
        # None) and therefore the jitted program, so it is computed over
        # the full roidb — every host must agree even when all the ignore
        # regions happen to land in one host's rows.  Quarantined-annotation
        # records contribute nothing (their gt is blanked at assembly).
        self.with_ignore = any(
            r.ignore_flags.any() for r in roidb
            if r.image_id not in self._bad_annotations
        )
        # Every host keeps the FULL roidb and derives the SAME global batch
        # schedule (shuffle, orientation buckets, flips); a host then
        # assembles only its rank's rows of each global batch.  Per-host
        # roidb slices would desync multi-host runs the moment schedules
        # depend on per-shard content (orientation buckets emit different
        # canvases at the same step) — global-schedule + row-slicing keeps
        # per-step collectives in lockstep by construction, for training
        # and eval alike.  Pixels are only ever decoded for local rows.
        self.roidb = list(roidb)
        self._rank = rank
        self._world = world
        if world > 1 and batch_size % world:
            raise ValueError(
                f"batch_size {batch_size} not divisible by world={world}"
            )
        self.cfg = cfg
        self.batch_size = batch_size
        self.train = train
        self.seed = seed
        self.with_masks = with_masks
        self.prefetch = prefetch and train
        if num_workers is None:
            # Scale with the host: decode+letterbox is ~15ms/image/core at
            # 1024^2 while a v5e consumes ~2ms/image — TPU hosts have the
            # cores; a 1-core CI box gets no pool (threads only add churn).
            import os as _os

            cores = _os.cpu_count() or 1
            num_workers = (min(8, cores) if cores > 1 else 0) if train else 0
        # In-process thread pool width.  Eval loaders may now use it too
        # (explicitly requested — the auto heuristic stays train-only so
        # one-shot eval CLIs don't spin pools up by surprise); assembly is
        # deterministic, so pooled eval output is byte-identical to sync.
        self.num_workers = num_workers
        self._num_classes = num_classes
        # Process input service (data/service.py): decode workers as
        # independent failure domains, enabled by data.num_workers > 0 (or
        # the explicit constructor override).  0 keeps the in-process
        # thread pool above.  Workers rebuild this loader from a payload
        # and must never recurse into a service of their own —
        # _service_assembler pins service_workers=0.
        if service_workers is None:
            service_workers = getattr(cfg, "num_workers", 0)
        self.service_workers = max(int(service_workers), 0)
        if worker_respawns is None:
            worker_respawns = getattr(cfg, "worker_respawns", 2)
        self.worker_respawns = max(int(worker_respawns), 0)
        # Tensor cache (data/cache.py): decoded+letterboxed pixels memoized
        # under data.cache_dir, checksummed + atomically written; corrupt
        # blobs are quarantined to the same journal and rebuilt from
        # source.  Shared safely between the parent and service workers
        # (atomic publish, content-addressed keys).
        self._tensor_cache: Optional[TensorCache] = None
        if getattr(cfg, "cache_dir", ""):
            self._tensor_cache = TensorCache(
                cfg.cache_dir, cfg, quarantine_path=quarantine_path
            )
        self.proposals = proposals
        self.num_proposals = num_proposals
        self.run_length = max(run_length, 1)
        ch, cw = cfg.image_size
        self._square_canvas = ch == cw
        if not self._square_canvas and train and not cfg.aspect_grouping:
            # Mixed-orientation batches cannot stack into one static canvas;
            # the orientation-bucketed recipe requires the reference's
            # ASPECT_GROUPING (on by default).
            raise ValueError(
                "non-square image_size (orientation-bucketed canvases) "
                "requires data.aspect_grouping=true"
            )
        if proposals is not None:
            missing = [r.image_id for r in self.roidb if r.image_id not in proposals]
            if missing:
                raise ValueError(
                    f"{len(missing)} roidb image(s) have no proposals "
                    f"(first: {missing[0]!r})"
                )
        if not self.roidb:
            raise ValueError("empty roidb shard")
        bad_env = os.environ.get(CHAOS_BAD_IMAGES_ENV, "")
        self._chaos_bad_images = frozenset(
            tok.strip() for tok in bad_env.split(",") if tok.strip()
        )
        if self._chaos_bad_images:
            log.warning(
                "chaos: simulated-corrupt image ids armed: %s",
                sorted(self._chaos_bad_images),
            )
        nan_env = os.environ.get(CHAOS_NAN_ENV, "") if train else ""
        self._nan_steps = frozenset(
            int(tok) for tok in nan_env.split(",") if tok.strip()
        )
        if self._nan_steps:
            log.warning(
                "chaos: NaN injection armed for global batch indices %s",
                sorted(self._nan_steps),
            )

    # -- ordering ----------------------------------------------------------

    def _epoch_batches(self, epoch: int) -> list[np.ndarray]:
        """Shuffled FULL batches for one epoch, each single-orientation
        under aspect grouping (so every batch maps to one static canvas),
        grouped into runs of ``run_length`` same-orientation batches
        (stacked steps_per_call calls need identically-shaped batches).
        A group's tail that can't fill a batch (or a run) is padded by
        wrapping within the group — a small orientation group slightly
        oversamples rather than silently starving (the reference pads its
        final batch the same wrap-around way)."""
        n = len(self.roidb)
        bs = self.batch_size
        rng = np.random.RandomState(self.seed + epoch)
        if not self.cfg.aspect_grouping:
            order = rng.permutation(n)
            return [order[i:i + bs] for i in range(0, n - bs + 1, bs)]
        # Reference ASPECT_GROUPING: batch wide with wide, tall with tall.
        aspects = np.array([r.aspect for r in self.roidb])
        # Same-canvas run grouping only matters when orientations map to
        # different canvases; square canvases keep run=1 so the batch
        # schedule is IDENTICAL for any steps_per_call (a pinned property:
        # the scan loop must train bit-like the sequential loop).
        run = 1 if self._square_canvas else self.run_length
        runs: list[list[np.ndarray]] = []
        for group in (np.flatnonzero(aspects >= 1), np.flatnonzero(aspects < 1)):
            if len(group) == 0:
                continue
            rng.shuffle(group)
            batches = [
                group[i:i + bs] for i in range(0, len(group) - bs + 1, bs)
            ]
            if len(group) % bs:
                # Wrap-around fill of the group's tail batch.
                batches.append(
                    np.resize(group, (len(batches) + 1) * bs)[-bs:]
                )
            if len(batches) % run:
                # Wrap whole batches to complete the final run.
                need = run - len(batches) % run
                batches.extend(batches[i % len(batches)] for i in range(need))
            runs.extend(
                batches[i:i + run] for i in range(0, len(batches), run)
            )
        rng.shuffle(runs)
        return [b for r in runs for b in r]

    # -- single image ------------------------------------------------------

    def _quarantine(
        self, rec: RoiRecord, error: BaseException, reason: str = "io"
    ) -> None:
        retries = self.io_retries if reason == "io" else 0
        with self._quarantine_lock:
            if rec.image_id in self._quarantined:
                return  # already recorded; don't re-log every epoch
            self._quarantined.add(rec.image_id)
            log.error(
                "quarantining image %r (%s; %s: %s) after %d retries; "
                "substituting a blank example",
                rec.image_id, reason, type(error).__name__, error, retries,
            )
            if self.quarantine_path is None:
                return
            # Crash-safe append (data/cache.py): one O_APPEND write per
            # record — a kill mid-append tears at most this line, never
            # earlier ones, and concurrent writers (threads, service
            # workers) interleave at line granularity.
            quarantine_append(self.quarantine_path, {
                "image_id": rec.image_id,
                "path": rec.image_path,
                "reason": reason,
                "error": f"{type(error).__name__}: {error}",
                "retries": retries,
            })

    def _blank_pixels(self, rec: RoiRecord) -> np.ndarray:
        """A zero canvas in the record's NATIVE dtype — a uint8 blank inside
        an otherwise-float (synthetic/host-normalized) batch would trip the
        mixed-dtype guard in ``_assemble``."""
        if rec.image_array is not None:
            return np.zeros_like(rec.image_array)
        return np.zeros((rec.height, rec.width, 3), np.uint8)

    def _load_image(self, rec: RoiRecord) -> tuple[np.ndarray, bool]:
        """``(pixels, ok)`` — bounded retry on I/O errors, then a blank
        canvas with ``ok=False`` (the caller invalidates the gt)."""
        err: Optional[BaseException] = None
        for attempt in range(self.io_retries + 1):
            try:
                if rec.image_id in self._chaos_bad_images:
                    raise ValueError("chaos: simulated corrupt image")
                return load_image(rec), True
            except (OSError, ValueError) as e:
                err = e
                if attempt < self.io_retries:
                    time.sleep(0.1 * (2 ** attempt))
        self._quarantine(rec, err)
        return self._blank_pixels(rec), False

    def _pixels(self, rec: RoiRecord, flip: bool):
        """``(pixels, th, tw, ok)`` — the record's fully processed canvas
        (decoded, flipped, letterboxed, and normalized where the config
        says so), independent of any box/gt math.

        This is the cacheable unit: pixel processing is a pure function of
        (source bytes, flip, transform config) — exactly the
        :class:`TensorCache` key — while the box side stays the uniform
        ``boxes * record_scale`` in the caller.  A cache hit returns the
        same bytes a rebuild would (the blob stores the final tensor), so
        hits vs misses are bitwise-invisible downstream — the
        ``cache_corrupt`` chaos scenario pins that.
        """
        cache = self._tensor_cache
        if cache is not None and (
            rec.image_id in self._chaos_bad_images
            or rec.image_id in self._quarantined
        ):
            # A record that must exercise the quarantine/substitution path
            # (or already did) never reads the cache: a stale blob from a
            # healthier life of the file must not mask the failure.
            cache = None
        key = cache.key(rec, flip) if cache is not None else None
        if cache is not None:
            hit = cache.get(key, rec.image_id)
            if hit is not None:
                img, th, tw = hit
                return img, th, tw, True
        img, img_ok = self._load_image(rec)
        if flip:
            img = img[:, ::-1].copy()  # transforms.hflip's pixel half
        canvas = self.record_canvas(rec)
        scale = self.record_scale(rec)
        nh = int(round(rec.height * scale))
        nw = int(round(rec.width * scale))
        if img.dtype == np.uint8 and not self.cfg.normalize_on_host:
            # Default path: uint8 letterbox, normalization deferred into the
            # jitted graph (graph.py::prep_images) — the batch ships 1/4 the
            # bytes of float32 host-normalized pixels.  uint8->uint8 resize
            # is also what the reference does (rcnn/io/image.py resizes the
            # uint8 image before the float mean-subtract).
            img = letterbox_uint8(img, canvas, nh, nw)
            th, tw = nh, nw
        else:
            native = None
            if img.dtype == np.uint8:
                # Fused C++ resize+pad+normalize (mx_rcnn_tpu/native);
                # replaces the reference's two-pass cv2-resize + numpy
                # mean-subtract (rcnn/io/image.py) on the loader hot path.
                # None when the shared library isn't built — fall through
                # to the numpy letterbox.
                from mx_rcnn_tpu.native import letterbox_normalize

                native = letterbox_normalize(
                    img, canvas, nh, nw, scale,
                    self.cfg.pixel_mean, self.cfg.pixel_std,
                )
            if native is not None:
                img = native
                th, tw = nh, nw
            else:
                # letterbox's internal scale is the same min(resize_scale,
                # ch/h, cw/w) expression as record_scale — identical float
                # result, so dropping its box output loses nothing.
                img, _, _, (th, tw) = letterbox(
                    img.astype(np.float32), np.zeros((0, 4), np.float32),
                    canvas, self.cfg.short_side, self.cfg.max_side,
                )
                img = normalize_image(
                    img, self.cfg.pixel_mean, self.cfg.pixel_std
                )
        if img_ok and cache is not None:
            cache.put(key, img, th, tw)
        return img, th, tw, img_ok

    def _example(self, rec: RoiRecord, flip: bool):
        if rec.image_id in self._bad_annotations:
            # Quarantined annotations take the same substitution as
            # quarantined pixels: blank canvas, zero gt slots.  The stand-in
            # record never touches the (possibly malformed) box/class arrays.
            import dataclasses

            rec = dataclasses.replace(
                rec,
                boxes=np.zeros((0, 4), np.float32),
                gt_classes=np.zeros((0,), np.int32),
                ignore=None,
                masks=None,
                image_array=self._blank_pixels(rec),
                image_path="",
            )
        img, th, tw, img_ok = self._pixels(rec, flip)
        scale = self.record_scale(rec)
        boxes = rec.boxes
        if flip:
            boxes = flip_boxes(boxes, rec.width)
        # Uniform box geometry across every pixel path (uint8 / fused C++ /
        # float letterbox): flip in original coords, then the letterbox
        # scale — bit-identical to what letterbox itself would emit.
        boxes = boxes.astype(np.float32) * scale
        g = self.cfg.max_gt_boxes
        n = min(len(boxes), g)
        ign = rec.ignore_flags
        gt_boxes = np.zeros((g, 4), np.float32)
        gt_classes = np.zeros((g,), np.int32)
        gt_valid = np.zeros((g,), bool)
        gt_ignore = np.zeros((g,), bool)
        gt_boxes[:n] = boxes[:n]
        gt_classes[:n] = rec.gt_classes[:n]
        # A slot is either a real gt (valid), an ignore region (crowd/
        # difficult — never fg, shields bg sampling), or padding (neither).
        gt_valid[:n] = ~ign[:n]
        gt_ignore[:n] = ign[:n]
        if not img_ok:
            # Quarantined image: blank pixels with no gt — contributes
            # nothing to the loss but keeps every shape (and therefore
            # every collective) identical across hosts.
            gt_valid[:] = False
            gt_ignore[:] = False
        masks = None
        if self.with_masks:
            masks = np.zeros((g, GT_MASK_SIZE, GT_MASK_SIZE), np.float32)
            if rec.masks is not None:
                for i in range(n):
                    if ign[i]:
                        # Ignore slots can never be fg mask targets (IoU is
                        # masked by gt_valid); crowd RLEs are also the most
                        # expensive to rasterize.
                        continue
                    m = _rasterize_mask(rec.masks[i], rec.boxes[i])
                    masks[i] = m[:, ::-1] if flip else m
        ext = None
        if self.proposals is not None:
            # External proposals ride the exact same geometry as gt boxes:
            # flip in original coords, then the letterbox scale.
            p = self.proposals[rec.image_id]
            pb = np.asarray(p["boxes"], np.float32).reshape(-1, 4)
            ps = np.asarray(p["scores"], np.float32).reshape(len(pb))
            if flip:
                pb = flip_boxes(pb, rec.width)
            order = np.argsort(-ps, kind="mergesort")[: self.num_proposals]
            pb = pb[order] * scale
            np.clip(pb[:, 0::2], 0.0, tw - 1.0, out=pb[:, 0::2])
            np.clip(pb[:, 1::2], 0.0, th - 1.0, out=pb[:, 1::2])
            ext_rois = np.zeros((self.num_proposals, 4), np.float32)
            ext_valid = np.zeros((self.num_proposals,), bool)
            ext_rois[: len(pb)] = pb
            ext_valid[: len(pb)] = True
            ext = (ext_rois, ext_valid)
        return (
            img, (th, tw), gt_boxes, gt_classes, gt_valid, gt_ignore, masks,
            ext, scale,
        )

    def _assemble(self, recs: list[RoiRecord], flips: list[bool]) -> Batch:
        ims, hws, bs, cs, vs, igs, ms, ers, evs = [], [], [], [], [], [], [], [], []
        for rec, fl in zip(recs, flips):
            img, (th, tw), gb, gc, gv, gi, gm, ext, _ = self._example(rec, fl)
            if ims and img.dtype != ims[0].dtype:
                # A uint8 record rides raw (normalized in-graph) while a
                # float record arrives host-normalized; np.stack would
                # silently promote the mix to float32 and feed RAW 0-255
                # uint8 pixels past prep_images' dtype gate.
                raise ValueError(
                    f"mixed image dtypes in one batch ({ims[0].dtype} vs "
                    f"{img.dtype} for {rec.image_id!r}); a roidb must be "
                    "uniformly uint8 or float (or set "
                    "data.normalize_on_host=true)"
                )
            ims.append(img)
            hws.append([th, tw])
            bs.append(gb)
            cs.append(gc)
            vs.append(gv)
            igs.append(gi)
            if gm is not None:
                ms.append(gm)
            if ext is not None:
                ers.append(ext[0])
                evs.append(ext[1])
        return Batch(
            images=np.stack(ims),
            image_hw=np.asarray(hws, np.float32),
            gt_boxes=np.stack(bs),
            gt_classes=np.stack(cs),
            gt_valid=np.stack(vs),
            gt_masks=np.stack(ms) if ms else None,
            gt_ignore=np.stack(igs) if self.with_ignore else None,
            ext_rois=np.stack(ers) if ers else None,
            ext_valid=np.stack(evs) if evs else None,
        )

    # -- iteration ---------------------------------------------------------

    def _batch_index_specs(self, epochs: Optional[int] = None):
        """(roidb indices, flips) stream in GLOBAL epoch order — infinite
        unless ``epochs`` bounds it (tests; production training is open-
        ended).

        The schedule (shuffle order, flip draws) is derived identically on
        every host; multi-host runs slice each global spec to their rank's
        rows (``_local_index_spec``), so the flip rng must be consumed for
        the full global batch here, not per local slice.  Index-based specs
        are also what ships to input-service workers: a few ints + bools
        per batch, never pixel bytes."""
        epoch = 0
        rng = np.random.RandomState(self.seed + 17)
        while epochs is None or epoch < epochs:
            for batch_idx in self._epoch_batches(epoch):
                flips = [
                    self.cfg.flip and bool(rng.randint(2))
                    for _ in range(len(batch_idx))
                ]
                yield batch_idx, flips
            epoch += 1

    def _batch_specs(self, epochs: Optional[int] = None):
        """``_batch_index_specs`` with records materialized (legacy shape —
        tests introspect the schedule through this)."""
        for batch_idx, flips in self._batch_index_specs(epochs):
            yield [self.roidb[j] for j in batch_idx], flips

    def _local_index_spec(self, batch_idx, flips):
        """This host's rows of a global (indices, flips) spec, as plain
        ints/bools (small, pickles fast to service workers)."""
        local = self.batch_size // self._world
        lo = self._rank * local
        return (
            [int(j) for j in batch_idx[lo:lo + local]],
            [bool(f) for f in flips[lo:lo + local]],
        )

    def _local_rows(self, recs, flips):
        """This host's rows of a global (records, flips) spec."""
        local = self.batch_size // self._world
        lo = self._rank * local
        return recs[lo:lo + local], flips[lo:lo + local]

    def _assemble_rows(self, spec) -> Batch:
        """Assemble one LOCAL (roidb indices, flips) spec — the unit of
        work for the thread pool and the input service alike."""
        idxs, flips = spec
        return self._assemble([self.roidb[j] for j in idxs], flips)

    def _assemble_global_rows(self, spec) -> Batch:
        """Assemble one GLOBAL (roidb indices, flips) spec by slicing this
        host's rank rows first.  This is the multi-host service-worker
        unit of work: the parent ships the full global schedule and each
        host's workers decode ONLY their rank's rows — bit-identical to
        parent-side slicing because ``_local_index_spec`` is pure."""
        return self._assemble_rows(self._local_index_spec(*spec))

    def _local_spec_stream(self, skip_batches: int = 0,
                           epochs: Optional[int] = None):
        """Local (indices, flips) specs with resume fast-forward: spec
        generation (shuffle order + flip draws) is cheap; skipping specs
        instead of restarting keeps the resumed run on the same data
        schedule as an uninterrupted one."""
        specs = self._batch_index_specs(epochs)
        for _ in range(skip_batches):
            try:
                next(specs)
            except StopIteration:
                return
        for batch_idx, flips in specs:
            yield self._local_index_spec(batch_idx, flips)

    def _global_spec_stream(self, skip_batches: int = 0,
                            epochs: Optional[int] = None):
        """GLOBAL (indices, flips) specs as plain ints/bools, with the
        same resume fast-forward as ``_local_spec_stream``.  This is
        what ships to service workers on the multi-host path — rank
        slicing happens worker-side (``_assemble_global_rows``), so a
        host's decode workers see the full schedule but touch only
        their rank's pixels."""
        specs = self._batch_index_specs(epochs)
        for _ in range(skip_batches):
            try:
                next(specs)
            except StopIteration:
                return
        for batch_idx, flips in specs:
            yield (
                [int(j) for j in batch_idx],
                [bool(f) for f in flips],
            )

    def _worker_payload(self) -> dict:
        """Everything a service worker needs to rebuild this loader (spawn
        semantics: nothing is inherited).  ``quarantine_announced`` carries
        ids this process already journaled so workers don't re-append
        duplicate quarantine lines at construction."""
        return {
            "roidb": self.roidb,
            "cfg": self.cfg,
            "batch_size": self.batch_size,
            "train": self.train,
            "seed": self.seed,
            "rank": self._rank,
            "world": self._world,
            "with_masks": self.with_masks,
            "proposals": self.proposals,
            "num_proposals": self.num_proposals,
            "run_length": self.run_length,
            "quarantine_path": self.quarantine_path,
            "io_retries": self.io_retries,
            "num_classes": self._num_classes,
            "quarantine_announced": sorted(self._quarantined),
        }

    def _shm_slot_bytes(self) -> int:
        """Auto-size one shm ring slot to the worst-case assembled batch:
        float32 images (synthetic/normalized paths are 4x the uint8 fast
        path) plus gt arrays, masks, and external proposals when on, with
        25% headroom over the payload and the fixed header region on top.
        An overflowing batch is not an error — it falls back to pickle for
        that batch — so this is a throughput knob, not a correctness one."""
        from mx_rcnn_tpu.data.shm_ring import HEADER_RESERVE

        slot_mb = int(getattr(self.cfg, "shm_slot_mb", 0) or 0)
        if slot_mb > 0:
            return slot_mb * (1 << 20)
        b = max(self.batch_size // self._world, 1)
        h, w = self.cfg.image_size
        g = self.cfg.max_gt_boxes
        payload = b * h * w * 3 * 4          # images, float32 worst case
        payload += b * (2 * 4)               # image_hw
        payload += b * g * (4 * 4 + 4 + 1 + 1)  # boxes/classes/valid/ignore
        if self.with_masks:
            payload += b * g * GT_MASK_SIZE * GT_MASK_SIZE * 4
        if self.proposals is not None:
            payload += b * self.num_proposals * (4 * 4 + 1)
        return int(payload * 1.25) + HEADER_RESERVE + 4096

    def _service_batches(self, spec_iter, start_index: int = 0,
                         global_specs: bool = False):
        """Run a spec stream through the process input service
        (data/service.py).  Yields in spec order; closing this generator
        (or exhausting it) tears the service down.

        ``global_specs=True`` means ``spec_iter`` carries the GLOBAL
        schedule and workers slice their rank's rows themselves
        (``_assemble_global_rows``) — the training path.  False keeps
        pre-sliced LOCAL specs (the eval path, whose sharding already
        happened upstream)."""
        from mx_rcnn_tpu.data.service import InputService

        shm_slots = 0
        if getattr(self.cfg, "shm_transport", True):
            shm_slots = max(int(getattr(self.cfg, "shm_slots", 4)), 0)
        svc = InputService(
            specs=spec_iter,
            assemble=(
                self._assemble_global_rows if global_specs
                else self._assemble_rows
            ),
            builder=(
                _service_assembler_global if global_specs
                else _service_assembler
            ),
            payload=self._worker_payload(),
            num_workers=self.service_workers,
            start_index=start_index,
            respawns=self.worker_respawns,
            shm_slots=shm_slots,
            shm_slot_bytes=self._shm_slot_bytes() if shm_slots else 0,
            quarantine_path=self.quarantine_path,
        )
        try:
            yield from svc
        finally:
            svc.close()

    def _pooled_batches(self, spec_iter) -> Iterator[Batch]:
        """Thread pool assembling ``num_workers`` batches ahead, yielded in
        order.  Decode/resize/normalize release the GIL (cv2 and the C++
        letterbox kernel), so threads give real parallelism — the TPU step
        is ~2ms/image while host assembly is ~5-10ms/image.  When the spec
        stream runs dry (bounded epochs, eval shards) the pending deque is
        DRAINED, not dropped: every scheduled batch is yielded and the
        generator returns cleanly instead of letting ``next(specs)``
        escape as a PEP-479 RuntimeError."""
        import collections
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(self.num_workers) as pool:
            pending: collections.deque = collections.deque()

            def pump() -> bool:
                try:
                    spec = next(spec_iter)
                except StopIteration:
                    return False
                pending.append(pool.submit(self._assemble_rows, spec))
                return True

            for _ in range(self.num_workers):
                if not pump():
                    break
            while pending:
                batch = pending.popleft().result()
                pump()
                yield batch

    def _poison(self, batch: Batch, idx: int) -> Batch:
        """Chaos hook (CHAOS_NAN_ENV): replace the batch's pixels with NaN."""
        if not np.issubdtype(batch.images.dtype, np.floating):
            raise ValueError(
                f"{CHAOS_NAN_ENV} needs float images (synthetic/normalized "
                f"paths); batch {idx} is {batch.images.dtype}"
            )
        log.warning("chaos: injecting NaN images at global batch %d", idx)
        return batch._replace(
            images=np.full_like(batch.images, np.nan)
        )

    def _train_batches(self, skip_batches: int = 0) -> Iterator[Batch]:
        it = self._raw_train_batches(skip_batches)
        if not self._nan_steps:
            yield from it
            return
        # All paths below yield batches in global-schedule order, so the
        # yielded position IS the global batch index.  NaN poisoning stays
        # parent-side (after the service): the chaos hook targets the
        # guardian, not the decode workers.
        try:
            for idx, batch in enumerate(it, start=skip_batches):
                yield (
                    self._poison(batch, idx) if idx in self._nan_steps
                    else batch
                )
        finally:
            it.close()

    def _raw_train_batches(
        self, skip_batches: int = 0, epochs: Optional[int] = None
    ) -> Iterator[Batch]:
        if self.service_workers > 0:
            # Process input service: decode workers as independent failure
            # domains (data/service.py).  start_index keys the service's
            # yield cursor to the GLOBAL batch index so resume and chaos
            # logs speak the same coordinates as the schedule.  The
            # service ships GLOBAL specs — each host's workers slice
            # their own rank rows, so every host's parent process emits
            # one identical schedule and decode is rank-sharded at the
            # worker (docs/input-service.md, ROADMAP item 2).
            yield from self._service_batches(
                self._global_spec_stream(skip_batches, epochs),
                start_index=skip_batches, global_specs=True,
            )
            return
        specs = self._local_spec_stream(skip_batches, epochs)
        if self.num_workers <= 1:
            for spec in specs:
                yield self._assemble_rows(spec)
        else:
            yield from self._pooled_batches(specs)

    def eval_specs(self) -> list[tuple[list[RoiRecord], list[RoiRecord]]]:
        """The GLOBAL eval batch schedule with NO pixel decode: one
        ``(local_rows, global_records)`` entry per eval batch.

        This is the schedule ``_eval_batches`` assembles pixels for; it is
        exposed separately so resumable evaluation (evalutil/pred_eval.py)
        can fingerprint the schedule, partition it into shards, and skip
        completed shards without paying a decode for batches it will never
        run.

        Non-square canvases: landscape images first, then portrait, each in
        roidb order — every batch shares one canvas (two compiled eval
        programs).  Detections map back through the records, so the
        reordering is invisible to the evaluator.

        Multi-host (world > 1): every host derives the SAME global schedule
        from the full roidb; ``local_rows`` is this rank's slice of each
        padded global batch — per-step collectives stay in lockstep by
        construction, and rank-local batches concatenate into exactly the
        single-host global batch.
        """
        return [
            ([self.roidb[j] for j in rows], [self.roidb[j] for j in grecs])
            for (rows, _), grecs in self._eval_index_specs()
        ]

    def _eval_index_specs(self):
        """Index-based eval schedule: one ``((local_row_indices, flips),
        global_record_indices)`` entry per eval batch — the same contract
        as ``eval_specs`` but picklable-small, so the worker pool and the
        input service can assemble eval shards too."""
        rank, world = self._rank, self._world
        local = self.batch_size // world
        idx_all = list(range(len(self.roidb)))
        if self._square_canvas:
            groups = [idx_all]
        else:
            groups = [
                [j for j in idx_all if self.roidb[j].aspect >= 1],
                [j for j in idx_all if self.roidb[j].aspect < 1],
            ]
        specs = []
        for group in groups:
            for i in range(0, len(group), self.batch_size):
                idxs = group[i : i + self.batch_size]
                pad = self.batch_size - len(idxs)
                padded = idxs + [idxs[-1]] * pad
                rows = padded[rank * local : (rank + 1) * local]
                specs.append(((rows, [False] * len(rows)), idxs))
        return specs

    def eval_batch_range(self, start: int = 0, stop: Optional[int] = None):
        """Assemble and yield eval batches ``start:stop`` of the global
        schedule (``eval_specs`` order).  Sharded/resumable evaluation runs
        each shard as one contiguous range and never decodes pixels for
        batches outside it.

        Assembly is deterministic, so the thread pool (``num_workers``)
        and the process service (``service_workers``) produce output
        byte-identical to the synchronous path — resumable sharded eval
        keeps its digest contract with either enabled."""
        specs = self._eval_index_specs()[start:stop]
        rec_lists = [[self.roidb[j] for j in g] for _, g in specs]
        row_specs = iter([rows for rows, _ in specs])
        if self.service_workers > 0:
            batches: Iterator[Batch] = self._service_batches(
                row_specs, start_index=start
            )
        elif self.num_workers > 1:
            batches = self._pooled_batches(row_specs)
        else:
            batches = (self._assemble_rows(s) for s in row_specs)
        try:
            for batch, recs in zip(batches, rec_lists):
                yield batch, recs
        finally:
            batches.close()

    def _eval_batches(self, skip_batches: int = 0):
        return self.eval_batch_range(skip_batches)

    def __iter__(self):
        return self.iter_from()

    def iter_from(self, skip_batches: int = 0):
        """Iterate, skipping the first ``skip_batches`` batches (resume
        continuity: step k of a resumed run sees the batch step k of an
        uninterrupted run would have — training and eval alike)."""
        if not self.train:
            return self._eval_batches(skip_batches)
        it = self._train_batches(skip_batches)
        if not self.prefetch or self.service_workers > 0:
            # The input service already overlaps decode with compute via
            # its worker processes and bounded result queues; a loader
            # prefetch thread on top would only add a hop (and a second
            # owner of the service generator).
            return it
        return _Prefetched(it, depth=2)

    def record_canvas(self, rec: RoiRecord) -> tuple[int, int]:
        """The static canvas this record letterboxes into (orientation-
        matched transpose of ``cfg.image_size`` for portrait images)."""
        return oriented_canvas(self.cfg.image_size, rec.height, rec.width)

    def record_scale(self, rec: RoiRecord) -> float:
        """The letterbox scale applied to a record (for box un-scaling at
        eval, the reference's ``/ im_scale`` in ``im_detect``).  With an
        orientation-matched canvas sized for the short/max rule the clamp
        terms only guard rounding — the recipe scale always fits."""
        ch, cw = self.record_canvas(rec)
        return min(
            resize_scale(rec.height, rec.width, self.cfg.short_side, self.cfg.max_side),
            ch / rec.height,
            cw / rec.width,
        )


def _service_assembler(payload: dict):
    """Rebuild the parent's loader inside a spawned service worker and
    return its ``_assemble_rows`` (module-level so it pickles by reference).

    ``service_workers=0`` is load-bearing: a worker rebuilding a loader
    whose config says ``data.num_workers > 0`` must not recurse into a
    service of its own.  ``prefetch=False`` and ``num_workers=0`` keep the
    worker single-threaded — its parallelism is the process pool itself.
    """
    loader = DetectionLoader(
        payload["roidb"],
        payload["cfg"],
        payload["batch_size"],
        train=payload["train"],
        seed=payload["seed"],
        rank=payload["rank"],
        world=payload["world"],
        with_masks=payload["with_masks"],
        prefetch=False,
        num_workers=0,
        proposals=payload["proposals"],
        num_proposals=payload["num_proposals"],
        run_length=payload["run_length"],
        quarantine_path=payload["quarantine_path"],
        io_retries=payload["io_retries"],
        num_classes=payload["num_classes"],
        service_workers=0,
        worker_respawns=0,
        quarantine_announced=payload["quarantine_announced"],
    )
    return loader._assemble_rows


def _service_assembler_global(payload: dict):
    """Like :func:`_service_assembler`, but the returned callable takes
    GLOBAL specs and slices the worker's host-rank rows itself — the
    payload's ``rank``/``world`` make the rebuilt loader's
    ``_local_index_spec`` identical to the parent's, so the stream stays
    bit-identical to parent-side slicing."""
    assemble_local = _service_assembler(payload)
    loader = assemble_local.__self__
    return loader._assemble_global_rows


class _Prefetched:
    """One-deep-ish background prefetch over a batch iterator, with a
    ``close()`` that actually reclaims the thread.

    The old ``_prefetched`` generator leaked its daemon thread when the
    consumer stopped early: the thread sat blocked on ``q.put`` against a
    full queue forever, pinning the source iterator (and any service
    workers under it) alive.  ``close()`` drains the queue until the
    thread can finish, joins it, closes the source, and — with
    ``raise_pending=True`` — re-raises an exception the worker hit that
    the consumer never got to see (otherwise a source failure after the
    consumer's last ``next()`` would vanish silently).
    """

    def __init__(self, it: Iterator, depth: int = 2) -> None:
        self._it = it
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = object()
        self._exc: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="loader-prefetch", daemon=True
        )
        self._thread.start()

    def _worker(self) -> None:
        try:
            for item in self._it:
                self._q.put(item)
                if self._closed:
                    break
        except BaseException as e:  # noqa: BLE001 — handed to the consumer
            self._exc = e
        finally:
            self._q.put(self._stop)

    def __iter__(self) -> "_Prefetched":
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        item = self._q.get()
        if item is self._stop:
            self._closed = True
            self._thread.join(timeout=5.0)
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        return item

    def close(self, raise_pending: bool = True) -> None:
        """Join the prefetch thread and close the source iterator.  With
        ``raise_pending`` a worker-side exception the consumer never
        consumed is re-raised here instead of being swallowed."""
        if self._closed:
            self._close_source()
            return
        self._closed = True
        # Unblock a worker stuck on a full queue, then wait for its final
        # stop marker (bounded: the worker checks _closed after each put).
        while self._thread.is_alive():
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
        self._close_source()
        if raise_pending and self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc

    def _close_source(self) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            try:
                close()
            except RuntimeError:
                pass  # generator already executing/closed


def _prefetched(it: Iterator, depth: int = 2) -> "_Prefetched":
    """Legacy alias — prefetching now returns a closeable iterator."""
    return _Prefetched(it, depth=depth)
