"""Batch assembly: roidb → statically-shaped Batch pytrees.

Replaces ``rcnn/core/loader.py::AnchorLoader`` minus the anchor labeling
(in-graph now).  Keeps the reference's load-time behaviors: epoch shuffle,
aspect-ratio grouping (``ASPECT_GROUPING`` — portrait/landscape batched
together so letterbox padding is minimized), flip augmentation, and
per-host sharding for data parallelism — every host derives the SAME
global batch schedule from the full roidb and decodes only its rank's
rows of each global batch (lockstep by construction; the reference
instead slices batches across ``ctx`` GPUs inside one process).  A
one-deep background prefetch thread overlaps host decode with device
compute (the reference relied on MXNet's threaded DataIter for the same).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from typing import Iterator, Optional

import numpy as np

from mx_rcnn_tpu.config import DataConfig
from mx_rcnn_tpu.data.roidb import RoiRecord
from mx_rcnn_tpu.data.transforms import (
    flip_boxes,
    hflip,
    letterbox,
    letterbox_uint8,
    normalize_image,
    oriented_canvas,
    resize_scale,
)
from mx_rcnn_tpu.detection.graph import Batch

try:
    import cv2
except Exception:  # pragma: no cover
    cv2 = None

log = logging.getLogger("mx_rcnn_tpu")

# tools/chaos.py fault hook: comma-separated GLOBAL batch indices whose
# images are replaced with NaN before yielding (training only) — exercises
# the guardian's detect/rollback path end-to-end without touching the
# model or the schedule.
CHAOS_NAN_ENV = "MX_RCNN_CHAOS_NAN_STEPS"

# tools/chaos.py fault hook: comma-separated image_ids whose pixel load
# RAISES (as a corrupt/unreadable file would) — drives the retry +
# quarantine + blank-substitution path against real loaders, including
# in-memory synthetic records that can't otherwise fail.  Active for
# training AND eval (the eval_corrupt chaos scenario).
CHAOS_BAD_IMAGES_ENV = "MX_RCNN_CHAOS_BAD_IMAGES"

# Box-relative resolution at which gt instance masks are rasterized on host;
# the device crops these to the mask head's target size per sampled roi.
GT_MASK_SIZE = 112


def load_proposals(path: str) -> dict:
    """Load and validate a proposal pkl (``test.py --proposals`` format:
    image_id → {"boxes": (n, 4) original-image coords, "scores": (n,)}).
    Fails fast on schema problems instead of mid-epoch in the loader."""
    import pickle

    with open(path, "rb") as f:
        props = pickle.load(f)
    if not isinstance(props, dict) or not props:
        raise ValueError(f"{path}: expected a non-empty image_id->dict map")
    for key, p in props.items():
        boxes = np.asarray(p.get("boxes", None))
        scores = np.asarray(p.get("scores", None))
        if boxes.ndim != 2 or boxes.shape[1] != 4 or scores.shape != boxes.shape[:1]:
            raise ValueError(
                f"{path}: image {key!r} needs boxes (n, 4) + scores (n,), "
                f"got {boxes.shape} / {scores.shape}"
            )
        break  # spot-check one entry; full arrays validate lazily per image
    return props


def annotation_error(rec: RoiRecord, num_classes: Optional[int] = None) -> Optional[str]:
    """Why this record's annotations are unusable, or None if they're fine.

    Mirrors the image-quarantine contract for the OTHER way a dataset rots
    in place: a truncated/corrupt annotation record (malformed box arrays,
    non-finite or inverted coordinates, out-of-range class ids) used to
    crash mid-epoch deep inside ``_example``; now it is detected up front
    and the record is quarantined + blank-substituted instead.
    """
    boxes = np.asarray(rec.boxes)
    if boxes.ndim != 2 or boxes.shape[1] != 4:
        return f"boxes shape {boxes.shape} is not (n, 4)"
    if boxes.dtype.kind not in "fiu" or not np.isfinite(
        boxes.astype(np.float64, copy=False)
    ).all():
        return "non-finite or non-numeric box coordinates"
    if (boxes[:, 2] < boxes[:, 0]).any() or (boxes[:, 3] < boxes[:, 1]).any():
        return "inverted box (x2 < x1 or y2 < y1)"
    cls = np.asarray(rec.gt_classes)
    if cls.shape != (len(boxes),):
        return f"gt_classes shape {cls.shape} does not match {len(boxes)} boxes"
    if len(cls) and cls.min() < 1:
        return "class id < 1 (foreground labels are 1-based)"
    if num_classes is not None and len(cls) and cls.max() >= num_classes:
        return f"class id {int(cls.max())} >= num_classes {num_classes}"
    if rec.ignore is not None and np.asarray(rec.ignore).shape != (len(boxes),):
        return "ignore flags do not match the box count"
    return None


def load_image(rec: RoiRecord) -> np.ndarray:
    """uint8 RGB from disk (float32 for in-memory synthetic images)."""
    if rec.image_array is not None:
        return rec.image_array
    if cv2 is None:  # pragma: no cover
        from PIL import Image

        return np.asarray(Image.open(rec.image_path).convert("RGB"), np.uint8)
    img = cv2.imread(rec.image_path, cv2.IMREAD_COLOR)
    if img is None:
        raise FileNotFoundError(rec.image_path)
    return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)


def _rasterize_mask(seg, box: np.ndarray) -> np.ndarray:
    """Polygon/RLE segmentation → (GT_MASK_SIZE,)*2 box-relative float mask."""
    out = np.zeros((GT_MASK_SIZE, GT_MASK_SIZE), np.float32)
    if seg is None or cv2 is None:
        return out
    x1, y1, x2, y2 = box
    bw, bh = max(x2 - x1 + 1, 1.0), max(y2 - y1 + 1, 1.0)
    if isinstance(seg, list):  # polygons in image coords
        polys = []
        for p in seg:
            pts = np.asarray(p, np.float32).reshape(-1, 2)
            pts[:, 0] = (pts[:, 0] - x1) / bw * GT_MASK_SIZE
            pts[:, 1] = (pts[:, 1] - y1) / bh * GT_MASK_SIZE
            polys.append(pts.round().astype(np.int32))
        cv2.fillPoly(out, polys, 1.0)
    elif isinstance(seg, dict):  # uncompressed RLE {"counts": [...], "size": [h, w]}
        h, w = seg["size"]
        counts = seg["counts"]
        if isinstance(counts, list):
            flat = np.zeros(h * w, np.uint8)
            pos, val = 0, 0
            for c in counts:
                flat[pos : pos + c] = val
                pos += c
                val = 1 - val
            full = flat.reshape((w, h)).T.astype(np.float32)
            crop = full[
                int(max(y1, 0)) : int(y2) + 1, int(max(x1, 0)) : int(x2) + 1
            ]
            if crop.size:
                out = cv2.resize(crop, (GT_MASK_SIZE, GT_MASK_SIZE))
    return out


class DetectionLoader:
    """Iterable over statically-shaped Batches.

    train=True: infinite, shuffled per epoch, flip augmentation.
    train=False: one pass in roidb order, no flip, yields (batch, records)
    so eval can map detections back to image ids and scales.
    """

    def __init__(
        self,
        roidb: list[RoiRecord],
        cfg: DataConfig,
        batch_size: int,
        train: bool = True,
        seed: int = 0,
        rank: int = 0,
        world: int = 1,
        with_masks: bool = False,
        prefetch: bool = True,
        num_workers: Optional[int] = None,
        proposals: Optional[dict] = None,
        num_proposals: int = 1000,
        run_length: int = 1,
        quarantine_path: Optional[str] = None,
        io_retries: int = 2,
        num_classes: Optional[int] = None,
    ) -> None:
        """``proposals``: image_id → {"boxes": (n, 4) ORIGINAL-image coords,
        "scores": (n,)} (the ``test.py --proposals`` pkl format) — shipped
        per batch as score-ordered, letterbox-scaled, padded ext_rois for
        Fast R-CNN training/testing (reference ``ROIIter``).  Boxes are
        truncated/padded to the static ``num_proposals``.

        ``run_length``: emit training batches in runs of this many
        consecutive SAME-CANVAS batches (steps_per_call stacking needs K
        identically-shaped batches per device call).  Irrelevant for
        square canvases — every batch shares the shape anyway.

        ``num_classes``: when given, annotation validation additionally
        rejects class ids outside ``[1, num_classes)``."""
        # I/O hardening (docs/robustness.md): a record whose pixels cannot
        # be loaded after bounded retries is quarantined — recorded to
        # ``quarantine_path`` and substituted with a black canvas whose gt
        # slots are all invalid — instead of killing the run.  The batch
        # SCHEDULE never depends on load success (it is derived from the
        # roidb alone), so substitution is schedule-deterministic and
        # multi-host ranks stay in lockstep: shapes and collectives are
        # unchanged, only local pixel content differs.
        self.quarantine_path = quarantine_path
        self.io_retries = max(int(io_retries), 0)
        self._quarantine_lock = threading.Lock()
        self._quarantined: set[str] = set()
        # Annotation hardening (same contract as pixels): a corrupt or
        # truncated annotation record is detected HERE — before the first
        # epoch touches it — quarantined, and blank-substituted at assembly.
        # The record stays in the roidb, so the schedule (and therefore
        # every host's collectives) is identical to a clean run.
        self._bad_annotations: dict[str, str] = {}
        for r in roidb:
            why = annotation_error(r, num_classes)
            if why is not None and r.image_id not in self._bad_annotations:
                self._bad_annotations[r.image_id] = why
                self._quarantine(r, ValueError(why), reason="annotation")
        # The flag decides the Batch pytree structure (gt_ignore present or
        # None) and therefore the jitted program, so it is computed over
        # the full roidb — every host must agree even when all the ignore
        # regions happen to land in one host's rows.  Quarantined-annotation
        # records contribute nothing (their gt is blanked at assembly).
        self.with_ignore = any(
            r.ignore_flags.any() for r in roidb
            if r.image_id not in self._bad_annotations
        )
        # Every host keeps the FULL roidb and derives the SAME global batch
        # schedule (shuffle, orientation buckets, flips); a host then
        # assembles only its rank's rows of each global batch.  Per-host
        # roidb slices would desync multi-host runs the moment schedules
        # depend on per-shard content (orientation buckets emit different
        # canvases at the same step) — global-schedule + row-slicing keeps
        # per-step collectives in lockstep by construction, for training
        # and eval alike.  Pixels are only ever decoded for local rows.
        self.roidb = list(roidb)
        self._rank = rank
        self._world = world
        if world > 1 and batch_size % world:
            raise ValueError(
                f"batch_size {batch_size} not divisible by world={world}"
            )
        self.cfg = cfg
        self.batch_size = batch_size
        self.train = train
        self.seed = seed
        self.with_masks = with_masks
        self.prefetch = prefetch and train
        if num_workers is None:
            # Scale with the host: decode+letterbox is ~15ms/image/core at
            # 1024^2 while a v5e consumes ~2ms/image — TPU hosts have the
            # cores; a 1-core CI box gets no pool (threads only add churn).
            import os as _os

            cores = _os.cpu_count() or 1
            num_workers = min(8, cores) if cores > 1 else 0
        self.num_workers = num_workers if train else 0
        self.proposals = proposals
        self.num_proposals = num_proposals
        self.run_length = max(run_length, 1)
        ch, cw = cfg.image_size
        self._square_canvas = ch == cw
        if not self._square_canvas and train and not cfg.aspect_grouping:
            # Mixed-orientation batches cannot stack into one static canvas;
            # the orientation-bucketed recipe requires the reference's
            # ASPECT_GROUPING (on by default).
            raise ValueError(
                "non-square image_size (orientation-bucketed canvases) "
                "requires data.aspect_grouping=true"
            )
        if proposals is not None:
            missing = [r.image_id for r in self.roidb if r.image_id not in proposals]
            if missing:
                raise ValueError(
                    f"{len(missing)} roidb image(s) have no proposals "
                    f"(first: {missing[0]!r})"
                )
        if not self.roidb:
            raise ValueError("empty roidb shard")
        bad_env = os.environ.get(CHAOS_BAD_IMAGES_ENV, "")
        self._chaos_bad_images = frozenset(
            tok.strip() for tok in bad_env.split(",") if tok.strip()
        )
        if self._chaos_bad_images:
            log.warning(
                "chaos: simulated-corrupt image ids armed: %s",
                sorted(self._chaos_bad_images),
            )
        nan_env = os.environ.get(CHAOS_NAN_ENV, "") if train else ""
        self._nan_steps = frozenset(
            int(tok) for tok in nan_env.split(",") if tok.strip()
        )
        if self._nan_steps:
            log.warning(
                "chaos: NaN injection armed for global batch indices %s",
                sorted(self._nan_steps),
            )

    # -- ordering ----------------------------------------------------------

    def _epoch_batches(self, epoch: int) -> list[np.ndarray]:
        """Shuffled FULL batches for one epoch, each single-orientation
        under aspect grouping (so every batch maps to one static canvas),
        grouped into runs of ``run_length`` same-orientation batches
        (stacked steps_per_call calls need identically-shaped batches).
        A group's tail that can't fill a batch (or a run) is padded by
        wrapping within the group — a small orientation group slightly
        oversamples rather than silently starving (the reference pads its
        final batch the same wrap-around way)."""
        n = len(self.roidb)
        bs = self.batch_size
        rng = np.random.RandomState(self.seed + epoch)
        if not self.cfg.aspect_grouping:
            order = rng.permutation(n)
            return [order[i:i + bs] for i in range(0, n - bs + 1, bs)]
        # Reference ASPECT_GROUPING: batch wide with wide, tall with tall.
        aspects = np.array([r.aspect for r in self.roidb])
        # Same-canvas run grouping only matters when orientations map to
        # different canvases; square canvases keep run=1 so the batch
        # schedule is IDENTICAL for any steps_per_call (a pinned property:
        # the scan loop must train bit-like the sequential loop).
        run = 1 if self._square_canvas else self.run_length
        runs: list[list[np.ndarray]] = []
        for group in (np.flatnonzero(aspects >= 1), np.flatnonzero(aspects < 1)):
            if len(group) == 0:
                continue
            rng.shuffle(group)
            batches = [
                group[i:i + bs] for i in range(0, len(group) - bs + 1, bs)
            ]
            if len(group) % bs:
                # Wrap-around fill of the group's tail batch.
                batches.append(
                    np.resize(group, (len(batches) + 1) * bs)[-bs:]
                )
            if len(batches) % run:
                # Wrap whole batches to complete the final run.
                need = run - len(batches) % run
                batches.extend(batches[i % len(batches)] for i in range(need))
            runs.extend(
                batches[i:i + run] for i in range(0, len(batches), run)
            )
        rng.shuffle(runs)
        return [b for r in runs for b in r]

    # -- single image ------------------------------------------------------

    def _quarantine(
        self, rec: RoiRecord, error: BaseException, reason: str = "io"
    ) -> None:
        retries = self.io_retries if reason == "io" else 0
        with self._quarantine_lock:
            if rec.image_id in self._quarantined:
                return  # already recorded; don't re-log every epoch
            self._quarantined.add(rec.image_id)
            log.error(
                "quarantining image %r (%s; %s: %s) after %d retries; "
                "substituting a blank example",
                rec.image_id, reason, type(error).__name__, error, retries,
            )
            if self.quarantine_path is None:
                return
            os.makedirs(
                os.path.dirname(self.quarantine_path) or ".", exist_ok=True
            )
            with open(self.quarantine_path, "a") as f:
                f.write(json.dumps({
                    "image_id": rec.image_id,
                    "path": rec.image_path,
                    "reason": reason,
                    "error": f"{type(error).__name__}: {error}",
                    "retries": retries,
                }) + "\n")

    def _blank_pixels(self, rec: RoiRecord) -> np.ndarray:
        """A zero canvas in the record's NATIVE dtype — a uint8 blank inside
        an otherwise-float (synthetic/host-normalized) batch would trip the
        mixed-dtype guard in ``_assemble``."""
        if rec.image_array is not None:
            return np.zeros_like(rec.image_array)
        return np.zeros((rec.height, rec.width, 3), np.uint8)

    def _load_image(self, rec: RoiRecord) -> tuple[np.ndarray, bool]:
        """``(pixels, ok)`` — bounded retry on I/O errors, then a blank
        canvas with ``ok=False`` (the caller invalidates the gt)."""
        err: Optional[BaseException] = None
        for attempt in range(self.io_retries + 1):
            try:
                if rec.image_id in self._chaos_bad_images:
                    raise ValueError("chaos: simulated corrupt image")
                return load_image(rec), True
            except (OSError, ValueError) as e:
                err = e
                if attempt < self.io_retries:
                    time.sleep(0.1 * (2 ** attempt))
        self._quarantine(rec, err)
        return self._blank_pixels(rec), False

    def _example(self, rec: RoiRecord, flip: bool):
        if rec.image_id in self._bad_annotations:
            # Quarantined annotations take the same substitution as
            # quarantined pixels: blank canvas, zero gt slots.  The stand-in
            # record never touches the (possibly malformed) box/class arrays.
            import dataclasses

            rec = dataclasses.replace(
                rec,
                boxes=np.zeros((0, 4), np.float32),
                gt_classes=np.zeros((0,), np.int32),
                ignore=None,
                masks=None,
                image_array=self._blank_pixels(rec),
                image_path="",
            )
        img, img_ok = self._load_image(rec)
        boxes = rec.boxes
        if flip:
            img, boxes = hflip(img, boxes, rec.width)
        canvas = self.record_canvas(rec)
        scale = self.record_scale(rec)
        nh = int(round(rec.height * scale))
        nw = int(round(rec.width * scale))
        if img.dtype == np.uint8 and not self.cfg.normalize_on_host:
            # Default path: uint8 letterbox, normalization deferred into the
            # jitted graph (graph.py::prep_images) — the batch ships 1/4 the
            # bytes of float32 host-normalized pixels.  uint8->uint8 resize
            # is also what the reference does (rcnn/io/image.py resizes the
            # uint8 image before the float mean-subtract).
            img = letterbox_uint8(img, canvas, nh, nw)
            boxes = boxes.astype(np.float32) * scale
            th, tw = nh, nw
        else:
            native = None
            if img.dtype == np.uint8:
                # Fused C++ resize+pad+normalize (mx_rcnn_tpu/native);
                # replaces the reference's two-pass cv2-resize + numpy
                # mean-subtract (rcnn/io/image.py) on the loader hot path.
                # None when the shared library isn't built — fall through
                # to the numpy letterbox.
                from mx_rcnn_tpu.native import letterbox_normalize

                native = letterbox_normalize(
                    img, canvas, nh, nw, scale,
                    self.cfg.pixel_mean, self.cfg.pixel_std,
                )
            if native is not None:
                img = native
                boxes = boxes.astype(np.float32) * scale
                th, tw = nh, nw
            else:
                img, boxes, scale, (th, tw) = letterbox(
                    img.astype(np.float32), boxes, canvas,
                    self.cfg.short_side, self.cfg.max_side,
                )
                img = normalize_image(
                    img, self.cfg.pixel_mean, self.cfg.pixel_std
                )
        g = self.cfg.max_gt_boxes
        n = min(len(boxes), g)
        ign = rec.ignore_flags
        gt_boxes = np.zeros((g, 4), np.float32)
        gt_classes = np.zeros((g,), np.int32)
        gt_valid = np.zeros((g,), bool)
        gt_ignore = np.zeros((g,), bool)
        gt_boxes[:n] = boxes[:n]
        gt_classes[:n] = rec.gt_classes[:n]
        # A slot is either a real gt (valid), an ignore region (crowd/
        # difficult — never fg, shields bg sampling), or padding (neither).
        gt_valid[:n] = ~ign[:n]
        gt_ignore[:n] = ign[:n]
        if not img_ok:
            # Quarantined image: blank pixels with no gt — contributes
            # nothing to the loss but keeps every shape (and therefore
            # every collective) identical across hosts.
            gt_valid[:] = False
            gt_ignore[:] = False
        masks = None
        if self.with_masks:
            masks = np.zeros((g, GT_MASK_SIZE, GT_MASK_SIZE), np.float32)
            if rec.masks is not None:
                for i in range(n):
                    if ign[i]:
                        # Ignore slots can never be fg mask targets (IoU is
                        # masked by gt_valid); crowd RLEs are also the most
                        # expensive to rasterize.
                        continue
                    m = _rasterize_mask(rec.masks[i], rec.boxes[i])
                    masks[i] = m[:, ::-1] if flip else m
        ext = None
        if self.proposals is not None:
            # External proposals ride the exact same geometry as gt boxes:
            # flip in original coords, then the letterbox scale.
            p = self.proposals[rec.image_id]
            pb = np.asarray(p["boxes"], np.float32).reshape(-1, 4)
            ps = np.asarray(p["scores"], np.float32).reshape(len(pb))
            if flip:
                pb = flip_boxes(pb, rec.width)
            order = np.argsort(-ps, kind="mergesort")[: self.num_proposals]
            pb = pb[order] * scale
            np.clip(pb[:, 0::2], 0.0, tw - 1.0, out=pb[:, 0::2])
            np.clip(pb[:, 1::2], 0.0, th - 1.0, out=pb[:, 1::2])
            ext_rois = np.zeros((self.num_proposals, 4), np.float32)
            ext_valid = np.zeros((self.num_proposals,), bool)
            ext_rois[: len(pb)] = pb
            ext_valid[: len(pb)] = True
            ext = (ext_rois, ext_valid)
        return (
            img, (th, tw), gt_boxes, gt_classes, gt_valid, gt_ignore, masks,
            ext, scale,
        )

    def _assemble(self, recs: list[RoiRecord], flips: list[bool]) -> Batch:
        ims, hws, bs, cs, vs, igs, ms, ers, evs = [], [], [], [], [], [], [], [], []
        for rec, fl in zip(recs, flips):
            img, (th, tw), gb, gc, gv, gi, gm, ext, _ = self._example(rec, fl)
            if ims and img.dtype != ims[0].dtype:
                # A uint8 record rides raw (normalized in-graph) while a
                # float record arrives host-normalized; np.stack would
                # silently promote the mix to float32 and feed RAW 0-255
                # uint8 pixels past prep_images' dtype gate.
                raise ValueError(
                    f"mixed image dtypes in one batch ({ims[0].dtype} vs "
                    f"{img.dtype} for {rec.image_id!r}); a roidb must be "
                    "uniformly uint8 or float (or set "
                    "data.normalize_on_host=true)"
                )
            ims.append(img)
            hws.append([th, tw])
            bs.append(gb)
            cs.append(gc)
            vs.append(gv)
            igs.append(gi)
            if gm is not None:
                ms.append(gm)
            if ext is not None:
                ers.append(ext[0])
                evs.append(ext[1])
        return Batch(
            images=np.stack(ims),
            image_hw=np.asarray(hws, np.float32),
            gt_boxes=np.stack(bs),
            gt_classes=np.stack(cs),
            gt_valid=np.stack(vs),
            gt_masks=np.stack(ms) if ms else None,
            gt_ignore=np.stack(igs) if self.with_ignore else None,
            ext_rois=np.stack(ers) if ers else None,
            ext_valid=np.stack(evs) if evs else None,
        )

    # -- iteration ---------------------------------------------------------

    def _batch_specs(self):
        """Infinite (records, flips) stream in GLOBAL epoch order.

        The schedule (shuffle order, flip draws) is derived identically on
        every host; multi-host runs slice each global spec to their rank's
        rows (``_local_rows``), so the flip rng must be consumed for the
        full global batch here, not per local slice."""
        epoch = 0
        rng = np.random.RandomState(self.seed + 17)
        while True:
            for batch_idx in self._epoch_batches(epoch):
                recs = [self.roidb[j] for j in batch_idx]
                flips = [
                    self.cfg.flip and bool(rng.randint(2)) for _ in recs
                ]
                yield recs, flips
            epoch += 1

    def _local_rows(self, recs, flips):
        """This host's rows of a global (records, flips) spec."""
        local = self.batch_size // self._world
        lo = self._rank * local
        return recs[lo:lo + local], flips[lo:lo + local]

    def _poison(self, batch: Batch, idx: int) -> Batch:
        """Chaos hook (CHAOS_NAN_ENV): replace the batch's pixels with NaN."""
        if not np.issubdtype(batch.images.dtype, np.floating):
            raise ValueError(
                f"{CHAOS_NAN_ENV} needs float images (synthetic/normalized "
                f"paths); batch {idx} is {batch.images.dtype}"
            )
        log.warning("chaos: injecting NaN images at global batch %d", idx)
        return batch._replace(
            images=np.full_like(batch.images, np.nan)
        )

    def _train_batches(self, skip_batches: int = 0) -> Iterator[Batch]:
        it = self._raw_train_batches(skip_batches)
        if not self._nan_steps:
            yield from it
            return
        # Both paths below yield batches in global-schedule order, so the
        # yielded position IS the global batch index.
        for idx, batch in enumerate(it, start=skip_batches):
            yield self._poison(batch, idx) if idx in self._nan_steps else batch

    def _raw_train_batches(self, skip_batches: int = 0) -> Iterator[Batch]:
        specs = self._batch_specs()
        # Resume fast-forward: spec generation (shuffle order + flip draws)
        # is cheap; skipping specs instead of restarting keeps the resumed
        # run on the same data schedule as an uninterrupted one.
        for _ in range(skip_batches):
            next(specs)
        if self.num_workers <= 1:
            for recs, flips in specs:
                yield self._assemble(*self._local_rows(recs, flips))
            return
        # Worker pool assembling num_workers batches ahead, yielded in
        # order.  Decode/resize/normalize release the GIL (cv2 and the C++
        # letterbox kernel), so threads give real parallelism — the TPU
        # step is ~2ms/image while host assembly is ~5-10ms/image.
        import collections
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(self.num_workers) as pool:
            pending = collections.deque(
                pool.submit(self._assemble, *self._local_rows(*next(specs)))
                for _ in range(self.num_workers)
            )
            while True:
                pending.append(
                    pool.submit(self._assemble, *self._local_rows(*next(specs)))
                )
                yield pending.popleft().result()

    def eval_specs(self) -> list[tuple[list[RoiRecord], list[RoiRecord]]]:
        """The GLOBAL eval batch schedule with NO pixel decode: one
        ``(local_rows, global_records)`` entry per eval batch.

        This is the schedule ``_eval_batches`` assembles pixels for; it is
        exposed separately so resumable evaluation (evalutil/pred_eval.py)
        can fingerprint the schedule, partition it into shards, and skip
        completed shards without paying a decode for batches it will never
        run.

        Non-square canvases: landscape images first, then portrait, each in
        roidb order — every batch shares one canvas (two compiled eval
        programs).  Detections map back through the records, so the
        reordering is invisible to the evaluator.

        Multi-host (world > 1): every host derives the SAME global schedule
        from the full roidb; ``local_rows`` is this rank's slice of each
        padded global batch — per-step collectives stay in lockstep by
        construction, and rank-local batches concatenate into exactly the
        single-host global batch.
        """
        rank, world = self._rank, self._world
        local = self.batch_size // world
        if self._square_canvas:
            groups = [self.roidb]
        else:
            groups = [
                [r for r in self.roidb if r.aspect >= 1],
                [r for r in self.roidb if r.aspect < 1],
            ]
        specs = []
        for group in groups:
            for i in range(0, len(group), self.batch_size):
                recs = group[i : i + self.batch_size]
                pad = self.batch_size - len(recs)
                padded = recs + [recs[-1]] * pad
                specs.append((padded[rank * local : (rank + 1) * local], recs))
        return specs

    def eval_batch_range(self, start: int = 0, stop: Optional[int] = None):
        """Assemble and yield eval batches ``start:stop`` of the global
        schedule (``eval_specs`` order).  Sharded/resumable evaluation runs
        each shard as one contiguous range and never decodes pixels for
        batches outside it."""
        for rows, recs in self.eval_specs()[start:stop]:
            yield self._assemble(rows, [False] * len(rows)), recs

    def _eval_batches(self, skip_batches: int = 0):
        return self.eval_batch_range(skip_batches)

    def __iter__(self):
        return self.iter_from()

    def iter_from(self, skip_batches: int = 0):
        """Iterate, skipping the first ``skip_batches`` batches (resume
        continuity: step k of a resumed run sees the batch step k of an
        uninterrupted run would have — training and eval alike)."""
        if not self.train:
            return self._eval_batches(skip_batches)
        it = self._train_batches(skip_batches)
        if not self.prefetch:
            return it
        return _prefetched(it, depth=2)

    def record_canvas(self, rec: RoiRecord) -> tuple[int, int]:
        """The static canvas this record letterboxes into (orientation-
        matched transpose of ``cfg.image_size`` for portrait images)."""
        return oriented_canvas(self.cfg.image_size, rec.height, rec.width)

    def record_scale(self, rec: RoiRecord) -> float:
        """The letterbox scale applied to a record (for box un-scaling at
        eval, the reference's ``/ im_scale`` in ``im_detect``).  With an
        orientation-matched canvas sized for the short/max rule the clamp
        terms only guard rounding — the recipe scale always fits."""
        ch, cw = self.record_canvas(rec)
        return min(
            resize_scale(rec.height, rec.width, self.cfg.short_side, self.cfg.max_side),
            ch / rec.height,
            cw / rec.width,
        )


def _prefetched(it: Iterator, depth: int = 2) -> Iterator:
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = object()

    def worker():
        try:
            for item in it:
                q.put(item)
        finally:
            q.put(stop)

    threading.Thread(target=worker, daemon=True).start()
    while True:
        item = q.get()
        if item is stop:
            return
        yield item
