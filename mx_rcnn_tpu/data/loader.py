"""Batch assembly: roidb → statically-shaped Batch pytrees.

Replaces ``rcnn/core/loader.py::AnchorLoader`` minus the anchor labeling
(in-graph now).  Keeps the reference's load-time behaviors: epoch shuffle,
aspect-ratio grouping (``ASPECT_GROUPING`` — portrait/landscape batched
together so letterbox padding is minimized), flip augmentation, per-host
sharding for data parallelism (the reference slices batches across
``ctx`` GPUs; here each host process reads ``roidb[rank::world]`` and the
mesh shards the global batch).  A one-deep background prefetch thread
overlaps host decode with device compute (the reference relied on MXNet's
threaded DataIter for the same).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import numpy as np

from mx_rcnn_tpu.config import DataConfig
from mx_rcnn_tpu.data.roidb import RoiRecord
from mx_rcnn_tpu.data.transforms import (
    flip_boxes,
    hflip,
    letterbox,
    normalize_image,
    resize_scale,
)
from mx_rcnn_tpu.detection.graph import Batch

try:
    import cv2
except Exception:  # pragma: no cover
    cv2 = None

# Box-relative resolution at which gt instance masks are rasterized on host;
# the device crops these to the mask head's target size per sampled roi.
GT_MASK_SIZE = 112


def load_proposals(path: str) -> dict:
    """Load and validate a proposal pkl (``test.py --proposals`` format:
    image_id → {"boxes": (n, 4) original-image coords, "scores": (n,)}).
    Fails fast on schema problems instead of mid-epoch in the loader."""
    import pickle

    with open(path, "rb") as f:
        props = pickle.load(f)
    if not isinstance(props, dict) or not props:
        raise ValueError(f"{path}: expected a non-empty image_id->dict map")
    for key, p in props.items():
        boxes = np.asarray(p.get("boxes", None))
        scores = np.asarray(p.get("scores", None))
        if boxes.ndim != 2 or boxes.shape[1] != 4 or scores.shape != boxes.shape[:1]:
            raise ValueError(
                f"{path}: image {key!r} needs boxes (n, 4) + scores (n,), "
                f"got {boxes.shape} / {scores.shape}"
            )
        break  # spot-check one entry; full arrays validate lazily per image
    return props


def load_image(rec: RoiRecord) -> np.ndarray:
    """uint8 RGB from disk (float32 for in-memory synthetic images)."""
    if rec.image_array is not None:
        return rec.image_array
    if cv2 is None:  # pragma: no cover
        from PIL import Image

        return np.asarray(Image.open(rec.image_path).convert("RGB"), np.uint8)
    img = cv2.imread(rec.image_path, cv2.IMREAD_COLOR)
    if img is None:
        raise FileNotFoundError(rec.image_path)
    return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)


def _rasterize_mask(seg, box: np.ndarray) -> np.ndarray:
    """Polygon/RLE segmentation → (GT_MASK_SIZE,)*2 box-relative float mask."""
    out = np.zeros((GT_MASK_SIZE, GT_MASK_SIZE), np.float32)
    if seg is None or cv2 is None:
        return out
    x1, y1, x2, y2 = box
    bw, bh = max(x2 - x1 + 1, 1.0), max(y2 - y1 + 1, 1.0)
    if isinstance(seg, list):  # polygons in image coords
        polys = []
        for p in seg:
            pts = np.asarray(p, np.float32).reshape(-1, 2)
            pts[:, 0] = (pts[:, 0] - x1) / bw * GT_MASK_SIZE
            pts[:, 1] = (pts[:, 1] - y1) / bh * GT_MASK_SIZE
            polys.append(pts.round().astype(np.int32))
        cv2.fillPoly(out, polys, 1.0)
    elif isinstance(seg, dict):  # uncompressed RLE {"counts": [...], "size": [h, w]}
        h, w = seg["size"]
        counts = seg["counts"]
        if isinstance(counts, list):
            flat = np.zeros(h * w, np.uint8)
            pos, val = 0, 0
            for c in counts:
                flat[pos : pos + c] = val
                pos += c
                val = 1 - val
            full = flat.reshape((w, h)).T.astype(np.float32)
            crop = full[
                int(max(y1, 0)) : int(y2) + 1, int(max(x1, 0)) : int(x2) + 1
            ]
            if crop.size:
                out = cv2.resize(crop, (GT_MASK_SIZE, GT_MASK_SIZE))
    return out


class DetectionLoader:
    """Iterable over statically-shaped Batches.

    train=True: infinite, shuffled per epoch, flip augmentation.
    train=False: one pass in roidb order, no flip, yields (batch, records)
    so eval can map detections back to image ids and scales.
    """

    def __init__(
        self,
        roidb: list[RoiRecord],
        cfg: DataConfig,
        batch_size: int,
        train: bool = True,
        seed: int = 0,
        rank: int = 0,
        world: int = 1,
        with_masks: bool = False,
        prefetch: bool = True,
        num_workers: Optional[int] = None,
        proposals: Optional[dict] = None,
        num_proposals: int = 1000,
    ) -> None:
        """``proposals``: image_id → {"boxes": (n, 4) ORIGINAL-image coords,
        "scores": (n,)} (the ``test.py --proposals`` pkl format) — shipped
        per batch as score-ordered, letterbox-scaled, padded ext_rois for
        Fast R-CNN training/testing (reference ``ROIIter``).  Boxes are
        truncated/padded to the static ``num_proposals``."""
        self.roidb = list(roidb[rank::world]) if world > 1 else list(roidb)
        self.cfg = cfg
        self.batch_size = batch_size
        self.train = train
        self.seed = seed
        self.with_masks = with_masks
        self.prefetch = prefetch and train
        if num_workers is None:
            # Scale with the host: decode+letterbox is ~15ms/image/core at
            # 1024^2 while a v5e consumes ~2ms/image — TPU hosts have the
            # cores; a 1-core CI box gets no pool (threads only add churn).
            import os as _os

            cores = _os.cpu_count() or 1
            num_workers = min(8, cores) if cores > 1 else 0
        self.num_workers = num_workers if train else 0
        self.proposals = proposals
        self.num_proposals = num_proposals
        if proposals is not None:
            missing = [r.image_id for r in self.roidb if r.image_id not in proposals]
            if missing:
                raise ValueError(
                    f"{len(missing)} roidb image(s) have no proposals "
                    f"(first: {missing[0]!r})"
                )
        if not self.roidb:
            raise ValueError("empty roidb shard")
        # Datasets without any ignore regions ship gt_ignore=None so the
        # train graph keeps the cheaper no-IoA form (the flag decides the
        # jitted program's pytree structure, so it must be per-run, not
        # per-batch).
        self.with_ignore = any(r.ignore_flags.any() for r in self.roidb)

    # -- ordering ----------------------------------------------------------

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self.roidb)
        rng = np.random.RandomState(self.seed + epoch)
        if not self.cfg.aspect_grouping:
            return rng.permutation(n)
        # Reference ASPECT_GROUPING: batch wide with wide, tall with tall.
        aspects = np.array([r.aspect for r in self.roidb])
        horz = np.flatnonzero(aspects >= 1)
        vert = np.flatnonzero(aspects < 1)
        rng.shuffle(horz)
        rng.shuffle(vert)
        inds = np.concatenate([horz, vert])
        # Shuffle whole batches so groups stay contiguous.
        nb = n // self.batch_size
        if nb > 0:
            batches = inds[: nb * self.batch_size].reshape(nb, self.batch_size)
            batches = batches[rng.permutation(nb)]
            inds = np.concatenate([batches.reshape(-1), inds[nb * self.batch_size:]])
        return inds

    # -- single image ------------------------------------------------------

    def _example(self, rec: RoiRecord, flip: bool):
        img = load_image(rec)
        boxes = rec.boxes
        if flip:
            img, boxes = hflip(img, boxes, rec.width)
        scale = self.record_scale(rec)
        nh = int(round(rec.height * scale))
        nw = int(round(rec.width * scale))
        native = None
        if img.dtype == np.uint8:
            # Fused C++ resize+pad+normalize (mx_rcnn_tpu/native); replaces
            # the reference's two-pass cv2-resize + numpy mean-subtract
            # (rcnn/io/image.py) on the loader hot path.
            from mx_rcnn_tpu.native import letterbox_normalize

            native = letterbox_normalize(
                img, self.cfg.image_size, nh, nw, scale,
                self.cfg.pixel_mean, self.cfg.pixel_std,
            )
        if native is not None:
            img = native
            boxes = boxes.astype(np.float32) * scale
            th, tw = nh, nw
        else:
            img, boxes, scale, (th, tw) = letterbox(
                img.astype(np.float32), boxes, self.cfg.image_size,
                self.cfg.short_side, self.cfg.max_side,
            )
            img = normalize_image(img, self.cfg.pixel_mean, self.cfg.pixel_std)
        g = self.cfg.max_gt_boxes
        n = min(len(boxes), g)
        ign = rec.ignore_flags
        gt_boxes = np.zeros((g, 4), np.float32)
        gt_classes = np.zeros((g,), np.int32)
        gt_valid = np.zeros((g,), bool)
        gt_ignore = np.zeros((g,), bool)
        gt_boxes[:n] = boxes[:n]
        gt_classes[:n] = rec.gt_classes[:n]
        # A slot is either a real gt (valid), an ignore region (crowd/
        # difficult — never fg, shields bg sampling), or padding (neither).
        gt_valid[:n] = ~ign[:n]
        gt_ignore[:n] = ign[:n]
        masks = None
        if self.with_masks:
            masks = np.zeros((g, GT_MASK_SIZE, GT_MASK_SIZE), np.float32)
            if rec.masks is not None:
                for i in range(n):
                    if ign[i]:
                        # Ignore slots can never be fg mask targets (IoU is
                        # masked by gt_valid); crowd RLEs are also the most
                        # expensive to rasterize.
                        continue
                    m = _rasterize_mask(rec.masks[i], rec.boxes[i])
                    masks[i] = m[:, ::-1] if flip else m
        ext = None
        if self.proposals is not None:
            # External proposals ride the exact same geometry as gt boxes:
            # flip in original coords, then the letterbox scale.
            p = self.proposals[rec.image_id]
            pb = np.asarray(p["boxes"], np.float32).reshape(-1, 4)
            ps = np.asarray(p["scores"], np.float32).reshape(len(pb))
            if flip:
                pb = flip_boxes(pb, rec.width)
            order = np.argsort(-ps, kind="mergesort")[: self.num_proposals]
            pb = pb[order] * scale
            np.clip(pb[:, 0::2], 0.0, tw - 1.0, out=pb[:, 0::2])
            np.clip(pb[:, 1::2], 0.0, th - 1.0, out=pb[:, 1::2])
            ext_rois = np.zeros((self.num_proposals, 4), np.float32)
            ext_valid = np.zeros((self.num_proposals,), bool)
            ext_rois[: len(pb)] = pb
            ext_valid[: len(pb)] = True
            ext = (ext_rois, ext_valid)
        return (
            img, (th, tw), gt_boxes, gt_classes, gt_valid, gt_ignore, masks,
            ext, scale,
        )

    def _assemble(self, recs: list[RoiRecord], flips: list[bool]) -> Batch:
        ims, hws, bs, cs, vs, igs, ms, ers, evs = [], [], [], [], [], [], [], [], []
        for rec, fl in zip(recs, flips):
            img, (th, tw), gb, gc, gv, gi, gm, ext, _ = self._example(rec, fl)
            ims.append(img)
            hws.append([th, tw])
            bs.append(gb)
            cs.append(gc)
            vs.append(gv)
            igs.append(gi)
            if gm is not None:
                ms.append(gm)
            if ext is not None:
                ers.append(ext[0])
                evs.append(ext[1])
        return Batch(
            images=np.stack(ims),
            image_hw=np.asarray(hws, np.float32),
            gt_boxes=np.stack(bs),
            gt_classes=np.stack(cs),
            gt_valid=np.stack(vs),
            gt_masks=np.stack(ms) if ms else None,
            gt_ignore=np.stack(igs) if self.with_ignore else None,
            ext_rois=np.stack(ers) if ers else None,
            ext_valid=np.stack(evs) if evs else None,
        )

    # -- iteration ---------------------------------------------------------

    def _batch_specs(self):
        """Infinite (records, flips) stream in epoch order."""
        epoch = 0
        rng = np.random.RandomState(self.seed + 17)
        while True:
            order = self._epoch_order(epoch)
            for i in range(0, len(order) - self.batch_size + 1, self.batch_size):
                recs = [self.roidb[j] for j in order[i : i + self.batch_size]]
                flips = [
                    self.cfg.flip and bool(rng.randint(2)) for _ in recs
                ]
                yield recs, flips
            epoch += 1

    def _train_batches(self, skip_batches: int = 0) -> Iterator[Batch]:
        specs = self._batch_specs()
        # Resume fast-forward: spec generation (shuffle order + flip draws)
        # is cheap; skipping specs instead of restarting keeps the resumed
        # run on the same data schedule as an uninterrupted one.
        for _ in range(skip_batches):
            next(specs)
        if self.num_workers <= 1:
            for recs, flips in specs:
                yield self._assemble(recs, flips)
            return
        # Worker pool assembling num_workers batches ahead, yielded in
        # order.  Decode/resize/normalize release the GIL (cv2 and the C++
        # letterbox kernel), so threads give real parallelism — the TPU
        # step is ~2ms/image while host assembly is ~5-10ms/image.
        import collections
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(self.num_workers) as pool:
            pending = collections.deque(
                pool.submit(self._assemble, *next(specs))
                for _ in range(self.num_workers)
            )
            while True:
                pending.append(pool.submit(self._assemble, *next(specs)))
                yield pending.popleft().result()

    def _eval_batches(self):
        n = len(self.roidb)
        for i in range(0, n, self.batch_size):
            recs = self.roidb[i : i + self.batch_size]
            pad = self.batch_size - len(recs)
            padded = recs + [recs[-1]] * pad
            batch = self._assemble(padded, [False] * len(padded))
            yield batch, recs

    def __iter__(self):
        return self.iter_from()

    def iter_from(self, skip_batches: int = 0):
        """Iterate, skipping the first ``skip_batches`` training batches
        (resume continuity: step k of a resumed run sees the batch step k
        of an uninterrupted run would have)."""
        if not self.train:
            return self._eval_batches()
        it = self._train_batches(skip_batches)
        if not self.prefetch:
            return it
        return _prefetched(it, depth=2)

    def record_scale(self, rec: RoiRecord) -> float:
        """The letterbox scale applied to a record (for box un-scaling at
        eval, the reference's ``/ im_scale`` in ``im_detect``)."""
        return min(
            resize_scale(rec.height, rec.width, self.cfg.short_side, self.cfg.max_side),
            self.cfg.image_size[0] / rec.height,
            self.cfg.image_size[1] / rec.width,
        )


def _prefetched(it: Iterator, depth: int = 2) -> Iterator:
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = object()

    def worker():
        try:
            for item in it:
                q.put(item)
        finally:
            q.put(stop)

    threading.Thread(target=worker, daemon=True).start()
    while True:
        item = q.get()
        if item is stop:
            return
        yield item
