"""The roidb record contract and its utilities.

Mirrors the reference's roidb list-of-dicts (``rcnn/dataset/imdb.py``:
``boxes, gt_classes, flipped, image, height, width``) minus the fields that
only existed to serve host-side sampling (``gt_overlaps, max_classes,
max_overlaps`` — IoU matching is in-graph now).  ``flipped`` stays a
record-level flag (reference: ``append_flipped_images`` doubles the roidb)
but flipping is applied at load time on pixels+boxes, so no second copy of
the dataset lives in memory.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class RoiRecord:
    image_id: str
    image_path: str            # "" for synthetic/in-memory images
    height: int
    width: int
    boxes: np.ndarray          # (n, 4) float32 x1 y1 x2 y2, unflipped coords
    gt_classes: np.ndarray     # (n,) int32, 1-based foreground labels
    flipped: bool = False
    # Optional instance masks as per-box binary maps in image coords
    # (COCO polygon/RLE decoded lazily by the dataset).
    masks: Optional[list] = None
    # In-memory image for synthetic data.
    image_array: Optional[np.ndarray] = field(default=None, repr=False)
    # (n,) bool: COCO crowd / VOC difficult regions.  Kept in the roidb
    # (the reference drops them — ``rcnn/dataset/coco.py`` skips iscrowd,
    # ``rcnn/dataset/pascal_voc.py`` drops difficult) so training can
    # exclude them from negatives and eval can ignore-match them.  None
    # means all-False.  Datasets order non-ignore boxes first so gt-slot
    # truncation sheds ignore regions before real objects.
    ignore: Optional[np.ndarray] = None

    @property
    def aspect(self) -> float:
        return self.width / max(self.height, 1)

    @property
    def ignore_flags(self) -> np.ndarray:
        """(n,) bool ignore mask, materialized (None → all False)."""
        if self.ignore is None:
            return np.zeros(len(self.boxes), bool)
        return np.asarray(self.ignore, bool)


def filter_roidb(roidb: list[RoiRecord]) -> list[RoiRecord]:
    """Drop images without valid (non-ignore) gt boxes (reference:
    ``rcnn/utils/load_data.py::filter_roidb``)."""
    kept = [r for r in roidb if int((~r.ignore_flags).sum()) > 0]
    return kept


def merge_roidb(roidbs: list[list[RoiRecord]]) -> list[RoiRecord]:
    """Concatenate roidbs from several splits (reference: merge_roidb,
    used for 07+12 VOC training)."""
    out: list[RoiRecord] = []
    for r in roidbs:
        out.extend(r)
    return out


def with_flipped(roidb: list[RoiRecord]) -> list[RoiRecord]:
    """Append flipped duplicates (reference: append_flipped_images).  Only
    the flag differs; pixel/box flipping happens in the loader."""
    flipped = [
        RoiRecord(
            image_id=r.image_id,
            image_path=r.image_path,
            height=r.height,
            width=r.width,
            boxes=r.boxes,
            gt_classes=r.gt_classes,
            flipped=True,
            masks=r.masks,
            image_array=r.image_array,
            ignore=r.ignore,
        )
        for r in roidb
    ]
    return list(roidb) + flipped
