"""Crash-tolerant multi-process input service.

The decode/augment half of the input pipeline, promoted from threads
inside the training process to a pool of **independent failure domains**:
spawned worker processes that can die (OOM-killed decode, a segfaulting
image codec, chaos SIGKILL) or wedge (stuck NFS read) without taking the
run down or perturbing the data schedule.

Determinism doctrine (the property every robustness mechanism below must
preserve): batch CONTENT is a pure function of the global batch index —
the parent derives the schedule (shuffle order, flip draws) exactly as
the in-process loader does and ships each batch as a ``(index, spec)``
task, where the spec is just roidb row indices + flip flags.  Workers
only assemble pixels; they never draw randomness or see the schedule.
Results are reordered on the consumer side by index, so the yielded
stream is **bit-identical for any worker count, after any worker death
or reassignment, and on resume** — the PR-3 bit-exact chaos guarantee
holds with workers ON (proved by ``tools/chaos.py --scenario
data_worker_kill``).

Failure handling mirrors the serving fleet (serve/fleet.py):

- **Heartbeats + watchdog** — each worker stamps a shared heartbeat slot
  from its main loop only (a wedged decode therefore stales it; a
  background-thread heartbeat would mask exactly the failure it exists
  to catch).  The consumer doubles as watchdog: a dead process or a
  stale heartbeat gets the worker killed.
- **Deterministic reassignment** — the dead worker's private queues are
  discarded, its delivered-but-unconsumed results are salvaged, and its
  remaining in-flight batch indices go back on the pending heap for live
  workers; the respawned worker starts clean.
- **Bounded respawns** — each worker slot carries a respawn budget;
  exhausting every slot raises the typed :class:`InputServiceDead` (or,
  with ``fallback=True``, degrades to in-process synchronous assembly
  with a logged health transition — the run completes, slower).
- **Backpressure** — per-worker result queues are bounded, so workers
  block (still heartbeating) instead of ballooning host RAM when the
  consumer is slow.  Per-worker result queues also isolate the failure:
  a worker SIGKILLed mid-write can only tear its own pipe, which dies
  with it — a shared queue would corrupt every producer's stream.
- **Orphan protection** — workers poll ``getppid`` and exit when the
  parent vanishes (a SIGKILLed parent can run no cleanup), so chaos
  kills never leak decode processes.

- **Zero-copy shm transport** (``shm_slots > 0``) — each worker owns a
  CRC-stamped shared-memory ring (data/shm_ring.py) and ships batches as
  slot references instead of pickles; the consumer maps slots as numpy
  views.  Bounded slots are the backpressure (a full ring blocks the
  worker, heartbeating, counted as a stall); a corrupt/torn slot is
  quarantined like a corrupt cache blob and its batch index reassigned,
  so the yielded stream stays bit-identical.  Values the ring cannot
  encode (or that overflow a slot) fall back to the pickle path
  per-batch — the transport degrades, the schedule does not.

Chaos hooks (tools/chaos.py, real-subprocess scenarios): workers
self-SIGKILL or wedge on a claimed batch index; an ``O_EXCL`` sentinel
file makes the claim exclusive, so the reassigned batch does not
re-trigger the fault on the next worker.  ``MX_RCNN_CHAOS_SHM_CORRUPT``
flips a payload byte in one delivered slot before the consumer reads it
(CRC detect -> quarantine -> reassign, parent-side, one-shot).
"""

from __future__ import annotations

import heapq
import logging
import multiprocessing as mp
import os
import queue
import signal
import sys
import time
from typing import Callable, Iterator, Optional

from mx_rcnn_tpu import obs
from mx_rcnn_tpu.data.cache import quarantine_append
from mx_rcnn_tpu.data.shm_ring import (
    ShmRing,
    ShmRingWriter,
    SlotOverflow,
    shm_eligible,
)

log = logging.getLogger("mx_rcnn_tpu")

# Watchdog staleness threshold override (seconds, float) — chaos scenarios
# tighten it so a wedged worker is reaped inside the test budget.
WATCHDOG_ENV = "MX_RCNN_DATA_WATCHDOG_S"
# Chaos: "always" or "<global_batch_idx>:<sentinel_path>" — the (first)
# worker to claim that batch SIGKILLs itself before assembling.
CHAOS_SUICIDE_ENV = "MX_RCNN_CHAOS_DATA_SUICIDE"
# Chaos: "<global_batch_idx>:<sentinel_path>" — the claiming worker wedges
# (sleeps without heartbeating) so the watchdog must reap + reassign.
CHAOS_WEDGE_ENV = "MX_RCNN_CHAOS_DATA_WEDGE"
# Chaos: "<global_batch_idx>" — the consumer flips one payload byte in
# that batch's delivered shm slot before decoding it (one-shot): CRC
# detect -> quarantine -> deterministic reassignment, no worker involved.
CHAOS_SHM_CORRUPT_ENV = "MX_RCNN_CHAOS_SHM_CORRUPT"

_WORKER_DEPTH = 2      # in-flight tasks per worker (decode pipelining)
_RESULT_DEPTH = 2      # bounded per-worker result queue (backpressure)
_POLL_S = 0.02         # consumer poll cadence when nothing is ready
_BOOT_GRACE_S = 120.0  # heartbeat grace for a worker still importing
# How long a worker waits on a full shm ring before shipping THAT batch
# via pickle instead.  Zero-copy slots are pinned until the consumer
# DROPS the batch, so a consumer that retains every batch (list(...) in
# tests, an unbounded prefetch buffer) would pin every slot forever —
# the bounded wait turns that would-be deadlock into a counted, per-batch
# degrade to the legacy transport.
_SHM_STALL_BUDGET_S = 0.5


class InputServiceDead(RuntimeError):
    """Every worker slot is dead and the respawn budget is exhausted."""


class InputServiceError(RuntimeError):
    """A worker's assembly raised — deterministic, so not retried."""


def _parse_chaos(env: str, allow_always: bool = False):
    """``"always"`` or ``"<idx>:<sentinel>"`` → ('always'|int, path|None)."""
    raw = os.environ.get(env, "").strip()
    if not raw:
        return None
    if raw == "always" and allow_always:
        return ("always", None)
    idx, _, sentinel = raw.partition(":")
    return (int(idx), sentinel or None)


def _chaos_claims(spec, idx: int) -> bool:
    """Does this worker claim the fault for batch ``idx``?  The O_EXCL
    sentinel makes the claim exclusive across workers AND respawns — the
    reassigned batch must not re-trigger the same fault forever."""
    if spec is None:
        return False
    target, sentinel = spec
    if target != "always" and idx != target:
        return False
    if sentinel is None:
        return True
    try:
        os.close(os.open(sentinel, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
        return True
    except FileExistsError:
        return False
    except OSError:
        return True


def _ship_via_ring(writer, idx: int, val, heartbeat, wid: int,
                   parent_pid: int):
    """Try the shm path for one assembled value: claim a slot (blocking
    on backpressure, heartbeating, counting stalls), write, and return
    the control message — or None to fall back to the pickle path
    (ineligible value, slot overflow, or a torn-down ring).

    The wait for a free slot is BOUNDED (``_SHM_STALL_BUDGET_S``): slots
    stay pinned until the consumer drops the delivered batch, so a
    consumer that retains every batch would otherwise pin every slot and
    wedge the stream.  When the budget runs out, THIS batch ships as a
    stall-fallback pickle message (stall count attached) and the ring is
    retried on the next batch."""
    if writer is None or not shm_eligible(val):
        return None
    stalls = 0
    slot = writer.acquire(timeout=0.02)
    while slot is None:
        # Every slot is in flight: bounded-slot backpressure.  Keep
        # heartbeating (this is a slow consumer, not a wedge) and count
        # the wait so the consumer can export it as a ring stall.
        stalls += 1
        heartbeat[wid] = time.time()
        if os.getppid() != parent_pid:
            os._exit(2)
        if stalls * 0.2 >= _SHM_STALL_BUDGET_S:
            return ("shm_stall", idx, (val, stalls))
        slot = writer.acquire(timeout=0.2)
    try:
        nbytes = writer.write(slot, val)
    except SlotOverflow:
        writer.unget(slot)
        return None  # one oversized batch degrades, the stream survives
    except Exception:  # noqa: BLE001 — ring gone (teardown race)
        writer.unget(slot)
        return None
    return ("shm", idx, (slot, nbytes, stalls))


def _service_worker(
    wid: int,
    builder: Callable,
    payload: dict,
    task_q,
    result_q,
    heartbeat,
    parent_pid: int,
    ring_handle: Optional[dict] = None,
) -> None:
    """Worker main: pull (idx, spec) tasks, assemble, ship (kind, idx, …).

    The heartbeat is stamped ONLY here, between units of real work — a
    wedged assemble or a wedged queue therefore reads as stale, which is
    the watchdog's entire signal.  Workers never initialize a jax
    backend; they import the package (threefry flag) and the loader, not
    the model stack.

    With ``ring_handle`` (shm transport) the assembled tensors go into a
    ring slot and ``result_q`` carries only the slot reference; the
    pickle message remains the per-batch fallback.
    """
    signal.signal(signal.SIGINT, signal.SIG_IGN)  # parent owns Ctrl-C
    suicide = _parse_chaos(CHAOS_SUICIDE_ENV, allow_always=True)
    wedge = _parse_chaos(CHAOS_WEDGE_ENV)
    assemble = builder(payload)
    writer = ShmRingWriter(ring_handle) if ring_handle else None
    while True:
        if os.getppid() != parent_pid:
            os._exit(2)  # orphaned (parent SIGKILLed) — no cleanup to run
        heartbeat[wid] = time.time()
        try:
            task = task_q.get(timeout=0.2)
        except (queue.Empty, OSError, EOFError):
            continue
        if task is None:
            if writer is not None:
                writer.close()
            return
        idx, spec = task
        if _chaos_claims(suicide, idx):
            print(
                f"[input-service worker {wid}] chaos: self-SIGKILL on "
                f"batch {idx}", file=sys.stderr, flush=True,
            )
            os.kill(os.getpid(), signal.SIGKILL)
        if _chaos_claims(wedge, idx):
            print(
                f"[input-service worker {wid}] chaos: wedging on batch "
                f"{idx}", file=sys.stderr, flush=True,
            )
            time.sleep(3600.0)  # no heartbeat: the watchdog reaps us
        try:
            val = assemble(spec)
            msg = _ship_via_ring(
                writer, idx, val, heartbeat, wid, parent_pid
            ) or ("ok", idx, val)
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            msg = ("err", idx, f"{type(e).__name__}: {e}")
        while True:
            heartbeat[wid] = time.time()
            if os.getppid() != parent_pid:
                os._exit(2)
            try:
                result_q.put(msg, timeout=0.2)
                break
            except queue.Full:
                continue  # backpressure: bounded queue, consumer is slow


class _Slot:
    """One worker's parent-side state: process, private queues, shm ring
    (when the transport is on), in-flight indices, and the remaining
    respawn budget."""

    def __init__(self, proc, task_q, result_q, respawns_left: int,
                 ring: Optional[ShmRing] = None) -> None:
        self.proc = proc
        self.task_q = task_q
        self.result_q = result_q
        self.respawns_left = respawns_left
        self.ring = ring
        self.outstanding: set[int] = set()
        self.spawned_at = time.time()


class InputService:
    """Deterministic process-pool batch assembly (iterator protocol).

    ``specs`` yields picklable local batch specs in global-schedule
    order; ``assemble(spec)`` is the parent-side (fallback) assembler;
    ``builder(payload)`` — both picklable — reconstructs the same
    assembler inside a spawned worker.  Yields batches in exactly
    ``specs`` order, whatever happens to the workers.
    """

    def __init__(
        self,
        specs: Iterator,
        assemble: Callable,
        builder: Callable,
        payload: dict,
        num_workers: int,
        start_index: int = 0,
        respawns: int = 2,
        watchdog_s: Optional[float] = None,
        fallback: bool = True,
        name: str = "input-service",
        shm_slots: int = 0,
        shm_slot_bytes: int = 0,
        quarantine_path: Optional[str] = None,
    ) -> None:
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self._specs = specs
        self._assemble = assemble
        self._builder = builder
        self._payload = payload
        self._fallback = fallback
        self._name = name
        if watchdog_s is None:
            watchdog_s = float(os.environ.get(WATCHDOG_ENV, "30"))
        self._watchdog_s = watchdog_s
        self._boot_grace_s = max(_BOOT_GRACE_S, watchdog_s)
        # Zero-copy shm transport: one ring per worker when both knobs
        # are set (data/shm_ring.py); 0 keeps the pickle-through-queue
        # hand-off.  The quarantine journal is shared with the tensor
        # cache so corrupt slots and corrupt blobs land in one place.
        self._shm_slots = max(int(shm_slots), 0)
        self._shm_slot_bytes = max(int(shm_slot_bytes), 0)
        self._quarantine_path = quarantine_path
        self._ring_seq = 0
        raw = os.environ.get(CHAOS_SHM_CORRUPT_ENV, "").strip()
        self._chaos_shm_corrupt: Optional[int] = int(raw) if raw else None
        # spawn, not fork: the parent has jax (and often a live backend)
        # loaded — forking a multithreaded jax process deadlocks.
        self._ctx = mp.get_context("spawn")
        self._heartbeat = self._ctx.Array("d", num_workers, lock=False)
        self._slots: list[Optional[_Slot]] = [None] * num_workers
        for wid in range(num_workers):
            self._slots[wid] = self._spawn(wid, respawns)
        # Consumer-side reorder buffer + dispatch window: specs are pulled
        # at most `window` ahead of the yield cursor, so memory stays
        # bounded however unevenly workers finish.
        self._window = max(4, 2 * num_workers * _WORKER_DEPTH)
        self._pending: list[int] = []   # indices needing (re)assignment
        self._spec_buf: dict[int, object] = {}  # idx -> spec until yielded
        self._done: dict[int, object] = {}      # idx -> assembled batch
        self._next_yield = start_index
        self._next_spec = start_index
        self._exhausted = False
        self._mode = "service"  # -> "sync" after fallback degradation
        self._closed = False
        self._last_watchdog = 0.0
        self.deaths = 0
        self.reassigned = 0
        log.info(
            "%s: %d decode worker(s) (spawn), respawn budget %d/worker, "
            "watchdog %.1fs, transport %s", name, num_workers, respawns,
            watchdog_s,
            f"shm ring ({self._shm_slots} x {self._shm_slot_bytes}B/worker)"
            if self._shm_on else "pickle queue",
        )

    @property
    def _shm_on(self) -> bool:
        return self._shm_slots > 0 and self._shm_slot_bytes > 0

    # -- lifecycle ---------------------------------------------------------

    def _spawn(self, wid: int, respawns_left: int) -> _Slot:
        task_q = self._ctx.Queue()
        result_q = self._ctx.Queue(maxsize=_RESULT_DEPTH)
        ring = None
        if self._shm_on:
            # A FRESH ring per (worker, respawn): failure isolation
            # matches the per-worker queues — a crashing writer can tear
            # only its own segment, and the respawn starts clean.
            self._ring_seq += 1
            ring = ShmRing(
                self._ctx, self._shm_slots, self._shm_slot_bytes,
                name=f"mxr{os.getpid()}_{self._ring_seq}",
            )
        self._heartbeat[wid] = 0.0  # 0 = not yet booted (grace applies)
        proc = self._ctx.Process(
            target=_service_worker,
            args=(wid, self._builder, self._payload, task_q, result_q,
                  self._heartbeat, os.getpid(),
                  ring.handle() if ring else None),
            name=f"{self._name}-worker-{wid}",
            daemon=True,
        )
        proc.start()
        return _Slot(proc, task_q, result_q, respawns_left, ring=ring)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for slot in self._slots:
            if slot is None:
                continue
            try:
                slot.task_q.put_nowait(None)
            except Exception:  # noqa: BLE001 — queue may be broken/full
                pass
        for slot in self._slots:
            if slot is None:
                continue
            slot.proc.join(timeout=2.0)
            if slot.proc.is_alive():
                slot.proc.kill()
                slot.proc.join(timeout=2.0)
            self._discard_queues(slot)
            if slot.ring is not None:
                # Unlinks now; the segment unmaps once any still-live
                # zero-copy batch views (already yielded) are collected.
                slot.ring.close()
        self._slots = [None] * len(self._slots)

    @staticmethod
    def _discard_queues(slot: _Slot) -> None:
        for q in (slot.task_q, slot.result_q):
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    # -- iterator protocol -------------------------------------------------

    def __iter__(self) -> "InputService":
        return self

    def __next__(self):
        if self._mode == "sync":
            return self._sync_next()
        while True:
            if self._next_yield in self._done:
                batch = self._done.pop(self._next_yield)
                self._spec_buf.pop(self._next_yield, None)
                self._next_yield += 1
                return batch
            if self._finished():
                self.close()
                raise StopIteration
            self._dispatch()
            if not self._poll_results():
                now = time.time()
                if now - self._last_watchdog >= min(0.2, self._watchdog_s / 4):
                    self._watchdog(now)
                    if self._mode == "sync":
                        return self._sync_next()
                time.sleep(_POLL_S)

    def _finished(self) -> bool:
        return (
            self._exhausted
            and not self._pending
            and not self._done
            and not any(s and s.outstanding for s in self._slots)
        )

    # -- dispatch / results ------------------------------------------------

    def _dispatch(self) -> None:
        while True:
            slot = self._idle_slot()
            if slot is None:
                return
            if self._pending:
                idx = heapq.heappop(self._pending)
                spec = self._spec_buf[idx]
            else:
                if self._exhausted or self._next_spec >= self._next_yield + self._window:
                    return
                try:
                    spec = next(self._specs)
                except StopIteration:
                    self._exhausted = True
                    return
                idx = self._next_spec
                self._next_spec += 1
                self._spec_buf[idx] = spec
            slot.outstanding.add(idx)
            try:
                slot.task_q.put_nowait((idx, spec))
            except Exception:  # noqa: BLE001 — broken pipe: watchdog reaps
                return

    def _idle_slot(self) -> Optional[_Slot]:
        best = None
        for slot in self._slots:
            if slot is None or len(slot.outstanding) >= _WORKER_DEPTH:
                continue
            if best is None or len(slot.outstanding) < len(best.outstanding):
                best = slot
        return best

    def _poll_results(self) -> bool:
        got = False
        for wid, slot in enumerate(self._slots):
            if slot is None:
                continue
            while True:
                try:
                    msg = slot.result_q.get_nowait()
                except queue.Empty:
                    break
                except Exception as e:  # noqa: BLE001 — torn result pipe
                    self._fail_slot(wid, f"result stream corrupt ({e})")
                    break
                self._accept(slot, msg)
                got = True
        return got

    def _accept(self, slot: Optional[_Slot], msg,
                salvage: bool = False) -> None:
        kind, idx, val = msg
        if slot is not None:
            slot.outstanding.discard(idx)
        if idx < self._next_yield or idx in self._done:
            if kind == "shm" and slot is not None and slot.ring is not None:
                slot.ring.release(val[0])  # duplicate: recycle the slot
            return  # duplicate after reassignment — content is identical
        if kind == "err":
            # Assembly is deterministic (the loader already absorbs I/O
            # flakiness via retry+quarantine inside _assemble), so a raise
            # here reproduces on any worker: surface it, typed.
            self.close()
            raise InputServiceError(
                f"{self._name}: batch {idx} assembly failed in a worker: "
                f"{val}"
            )
        if kind == "shm":
            self._accept_shm(slot, idx, val, salvage)
            return
        if kind == "shm_stall":
            # Worker gave up waiting on a full ring (consumer is holding
            # yielded batches alive, pinning the slots) and shipped this
            # batch via pickle.  Count the wait; content is identical.
            val, stalls = val
            obs.counter(
                "data_shm_ring_stalls_total",
                "worker waits on a full shm ring (backpressure)",
            ).inc(stalls, service=self._name)
        self._done[idx] = val

    def _accept_shm(self, slot: _Slot, idx: int, ref, salvage: bool) -> None:
        """Map one delivered ring slot.  ``salvage=True`` (dead worker)
        copies out of the segment so the ring can be unlinked; the normal
        path hands the consumer zero-copy views that release the slot when
        garbage-collected.  A CRC/torn-write failure is quarantined like a
        corrupt cache blob and the index reassigned — the yielded stream
        stays bit-identical."""
        slot_id, nbytes, stalls = ref
        if self._chaos_shm_corrupt == idx:
            self._chaos_shm_corrupt = None  # one-shot
            log.warning(
                "%s: chaos: corrupting shm slot %d (batch %d)",
                self._name, slot_id, idx,
            )
            slot.ring.corrupt_slot(slot_id)
        try:
            val, _ = slot.ring.read(slot_id, copy=salvage)
        except ValueError as e:
            reason = str(e).split(":", 1)[0]
            if reason not in ("shm_checksum", "shm_truncated"):
                reason = "shm_decode"
            obs.emit("data", "shm_quarantine", {
                "service": self._name, "batch_index": idx,
                "slot": slot_id, "reason": reason, "error": str(e),
            }, logger=log)
            obs.counter(
                "data_shm_quarantines_total",
                "corrupt/torn shm ring slots quarantined",
            ).inc(service=self._name, reason=reason)
            if self._quarantine_path:
                quarantine_append(self._quarantine_path, {
                    "kind": "shm_slot", "service": self._name,
                    "batch_index": idx, "slot": slot_id,
                    "reason": reason, "error": str(e),
                    "time": time.time(),
                })
            slot.ring.release(slot_id)
            heapq.heappush(self._pending, idx)
            self.reassigned += 1
            obs.counter(
                "data_batches_reassigned_total",
                "in-flight batches returned to the pending heap",
            ).inc(service=self._name)
            return
        obs.counter(
            "data_shm_bytes_total",
            "tensor bytes shipped zero-copy through shm rings",
        ).inc(nbytes, service=self._name)
        if stalls:
            obs.counter(
                "data_shm_ring_stalls_total",
                "worker waits on a full shm ring (backpressure)",
            ).inc(stalls, service=self._name)
        self._done[idx] = val

    # -- watchdog / failure handling ---------------------------------------

    def _watchdog(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self._last_watchdog = now
        for wid, slot in enumerate(self._slots):
            if slot is None:
                continue
            alive = slot.proc.is_alive()
            hb = self._heartbeat[wid]
            if hb > 0:
                stale = now - hb > self._watchdog_s
            else:  # still booting (spawn + package import)
                stale = now - slot.spawned_at > self._boot_grace_s
            if alive and not stale:
                continue
            if alive:
                obs.emit("data", "worker_wedged", {
                    "service": self._name, "worker": wid,
                    "heartbeat_age_s": now - (hb or slot.spawned_at),
                }, logger=log)
                slot.proc.kill()
                slot.proc.join(timeout=5.0)
                why = "wedged"
            else:
                why = f"died (exit {slot.proc.exitcode})"
            self._fail_slot(wid, why)
        if all(s is None for s in self._slots):
            self._go_dead()

    def _fail_slot(self, wid: int, why: str) -> None:
        slot = self._slots[wid]
        if slot is None:
            return
        self.deaths += 1
        if slot.proc.is_alive():
            slot.proc.kill()
            slot.proc.join(timeout=5.0)
        # Salvage results the worker delivered before dying — re-assembling
        # them would be wasted work (content is deterministic either way).
        # salvage=True: shm results are copied out so the dead worker's
        # ring can be torn down instead of pinning live batch views to an
        # unlinked segment.
        while True:
            try:
                self._accept(slot, slot.result_q.get_nowait(), salvage=True)
            except queue.Empty:
                break
            except Exception:  # noqa: BLE001 — torn pipe dies with worker
                break
        # Deterministic reassignment: every in-flight index goes back on
        # the pending heap; live workers pick them up in index order.
        lost = sorted(slot.outstanding)
        for idx in lost:
            heapq.heappush(self._pending, idx)
        self.reassigned += len(lost)
        self._discard_queues(slot)
        if slot.ring is not None:
            slot.ring.close()  # respawn gets a FRESH ring
        obs.counter(
            "data_worker_deaths_total", "decode worker deaths/wedges"
        ).inc(service=self._name)
        obs.counter(
            "data_batches_reassigned_total",
            "in-flight batches returned to the pending heap",
        ).inc(len(lost), service=self._name)
        if slot.respawns_left > 0:
            obs.emit("data", "worker_death", {
                "service": self._name, "worker": wid, "why": why,
                "lost": len(lost), "indices": lost,
                "respawns_left": slot.respawns_left - 1,
            }, logger=log)
            self._slots[wid] = self._spawn(wid, slot.respawns_left - 1)
        else:
            obs.emit("data", "worker_retired", {
                "service": self._name, "worker": wid, "why": why,
                "lost": len(lost), "indices": lost,
            }, logger=log)
            self._slots[wid] = None

    def _go_dead(self) -> None:
        """No live workers, no respawn budget: degrade or die — typed."""
        self.close()
        if not self._fallback:
            raise InputServiceDead(
                f"{self._name}: all workers dead and respawn budget "
                f"exhausted after {self.deaths} death(s)"
            )
        obs.emit("data", "service_fallback", {
            "service": self._name, "deaths": self.deaths,
        }, logger=log)
        self._mode = "sync"

    # -- degraded mode -----------------------------------------------------

    def _sync_next(self):
        """In-process assembly from the yield cursor onward.  Uses salvaged
        ``_done`` results first; specs already pulled from the stream sit
        in ``_spec_buf``, the rest come straight off the iterator — the
        yielded schedule is unchanged."""
        idx = self._next_yield
        if idx in self._done:
            batch = self._done.pop(idx)
            self._spec_buf.pop(idx, None)
            self._next_yield += 1
            return batch
        spec = self._spec_buf.pop(idx, None)
        if spec is None:
            if self._exhausted:
                raise StopIteration
            try:
                spec = next(self._specs)
            except StopIteration:
                self._exhausted = True
                raise StopIteration from None
            assert self._next_spec == idx, (
                f"spec cursor desync: {self._next_spec} != {idx}"
            )
            self._next_spec += 1
        self._next_yield += 1
        return self._assemble(spec)
