"""CRC-stamped shared-memory ring buffers for the input service.

The zero-copy tensor hand-off between decode workers and the consumer
(data/service.py).  The legacy transport pickles whole ``Batch`` tuples
through ``multiprocessing.Queue`` pipes — every batch is serialized in
the worker, copied through the OS pipe, and deserialized in the parent:
three full copies of the pixel payload per batch.  Here each worker owns
one ``multiprocessing.shared_memory`` segment divided into fixed-size
**slots**; the worker writes tensors straight into a slot and ships only
a tiny ``("shm", idx, (slot, nbytes, stalls))`` control message, and the
consumer maps the slot as numpy views without copying a byte.

Blob discipline mirrors the tensor cache (data/cache.py, ``MXTC1``):
``MXRB1`` magic, u32 header length, JSON header (per-field dtype / shape
/ offset, payload CRC32, total bytes), payload.  Two deliberate
differences, both because a slot is rewritten in place rather than
published atomically via ``os.replace``:

* the header lives in a fixed reserve at the slot start and the payload
  at a fixed offset after it, so the payload can be written (and CRC'd)
  **before** the header that describes it;
* the magic is zeroed before any write and restored last, so a torn
  writer (worker SIGKILLed mid-write) leaves a slot that fails the magic
  check, not one that parses.

Validation order on read — magic, header bounds, JSON, payload CRC —
raises ``ValueError`` with the same category-prefix convention as the
cache (``shm_truncated: ...`` / ``shm_checksum: ...``), so the service
can quarantine with one ``reason = str(e).split(":")[0]``.

**Slot lifecycle / backpressure.**  Free slot ids travel a bounded
``free_q`` (consumer -> worker): the worker blocks (still heartbeating)
when every slot is full — the bounded-slot equivalent of the legacy
bounded result queue, and the wait is counted as a **stall** the service
exports as ``data_shm_ring_stalls_total``.  A zero-copy read pins the
slot: the returned arrays are ``_ShmArray`` views whose finalizers
return the slot to ``free_q`` only when the LAST array dies, so a slot
can never be rewritten under a batch the training loop still holds.
Finalizers cannot see *device* lifetimes, though: jax's CPU backend
zero-copies 64-byte-aligned host arrays into device buffers that outlive
the views, so every field is deliberately placed at 8 (mod 64)
(``MISALIGN``) — unaligned for XLA, which forces ``device_put`` to copy
and keeps the lease protocol sound.
``close()`` unlinks the segment immediately (the name is gone) but
defers the unmap until every lease drains — live views stay valid on a
ring whose worker already died.

Failure isolation matches the per-worker result queues it replaces: one
ring per worker, torn down whole on death and recreated fresh for the
respawn, so a crashed writer can corrupt at most its own slots — and a
corrupt slot is detected by CRC, quarantined, and the batch index
reassigned (content is deterministic, so the stream stays bit-identical;
see data/service.py).
"""

from __future__ import annotations

import importlib
import json
import queue
import struct
import threading
import weakref
import zlib
from multiprocessing import shared_memory
from typing import Any, Optional

import numpy as np

MAGIC = b"MXRB1\n"
# Fixed header region per slot: magic + u32 length + JSON header.  The
# payload starts here so it can be written and CRC'd before the header.
HEADER_RESERVE = 4096
# Field payloads start at this residue (mod 64) within the payload area:
# 8-byte aligned (every dtype we ship), but never 16-byte aligned — XLA
# requires >=16-byte-aligned input buffers, so jax.device_put is forced
# to copy rather than zero-copy-alias the slot (see encode_into).
MISALIGN = 8


class SlotOverflow(RuntimeError):
    """The value does not fit one slot — caller falls back to pickle."""


class _Segment(shared_memory.SharedMemory):
    """SharedMemory whose ``__del__`` tolerates live exported views.
    When the consumer holds zero-copy arrays at interpreter shutdown the
    base class raises ``BufferError`` from ``mmap.close()``; the OS
    reclaims the mapping at process exit anyway, so swallow it instead
    of spraying "Exception ignored" tracebacks."""

    def __del__(self) -> None:
        try:
            super().__del__()
        except BufferError:
            pass


class _ShmArray(np.ndarray):
    """ndarray view into a ring slot.  A Python-level subclass so
    instances accept weakrefs (base ndarrays do not); the finalizer on
    each field view is what returns the slot to the free queue."""


def shm_eligible(value: Any) -> bool:
    """True when ``value`` is a NamedTuple of ndarray-or-None fields —
    the only shape the ring encodes; anything else rides the pickle
    fallback."""
    if not (isinstance(value, tuple) and hasattr(value, "_fields")):
        return False
    return all(
        f is None or (isinstance(f, np.ndarray) and f.dtype != object)
        for f in value
    )


def encode_into(buf, base: int, slot_bytes: int, value) -> int:
    """Write ``value`` (an :func:`shm_eligible` NamedTuple) into the slot
    at ``buf[base:base+slot_bytes]``; returns payload bytes written.
    Raises :class:`SlotOverflow` when it does not fit (the slot is left
    invalid — magic zeroed — and can be reused)."""
    # Invalidate first: a reader (or a crash before the final magic
    # write) must see a torn slot, never a stale-but-valid one.
    buf[base:base + len(MAGIC)] = b"\x00" * len(MAGIC)
    fields = []
    off = 0
    for name, arr in zip(type(value)._fields, value):
        if arr is None:
            fields.append({"name": name, "null": True})
            continue
        a = np.ascontiguousarray(arr)
        nb = a.nbytes
        # Place every field at 8 (mod 64) so no exported view is ever
        # 16-byte aligned.  XLA requires aligned input buffers, which
        # forces jax.device_put to COPY instead of zero-copy-aliasing
        # the slot: an aliased device buffer would outlive the view
        # finalizers that return the slot to the free queue, and a
        # worker could rewrite the slot under a live device call.  The
        # gap bytes are zeroed so the contiguous payload CRC stays
        # deterministic.
        pad = (MISALIGN - off) % 64
        if pad:
            gap = base + HEADER_RESERVE + off
            buf[gap:gap + pad] = b"\x00" * pad
            off += pad
        if HEADER_RESERVE + off + nb > slot_bytes:
            raise SlotOverflow(
                f"field {name} ({nb} bytes at offset {off}) exceeds slot "
                f"of {slot_bytes} bytes"
            )
        dst = np.ndarray(
            a.shape, dtype=a.dtype, buffer=buf,
            offset=base + HEADER_RESERVE + off,
        )
        np.copyto(dst, a)
        fields.append({
            "name": name, "dtype": str(a.dtype), "shape": list(a.shape),
            "off": off, "nbytes": nb,
        })
        off += nb
    crc = zlib.crc32(buf[base + HEADER_RESERVE:base + HEADER_RESERVE + off])
    header = json.dumps({
        "v": 1,
        "cls": [type(value).__module__, type(value).__qualname__],
        "nbytes": off,
        "crc32": crc,
        "fields": fields,
    }).encode()
    if len(MAGIC) + 4 + len(header) > HEADER_RESERVE:
        raise SlotOverflow(
            f"header of {len(header)} bytes exceeds the "
            f"{HEADER_RESERVE}-byte reserve"
        )
    struct.pack_into("<I", buf, base + len(MAGIC), len(header))
    hoff = base + len(MAGIC) + 4
    buf[hoff:hoff + len(header)] = header
    buf[base:base + len(MAGIC)] = MAGIC  # valid LAST
    return off


def decode_from(buf, base: int, slot_bytes: int, copy: bool,
                on_array_freed=None) -> tuple[Any, int]:
    """Rebuild the NamedTuple from the slot; ``(value, payload_bytes)``.

    ``copy=False`` returns read-only :class:`_ShmArray` views into the
    slot, each registered with ``on_array_freed`` (called once per field
    array as it is garbage collected).  ``copy=True`` returns owning
    arrays — safe after the ring is gone (death salvage).

    Raises ``ValueError("shm_truncated: ...")`` /
    ``ValueError("shm_checksum: ...")`` — same category-prefix discipline
    as the tensor cache.
    """
    if bytes(buf[base:base + len(MAGIC)]) != MAGIC:
        raise ValueError("shm_truncated: bad slot magic (torn writer)")
    (hlen,) = struct.unpack_from("<I", buf, base + len(MAGIC))
    if not 0 < hlen <= HEADER_RESERVE - len(MAGIC) - 4:
        raise ValueError(f"shm_truncated: header length {hlen} out of range")
    hoff = base + len(MAGIC) + 4
    try:
        header = json.loads(bytes(buf[hoff:hoff + hlen]))
    except ValueError as e:
        raise ValueError(f"shm_truncated: header unparseable ({e})")
    total = int(header["nbytes"])
    if HEADER_RESERVE + total > slot_bytes:
        raise ValueError(
            f"shm_truncated: payload {total} exceeds slot {slot_bytes}"
        )
    pbase = base + HEADER_RESERVE
    if zlib.crc32(buf[pbase:pbase + total]) != header["crc32"]:
        raise ValueError("shm_checksum: payload crc mismatch")
    mod, qual = header["cls"]
    cls = getattr(importlib.import_module(mod), qual)
    values = []
    for f in header["fields"]:
        if f.get("null"):
            values.append(None)
            continue
        arr = np.frombuffer(
            buf, dtype=np.dtype(f["dtype"]),
            count=int(np.prod(f["shape"], dtype=np.int64)) if f["shape"]
            else 1,
            offset=pbase + f["off"],
        ).reshape(f["shape"])
        if copy:
            values.append(arr.copy())
        else:
            view = arr.view(_ShmArray)
            view.flags.writeable = False
            if on_array_freed is not None:
                weakref.finalize(view, on_array_freed)
            values.append(view)
    return cls(*values), total


class ShmRing:
    """Parent-side ring: one shared segment, ``slots`` fixed slots, and
    the free-slot queue that doubles as backpressure."""

    def __init__(self, ctx, slots: int, slot_bytes: int,
                 name: Optional[str] = None) -> None:
        if slots < 1 or slot_bytes <= HEADER_RESERVE:
            raise ValueError(
                f"need slots >= 1 and slot_bytes > {HEADER_RESERVE}, got "
                f"{slots} x {slot_bytes}"
            )
        self.slots = int(slots)
        # Round slot size up to a 64-byte multiple: the segment is page-
        # aligned, so this keeps every slot base at 0 (mod 64) and the
        # encode-side MISALIGN residue therefore holds for absolute
        # addresses too.
        self.slot_bytes = -(-int(slot_bytes) // 64) * 64
        self._shm = _Segment(
            create=True, size=self.slots * self.slot_bytes, name=name,
        )
        self.name = self._shm.name
        self._free_q = ctx.Queue(maxsize=self.slots)
        for s in range(self.slots):
            self._free_q.put(s)
        self._lock = threading.Lock()
        self._leases = 0      # outstanding zero-copy field arrays
        self._closed = False
        self._unmapped = False

    def handle(self) -> dict:
        """Picklable worker-side handle (spawn Process args)."""
        return {
            "name": self.name, "slots": self.slots,
            "slot_bytes": self.slot_bytes, "free_q": self._free_q,
        }

    # -- consumer side -----------------------------------------------------

    def read(self, slot: int, copy: bool = False) -> tuple[Any, int]:
        """Decode slot -> ``(value, payload_bytes)``.  ``copy=False``
        pins the slot until every returned field array is collected;
        ``copy=True`` releases it immediately.  ``ValueError`` on a
        torn/corrupt slot (the caller quarantines and must
        :meth:`release` the slot itself)."""
        base = slot * self.slot_bytes
        if copy:
            value, nbytes = decode_from(
                self._shm.buf, base, self.slot_bytes, copy=True
            )
            self.release(slot)
            return value, nbytes
        n_arrays = 0
        state = {"left": 0}

        def freed() -> None:
            with self._lock:
                state["left"] -= 1
                last = state["left"] == 0
                if last:
                    self._leases -= 1
            if last:
                self.release(slot)
                self._maybe_unmap()

        value, nbytes = decode_from(
            self._shm.buf, base, self.slot_bytes, copy=False,
            on_array_freed=freed,
        )
        n_arrays = sum(1 for v in value if v is not None)
        if n_arrays == 0:
            return value, nbytes  # all-None tuple: nothing pins the slot
        with self._lock:
            state["left"] = n_arrays
            self._leases += 1
        return value, nbytes

    def release(self, slot: int) -> None:
        """Return a slot to the writer (duplicate / corrupt / drained)."""
        with self._lock:
            if self._closed:
                return
        try:
            self._free_q.put_nowait(slot)
        except Exception:  # noqa: BLE001 — queue torn down under us
            pass

    def corrupt_slot(self, slot: int) -> None:
        """Chaos hook: flip one payload byte so the CRC check fires."""
        off = slot * self.slot_bytes + HEADER_RESERVE
        self._shm.buf[off] ^= 0xFF

    @property
    def leases(self) -> int:
        with self._lock:
            return self._leases

    # -- teardown ----------------------------------------------------------

    def close(self) -> None:
        """Unlink the segment now (the name is gone from /dev/shm); the
        unmap waits for outstanding zero-copy leases, so batches already
        handed to the consumer stay valid."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for q_op in ("cancel_join_thread", "close"):
            try:
                getattr(self._free_q, q_op)()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
        self._maybe_unmap()

    def _maybe_unmap(self) -> None:
        with self._lock:
            if not self._closed or self._unmapped or self._leases > 0:
                return
            self._unmapped = True
        try:
            self._shm.close()
        except BufferError:
            # A lease raced us; its finalizer calls back in here.
            with self._lock:
                self._unmapped = False


class ShmRingWriter:
    """Worker-side writer built from :meth:`ShmRing.handle`.  Attaches
    lazily (first write) so constructing it in the spawn args costs
    nothing if the worker dies in boot."""

    def __init__(self, handle: dict) -> None:
        self._name = handle["name"]
        self.slots = handle["slots"]
        self.slot_bytes = handle["slot_bytes"]
        self._free_q = handle["free_q"]
        self._shm: Optional[shared_memory.SharedMemory] = None

    def _buf(self):
        if self._shm is None:
            self._shm = _Segment(name=self._name)
        return self._shm.buf

    def acquire(self, timeout: float) -> Optional[int]:
        """Next free slot id, or None after ``timeout`` (the caller
        loops, heartbeating — a full ring is backpressure, not death)."""
        try:
            return self._free_q.get(timeout=timeout)
        except queue.Empty:
            return None
        except (OSError, EOFError, ValueError):
            return None  # parent tore the queue down; caller falls back

    def unget(self, slot: int) -> None:
        try:
            self._free_q.put_nowait(slot)
        except Exception:  # noqa: BLE001
            pass

    def write(self, slot: int, value) -> int:
        """Encode ``value`` into ``slot``; returns payload bytes.
        :class:`SlotOverflow` when it does not fit."""
        return encode_into(
            self._buf(), slot * self.slot_bytes, self.slot_bytes, value
        )

    def close(self) -> None:
        if self._shm is not None:
            try:
                self._shm.close()
            except BufferError:
                pass
            self._shm = None
