"""Host-side image transforms (numpy/cv2; pixels only — no labeling).

Replaces ``rcnn/io/image.py``: the reference resizes the short side to
``SCALES`` capped by ``MAX_SIZE`` (variable output shape) and pads at stack
time (``tensor_vstack``); here :func:`letterbox` produces the final static
canvas directly.  Box coordinates are scaled by the same factor, exactly as
``get_rpn_batch`` scales gt by ``im_scale``.
"""

from __future__ import annotations

import numpy as np

try:  # cv2 for fast resize; PIL fallback keeps the module importable anywhere
    import cv2
except Exception:  # pragma: no cover
    cv2 = None


def oriented_canvas(canvas_hw: tuple[int, int], h: int, w: int) -> tuple[int, int]:
    """The static canvas for an image of true size (h, w).

    ``canvas_hw`` is the LANDSCAPE canvas (h <= w); portrait images use its
    transpose.  Two canvases instead of one square: a square canvas sized
    for the short side silently under-resolves the reference recipe's
    short/max rule (e.g. 480x640 COCO into 1024^2 lands at short side 768,
    not 800), while a single canvas sized for both orientations
    (max x max) wastes ~1.7x the conv FLOPs.  ``aspect_grouping`` keeps
    batches single-orientation, so each orientation is one compiled
    program.  Square canvases are orientation-free (synthetic/tiny)."""
    ch, cw = canvas_hw
    if h > w and ch != cw:
        return cw, ch
    return ch, cw


def resize_scale(h: int, w: int, short_side: int, max_side: int) -> float:
    """The reference's scale rule: short side → ``short_side`` unless that
    pushes the long side past ``max_side``."""
    scale = short_side / min(h, w)
    if round(scale * max(h, w)) > max_side:
        scale = max_side / max(h, w)
    return scale


def _resize_linear(image: np.ndarray, nh: int, nw: int) -> np.ndarray:
    """Bilinear resize, dtype-preserving.  One definition for every
    letterbox path: cv2.INTER_LINEAR, with a PIL BILINEAR fallback that
    MUST stay bilinear (PIL defaults to BICUBIC — different pixels,
    cross-host drift)."""
    if cv2 is not None:
        return cv2.resize(image, (nw, nh), interpolation=cv2.INTER_LINEAR)
    from PIL import Image  # pragma: no cover

    return np.asarray(  # pragma: no cover
        Image.fromarray(image.astype(np.uint8)).resize(
            (nw, nh), Image.BILINEAR
        )
    )


def letterbox(
    image: np.ndarray,
    boxes: np.ndarray,
    canvas_hw: tuple[int, int],
    short_side: int,
    max_side: int,
) -> tuple[np.ndarray, np.ndarray, float, tuple[int, int]]:
    """Resize by the reference scale rule and paste top-left into a static
    canvas.  Returns (canvas, scaled_boxes, scale, (true_h, true_w))."""
    h, w = image.shape[:2]
    ch, cw = canvas_hw
    scale = resize_scale(h, w, short_side, max_side)
    # Never overflow the canvas (canvas is sized for max_side but guard
    # rounding).
    scale = min(scale, ch / h, cw / w)
    nh, nw = int(round(h * scale)), int(round(w * scale))
    canvas = np.zeros((ch, cw, 3), dtype=np.float32)
    canvas[:nh, :nw] = _resize_linear(image, nh, nw)
    out_boxes = boxes.astype(np.float32) * scale
    return canvas, out_boxes, scale, (nh, nw)


def letterbox_uint8(
    image: np.ndarray, canvas_hw: tuple[int, int], nh: int, nw: int
) -> np.ndarray:
    """The pixel half of :func:`letterbox` for the ship-raw-uint8 path:
    uint8->uint8 bilinear resize to (nh, nw), pasted top-left into a
    zeroed uint8 canvas.  The scale rule (and its canvas-overflow clamp)
    ran upstream — ``DetectionLoader.record_scale`` — so nh/nw arrive
    already bounded.  uint8 zeros in the padding normalize in-graph to
    the same value the host-normalized path pads with."""
    canvas = np.zeros((*canvas_hw, 3), np.uint8)
    canvas[:nh, :nw] = _resize_linear(image, nh, nw)
    return canvas


def normalize_image(
    image: np.ndarray, mean: tuple[float, ...], std: tuple[float, ...]
) -> np.ndarray:
    """(x - mean) / std channelwise; RGB order (reference used raw BGR
    mean-subtraction — the constant differs, the op is the same)."""
    return (image - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)


def flip_boxes(boxes: np.ndarray, width: int) -> np.ndarray:
    """Horizontal box remap, the reference's flipped-roidb convention:
    x1, x2 = w-1-x2, w-1-x1."""
    fb = boxes.copy()
    fb[:, 0] = width - 1 - boxes[:, 2]
    fb[:, 2] = width - 1 - boxes[:, 0]
    return fb


def hflip(image: np.ndarray, boxes: np.ndarray, width: int):
    """Horizontal flip of pixels + boxes (reference: flipped roidb entries
    remap x1,x2 = w-1-x2, w-1-x1 at batch time)."""
    return image[:, ::-1].copy(), flip_boxes(boxes, width)
