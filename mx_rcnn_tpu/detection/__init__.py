from mx_rcnn_tpu.detection.detector import TwoStageDetector
from mx_rcnn_tpu.detection.graph import (
    Batch,
    Detections,
    forward_train,
    forward_inference,
    forward_proposals,
    init_detector,
)

__all__ = [
    "TwoStageDetector",
    "Batch",
    "Detections",
    "forward_train",
    "forward_inference",
    "forward_proposals",
    "init_detector",
]
