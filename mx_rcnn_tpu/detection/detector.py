"""The assembled two-stage detector as one flax module.

Replaces the reference's symbol-graph builders (``rcnn/symbol/symbol_vgg.py``
``get_vgg_train/test`` and ``symbol_resnet.py`` equivalents).  Where the
reference builds four separate static graphs (train / test / rpn-only /
rcnn-only) and stitches host-side custom ops between them, this module only
owns the *parameterized* pieces (backbone, neck, heads) as callable methods;
the parameter-free detection logic (anchors, proposals, sampling, ROIAlign,
losses) lives in :mod:`mx_rcnn_tpu.detection.graph` as pure functions, so
train/test/rpn-phase graphs are compositions, not copies.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from mx_rcnn_tpu.config import ModelConfig
from mx_rcnn_tpu.models.build import build_backbone
from mx_rcnn_tpu.models.fpn import FPN
from mx_rcnn_tpu.models.heads import BoxHead, MaskHead, RPNHead
from mx_rcnn_tpu.utils.precision import policy_of


class TwoStageDetector(nn.Module):
    cfg: ModelConfig

    @property
    def feature_levels(self) -> tuple[int, ...]:
        """Levels the RPN sees (stride of level l is 2**l)."""
        if self.cfg.fpn.enabled:
            return tuple(range(self.cfg.fpn.min_level, self.cfg.fpn.max_level + 1))
        return (4,)  # C4 recipe: single stride-16 feature

    @property
    def roi_levels(self) -> tuple[int, ...]:
        """Levels ROIAlign reads (FPN excludes the RPN-only P6)."""
        if self.cfg.fpn.enabled:
            return tuple(range(self.cfg.fpn.min_level, min(self.cfg.fpn.max_level, 5) + 1))
        return (4,)

    def param_families(self) -> tuple[str, ...]:
        """Top-level param-tree names this config instantiates.

        The canonical vocabulary the execution plan's partition rules are
        built over (parallel/plan.py): every param, optimizer-momentum and
        BN-stat leaf carries exactly one of these names in its path.  A new
        head added without extending this list (and the rule set) fails the
        plan's unmatched-leaf check at build time rather than silently
        training unsharded.
        """
        fams = ["backbone"]
        if self.cfg.fpn.enabled:
            fams.append("fpn")
        fams += ["rpn", "box_head"]
        if self.cfg.mask.enabled:
            fams.append("mask_head")
        return tuple(fams)

    def setup(self):
        cfg = self.cfg
        # The resolved mixed-precision policy (utils/precision.py) owns
        # every head dtype: compute_dtype for conv/matmul, output_dtype
        # for what crosses into the detection middle.  Under "widen" /
        # float32 backbones this reproduces the historical graphs
        # bitwise; under "mixed" the heads stop upcasting their outputs.
        policy = policy_of(cfg)
        dtype = policy.compute_dtype
        out_dtype = policy.output_dtype
        backbone_levels = (2, 3, 4, 5) if cfg.fpn.enabled else (4,)
        self.backbone = build_backbone(
            cfg.backbone, out_levels=backbone_levels, dtype=dtype
        )
        if cfg.fpn.enabled:
            self.fpn = FPN(
                channels=cfg.fpn.channels,
                min_level=cfg.fpn.min_level,
                max_level=cfg.fpn.max_level,
                dtype=dtype,
                name="fpn",
            )
        self.rpn_head = RPNHead(
            num_anchors=cfg.anchors.num_anchors(),
            channels=cfg.rpn.channels,
            dtype=dtype,
            out_dtype=out_dtype,
            name="rpn",
        )
        self.box_head = BoxHead(
            num_classes=cfg.num_classes,
            hidden_dim=cfg.rcnn.hidden_dim,
            class_agnostic=cfg.rcnn.class_agnostic,
            dtype=dtype,
            out_dtype=out_dtype,
            name="box_head",
        )
        if cfg.mask.enabled:
            self.mask_head = MaskHead(
                num_classes=cfg.num_classes,
                channels=cfg.mask.channels,
                num_convs=cfg.mask.num_convs,
                dtype=dtype,
                out_dtype=out_dtype,
                name="mask_head",
            )

    def features(self, images: jnp.ndarray) -> dict[int, jnp.ndarray]:
        """images (B, H, W, 3) normalized -> {level: (B, H_l, W_l, C)}."""
        feats = self.backbone(images)
        if self.cfg.fpn.enabled:
            feats = self.fpn(feats)
        return feats

    def rpn(self, feats: dict[int, jnp.ndarray]):
        """Per-level RPN outputs: {level: (logits (B, A_l), deltas (B, A_l, 4))}.

        One weight-shared head over all levels (FPN paper); for C4 there is
        only one level.  ``rpn.packed_head`` runs all levels as one packed
        computation (models/heads.py::RPNHead.packed — exact, same
        per-level outputs) instead of len(feats) sequential head applies.
        """
        if self.cfg.rpn.packed_head and len(feats) > 1:
            return self.rpn_head.packed(feats)
        return {lvl: self.rpn_head(feats[lvl]) for lvl in sorted(feats)}

    def box(self, pooled: jnp.ndarray):
        """pooled (R, S, S, C) -> (cls_logits (R, C), deltas (R, C or 1, 4))."""
        return self.box_head(pooled)

    def mask(self, pooled: jnp.ndarray) -> jnp.ndarray:
        return self.mask_head(pooled)

    def __call__(self, images: jnp.ndarray):
        """Init-only pass touching every parameter."""
        feats = self.features(images)
        rpn_out = self.rpn(feats)
        c = feats[self.roi_levels[0]].shape[-1]
        s = self.cfg.rcnn.pooled_size
        dummy = jnp.zeros((1, s, s, c), feats[self.roi_levels[0]].dtype)
        box_out = self.box(dummy)
        if self.cfg.mask.enabled:
            sm = self.cfg.mask.pooled_size
            self.mask(jnp.zeros((1, sm, sm, c), dummy.dtype))
        return rpn_out, box_out
