"""Train / inference computations for the two-stage detector.

This file is the TPU-native replacement for the reference's whole execution
sandwich (SURVEY.md section 4.1): the symbolic train graph with two
host-round-trip custom ops in its middle (``rcnn/symbol/proposal.py``,
``rcnn/symbol/proposal_target.py``), the host-side anchor labeling inside
the loader (``rcnn/io/rpn.py::assign_anchor``), and the test-time
``rcnn/core/tester.py::im_detect`` + per-class NMS loop.  Everything here is
a pure function of (variables, batch, rng) with static shapes — one jitted
region per train/eval step, zero host interaction.

Shape conventions:
  B = batch, G = max gt boxes, A = total anchors over levels,
  R = proposals per image, S = pooled size, C = num classes (incl. bg 0).
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from mx_rcnn_tpu.config import ModelConfig
from mx_rcnn_tpu.detection.detector import TwoStageDetector
from mx_rcnn_tpu.geometry import (
    clip_boxes,
    decode_boxes,
    generate_base_anchors,
    masked_softmax_cross_entropy,
    shifted_anchors_np,
    weighted_smooth_l1,
)
from mx_rcnn_tpu.ops import assign_anchors, generate_proposals, roi_align, sample_rois
from mx_rcnn_tpu.ops.nms import batched_nms, nms_indices
from mx_rcnn_tpu.ops.pallas.roi_align import (
    POOL_WINDOW,
    multilevel_roi_align_fast,
    pallas_supported,
    sharded_multilevel_roi_align,
)
from mx_rcnn_tpu.ops.proposals import Proposals, generate_fpn_proposals
from mx_rcnn_tpu.ops.roi_align import multilevel_roi_align

# Batch moved to data/batch.py (jax-free) so input-service workers can
# unpickle batches without importing the model stack; re-exported here so
# every historical `from mx_rcnn_tpu.detection.graph import Batch` holds.
from mx_rcnn_tpu.data.batch import Batch  # noqa: F401  (re-export)


class Detections(NamedTuple):
    boxes: jnp.ndarray    # (B, D, 4) in input-image coordinates
    scores: jnp.ndarray   # (B, D)
    classes: jnp.ndarray  # (B, D) int32, 1-based foreground ids
    valid: jnp.ndarray    # (B, D) bool
    masks: Optional[jnp.ndarray] = None  # (B, D, M, M) probabilities


# ---------------------------------------------------------------------------
# Anchors


@lru_cache(maxsize=64)
def _cached_level_anchor(stride: int, ratios, scales, h: int, w: int):
    """One level's anchor grid, memoized as host numpy.

    ``generate_base_anchors``/``shifted_anchors`` enumerate the grid in
    host numpy — O(H*W*k) work the old code redid on EVERY trace (retrace
    per canvas orientation, per eval bucket, per chaos-restart).  The
    geometry is a pure function of this static key, so cache it; repeated
    traces of the same shapes reuse it for free.  Cached in NUMPY form on
    purpose: a jnp array built while tracing is a tracer, and handing a
    cached tracer to a later trace leaks it.  ``level_anchors`` does the
    (cheap, constant-embedding) jnp.asarray per trace.
    """
    base = generate_base_anchors(base_size=stride, ratios=ratios, scales=scales)
    return shifted_anchors_np(base, stride, h, w)


def level_anchors(
    cfg: ModelConfig, feats: dict[int, jnp.ndarray]
) -> dict[int, jnp.ndarray]:
    """Static per-level anchor grids for the given feature shapes.

    Anchor base size is the level stride (FPN: one octave per level); the C4
    recipe's single level 4 with scales (8, 16, 32) reproduces the
    reference's 128/256/512-pixel anchors exactly.
    """
    out = {}
    for lvl in sorted(feats):
        stride = 2**lvl
        _, h, w, _ = feats[lvl].shape
        out[lvl] = jnp.asarray(_cached_level_anchor(
            stride, tuple(cfg.anchors.ratios), tuple(cfg.anchors.scales), h, w
        ))
    return out


# ---------------------------------------------------------------------------
# Losses


def _rpn_losses(rpn_logits, rpn_deltas, targets, loss_impl: str = "dense"):
    """RPN objectness + box losses, per reference normalization.

    rpn_logits (B, A), rpn_deltas (B, A, 4); targets from assign_anchors
    vmapped over B.  Objectness is sigmoid BCE over sampled anchors
    normalized by valid count (the reference's 2-way softmax with
    ignore_label=-1 and normalization='valid' — same quantity); box loss is
    smooth_l1(sigma=3) on fg anchors normalized by the same count
    (reference grad_scale = 1/RPN_BATCH_SIZE per image).

    ``loss_impl``: "dense" reduces over the full (B, A) anchor axis with
    masks (bit-identical to the historical form); "compact" reduces only
    the Q sampled rows via AnchorTargets.sel_* — same terms, different
    summation order (see RPNConfig.loss_impl).
    """
    with jax.named_scope("rpn_loss"):
        if loss_impl == "compact":
            if targets.sel_idx is None:
                raise ValueError(
                    "loss_impl='compact' needs AnchorTargets.sel_* (produced "
                    "by assign_anchors)"
                )
            return _rpn_losses_compact(rpn_logits, rpn_deltas, targets)
        if loss_impl != "dense":
            raise ValueError(
                f"rpn.loss_impl must be 'dense' or 'compact', got {loss_impl!r}"
            )
        return _rpn_losses_impl(rpn_logits, rpn_deltas, targets)


def _rpn_losses_impl(rpn_logits, rpn_deltas, targets):
    # Accumulation-precision entry (mixed policy: the head emits bf16).
    # The upcast happens HERE, inside the rpn_loss named scope — the
    # tpulint TPU006 allowlist — so loss sums always run in f32.  No-op
    # on f32 inputs.  The dense form pays a (B, A) f32 materialization;
    # the compact form below upcasts after the Q-row gather instead.
    rpn_logits = rpn_logits.astype(jnp.float32)
    rpn_deltas = rpn_deltas.astype(jnp.float32)
    labels = targets.labels            # (B, A) 1/0/-1
    valid = targets.valid_mask         # (B, A)
    fg = targets.fg_mask               # (B, A)
    n_valid = jnp.maximum(jnp.sum(valid), 1.0)

    logp = jax.nn.log_sigmoid(rpn_logits)
    log1mp = jax.nn.log_sigmoid(-rpn_logits)
    is_fg = (labels == 1).astype(rpn_logits.dtype)
    bce = -(is_fg * logp + (1.0 - is_fg) * log1mp)
    cls_loss = jnp.sum(bce * valid) / n_valid

    box_loss = weighted_smooth_l1(
        rpn_deltas,
        targets.bbox_targets,
        inside_weight=fg[..., None].astype(rpn_deltas.dtype),
        sigma=3.0,
        normalizer=n_valid,
    )

    pred_fg = rpn_logits > 0.0
    acc = jnp.sum((pred_fg == (labels == 1)) * valid) / n_valid
    return cls_loss, box_loss, acc


def _rpn_losses_compact(rpn_logits, rpn_deltas, targets):
    """RPN losses over the Q sampled anchor rows only.

    The dense form reduces BCE over all (B, A) anchors with at most
    ``batch_size`` nonzero terms per image; here the assignment masks are
    fused into the loss by gathering the sampled rows assign_anchors
    already knows (``sel_idx`` — the subsample top_k's own output), so
    forward AND backward touch Q = fg_quota + batch_size rows per image
    instead of A = 268k.  Same loss terms (every masked-out dense term is
    an exact 0.0); only the summation order differs, so metrics agree to
    f32 round-off rather than bitwise.  The accuracy metric is a 0/1
    count and matches the dense value exactly.
    """
    idx = targets.sel_idx              # (B, Q)
    take = targets.sel_take.astype(jnp.float32)
    is_fg = targets.sel_fg             # (B, Q)
    n_valid = jnp.maximum(jnp.sum(take), 1.0)

    # Gather in the head's output dtype, upcast only the Q selected rows
    # (accumulation allowlist: we are inside the rpn_loss named scope).
    logit_sel = jnp.take_along_axis(rpn_logits, idx, axis=1)      # (B, Q)
    logit_sel = logit_sel.astype(jnp.float32)
    fgf = is_fg.astype(jnp.float32)
    bce = -(
        fgf * jax.nn.log_sigmoid(logit_sel)
        + (1.0 - fgf) * jax.nn.log_sigmoid(-logit_sel)
    )
    cls_loss = jnp.sum(bce * take) / n_valid

    deltas_sel = jnp.take_along_axis(rpn_deltas, idx[..., None], axis=1)
    deltas_sel = deltas_sel.astype(jnp.float32)
    targets_sel = jnp.take_along_axis(targets.bbox_targets, idx[..., None], axis=1)
    box_loss = weighted_smooth_l1(
        deltas_sel,
        targets_sel,
        inside_weight=fgf[..., None],
        sigma=3.0,
        normalizer=n_valid,
    )

    pred_fg = logit_sel > 0.0
    acc = jnp.sum((pred_fg == is_fg) * take) / n_valid
    return cls_loss, box_loss, acc


def _rcnn_losses(cls_logits, box_deltas, samples, class_agnostic: bool):
    """R-CNN classification + per-class box regression losses.

    cls_logits (N, C), box_deltas (N, C or 1, 4) over N = B*roi_batch
    flattened samples.  Matches the reference's SoftmaxOutput
    (normalization='valid') + smooth_l1(sigma=1) scaled 1/BATCH_ROIS.
    """
    with jax.named_scope("rcnn_loss"):
        return _rcnn_losses_impl(cls_logits, box_deltas, samples,
                                 class_agnostic)


def _rcnn_losses_impl(cls_logits, box_deltas, samples, class_agnostic: bool):
    # Accumulation-precision entry (see _rpn_losses_impl): N = B*roi_batch
    # rows only, upcast inside the rcnn_loss named scope.
    cls_logits = cls_logits.astype(jnp.float32)
    box_deltas = box_deltas.astype(jnp.float32)
    labels = samples.labels.reshape(-1)            # (N,)
    weights = samples.label_weights.reshape(-1)    # (N,)
    fg = samples.fg_mask.reshape(-1)               # (N,)
    targets = samples.bbox_targets.reshape(-1, 4)  # (N, 4)
    n_valid = jnp.maximum(jnp.sum(weights), 1.0)

    cls_loss = masked_softmax_cross_entropy(cls_logits, labels, weights)

    if class_agnostic:
        sel = box_deltas[:, 0, :]
    else:
        idx = jnp.clip(labels, 0, box_deltas.shape[1] - 1)
        sel = jnp.take_along_axis(box_deltas, idx[:, None, None].repeat(4, -1), axis=1)[:, 0, :]
    box_loss = weighted_smooth_l1(
        sel,
        targets,
        inside_weight=fg[:, None].astype(sel.dtype),
        sigma=1.0,
        normalizer=n_valid,
    )

    pred = jnp.argmax(cls_logits, axis=-1)
    acc = jnp.sum((pred == labels) * weights) / n_valid
    return cls_loss, box_loss, acc


# ---------------------------------------------------------------------------
# Proposal plumbing (per-image, vmapped)


def _propose_one(cfg: ModelConfig, train: bool):
    """Builds the per-image proposal fn over concatenated level outputs.

    ``rpn.fused_middle``/``rpn.nms_impl`` select the detection-middle
    backend: the fused Pallas kernel (ops/pallas/middle.py — decode ->
    clip -> snap -> NMS VMEM-resident, bit-identical to the dense chain),
    the pallas keep-mask sweep under the dense decode, or the all-XLA
    oracle.  Same fallback discipline as ``_pool_rois_impl``: pallas
    backends need a TPU or MX_RCNN_PALLAS_INTERPRET=1; anything else
    quietly drops to the XLA path (the knobs are default-off, so a
    fallback can only happen when explicitly requested — warn on TPU,
    debug-log off it).
    """
    global LAST_MIDDLE_IMPL
    rpn_cfg = cfg.rpn
    pre = rpn_cfg.train_pre_nms_top_n if train else rpn_cfg.test_pre_nms_top_n
    post = rpn_cfg.train_post_nms_top_n if train else rpn_cfg.test_post_nms_top_n

    if rpn_cfg.nms_impl not in ("xla", "pallas"):
        raise ValueError(
            f"rpn.nms_impl must be 'xla' or 'pallas', got {rpn_cfg.nms_impl!r}"
        )
    interpret = _pallas_interpret()
    can_pallas = jax.default_backend() == "tpu" or interpret
    want_pallas = rpn_cfg.fused_middle or rpn_cfg.nms_impl == "pallas"
    if want_pallas and not can_pallas:
        import logging

        lg = logging.getLogger("mx_rcnn_tpu")
        (lg.warning if jax.default_backend() == "tpu" else lg.debug)(
            "rpn fused_middle/nms_impl='pallas' unavailable (backend=%s) "
            "— using the XLA detection middle",
            jax.default_backend(),
        )
    fused = rpn_cfg.fused_middle and can_pallas
    nms_impl = rpn_cfg.nms_impl if can_pallas else "xla"
    LAST_MIDDLE_IMPL = (
        "fused" if fused else ("pallas-nms" if nms_impl == "pallas" else "xla")
    )

    def single(level_scores, level_deltas, level_anchor, hw) -> Proposals:
        if len(level_scores) == 1:
            (s,), (d,), (a,) = (
                list(level_scores.values()),
                list(level_deltas.values()),
                list(level_anchor.values()),
            )
            return generate_proposals(
                s, d, a, hw[0], hw[1],
                pre_nms_top_n=pre, post_nms_top_n=post,
                nms_threshold=rpn_cfg.nms_threshold, min_size=rpn_cfg.min_size,
                topk_impl=rpn_cfg.topk_impl, topk_recall=rpn_cfg.topk_recall,
                topk_block=rpn_cfg.topk_block,
                nms_sweep_cap=rpn_cfg.nms_sweep_cap,
                nms_impl=nms_impl, fused_middle=fused,
                pallas_interpret=interpret,
            )
        return generate_fpn_proposals(
            level_scores, level_deltas, level_anchor, hw[0], hw[1],
            pre_nms_top_n=pre, post_nms_top_n=post,
            nms_threshold=rpn_cfg.nms_threshold, min_size=rpn_cfg.min_size,
            topk_impl=rpn_cfg.topk_impl, topk_recall=rpn_cfg.topk_recall,
            topk_block=rpn_cfg.topk_block,
            nms_sweep_cap=rpn_cfg.nms_sweep_cap,
            nms_impl=nms_impl, fused_middle=fused,
            pallas_interpret=interpret,
        )

    return single


def _slice_levels(levels, anchors, score_row, delta_row):
    """Split concatenated per-anchor rows back into per-level dicts, paired
    with each level's static anchor grid.  Shared by train and inference."""
    off = 0
    s_lvls, d_lvls, a_lvls = {}, {}, {}
    for l in levels:
        n = anchors[l].shape[0]
        s_lvls[l] = score_row[off:off + n]
        d_lvls[l] = delta_row[off:off + n]
        a_lvls[l] = anchors[l]
        off += n
    return s_lvls, d_lvls, a_lvls


# Trace-time record of the backend _pool_rois last selected ("pallas",
# "pallas-shardmap", or "xla") — set while jit traces, so tests and the
# driver dryrun can assert which path a compiled program actually took.
LAST_POOL_IMPL: Optional[str] = None

# Same record for the detection middle (_propose_one): "fused" (the Pallas
# fused middle), "pallas-nms" (dense decode + pallas keep-mask sweep), or
# "xla" (the all-XLA oracle / fallback).
LAST_MIDDLE_IMPL: Optional[str] = None


def _pallas_interpret() -> bool:
    """Off-TPU escape hatch: MX_RCNN_PALLAS_INTERPRET=1 runs the kernel in
    pallas interpret mode (pure-JAX emulation of grid/DMA) so fake-mesh CPU
    tests and the driver's multichip dryrun exercise the production path."""
    import os

    return (
        jax.default_backend() != "tpu"
        and os.environ.get("MX_RCNN_PALLAS_INTERPRET") == "1"
    )


def _pool_rois(cfg: ModelConfig, feats, rois, pooled_size: int, roi_level_set,
               mesh=None):
    # Named scope so per-component cost attribution (utils/hlo_profile.py)
    # can see the parameter-free ROI stage, which no flax module names.
    with jax.named_scope("roi_align"):
        return _pool_rois_impl(
            cfg, feats, rois, pooled_size, roi_level_set, mesh
        )


def _pool_rois_impl(cfg: ModelConfig, feats, rois, pooled_size: int,
                    roi_level_set, mesh=None):
    """ROIAlign over the batch. rois: (B, R, 4) -> (B, R, S, S, C).

    ``cfg.rcnn.roi_align_impl`` picks the backend: "pallas" (default — ONE
    batch-folded kernel launch per step; measured 83.1 -> 77.6 ms on the
    full R50-FPN train step, 219.5 -> 118.8 ms on the batch-8 eval step)
    or "xla" (flattened-pyramid gather — the oracle and the automatic
    fallback off-TPU, on single-level C4 pyramids, and on unsupported
    layouts).  Since r3 the pallas path's backward is a Pallas window-RMW
    kernel too (ops/pallas/roi_align.py::_bwd_kernel; MX_RCNN_POOL_BWD=xla
    restores the autodiff-of-XLA backward).

    ``mesh``: a >1-data-axis mesh wraps the kernel in ``shard_map`` so each
    chip pools its own images (the kernel's per-shard contract) instead of
    GSPMD replicating the opaque kernel call; None = single-device jit or
    a caller that keeps the XLA path (spatial partitioning).
    """
    global LAST_POOL_IMPL
    if cfg.rcnn.roi_align_impl not in ("xla", "pallas"):
        raise ValueError(
            f"rcnn.roi_align_impl must be 'xla' or 'pallas', "
            f"got {cfg.rcnn.roi_align_impl!r}"
        )
    if cfg.rcnn.roi_align_bwd_impl not in ("xla", "pallas"):
        raise ValueError(
            f"rcnn.roi_align_bwd_impl must be 'xla' or 'pallas', "
            f"got {cfg.rcnn.roi_align_bwd_impl!r}"
        )
    levels = sorted(feats)
    want_pallas = cfg.rcnn.roi_align_impl == "pallas"
    roi_levels = {l: f for l, f in feats.items() if l in roi_level_set}
    interpret = _pallas_interpret()
    can_pallas = (
        len(levels) > 1
        and (jax.default_backend() == "tpu" or interpret)
        and pallas_supported(roi_levels)
    )
    if want_pallas and not can_pallas:
        import logging

        # Expected fallbacks (off-TPU; single-level C4 pyramid) are quiet —
        # pallas is the config default.  A genuinely unsupported LAYOUT on
        # a multi-level TPU pyramid is worth a warning.
        lg = logging.getLogger("mx_rcnn_tpu")
        unexpected = jax.default_backend() == "tpu" and len(levels) > 1
        (lg.warning if unexpected else lg.debug)(
            "roi_align_impl='pallas' unavailable "
            "(levels=%d, backend=%s) — using the XLA path",
            len(levels), jax.default_backend(),
        )
    if len(levels) > 1:
        if want_pallas and can_pallas:
            from mx_rcnn_tpu.parallel.mesh import DATA_AXIS

            if mesh is not None and mesh.shape.get(DATA_AXIS, 1) > 1:
                LAST_POOL_IMPL = "pallas-shardmap"
                return sharded_multilevel_roi_align(
                    roi_levels, rois, pooled_size, cfg.rcnn.sampling_ratio,
                    mesh, DATA_AXIS, interpret=interpret,
                    bwd_impl=cfg.rcnn.roi_align_bwd_impl,
                )
            # Whole batch in ONE kernel launch: the batch folds into the
            # pallas grid (B*R roi steps), no per-image python unroll.
            LAST_POOL_IMPL = "pallas"
            return multilevel_roi_align_fast(
                roi_levels, rois, pooled_size, cfg.rcnn.sampling_ratio,
                POOL_WINDOW, interpret, cfg.rcnn.roi_align_bwd_impl,
            )
        LAST_POOL_IMPL = "xla"
        return jax.vmap(
            lambda fs, r: multilevel_roi_align(
                fs, r, output_size=pooled_size, sampling_ratio=cfg.rcnn.sampling_ratio
            )
        )(roi_levels, rois)
    lvl = levels[0]
    LAST_POOL_IMPL = "xla"
    return jax.vmap(
        lambda f, r: roi_align(
            f, r, pooled_size, 1.0 / (2**lvl), cfg.rcnn.sampling_ratio
        )
    )(feats[lvl], rois)


# ---------------------------------------------------------------------------
# Mask branch (Mask R-CNN, BASELINE config #5)


def crop_gt_masks(gt_masks, gt_boxes, gt_idx, rois, out_size: int):
    """Bilinear-crop each roi's matched gt mask to the mask-head grid.

    ``gt_masks`` are rasterized box-relative on the host
    (data/loader.py::GT_MASK_SIZE): mask pixel (v, u) spans its gt box
    uniformly.  For a sampled roi that only overlaps its gt, the crop maps
    roi-grid centers into the gt box frame; points outside the box are
    background (0).  Replaces the host-side polygon rasterization inside
    Detectron-style loaders with an in-graph resample.

    Args: gt_masks (G, Hm, Wm); gt_boxes (G, 4); gt_idx (B,); rois (B, 4).
    Returns: (B, out_size, out_size) float32 in [0, 1].
    """
    hm, wm = gt_masks.shape[-2:]
    masks = jnp.take(gt_masks, gt_idx, axis=0)      # (B, Hm, Wm)
    boxes = jnp.take(gt_boxes, gt_idx, axis=0)      # (B, 4)

    def one(mask, box, roi):
        # +1: the host rasterizer (data/loader.py::_rasterize_mask) spreads
        # the mask grid over the inclusive-pixel box extent (x2-x1+1); the
        # inverse mapping here must use the same convention or targets
        # shrink toward the top-left by 1/(bw+1).
        bw = jnp.maximum(box[2] - box[0] + 1.0, 1e-3)
        bh = jnp.maximum(box[3] - box[1] + 1.0, 1e-3)
        ys = roi[1] + (jnp.arange(out_size) + 0.5) / out_size * (roi[3] - roi[1])
        xs = roi[0] + (jnp.arange(out_size) + 0.5) / out_size * (roi[2] - roi[0])
        v = (ys - box[1]) / bh * hm - 0.5            # mask pixel coords
        u = (xs - box[0]) / bw * wm - 0.5
        inside = ((v > -1.0) & (v < hm))[:, None] & ((u > -1.0) & (u < wm))[None, :]
        v = jnp.clip(v, 0.0, hm - 1.0)
        u = jnp.clip(u, 0.0, wm - 1.0)
        v0 = jnp.floor(v).astype(jnp.int32)
        u0 = jnp.floor(u).astype(jnp.int32)
        lv = v - v0
        lu = u - u0
        v1 = jnp.minimum(v0 + 1, hm - 1)
        u1 = jnp.minimum(u0 + 1, wm - 1)
        val = (
            mask[v0][:, u0] * (1 - lv)[:, None] * (1 - lu)[None, :]
            + mask[v0][:, u1] * (1 - lv)[:, None] * lu[None, :]
            + mask[v1][:, u0] * lv[:, None] * (1 - lu)[None, :]
            + mask[v1][:, u1] * lv[:, None] * lu[None, :]
        )
        return val * inside

    return jax.vmap(one)(masks, boxes, rois)


def _mask_loss(mask_logits, samples, gt_masks, gt_boxes, resolution: int):
    """Per-fg-roi binary CE on the matched-class mask channel.

    mask_logits: (B_rois, M, M, C); averaged over fg rois x pixels
    (Mask R-CNN: the loss is defined only on positives' own class channel).
    """
    with jax.named_scope("mask_loss"):
        return _mask_loss_impl(
            mask_logits, samples, gt_masks, gt_boxes, resolution
        )


def _mask_loss_impl(mask_logits, samples, gt_masks, gt_boxes, resolution: int):
    targets = crop_gt_masks(
        gt_masks, gt_boxes, samples.gt_indices, samples.rois, resolution
    )                                                    # (B, M, M)
    b = mask_logits.shape[0]
    own = mask_logits[jnp.arange(b), :, :, samples.labels]  # (B, M, M)
    own = own.astype(jnp.float32)
    per_pix = optax_sigmoid_ce(own, targets)
    w = (samples.fg_mask & (samples.label_weights > 0)).astype(jnp.float32)
    per_roi = per_pix.mean(axis=(1, 2))
    return jnp.sum(per_roi * w) / jnp.maximum(jnp.sum(w), 1.0)


def optax_sigmoid_ce(logits, labels):
    """Numerically-stable sigmoid cross-entropy (optax formulation)."""
    return jnp.maximum(logits, 0.0) - logits * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )


# ---------------------------------------------------------------------------
# Public graphs


def prep_images(images: jnp.ndarray, pixel_stats=None) -> jnp.ndarray:
    """In-graph image normalization for uint8 batches.

    The reference normalizes on host (``rcnn/io/image.py::transform``) and
    ships float32 — 12 MB/image at the recipe canvas.  Shipping the uint8
    letterboxed pixels instead quarters host->device bytes and the
    device_prefetch HBM footprint; the (x - mean) / std here is one fused
    subtract/multiply XLA folds into the first conv's input, and it is the
    same float32 math either side of the transfer.  The arithmetic follows
    the native fused kernel's convention, (x - mean) * (1/std) with the
    reciprocal precomputed in float32 (native/src/native.cc inv_std) — the
    reciprocal is materialized HERE rather than left to XLA so the result
    is bit-identical to that host path by construction, not by hoping the
    compiler's divide-by-constant canonicalization rounds the same way (a
    jnp divide measured 1 ULP off the host value on XLA:CPU).  The numpy
    normalize_image divide can differ from either by 1 ULP per pixel.
    float32 inputs pass through unchanged (they arrive already
    normalized).  Padding behaves identically too: uint8 zeros normalize
    to (0 - mean) * (1/std), the value the native kernel pads with.
    """
    if images.dtype != jnp.uint8:
        return images
    if pixel_stats is None:
        raise ValueError(
            "uint8 Batch.images need pixel_stats=(mean, std) for in-graph "
            "normalization (pass cfg.data.pixel_mean / pixel_std)"
        )
    import numpy as np

    mean = np.asarray(pixel_stats[0], np.float32)
    inv_std = np.float32(1.0) / np.asarray(pixel_stats[1], np.float32)
    with jax.named_scope("prep_images"):
        return (
            images.astype(jnp.float32) - jnp.asarray(mean)
        ) * jnp.asarray(inv_std)


def init_detector(model: TwoStageDetector, rng: jax.Array, image_size, batch: int = 1):
    """Initialize all variables (params + frozen-BN constants)."""
    h, w = image_size
    dummy = jnp.zeros((batch, h, w, 3), jnp.float32)
    return model.init(rng, dummy)


def forward_train(model: TwoStageDetector, variables, rng: jax.Array, batch: Batch,
                  mesh=None, pixel_stats=None, rngs=None):
    """One full training forward pass -> (total_loss, metrics dict).

    Differentiable w.r.t. ``variables['params']``.  Equivalent of the
    reference's train symbol forward (SURVEY.md section 4.1 hot loop) with
    both CustomOp host syncs replaced by in-graph ops.  ``mesh``: >1-chip
    data mesh for the shard_map'd Pallas ROIAlign (see :func:`_pool_rois`).
    ``pixel_stats``: (mean, std) for uint8 batches (see :func:`prep_images`).

    ``rngs``: optional ``(assign_keys, sample_keys)`` per-image key arrays
    (each (B, 2), rows as produced by ``jax.random.split(..., B)``) that
    REPLACE the internal split of ``rng`` (then ignored; pass None).  The
    gradient-accumulation step uses this to hand each microbatch its slice
    of the keys a single big batch would derive, so microbatched and
    monolithic steps sample identical anchors/rois per image
    (parallel/step.py).  When omitted the split happens here exactly as it
    always has — the default trace is unchanged.
    """
    cfg = model.cfg
    images = prep_images(batch.images, pixel_stats)
    feats = model.apply(variables, images, method="features")

    b = images.shape[0]
    rng_assign = rng_sample = None
    if rngs is None:
        rng_assign, rng_sample = jax.random.split(rng)

    # gt_ignore=None keeps the cheaper no-IoA graph (in_axes=None maps the
    # leafless None through vmap untouched; the callees skip the overlap
    # computation entirely).
    gt_ignore = batch.gt_ignore
    gi_axis = 0 if gt_ignore is not None else None

    use_ext = batch.ext_rois is not None
    if use_ext and batch.ext_valid is None:
        raise ValueError("Batch.ext_rois requires ext_valid (pad mask)")
    if use_ext and cfg.rpn.loss_weight == 0.0:
        # Fast R-CNN mode (reference ``rcnn/tools/train_rcnn.py``): the box
        # head trains on externally supplied proposals and the RPN never
        # enters the graph — no head apply, no anchor labeling, no losses.
        rpn_cls = rpn_box = rpn_acc = jnp.zeros((), jnp.float32)
    else:
        rpn_out = model.apply(variables, feats, method="rpn")
        anchors = level_anchors(cfg, feats)
        levels = sorted(rpn_out)
        logits_cat = jnp.concatenate([rpn_out[l][0] for l in levels], axis=1)
        deltas_cat = jnp.concatenate([rpn_out[l][1] for l in levels], axis=1)
        anchors_cat = jnp.concatenate([anchors[l] for l in levels], axis=0)

        with jax.named_scope("assign_anchors"):
            targets = jax.vmap(
                lambda k, gt, gv, gi, hw: assign_anchors_cfg(
                    cfg, k, anchors_cat, gt, gv, hw[0], hw[1], gt_ignore=gi
                ),
                in_axes=(0, 0, 0, gi_axis, 0),
            )(
                rngs[0] if rngs is not None else jax.random.split(rng_assign, b),
                batch.gt_boxes,
                batch.gt_valid,
                gt_ignore,
                batch.image_hw,
            )

        rpn_cls, rpn_box, rpn_acc = _rpn_losses(
            logits_cat, deltas_cat, targets, cfg.rpn.loss_impl
        )

    if use_ext:
        prop_rois, prop_valid = batch.ext_rois, batch.ext_valid
    else:
        # Proposals are detached: the reference never backprops through the
        # Proposal op either (CustomOp forward-only); gradients reach the
        # RPN exclusively through its losses.
        with jax.named_scope("proposals"):
            scores = jax.nn.sigmoid(lax.stop_gradient(logits_cat))
            deltas_sg = lax.stop_gradient(deltas_cat)
            propose = _propose_one(cfg, train=True)
            props = jax.vmap(
                lambda s_row, d_row, hw: propose(*_slice_levels(levels, anchors, s_row, d_row), hw)
            )(scores, deltas_sg, batch.image_hw)  # Proposals (B, R, ...)
        prop_rois, prop_valid = props.rois, props.valid

    with jax.named_scope("sample_rois"):
        samples = jax.vmap(
            lambda k, rois, rv, gt, gc, gv, gi: sample_rois(
                k, rois, rv, gt, gc, gv,
                batch_size=cfg.rcnn.roi_batch_size,
                fg_fraction=cfg.rcnn.fg_fraction,
                fg_iou=cfg.rcnn.fg_iou,
                bg_iou_hi=cfg.rcnn.bg_iou_hi,
                bg_iou_lo=cfg.rcnn.bg_iou_lo,
                bbox_weights=cfg.rcnn.bbox_weights,
                gt_ignore=gi,
                roi_block=cfg.rcnn.roi_block,
            ),
            in_axes=(0, 0, 0, 0, 0, 0, gi_axis),
        )(
            rngs[1] if rngs is not None else jax.random.split(rng_sample, b),
            prop_rois,
            prop_valid,
            batch.gt_boxes,
            batch.gt_classes.astype(jnp.int32),
            batch.gt_valid,
            gt_ignore,
        )

    pooled = _pool_rois(
        cfg, feats, samples.rois, cfg.rcnn.pooled_size, model.roi_levels,
        mesh=mesh,
    )
    s = cfg.rcnn.pooled_size
    pooled_flat = pooled.reshape(-1, s, s, pooled.shape[-1])
    cls_logits, box_deltas = model.apply(variables, pooled_flat, method="box")

    rcnn_cls, rcnn_box, rcnn_acc = _rcnn_losses(
        cls_logits, box_deltas, samples, cfg.rcnn.class_agnostic
    )

    total = (
        cfg.rpn.loss_weight * (rpn_cls + rpn_box)
        + cfg.rcnn.loss_weight * (rcnn_cls + rcnn_box)
    )
    metrics = {
        # Names mirror the reference's six EvalMetrics (rcnn/core/metric.py).
        "RPNAcc": rpn_acc,
        "RPNLogLoss": rpn_cls,
        "RPNL1Loss": rpn_box,
        "RCNNAcc": rcnn_acc,
        "RCNNLogLoss": rcnn_cls,
        "RCNNL1Loss": rcnn_box,
        "loss": total,
    }

    if cfg.mask.enabled and batch.gt_masks is not None:
        # sample_rois compacts fg into a leading block, so the static fg
        # quota prefix contains every positive — the mask branch only needs
        # those rows (4x fewer rois at the default 0.25 fg fraction).
        n_fg = max(int(cfg.rcnn.roi_batch_size * cfg.rcnn.fg_fraction), 1)
        fg = jax.tree_util.tree_map(lambda x: x[:, :n_fg], samples)
        sm = cfg.mask.pooled_size
        pooled_m = _pool_rois(cfg, feats, fg.rois, sm, model.roi_levels, mesh=mesh)
        m_logits = model.apply(
            variables, pooled_m.reshape(-1, sm, sm, pooled_m.shape[-1]),
            method="mask",
        )                                                  # (B*n_fg, M, M, C)
        m_logits = m_logits.reshape(b, -1, *m_logits.shape[1:])
        mask_loss = jnp.mean(
            jax.vmap(
                lambda ml, sm_, gm, gb: _mask_loss(
                    ml, sm_, gm, gb, cfg.mask.resolution
                )
            )(m_logits, fg, batch.gt_masks, batch.gt_boxes)
        )
        total = total + cfg.mask.loss_weight * mask_loss
        metrics["MaskLogLoss"] = mask_loss
        metrics["loss"] = total

    return total, metrics


def assign_anchors_cfg(cfg: ModelConfig, key, anchors, gt, gv, h, w, gt_ignore=None):
    return assign_anchors(
        key, anchors, gt, gv, h, w,
        batch_size=cfg.rpn.batch_size,
        fg_fraction=cfg.rpn.fg_fraction,
        positive_iou=cfg.rpn.positive_iou,
        negative_iou=cfg.rpn.negative_iou,
        allowed_border=cfg.rpn.allowed_border,
        gt_ignore=gt_ignore,
        assign_block=cfg.rpn.assign_block,
        topk_block=cfg.rpn.topk_block,
    )


def forward_inference(model: TwoStageDetector, variables, batch: Batch,
                      mesh=None, pixel_stats=None,
                      box_head_apply=None) -> Detections:
    """Full inference: proposals -> box head -> per-class NMS -> top-D.

    Replaces ``rcnn/core/tester.py::im_detect`` + the per-class python NMS
    loop in ``pred_eval`` with one jitted region; detections come back
    padded to ``cfg.test.max_detections`` with a validity mask.  ``mesh``/
    ``pixel_stats``: see :func:`forward_train`.

    ``box_head_apply``: optional drop-in for the box-head apply —
    ``f(pooled_flat) -> (cls_logits (R, C), box_deltas (R, n_reg, 4))``,
    the exact :class:`~mx_rcnn_tpu.models.heads.BoxHead` contract.  The
    int8/bf16 serving program (serve/quantize.py) injects here; the rest
    of the graph (backbone, RPN, pooling, postprocess) is shared.
    """
    cfg = model.cfg
    feats = model.apply(
        variables, prep_images(batch.images, pixel_stats), method="features"
    )
    if batch.ext_rois is not None:
        # Fast R-CNN test mode (reference ``test_rcnn --has_rpn false``):
        # score externally supplied proposals; the RPN never runs.
        if batch.ext_valid is None:
            raise ValueError("Batch.ext_rois requires ext_valid (pad mask)")
        props = Proposals(
            rois=batch.ext_rois,
            scores=jnp.zeros(batch.ext_valid.shape, jnp.float32),
            valid=batch.ext_valid,
        )
    else:
        props = _propose_on_features(model, variables, feats, batch)

    pooled = _pool_rois(
        cfg, feats, props.rois, cfg.rcnn.pooled_size, model.roi_levels,
        mesh=mesh,
    )
    s = cfg.rcnn.pooled_size
    pooled_flat = pooled.reshape(-1, s, s, pooled.shape[-1])
    if box_head_apply is None:
        cls_logits, box_deltas = model.apply(
            variables, pooled_flat, method="box"
        )
    else:
        cls_logits, box_deltas = box_head_apply(pooled_flat)

    b, r = props.rois.shape[:2]
    num_classes = cfg.num_classes
    # Scores and box coordinates stay f32 through postprocess regardless
    # of the head's output dtype: the softmax/decode operands here are
    # (B*R, C)-sized — trivial next to the backbone — and f32 scores keep
    # ranking/threshold behavior identical across precision policies.
    cls_prob = jax.nn.softmax(
        cls_logits.astype(jnp.float32), axis=-1
    ).reshape(b, r, num_classes)
    box_deltas = box_deltas.astype(jnp.float32).reshape(b, r, -1, 4)

    if cfg.test.nms_mode == "fused":
        post_one = _postprocess_one_fused
    elif cfg.test.nms_mode == "per_class":
        post_one = _postprocess_one
    else:
        raise ValueError(
            f"test.nms_mode must be 'per_class' or 'fused', "
            f"got {cfg.test.nms_mode!r}"
        )
    post = jax.vmap(
        lambda rois, rv, probs, deltas, hw: post_one(
            cfg, rois, rv, probs, deltas, hw
        )
    )(props.rois, props.valid, cls_prob, box_deltas, batch.image_hw)
    dets = Detections(*post)

    if cfg.mask.enabled:
        # Mask branch on the final detections (Mask R-CNN inference order:
        # boxes first, then one mask crop per kept detection).
        sm = cfg.mask.pooled_size
        pooled_m = _pool_rois(cfg, feats, dets.boxes, sm, model.roi_levels,
                              mesh=mesh)
        m_logits = model.apply(
            variables, pooled_m.reshape(-1, sm, sm, pooled_m.shape[-1]),
            method="mask",
        )                                                  # (B*D, M, M, C)
        d = dets.boxes.shape[1]
        cls_flat = dets.classes.reshape(-1)
        own = m_logits[jnp.arange(m_logits.shape[0]), :, :, cls_flat]
        probs_m = jax.nn.sigmoid(own.astype(jnp.float32))
        dets = dets._replace(masks=probs_m.reshape(b, d, *own.shape[1:]))
    return dets


def _propose_on_features(model, variables, feats, batch: Batch) -> Proposals:
    """Shared RPN->proposal front-end of inference and the RPN-dump path."""
    cfg = model.cfg
    rpn_out = model.apply(variables, feats, method="rpn")
    anchors = level_anchors(cfg, feats)
    levels = sorted(rpn_out)
    logits_cat = jnp.concatenate([rpn_out[l][0] for l in levels], axis=1)
    deltas_cat = jnp.concatenate([rpn_out[l][1] for l in levels], axis=1)
    scores = jax.nn.sigmoid(logits_cat)
    propose = _propose_one(cfg, train=False)
    return jax.vmap(
        lambda s_row, d_row, hw: propose(*_slice_levels(levels, anchors, s_row, d_row), hw)
    )(scores, deltas_cat, batch.image_hw)


def forward_proposals(model: TwoStageDetector, variables, batch: Batch,
                      pixel_stats=None) -> Proposals:
    """RPN-only inference: backbone -> RPN -> proposal generation.

    Replaces ``rcnn/core/tester.py::generate_proposals`` (used by
    ``rcnn/tools/test_rpn.py`` to dump the proposal pkl between alternate
    training phases).  Returns padded Proposals (rois, scores, valid) in
    input-image coordinates.
    """
    feats = model.apply(
        variables, prep_images(batch.images, pixel_stats), method="features"
    )
    props = _propose_on_features(model, variables, feats, batch)
    # Proposal scores cross into host numpy on the serving/RPN-dump paths;
    # emit f32 however the head computed them ((B, post_nms) — tiny).
    return props._replace(scores=props.scores.astype(jnp.float32))


def _postprocess_one(cfg: ModelConfig, rois, roi_valid, probs, deltas, hw):
    """Per-image postprocess: decode per class, threshold, per-class NMS,
    global top-D.  All static shapes: (R rois) x (C-1 fg classes)."""
    num_classes = cfg.num_classes
    r = rois.shape[0]
    d_out = cfg.test.max_detections
    per_class_k = min(r, max(2 * d_out, 100))

    def one_class(c):
        delta_c = deltas[:, 0, :] if cfg.rcnn.class_agnostic else deltas[:, c, :]
        boxes = decode_boxes(delta_c, rois, weights=cfg.rcnn.bbox_weights)
        boxes = clip_boxes(boxes, hw[0], hw[1])
        sc = jnp.where(
            roi_valid & (probs[:, c] >= cfg.test.score_threshold),
            probs[:, c],
            -jnp.inf,
        )
        top_s, top_i = lax.top_k(sc, per_class_k)
        top_b = jnp.take(boxes, top_i, axis=0)
        keep_i, keep_v = nms_indices(
            top_b, top_s, cfg.test.nms_threshold, per_class_k,
            sweep_cap=cfg.test.nms_sweep_cap,
        )
        out_b = jnp.take(top_b, keep_i, axis=0)
        out_s = jnp.where(keep_v, jnp.take(top_s, keep_i), -jnp.inf)
        return out_b, out_s

    # vmap over foreground classes (1..C-1).
    cls_ids = jnp.arange(1, num_classes)
    all_b, all_s = jax.vmap(one_class)(cls_ids)        # (C-1, K, 4), (C-1, K)
    flat_b = all_b.reshape(-1, 4)
    flat_s = all_s.reshape(-1)
    flat_c = jnp.repeat(cls_ids, per_class_k)

    top_s, top_i = lax.top_k(flat_s, d_out)
    valid = jnp.isfinite(top_s)
    return (
        jnp.take(flat_b, top_i, axis=0) * valid[:, None],
        jnp.where(valid, top_s, 0.0),
        jnp.where(valid, jnp.take(flat_c, top_i), 0).astype(jnp.int32),
        valid,
    )


def _postprocess_one_fused(cfg: ModelConfig, rois, roi_valid, probs, deltas, hw):
    """Fused postprocess: global top-K candidates, ONE class-offset NMS.

    Same decode/threshold/suppression math as :func:`_postprocess_one`,
    restructured for the TPU: instead of C-1 per-class passes (each a
    top-k plus an NMS fixed point that vmap runs until the slowest class
    converges), score-rank ALL (roi, class) pairs once, keep the top
    ``cfg.test.fused_top_k``, decode only those, and suppress with one
    ``batched_nms`` (boxes translated to per-class disjoint regions, so
    one pass equals independent per-class NMS).  Equal output whenever no
    per-class/global candidate cap binds — the caps are the only
    semantic difference, and both are far above the reference's
    max-100-detections regime.
    """
    num_classes = cfg.num_classes
    r = rois.shape[0]
    d_out = cfg.test.max_detections
    fg = num_classes - 1
    k = min(r * fg, cfg.test.fused_top_k)

    sc = jnp.where(
        roi_valid[:, None] & (probs[:, 1:] >= cfg.test.score_threshold),
        probs[:, 1:],
        -jnp.inf,
    )                                                   # (R, C-1)
    top_s, top_i = lax.top_k(sc.reshape(-1), k)         # flat id = roi*fg + (c-1)
    roi_i = top_i // fg
    cls = top_i % fg + 1                                # 1-based fg class

    cand_rois = jnp.take(rois, roi_i, axis=0)
    if cfg.rcnn.class_agnostic:
        delta_sel = deltas[roi_i, 0, :]
    else:
        delta_sel = deltas[roi_i, cls, :]
    boxes = decode_boxes(delta_sel, cand_rois, weights=cfg.rcnn.bbox_weights)
    boxes = clip_boxes(boxes, hw[0], hw[1])

    cand_valid = jnp.isfinite(top_s)
    keep = batched_nms(
        boxes, top_s, cls, cfg.test.nms_threshold, valid=cand_valid,
        sweep_cap=cfg.test.nms_sweep_cap,
    )
    kept_s = jnp.where(keep, top_s, -jnp.inf)
    out_s, out_i = lax.top_k(kept_s, min(d_out, k))
    if k < d_out:
        pad = d_out - k
        out_s = jnp.concatenate([out_s, jnp.full(pad, -jnp.inf, out_s.dtype)])
        out_i = jnp.concatenate([out_i, jnp.zeros(pad, out_i.dtype)])
    valid = jnp.isfinite(out_s)
    return (
        jnp.take(boxes, out_i, axis=0) * valid[:, None],
        jnp.where(valid, out_s, 0.0),
        jnp.where(valid, jnp.take(cls, out_i), 0).astype(jnp.int32),
        valid,
    )
