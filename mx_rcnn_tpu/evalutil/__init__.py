"""Detection evaluation (host-side, numpy).

Replaces the reference's evaluation stack: ``rcnn/dataset/pascal_voc_eval.py``
(classic VOC AP), the vendored ``rcnn/pycocotools`` (COCO mAP@[.5:.95] —
re-implemented here from the metric definition because pycocotools is not
installed in this environment), ``rcnn/core/tester.py::pred_eval`` (the
predict→NMS→accumulate loop) and ``rcnn/tools/reeval.py`` (re-score cached
detections).
"""

from mx_rcnn_tpu.evalutil.coco_eval import CocoEvaluator
from mx_rcnn_tpu.evalutil.detections import (
    detections_from_json,
    load_detections,
    save_detections,
)
from mx_rcnn_tpu.evalutil.pred_eval import (
    collect_detections,
    collect_detections_sharded,
    evaluate_detections,
    merge_detection_shards,
    pred_eval,
)
from mx_rcnn_tpu.evalutil.submission import (
    read_coco_results,
    read_voc_dets,
    write_coco_results,
    write_voc_dets,
)
from mx_rcnn_tpu.evalutil.voc_eval import voc_ap, voc_eval

__all__ = [
    "CocoEvaluator",
    "collect_detections",
    "collect_detections_sharded",
    "detections_from_json",
    "evaluate_detections",
    "load_detections",
    "merge_detection_shards",
    "pred_eval",
    "read_coco_results",
    "read_voc_dets",
    "save_detections",
    "voc_ap",
    "voc_eval",
    "write_coco_results",
    "write_voc_dets",
]
