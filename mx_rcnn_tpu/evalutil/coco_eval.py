"""Self-contained COCO-style detection evaluator (numpy).

Re-implements the COCO bbox metric from its public definition — the
reference reaches it through vendored pycocotools
(``rcnn/pycocotools/cocoeval.py``; not installed in this image): per
(category, IoU∈0.5:0.05:0.95, area range, maxDets) greedy score-ordered
matching, 101-point interpolated AP, and the standard 12-number summary
(AP, AP50, AP75, APs/m/l, AR1/10/100, ARs/m/l).

Crowd-ignore matching follows pycocotools: crowd gts never count toward
recall, detections overlapping them (intersection-over-det-area, the
``iou(..., iscrowd=1)`` measure) match as *ignored* — neither TP nor FP —
and an already-matched crowd gt can absorb further detections.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

IOU_THRS = np.linspace(0.5, 0.95, 10)
RECALL_THRS = np.linspace(0.0, 1.0, 101)
AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}
MAX_DETS = (1, 10, 100)


def _xyxy_iou(d: np.ndarray, g: np.ndarray) -> np.ndarray:
    """(n, 4) x (m, 4) → (n, m) IoU (continuous coords, no +1: COCO
    convention, unlike the VOC evaluator's integer-pixel +1)."""
    ix1 = np.maximum(d[:, None, 0], g[None, :, 0])
    iy1 = np.maximum(d[:, None, 1], g[None, :, 1])
    ix2 = np.minimum(d[:, None, 2], g[None, :, 2])
    iy2 = np.minimum(d[:, None, 3], g[None, :, 3])
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    ad = (d[:, 2] - d[:, 0]) * (d[:, 3] - d[:, 1])
    ag = (g[:, 2] - g[:, 0]) * (g[:, 3] - g[:, 1])
    return inter / np.maximum(ad[:, None] + ag[None, :] - inter, 1e-10)


def _greedy_match_reference(
    ious: np.ndarray, g_ignore: np.ndarray, g_crowd: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """The pycocotools matching rule as a literal triple loop (test oracle).

    gts must be sorted non-ignored-first.  Returns (dt_match (T, D) holding
    1 + matched gt index or 0, gt_match (T, G) holding 1 + det index).
    """
    D, G = ious.shape
    T = len(IOU_THRS)
    dt_match = np.zeros((T, D), dtype=np.int64)
    gt_match = np.zeros((T, G), dtype=np.int64)
    for ti, t in enumerate(IOU_THRS):
        for di in range(D):
            best, best_j = min(t, 1 - 1e-10), -1
            for gi in range(G):
                # A matched real gt is consumed; a crowd gt can absorb
                # any number of detections (pycocotools iscrowd rule).
                if gt_match[ti, gi] and not g_crowd[gi]:
                    continue
                # Past non-ignored best, stop upgrading to ignored gt.
                if best_j > -1 and not g_ignore[best_j] and g_ignore[gi]:
                    break
                if ious[di, gi] < best:
                    continue
                best, best_j = ious[di, gi], gi
            if best_j > -1:
                dt_match[ti, di] = best_j + 1
                gt_match[ti, best_j] = di + 1
    return dt_match, gt_match


def _greedy_match_batched(
    ious: np.ndarray, g_ignore: np.ndarray, g_crowd: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized :func:`_greedy_match_reference` (bit-identical), batched
    over A independent problems sharing the det list — the evaluator folds
    the four area buckets (whose gt columns are permutations of one IoU
    matrix) into one call.

    The det loop is inherently sequential (each det consumes a gt), but per
    det the A×T×G search collapses to array ops: among available real gts
    pick the last index attaining the max IoU (the oracle's ``>=`` update
    makes later ties win); only if none clears the threshold may an
    available ignored gt match (the oracle's break rule — reaching the
    ignored block with a real candidate stops the scan).  Dets whose max
    IoU over every problem's gts misses the lowest threshold can never
    match anywhere and are skipped.

    Args: ious (A, D, G); g_ignore, g_crowd (A, G).
    Returns: (dt_match (A, T, D), gt_match (A, T, G)).
    """
    A, D, G = ious.shape
    T = len(IOU_THRS)
    dt_match = np.zeros((A, T, D), dtype=np.int64)
    gt_match = np.zeros((A, T, G), dtype=np.int64)
    if D == 0 or G == 0:
        return dt_match, gt_match
    thr = np.minimum(IOU_THRS, 1 - 1e-10)[None, :]  # (1, T)
    real = ~g_ignore[:, None, :]                    # (A, 1, G)
    ign = g_ignore[:, None, :]
    crowd_avail = (g_ignore & g_crowd)[:, None, :]  # crowd: matched-but-available
    aidx = np.arange(A)[:, None]
    tidx = np.arange(T)[None, :]
    active = np.flatnonzero(ious.max(axis=2).max(axis=0) >= thr.min())
    for d in active:
        iou_d = ious[:, d, None, :]                             # (A, 1, G)
        free = gt_match == 0                                    # (A, T, G)
        cand = np.where(real & free, iou_d, -1.0)
        j_real = G - 1 - np.argmax(cand[:, :, ::-1], axis=2)    # last argmax
        ok_real = cand[aidx, tidx, j_real] >= thr               # (A, T)
        cand = np.where(crowd_avail | (ign & free), iou_d, -1.0)
        j_ign = G - 1 - np.argmax(cand[:, :, ::-1], axis=2)
        ok_ign = ~ok_real & (cand[aidx, tidx, j_ign] >= thr)
        j = np.where(ok_real, j_real, np.where(ok_ign, j_ign, -1))
        hit = j >= 0
        dt_match[hit, d] = j[hit] + 1
        a_hit, t_hit = np.nonzero(hit)
        gt_match[a_hit, t_hit, j[hit]] = d + 1
    return dt_match, gt_match


def _greedy_match(
    ious: np.ndarray, g_ignore: np.ndarray, g_crowd: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Single-problem wrapper over :func:`_greedy_match_batched`."""
    dt, gtm = _greedy_match_batched(
        ious[None], np.asarray(g_ignore, bool)[None], np.asarray(g_crowd, bool)[None]
    )
    return dt[0], gtm[0]


class CocoEvaluator:
    """Accumulate per-image detections + gt, then summarize.

    add_image() per image; summarize() → the 12 COCO numbers plus
    per-category AP.  Labels are contiguous 1-based category indices.
    """

    def __init__(self, num_classes: int, iou_type: str = "bbox") -> None:
        if iou_type not in ("bbox", "segm"):
            raise ValueError(f"iou_type must be bbox|segm, got {iou_type!r}")
        self.num_classes = num_classes  # incl. background 0
        self.iou_type = iou_type
        # (cat, image) → dict(dt=..., gt=..., iou=...)
        self._dts: dict = defaultdict(list)
        self._gts: dict = defaultdict(list)
        # cat → insertion-ordered image ids with dets or gt of that class
        # (dict as ordered set: deterministic accumulation order).
        self._cat_images: dict = defaultdict(dict)

    def add_image(
        self,
        image_id,
        det_boxes: np.ndarray,    # (n, 4) xyxy in ORIGINAL image coords
        det_scores: np.ndarray,   # (n,)
        det_classes: np.ndarray,  # (n,) 1-based
        gt_boxes: np.ndarray,     # (m, 4)
        gt_classes: np.ndarray,   # (m,)
        det_masks: list | None = None,  # n RLE dicts (segm mode)
        gt_masks: list | None = None,   # m RLE dicts (segm mode)
        gt_crowd: np.ndarray | None = None,  # (m,) bool iscrowd flags
    ) -> None:
        det_boxes = np.asarray(det_boxes, float).reshape(-1, 4)
        gt_boxes = np.asarray(gt_boxes, float).reshape(-1, 4)
        if gt_crowd is None:
            gt_crowd = np.zeros(len(gt_boxes), bool)
        gt_crowd = np.asarray(gt_crowd, bool).reshape(len(gt_boxes))
        if self.iou_type == "segm" and (det_masks is None or gt_masks is None):
            raise ValueError("segm evaluation needs det_masks and gt_masks RLEs")
        for c in range(1, self.num_classes):
            dm = np.flatnonzero(np.asarray(det_classes) == c)
            gm = np.flatnonzero(np.asarray(gt_classes) == c)
            if dm.size:
                self._dts[(c, image_id)] = (
                    det_boxes[dm],
                    np.asarray(det_scores, float)[dm],
                    [det_masks[i] for i in dm] if det_masks is not None else None,
                )
            if gm.size:
                self._gts[(c, image_id)] = (
                    gt_boxes[gm],
                    [gt_masks[i] for i in gm] if gt_masks is not None else None,
                    gt_crowd[gm],
                )
            if dm.size or gm.size:
                self._cat_images[c][image_id] = None

    # -- matching ----------------------------------------------------------

    def _cached_ious(self, cat: int, img, cache: dict):
        """(ious, dscores, darea, garea, g_crowd) for a (cat, img) pair:
        dets score-sorted and capped at MAX_DETS[-1], gts in stored order,
        crowd columns already converted to intersection-over-det-area.
        Area-range filtering only permutes/ignores gt columns, so one cache
        entry serves all four area buckets (pycocotools computes its ious
        once the same way).
        """
        key = (cat, img)
        if key in cache:
            return cache[key]
        dt = self._dts.get(key)
        gt = self._gts.get(key)
        if dt is None:
            dboxes, dscores, dmasks = np.zeros((0, 4)), np.zeros(0), []
        else:
            dboxes, dscores, dmasks = dt
            order = np.argsort(-dscores, kind="mergesort")[: MAX_DETS[-1]]
            dboxes, dscores = dboxes[order], dscores[order]
            dmasks = [dmasks[i] for i in order] if dmasks is not None else []
        gboxes, gmasks, g_crowd = (
            gt if gt is not None else (np.zeros((0, 4)), [], np.zeros(0, bool))
        )
        if self.iou_type == "segm":
            from mx_rcnn_tpu.evalutil.masks import rle_area, rle_iou

            garea = np.asarray([rle_area(m) for m in (gmasks or [])], float)
            garea = garea.reshape(len(gboxes))
            darea = np.asarray([rle_area(m) for m in dmasks], float).reshape(
                len(dboxes)
            )
            ious = rle_iou(dmasks, gmasks or [])
        else:
            garea = (gboxes[:, 2] - gboxes[:, 0]) * (gboxes[:, 3] - gboxes[:, 1])
            darea = (dboxes[:, 2] - dboxes[:, 0]) * (dboxes[:, 3] - dboxes[:, 1])
            ious = _xyxy_iou(dboxes, gboxes)
        if g_crowd.any() and len(dboxes):
            # Crowd overlap is intersection-over-det-area (pycocotools
            # iou(..., iscrowd=1)): recover the intersection from the IoU
            # and the two areas, renormalize by det area alone.
            inter = ious * (darea[:, None] + garea[None, :]) / (1.0 + ious)
            ioa = inter / np.maximum(darea[:, None], 1e-10)
            ious = np.where(g_crowd[None, :], ioa, ious)
        entry = (ious, dscores, darea, garea, g_crowd)
        cache[key] = entry
        return entry

    def _evaluate_img(self, cat: int, img, cache: dict):
        """→ {area: per-image match record}, one batched matcher call.

        Matches at maxDet=MAX_DETS[-1]; smaller maxDets are prefix slices
        of the returned arrays (greedy matching in score order is
        prefix-consistent — det k's match never depends on det k+1).  The
        four area buckets share one IoU matrix (area filtering only flips
        ignore flags and permutes gt columns), so they run as one batched
        problem."""
        if (cat, img) not in self._dts and (cat, img) not in self._gts:
            return None
        ious, dscores, darea, garea, g_crowd = self._cached_ious(cat, img, cache)
        areas = list(AREA_RANGES.items())
        ious_a, ign_a, crowd_a = [], [], []
        for _, rng in areas:
            # Crowd gts are ignored regardless of area; area filtering
            # ignores the rest outside the range (pycocotools _ignore).
            g_ignore = g_crowd | (garea < rng[0]) | (garea > rng[1])
            # Sort gt: non-ignored first (COCO matches real gt first).
            g_order = np.argsort(g_ignore, kind="mergesort")
            ious_a.append(ious[:, g_order])
            ign_a.append(g_ignore[g_order])
            crowd_a.append(g_crowd[g_order])
        ign_a = np.stack(ign_a)
        dt_match_a, _ = _greedy_match_batched(
            np.stack(ious_a), ign_a, np.stack(crowd_a)
        )
        out = {}
        for ai, (name, rng) in enumerate(areas):
            dt_match, g_ignore = dt_match_a[ai], ign_a[ai]
            # Unmatched dets outside the area range are ignored, matched-
            # to-ignored-gt dets are ignored.
            matched = dt_match > 0
            matched_ignore = np.zeros_like(matched)
            if g_ignore.size:
                matched_ignore[matched] = g_ignore[dt_match[matched] - 1]
            d_out = (darea < rng[0]) | (darea > rng[1])
            out[name] = {
                "scores": dscores,
                "dt_match": dt_match,
                "dt_ignore": np.where(matched, matched_ignore, d_out[None, :]),
                "num_gt": int((~g_ignore).sum()),
            }
        return out

    @staticmethod
    def _accumulate(per_img: list, max_det: int):
        """→ (precision (T, R), recall (T,)) or None if no gt anywhere."""
        if not per_img:
            return None
        npos = sum(r["num_gt"] for r in per_img)
        if npos == 0:
            return None
        scores = np.concatenate([r["scores"][:max_det] for r in per_img])
        order = np.argsort(-scores, kind="mergesort")
        T = len(IOU_THRS)
        matches = np.concatenate(
            [r["dt_match"][:, :max_det] for r in per_img], axis=1
        )[:, order]
        ignores = np.concatenate(
            [r["dt_ignore"][:, :max_det] for r in per_img], axis=1
        )[:, order]

        keep = ~ignores
        tps = np.cumsum((matches > 0) & keep, axis=1)  # (T, D)
        fps = np.cumsum((matches == 0) & keep, axis=1)
        rc = tps / npos
        pr = tps / np.maximum(tps + fps, 1e-10)
        precision = np.zeros((T, len(RECALL_THRS)))
        recall = rc[:, -1] if rc.shape[1] else np.zeros(T)
        # Monotone non-increasing precision envelope.
        pr = np.maximum.accumulate(pr[:, ::-1], axis=1)[:, ::-1]
        for ti in range(T):
            idx = np.searchsorted(rc[ti], RECALL_THRS, side="left")
            valid = idx < pr.shape[1]
            precision[ti, valid] = pr[ti, idx[valid]]
        return precision, recall

    # -- summary -----------------------------------------------------------

    def summarize(self) -> dict[str, float]:
        cats = range(1, self.num_classes)
        iou_cache: dict = {}
        acc: dict = {}
        for c in cats:
            by_area: dict[str, list] = {a: [] for a in AREA_RANGES}
            for img in self._cat_images.get(c, ()):
                r = self._evaluate_img(c, img, iou_cache)
                if r:
                    for a, rec in r.items():
                        by_area[a].append(rec)
            for a in AREA_RANGES:
                # COCO only varies one of area / maxDet at a time.
                for m in MAX_DETS if a == "all" else (MAX_DETS[-1],):
                    acc[(c, a, m)] = self._accumulate(by_area[a], m)

        def mean_ap(area: str, max_det: int, iou_idx=None) -> float:
            vals = []
            for c in cats:
                r = acc.get((c, area, max_det))
                if r is None:
                    continue
                p = r[0] if iou_idx is None else r[0][iou_idx : iou_idx + 1]
                vals.append(np.mean(p))
            return float(np.mean(vals)) if vals else -1.0

        def mean_ar(area: str, max_det: int) -> float:
            vals = [
                np.mean(r[1])
                for c in cats
                if (r := acc.get((c, area, max_det))) is not None
            ]
            return float(np.mean(vals)) if vals else -1.0

        out = {
            "AP": mean_ap("all", 100),
            "AP50": mean_ap("all", 100, iou_idx=0),
            "AP75": mean_ap("all", 100, iou_idx=5),
            "APs": mean_ap("small", 100),
            "APm": mean_ap("medium", 100),
            "APl": mean_ap("large", 100),
            "AR1": mean_ar("all", 1),
            "AR10": mean_ar("all", 10),
            "AR100": mean_ar("all", 100),
            "ARs": mean_ar("small", 100),
            "ARm": mean_ar("medium", 100),
            "ARl": mean_ar("large", 100),
        }
        for c in cats:
            r = acc.get((c, "all", 100))
            if r is not None:
                out[f"AP/class_{c}"] = float(np.mean(r[0]))
        return out
