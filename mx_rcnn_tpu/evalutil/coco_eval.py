"""Self-contained COCO-style detection evaluator (numpy).

Re-implements the COCO bbox metric from its public definition — the
reference reaches it through vendored pycocotools
(``rcnn/pycocotools/cocoeval.py``; not installed in this image): per
(category, IoU∈0.5:0.05:0.95, area range, maxDets) greedy score-ordered
matching, 101-point interpolated AP, and the standard 12-number summary
(AP, AP50, AP75, APs/m/l, AR1/10/100, ARs/m/l).

Crowd-ignore matching follows pycocotools: crowd gts never count toward
recall, detections overlapping them (intersection-over-det-area, the
``iou(..., iscrowd=1)`` measure) match as *ignored* — neither TP nor FP —
and an already-matched crowd gt can absorb further detections.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

IOU_THRS = np.linspace(0.5, 0.95, 10)
RECALL_THRS = np.linspace(0.0, 1.0, 101)
AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}
MAX_DETS = (1, 10, 100)


def _xyxy_iou(d: np.ndarray, g: np.ndarray) -> np.ndarray:
    """(n, 4) x (m, 4) → (n, m) IoU (continuous coords, no +1: COCO
    convention, unlike the VOC evaluator's integer-pixel +1)."""
    ix1 = np.maximum(d[:, None, 0], g[None, :, 0])
    iy1 = np.maximum(d[:, None, 1], g[None, :, 1])
    ix2 = np.minimum(d[:, None, 2], g[None, :, 2])
    iy2 = np.minimum(d[:, None, 3], g[None, :, 3])
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    ad = (d[:, 2] - d[:, 0]) * (d[:, 3] - d[:, 1])
    ag = (g[:, 2] - g[:, 0]) * (g[:, 3] - g[:, 1])
    return inter / np.maximum(ad[:, None] + ag[None, :] - inter, 1e-10)


class CocoEvaluator:
    """Accumulate per-image detections + gt, then summarize.

    add_image() per image; summarize() → the 12 COCO numbers plus
    per-category AP.  Labels are contiguous 1-based category indices.
    """

    def __init__(self, num_classes: int, iou_type: str = "bbox") -> None:
        if iou_type not in ("bbox", "segm"):
            raise ValueError(f"iou_type must be bbox|segm, got {iou_type!r}")
        self.num_classes = num_classes  # incl. background 0
        self.iou_type = iou_type
        # (cat, image) → dict(dt=..., gt=..., iou=...)
        self._dts: dict = defaultdict(list)
        self._gts: dict = defaultdict(list)
        self._images: set = set()

    def add_image(
        self,
        image_id,
        det_boxes: np.ndarray,    # (n, 4) xyxy in ORIGINAL image coords
        det_scores: np.ndarray,   # (n,)
        det_classes: np.ndarray,  # (n,) 1-based
        gt_boxes: np.ndarray,     # (m, 4)
        gt_classes: np.ndarray,   # (m,)
        det_masks: list | None = None,  # n RLE dicts (segm mode)
        gt_masks: list | None = None,   # m RLE dicts (segm mode)
        gt_crowd: np.ndarray | None = None,  # (m,) bool iscrowd flags
    ) -> None:
        self._images.add(image_id)
        det_boxes = np.asarray(det_boxes, float).reshape(-1, 4)
        gt_boxes = np.asarray(gt_boxes, float).reshape(-1, 4)
        if gt_crowd is None:
            gt_crowd = np.zeros(len(gt_boxes), bool)
        gt_crowd = np.asarray(gt_crowd, bool).reshape(len(gt_boxes))
        if self.iou_type == "segm" and (det_masks is None or gt_masks is None):
            raise ValueError("segm evaluation needs det_masks and gt_masks RLEs")
        for c in range(1, self.num_classes):
            dm = np.flatnonzero(np.asarray(det_classes) == c)
            gm = np.flatnonzero(np.asarray(gt_classes) == c)
            if dm.size:
                self._dts[(c, image_id)] = (
                    det_boxes[dm],
                    np.asarray(det_scores, float)[dm],
                    [det_masks[i] for i in dm] if det_masks is not None else None,
                )
            if gm.size:
                self._gts[(c, image_id)] = (
                    gt_boxes[gm],
                    [gt_masks[i] for i in gm] if gt_masks is not None else None,
                    gt_crowd[gm],
                )

    # -- matching ----------------------------------------------------------

    def _evaluate_img(self, cat: int, img, area_rng, max_det: int):
        dt = self._dts.get((cat, img))
        gt = self._gts.get((cat, img))
        if dt is None and gt is None:
            return None
        if dt is None:
            dboxes = np.zeros((0, 4))
            dscores = np.zeros(0)
            dmasks = []
        else:
            dboxes, dscores, dmasks = dt
            order = np.argsort(-dscores, kind="mergesort")[:max_det]
            dboxes, dscores = dboxes[order], dscores[order]
            dmasks = [dmasks[i] for i in order] if dmasks is not None else []
        gboxes, gmasks, g_crowd = (
            gt if gt is not None else (np.zeros((0, 4)), [], np.zeros(0, bool))
        )

        if self.iou_type == "segm":
            from mx_rcnn_tpu.evalutil.masks import rle_area

            garea = np.asarray([rle_area(m) for m in (gmasks or [])], float)
            garea = garea.reshape(len(gboxes))
            darea = np.asarray([rle_area(m) for m in dmasks], float).reshape(
                len(dboxes)
            )
        else:
            garea = (gboxes[:, 2] - gboxes[:, 0]) * (gboxes[:, 3] - gboxes[:, 1])
            darea = (dboxes[:, 2] - dboxes[:, 0]) * (dboxes[:, 3] - dboxes[:, 1])
        # Crowd gts are ignored regardless of area; area filtering ignores
        # the rest outside the range (pycocotools _ignore).
        g_ignore = g_crowd | (garea < area_rng[0]) | (garea > area_rng[1])
        # Sort gt: non-ignored first (COCO matches real gt preferentially).
        g_order = np.argsort(g_ignore, kind="mergesort")
        gboxes, g_ignore, g_crowd = (
            gboxes[g_order], g_ignore[g_order], g_crowd[g_order]
        )
        garea = garea[g_order]

        if self.iou_type == "segm":
            from mx_rcnn_tpu.evalutil.masks import rle_iou

            gmasks = [gmasks[i] for i in g_order] if gmasks else []
            ious = rle_iou(dmasks, gmasks)
        else:
            ious = _xyxy_iou(dboxes, gboxes)
        if g_crowd.any() and len(dboxes):
            # Crowd overlap is intersection-over-det-area (pycocotools
            # iou(..., iscrowd=1)): recover the intersection from the IoU
            # and the two areas, renormalize by det area alone.
            inter = ious * (darea[:, None] + garea[None, :]) / (1.0 + ious)
            ioa = inter / np.maximum(darea[:, None], 1e-10)
            ious = np.where(g_crowd[None, :], ioa, ious)
        T, D, G = len(IOU_THRS), len(dboxes), len(gboxes)
        dt_match = np.zeros((T, D), dtype=np.int64)  # 1 + matched gt idx, 0 = none
        gt_match = np.zeros((T, G), dtype=np.int64)
        for ti, t in enumerate(IOU_THRS):
            for di in range(D):
                best, best_j = min(t, 1 - 1e-10), -1
                for gi in range(G):
                    # A matched real gt is consumed; a crowd gt can absorb
                    # any number of detections (pycocotools iscrowd rule).
                    if gt_match[ti, gi] and not g_crowd[gi]:
                        continue
                    # Past non-ignored best, stop upgrading to ignored gt.
                    if best_j > -1 and not g_ignore[best_j] and g_ignore[gi]:
                        break
                    if ious[di, gi] < best:
                        continue
                    best, best_j = ious[di, gi], gi
                if best_j > -1:
                    dt_match[ti, di] = best_j + 1
                    gt_match[ti, best_j] = di + 1
        # Unmatched dets outside the area range are ignored, matched-to-
        # ignored-gt dets are ignored.
        dt_ignore = np.zeros((T, D), bool)
        for ti in range(T):
            for di in range(D):
                j = dt_match[ti, di] - 1
                if j >= 0:
                    dt_ignore[ti, di] = g_ignore[j]
                else:
                    dt_ignore[ti, di] = (darea[di] < area_rng[0]) | (
                        darea[di] > area_rng[1]
                    )
        return {
            "scores": dscores,
            "dt_match": dt_match,
            "dt_ignore": dt_ignore,
            "num_gt": int((~g_ignore).sum()),
        }

    def _accumulate(self, cat: int, area: str, max_det: int):
        """→ (precision (T, R), recall (T,)) or None if no gt anywhere."""
        per_img = [
            r
            for img in self._images
            if (r := self._evaluate_img(cat, img, AREA_RANGES[area], max_det))
        ]
        if not per_img:
            return None
        npos = sum(r["num_gt"] for r in per_img)
        if npos == 0:
            return None
        scores = np.concatenate([r["scores"] for r in per_img])
        order = np.argsort(-scores, kind="mergesort")
        T = len(IOU_THRS)
        matches = np.concatenate([r["dt_match"] for r in per_img], axis=1)[:, order]
        ignores = np.concatenate([r["dt_ignore"] for r in per_img], axis=1)[:, order]

        precision = np.zeros((T, len(RECALL_THRS)))
        recall = np.zeros(T)
        for ti in range(T):
            keep = ~ignores[ti]
            tps = np.cumsum((matches[ti] > 0) & keep)
            fps = np.cumsum((matches[ti] == 0) & keep)
            rc = tps / npos
            pr = tps / np.maximum(tps + fps, 1e-10)
            if len(rc):
                recall[ti] = rc[-1]
            # Monotone non-increasing precision envelope.
            for i in range(len(pr) - 1, 0, -1):
                pr[i - 1] = max(pr[i - 1], pr[i])
            idx = np.searchsorted(rc, RECALL_THRS, side="left")
            valid = idx < len(pr)
            precision[ti, valid] = pr[idx[valid]]
        return precision, recall

    # -- summary -----------------------------------------------------------

    def summarize(self) -> dict[str, float]:
        cats = range(1, self.num_classes)
        acc = {
            (c, a, m): self._accumulate(c, a, m)
            for c in cats
            for a in AREA_RANGES
            for m in MAX_DETS
            if a == "all" or m == 100  # COCO only varies one of the two
        }

        def mean_ap(area: str, max_det: int, iou_idx=None) -> float:
            vals = []
            for c in cats:
                r = acc.get((c, area, max_det))
                if r is None:
                    continue
                p = r[0] if iou_idx is None else r[0][iou_idx : iou_idx + 1]
                vals.append(np.mean(p))
            return float(np.mean(vals)) if vals else -1.0

        def mean_ar(area: str, max_det: int) -> float:
            vals = [
                np.mean(r[1])
                for c in cats
                if (r := acc.get((c, area, max_det))) is not None
            ]
            return float(np.mean(vals)) if vals else -1.0

        out = {
            "AP": mean_ap("all", 100),
            "AP50": mean_ap("all", 100, iou_idx=0),
            "AP75": mean_ap("all", 100, iou_idx=5),
            "APs": mean_ap("small", 100),
            "APm": mean_ap("medium", 100),
            "APl": mean_ap("large", 100),
            "AR1": mean_ar("all", 1),
            "AR10": mean_ar("all", 10),
            "AR100": mean_ar("all", 100),
            "ARs": mean_ar("small", 100),
            "ARm": mean_ar("medium", 100),
            "ARl": mean_ar("large", 100),
        }
        for c in cats:
            r = acc.get((c, "all", 100))
            if r is not None:
                out[f"AP/class_{c}"] = float(np.mean(r[0]))
        return out
