"""Detection result caching (dump / load / re-eval).

Replaces the reference's ``all_boxes`` pickle written by ``pred_eval`` and
re-scored by ``rcnn/tools/reeval.py``.  Format: one JSON-serializable dict
per image — stable across refactors, unlike the reference's positional
per-class nested lists.
"""

from __future__ import annotations

import json

import numpy as np


def save_detections(path: str, per_image: dict[str, dict]) -> None:
    """per_image: image_id → {"boxes": (n,4), "scores": (n,), "classes": (n,)}
    plus optional "masks": list of RLE dicts (instance segmentation)."""
    ser = {}
    for k, v in per_image.items():
        entry = {
            "boxes": np.asarray(v["boxes"], float).reshape(-1, 4).tolist(),
            "scores": np.asarray(v["scores"], float).reshape(-1).tolist(),
            "classes": np.asarray(v["classes"], int).reshape(-1).tolist(),
        }
        if "masks" in v:
            entry["masks"] = [
                {"size": list(m["size"]), "counts": np.asarray(m["counts"]).tolist()}
                for m in v["masks"]
            ]
        ser[k] = entry
    with open(path, "w") as f:
        json.dump(ser, f)


def detections_from_json(raw: dict) -> dict[str, dict]:
    """Raw parsed-JSON dump (``save_detections`` format) → numpy arrays.

    Factored out of :func:`load_detections` so sharded evaluation can merge
    shard dumps at the raw-JSON level (byte-stable — the float32 round-trip
    here is lossy) and still hand arrays to the evaluator."""
    out = {}
    for k, v in raw.items():
        entry = {
            "boxes": np.asarray(v["boxes"], np.float32).reshape(-1, 4),
            "scores": np.asarray(v["scores"], np.float32).reshape(-1),
            "classes": np.asarray(v["classes"], np.int32).reshape(-1),
        }
        if "masks" in v:
            entry["masks"] = [
                {"size": tuple(m["size"]), "counts": np.asarray(m["counts"], np.uint32)}
                for m in v["masks"]
            ]
        out[k] = entry
    return out


def load_detections(path: str) -> dict[str, dict]:
    with open(path) as f:
        raw = json.load(f)
    return detections_from_json(raw)
