"""Host-side instance-mask utilities: paste-back, RLE codec, mask IoU.

The functionality of the reference's vendored COCO mask C library
(``rcnn/pycocotools/maskApi.c``: rleEncode/rleDecode/rleArea/rleIou —
SURVEY.md §3.5) reimplemented from the RLE definition.  The numpy versions
here are the reference implementation; the C++ extension
(:mod:`mx_rcnn_tpu.native`) accelerates the same contract when built.

RLE format: column-major (Fortran order, matching COCO) run lengths of
alternating 0/1 runs, starting with 0: {"size": (h, w), "counts": uint32[]}.
"""

from __future__ import annotations

import numpy as np

try:
    import cv2
except Exception:  # pragma: no cover
    cv2 = None


def paste_mask(
    mask: np.ndarray, box: np.ndarray, height: int, width: int,
    threshold: float = 0.5,
) -> np.ndarray:
    """(M, M) probability mask + xyxy box → (height, width) bool canvas.

    The inverse of the mask head's box-relative crop (the reference-era
    equivalent lives in Mask R-CNN's ``paste_mask_in_image``): resize the
    M×M grid to the box extent, threshold, paste clipped to the canvas.
    """
    x1, y1, x2, y2 = box
    x1i = int(np.floor(x1))
    y1i = int(np.floor(y1))
    x2i = int(np.ceil(x2)) + 1
    y2i = int(np.ceil(y2)) + 1
    bw = max(x2i - x1i, 1)
    bh = max(y2i - y1i, 1)
    if cv2 is not None:
        up = cv2.resize(mask.astype(np.float32), (bw, bh))
    else:  # pragma: no cover
        yi = np.clip(
            np.floor(np.arange(bh) / bh * mask.shape[0]).astype(int), 0,
            mask.shape[0] - 1,
        )
        xi = np.clip(
            np.floor(np.arange(bw) / bw * mask.shape[1]).astype(int), 0,
            mask.shape[1] - 1,
        )
        up = mask[yi][:, xi]
    out = np.zeros((height, width), bool)
    ys, xs = max(y1i, 0), max(x1i, 0)
    ye, xe = min(y2i, height), min(x2i, width)
    if ye > ys and xe > xs:
        out[ys:ye, xs:xe] = up[ys - y1i : ye - y1i, xs - x1i : xe - x1i] >= threshold
    return out


def rle_encode(binary: np.ndarray) -> dict:
    """(h, w) bool → COCO-style column-major RLE (C++ when built)."""
    from mx_rcnn_tpu.native import rle_encode_native

    native = rle_encode_native(binary)
    if native is not None:
        return native
    h, w = binary.shape
    flat = np.asarray(binary, np.uint8).T.reshape(-1)  # Fortran order
    # Run-length: indices where the value changes.
    change = np.flatnonzero(flat[1:] != flat[:-1]) + 1
    bounds = np.concatenate([[0], change, [flat.size]])
    counts = np.diff(bounds).astype(np.uint32)
    if flat.size and flat[0] == 1:  # first run must encode zeros
        counts = np.concatenate([[np.uint32(0)], counts])
    return {"size": (h, w), "counts": counts}


def rle_decode(rle: dict) -> np.ndarray:
    h, w = rle["size"]
    counts = np.asarray(rle["counts"], np.int64)
    vals = np.zeros(len(counts), np.uint8)
    vals[1::2] = 1
    flat = np.repeat(vals, counts)
    if flat.size < h * w:
        flat = np.concatenate([flat, np.zeros(h * w - flat.size, np.uint8)])
    return flat.reshape(w, h).T.astype(bool)


def rle_area(rle: dict) -> int:
    return int(np.asarray(rle["counts"][1::2], np.int64).sum())


def _intersection(a: dict, b: dict) -> int:
    """Run-intersection of two RLEs without decoding (maskApi rleIou core)."""
    ca = np.asarray(a["counts"], np.int64)
    cb = np.asarray(b["counts"], np.int64)
    ea = np.cumsum(ca)  # run end positions
    eb = np.cumsum(cb)
    # Merge run boundaries; count overlap where both runs are 1-runs.
    inter = 0
    ia = ib = 0
    pos = 0
    na, nb = len(ea), len(eb)
    while ia < na and ib < nb:
        end = min(ea[ia], eb[ib])
        if ia % 2 == 1 and ib % 2 == 1:
            inter += end - pos
        pos = end
        if ea[ia] == end:
            ia += 1
        if eb[ib] == end:
            ib += 1
    return int(inter)


def rle_iou(dts: list[dict], gts: list[dict]) -> np.ndarray:
    """(n dts) x (m gts) mask IoU matrix (C++ when built)."""
    from mx_rcnn_tpu.native import rle_iou_native

    native = rle_iou_native(dts, gts)
    if native is not None:
        return native
    n, m = len(dts), len(gts)
    out = np.zeros((n, m))
    d_areas = [rle_area(d) for d in dts]
    g_areas = [rle_area(g) for g in gts]
    for i in range(n):
        for j in range(m):
            inter = _intersection(dts[i], gts[j])
            union = d_areas[i] + g_areas[j] - inter
            out[i, j] = inter / union if union > 0 else 0.0
    return out


def rasterize_polygons(polys, height: int, width: int) -> np.ndarray:
    """COCO polygon list (image coords) → (h, w) bool mask."""
    out = np.zeros((height, width), np.uint8)
    if cv2 is None or polys is None:  # pragma: no cover
        return out.astype(bool)
    pts = [
        np.asarray(p, np.float32).reshape(-1, 2).round().astype(np.int32)
        for p in polys
    ]
    cv2.fillPoly(out, pts, 1)
    return out.astype(bool)


def gt_record_rles(rec) -> list:
    """Per-instance RLEs for a RoiRecord's gt masks (polygon / RLE dict /
    missing → full-box rectangle fallback)."""
    out = []
    n = len(rec.boxes)
    for i in range(n):
        seg = rec.masks[i] if rec.masks is not None and i < len(rec.masks) else None
        if isinstance(seg, list):
            out.append(rle_encode(rasterize_polygons(seg, rec.height, rec.width)))
        elif isinstance(seg, dict):
            counts = seg["counts"]
            if isinstance(counts, list):
                out.append(
                    {"size": tuple(seg["size"]), "counts": np.asarray(counts, np.uint32)}
                )
            else:
                out.append(rle_encode(rle_decode(seg)))
        else:
            canvas = np.zeros((rec.height, rec.width), bool)
            x1, y1, x2, y2 = np.asarray(rec.boxes[i], int)
            canvas[max(y1, 0) : y2 + 1, max(x1, 0) : x2 + 1] = True
            out.append(rle_encode(canvas))
    return out
