"""Per-image detection un-letterboxing shared by eval and demo.

One implementation of the "device detections → original image frame"
contract (the reference's ``im_detect`` tail: ``/ im_scale`` + clip): the
valid-mask filter, box unscaling, clipping to the original extents, and
instance-mask paste-back.  Masks are pasted from the UNCLIPPED boxes —
the M×M mask grid spans the full box, so pasting into a border-clipped
extent would squash it; ``paste_mask`` crops at the canvas edge instead.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def unletterbox_detections(
    boxes: np.ndarray,      # (D, 4) canvas coords
    scores: np.ndarray,     # (D,)
    classes: np.ndarray,    # (D,)
    valid: np.ndarray,      # (D,) bool
    scale: float,
    height: int,
    width: int,
    masks: Optional[np.ndarray] = None,   # (D, M, M) probabilities
    mask_threshold: float = 0.0,
    encode_rle: bool = False,
) -> dict:
    """→ {"boxes", "scores", "classes"[, "masks"]} in original image coords.

    Output boxes are clipped to the image; masks (when present) are pasted
    at full unclipped extent, one entry per kept detection — binary (h, w)
    arrays, or RLE dicts with ``encode_rle`` (None for detections under
    ``mask_threshold`` unless encoding for evaluation, which keeps every
    entry so indexes stay aligned).
    """
    valid = np.asarray(valid)
    raw = np.asarray(boxes)[valid] / scale
    clipped = raw.copy()
    clipped[:, [0, 2]] = clipped[:, [0, 2]].clip(0, width - 1)
    clipped[:, [1, 3]] = clipped[:, [1, 3]].clip(0, height - 1)
    out = {
        "boxes": clipped,
        "scores": np.asarray(scores)[valid],
        "classes": np.asarray(classes)[valid],
    }
    if masks is not None:
        from mx_rcnn_tpu.evalutil.masks import paste_mask, rle_encode

        pasted = []
        for m, b, s in zip(np.asarray(masks)[valid], raw, out["scores"]):
            if not encode_rle and s < mask_threshold:
                pasted.append(None)
                continue
            full = paste_mask(m, b, height, width)
            pasted.append(rle_encode(full) if encode_rle else full)
        out["masks"] = pasted
    return out
