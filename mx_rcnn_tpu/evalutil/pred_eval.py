"""The evaluation loop: model → detections → dataset metric.

Replaces ``rcnn/core/tester.py::pred_eval`` (Predictor loop, per-class NMS,
all_boxes accumulation, ``imdb.evaluate_detections``).  NMS and score
thresholding already happened in-graph (``forward_inference``); here we only
un-letterbox boxes back to original image coordinates (the reference's
``/ im_scale``) and feed the evaluator.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Callable, Optional

import jax
import numpy as np

from mx_rcnn_tpu.data.loader import DetectionLoader
from mx_rcnn_tpu.parallel.distributed import is_primary
from mx_rcnn_tpu.evalutil.coco_eval import CocoEvaluator
from mx_rcnn_tpu.evalutil.detections import detections_from_json, save_detections
from mx_rcnn_tpu.evalutil.voc_eval import voc_mean_ap

log = logging.getLogger("mx_rcnn_tpu")


def device_eval_batches(loader: DetectionLoader, mesh=None):
    """Yield (device-ready batch, records) from an eval loader.

    Multi-process: the loader yields each host's slice of a global batch;
    ``shard_batch`` assembles the global array over ``mesh`` (single
    process feeds numpy straight to the jitted step's in_shardings).
    Shared by the detection eval loop and the proposal dump."""
    multiproc = jax.process_count() > 1
    if multiproc and mesh is None:
        raise ValueError("multi-process eval needs the mesh for shard_batch")
    for batch, recs in loader:
        batch = jax.tree_util.tree_map(np.asarray, batch)
        if multiproc:
            from mx_rcnn_tpu.parallel.mesh import shard_batch

            batch = shard_batch(batch, mesh)
        yield batch, recs


def collect_detections(
    eval_step: Callable,
    variables,
    loader: DetectionLoader,
    progress: Optional[Callable[[int], None]] = None,
    mesh=None,
) -> dict[str, dict]:
    """Run inference over the loader; → image_id → original-coord results."""
    from mx_rcnn_tpu.evalutil.postprocess import unletterbox_detections

    out: dict[str, dict] = {}
    done = 0
    for batch, recs in device_eval_batches(loader, mesh):
        dets = jax.device_get(eval_step(variables, batch))
        for i, rec in enumerate(recs):
            out[rec.image_id] = unletterbox_detections(
                dets.boxes[i], dets.scores[i], dets.classes[i], dets.valid[i],
                loader.record_scale(rec), rec.height, rec.width,
                masks=dets.masks[i] if dets.masks is not None else None,
                encode_rle=True,
            )
            done += 1
            if progress:
                progress(done)
    return out


MANIFEST_NAME = "manifest.json"


def shard_path(shard_dir: str, idx: int) -> str:
    return os.path.join(shard_dir, f"shard-{idx:05d}.json")


def eval_schedule_fingerprint(loader: DetectionLoader, shard_size: int) -> str:
    """Hash of everything that determines which images land in which shard.

    A resumed run may only reuse shard files written under the SAME batch
    schedule — resuming a 2-image-per-batch dump into a 4-image-per-batch
    run would silently evaluate some images twice and others never."""
    h = hashlib.sha1()
    h.update(f"bs={loader.batch_size};shard={shard_size}".encode())
    for _, recs in loader.eval_specs():
        for r in recs:
            h.update(str(r.image_id).encode())
            h.update(b"\x00")
        h.update(b"\x01")
    return h.hexdigest()


def _write_json_atomic(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def collect_detections_sharded(
    eval_step: Callable,
    variables,
    loader: DetectionLoader,
    shard_dir: str,
    shard_size: int = 8,
    resume: bool = False,
    max_retries: int = 1,
    guard=None,
    progress: Optional[Callable[[int], None]] = None,
) -> list[str]:
    """Preemption-safe :func:`collect_detections`: the eval schedule is cut
    into shards of ``shard_size`` batches; each finished shard is written
    (atomically — tmp + ``os.replace``; presence means complete) under
    ``shard_dir`` in ``save_detections`` format, so an interrupted run
    resumes by re-running only the missing shards.

    ``resume=False`` starts clean (stale shard files are deleted);
    ``resume=True`` validates the manifest fingerprint and skips shards
    whose file already exists.  A shard that raises is retried up to
    ``max_retries`` times before the error propagates.  ``guard`` (a
    :class:`~mx_rcnn_tpu.train.preemption.PreemptionGuard`) is polled at
    shard boundaries: the in-progress shard is always finished and flushed,
    then :class:`~mx_rcnn_tpu.train.preemption.Preempted` is raised for the
    CLI to map to the resumable exit code.

    Returns the ordered list of shard file paths.  Single-process only —
    the sharded dump protocol has no multi-host story (run_eval gates it).
    """
    from mx_rcnn_tpu.evalutil.postprocess import unletterbox_detections
    from mx_rcnn_tpu.train.preemption import Preempted

    if shard_size < 1:
        raise ValueError("shard_size must be >= 1")
    specs = loader.eval_specs()
    num_batches = len(specs)
    num_shards = max(1, -(-num_batches // shard_size))
    fingerprint = eval_schedule_fingerprint(loader, shard_size)
    os.makedirs(shard_dir, exist_ok=True)
    manifest_path = os.path.join(shard_dir, MANIFEST_NAME)
    manifest = {
        "fingerprint": fingerprint,
        "batch_size": loader.batch_size,
        "shard_size": shard_size,
        "num_batches": num_batches,
        "num_shards": num_shards,
    }
    if resume and os.path.exists(manifest_path):
        with open(manifest_path) as f:
            prev = json.load(f)
        if prev.get("fingerprint") != fingerprint:
            raise ValueError(
                f"--resume refused: {shard_dir} was written under a "
                "different eval schedule (dataset/batch-size/shard-size "
                "changed); start fresh without --resume"
            )
    else:
        # Fresh start: stale shard files from an older schedule must not
        # merge into (or be skipped by) this run.
        for name in os.listdir(shard_dir):
            if name.startswith("shard-") and name.endswith(".json"):
                os.remove(os.path.join(shard_dir, name))
        _write_json_atomic(manifest_path, manifest)

    done_images = 0
    paths = []
    for s in range(num_shards):
        path = shard_path(shard_dir, s)
        paths.append(path)
        start, stop = s * shard_size, min((s + 1) * shard_size, num_batches)
        n_images = sum(len(recs) for _, recs in specs[start:stop])
        if resume and os.path.exists(path):
            done_images += n_images
            if progress:
                progress(done_images)
            continue
        for attempt in range(max_retries + 1):
            try:
                shard_out: dict[str, dict] = {}
                for batch, recs in loader.eval_batch_range(start, stop):
                    batch = jax.tree_util.tree_map(np.asarray, batch)
                    dets = jax.device_get(eval_step(variables, batch))
                    for i, rec in enumerate(recs):
                        shard_out[rec.image_id] = unletterbox_detections(
                            dets.boxes[i], dets.scores[i], dets.classes[i],
                            dets.valid[i],
                            loader.record_scale(rec), rec.height, rec.width,
                            masks=dets.masks[i] if dets.masks is not None else None,
                            encode_rle=True,
                        )
                tmp = path + ".tmp"
                save_detections(tmp, shard_out)
                os.replace(tmp, path)
                break
            except Exception:
                if attempt >= max_retries:
                    raise
                log.warning(
                    "eval shard %d/%d failed (attempt %d/%d); retrying",
                    s, num_shards, attempt + 1, max_retries + 1,
                    exc_info=True,
                )
        done_images += n_images
        if progress:
            progress(done_images)
        if guard is not None and guard.triggered:
            # The shard that was in flight when the signal landed is on
            # disk; tell the supervisor to re-run with --resume.
            raise Preempted(s, shard_dir)
    return paths


def merge_detection_shards(
    shard_paths: list[str], out_path: Optional[str] = None
) -> dict:
    """Merge shard dumps into one detections dict at the RAW JSON level.

    Byte-stability is the point: ``save_detections`` writes float64 values
    whose JSON text is the shortest round-trip repr; going through
    ``load_detections`` (float32) and re-saving would perturb the text.
    Merging parsed-JSON dicts and dumping keeps the final file byte-for-
    byte identical between an uninterrupted run and any interrupted+resumed
    run over the same schedule.  Returns the merged raw dict."""
    merged: dict = {}
    for p in shard_paths:
        with open(p) as f:
            merged.update(json.load(f))
    if out_path:
        _write_json_atomic(out_path, merged)
    return merged


def evaluate_detections(
    per_image: dict[str, dict],
    roidb,
    num_classes: int,
    style: str = "coco",
    class_names: Optional[tuple] = None,
    use_07_metric: bool = False,
) -> dict[str, float]:
    """Score cached detections against roidb gt (reeval parity: callable on
    loaded detections with no model)."""
    if style == "coco":
        ev = CocoEvaluator(num_classes)
        have_masks = any("masks" in d for d in per_image.values())
        seg_ev = CocoEvaluator(num_classes, iou_type="segm") if have_masks else None
        if seg_ev is not None:
            from mx_rcnn_tpu.evalutil.masks import gt_record_rles
        for rec in roidb:
            d = per_image.get(
                rec.image_id,
                {"boxes": np.zeros((0, 4)), "scores": np.zeros(0), "classes": np.zeros(0)},
            )
            ev.add_image(
                rec.image_id, d["boxes"], d["scores"], d["classes"],
                rec.boxes, rec.gt_classes,
                gt_crowd=rec.ignore_flags,
            )
            if seg_ev is not None:
                # An image entry without masks (e.g. merged dumps) contributes
                # its gt as misses rather than crashing on mask lookup.
                has_m = "masks" in d
                z = np.zeros(0)
                seg_ev.add_image(
                    rec.image_id,
                    d["boxes"] if has_m else np.zeros((0, 4)),
                    d["scores"] if has_m else z,
                    d["classes"] if has_m else z,
                    rec.boxes, rec.gt_classes,
                    det_masks=d.get("masks", []),
                    gt_masks=gt_record_rles(rec),
                    gt_crowd=rec.ignore_flags,
                )
        metrics = ev.summarize()
        if seg_ev is not None:
            metrics.update(
                {f"segm/{k}": v for k, v in seg_ev.summarize().items()}
            )
        return metrics
    if style == "voc":
        all_dets: dict[int, dict] = {c: {} for c in range(1, num_classes)}
        all_gt: dict[int, dict] = {c: {} for c in range(1, num_classes)}
        for rec in roidb:
            d = per_image.get(rec.image_id)
            for c in range(1, num_classes):
                if d is not None:
                    m = d["classes"] == c
                    if m.any():
                        all_dets[c][rec.image_id] = np.concatenate(
                            [d["boxes"][m], d["scores"][m, None]], axis=1
                        )
                gm = rec.gt_classes == c
                if gm.any():
                    # Difficult objects stay in the gt with their flag so
                    # voc_eval's ignore-matching fires (reference voc_eval
                    # semantics: matched-to-difficult is neither tp nor fp).
                    all_gt[c][rec.image_id] = {
                        "boxes": rec.boxes[gm],
                        "difficult": rec.ignore_flags[gm],
                    }
        names = class_names or tuple(str(i) for i in range(num_classes))
        return voc_mean_ap(all_dets, all_gt, names, use_07_metric=use_07_metric)
    raise ValueError(f"unknown eval style {style!r}")


def visualize_detections(
    per_image: dict[str, dict],
    roidb,
    out_dir: str,
    class_names: Optional[tuple] = None,
    count: int = 10,
    threshold: float = 0.5,
) -> int:
    """Draw the first ``count`` evaluated images with their detections
    (reference ``pred_eval(vis=True)`` / ``vis_all_detection`` parity,
    written to files instead of shown).  Returns images written."""
    import os
    import re

    from mx_rcnn_tpu.data import load_image
    from mx_rcnn_tpu.evalutil.masks import rle_decode
    from mx_rcnn_tpu.evalutil.vis import draw_detections

    os.makedirs(out_dir, exist_ok=True)
    written = 0
    for rec in roidb:
        if written >= count:
            break
        d = per_image.get(rec.image_id)
        if d is None:
            continue
        image = load_image(rec)
        masks = None
        if "masks" in d:
            masks = [
                rle_decode(m).astype(bool) if isinstance(m, dict) else m
                for m in d["masks"]
            ]
        name = re.sub(r"[^A-Za-z0-9_.-]", "_", str(rec.image_id))
        draw_detections(
            image, d["boxes"], d["scores"], d["classes"], class_names,
            os.path.join(out_dir, f"{name}.png"), threshold=threshold,
            masks=masks,
        )
        written += 1
    return written


def pred_eval(
    eval_step: Callable,
    variables,
    loader: DetectionLoader,
    roidb,
    num_classes: int,
    style: str = "coco",
    class_names: Optional[tuple] = None,
    use_07_metric: bool = False,
    dump_path: Optional[str] = None,
    vis_dir: Optional[str] = None,
    vis_count: int = 10,
    mesh=None,
    coco_results_path: Optional[str] = None,
    label_to_cat=None,
    voc_dets_dir: Optional[str] = None,
    voc_imageset: str = "test",
    shard_dir: Optional[str] = None,
    shard_size: int = 8,
    resume: bool = False,
    shard_retries: int = 1,
    guard=None,
) -> dict[str, float]:
    """``coco_results_path`` / ``voc_dets_dir`` additionally write the
    official interchange artifacts (COCO results json in ORIGINAL sparse
    category ids via ``label_to_cat``; VOC comp4 det files) — the
    reference's ``evaluate_detections`` side-effect outputs that external
    tools and the eval servers consume (SURVEY.md §3.6).

    ``shard_dir`` switches inference to the preemption-safe sharded path
    (:func:`collect_detections_sharded`): per-shard checkpoint files,
    ``resume`` skipping completed shards, ``guard`` polled at shard
    boundaries, and the final dump merged from the shard files at the raw
    JSON level so it is byte-identical across interruptions."""
    if shard_dir:
        if jax.process_count() > 1:
            raise ValueError(
                "sharded (resumable) evaluation is single-process only"
            )
        paths = collect_detections_sharded(
            eval_step, variables, loader, shard_dir,
            shard_size=shard_size, resume=resume,
            max_retries=shard_retries, guard=guard,
        )
        raw = merge_detection_shards(paths, out_path=dump_path)
        # Metrics come from the merged dump's parse, not live arrays:
        # interrupted-and-resumed and uninterrupted runs score the exact
        # same numbers because they score the exact same bytes.
        per_image = detections_from_json(raw)
    else:
        per_image = collect_detections(eval_step, variables, loader, mesh=mesh)
        # Multi-host: every host holds the full (gathered) detections and
        # computes identical metrics; artifacts are written once, by
        # process 0.
        if dump_path and is_primary():
            save_detections(dump_path, per_image)
    if (coco_results_path or voc_dets_dir) and is_primary():
        from mx_rcnn_tpu.evalutil.submission import write_submission_artifacts

        write_submission_artifacts(
            per_image,
            coco_results_path=coco_results_path,
            label_to_cat=label_to_cat,
            voc_dets_dir=voc_dets_dir,
            class_names=class_names or (),
            voc_imageset=voc_imageset,
        )
    if vis_dir and is_primary():
        n = visualize_detections(
            per_image, roidb, vis_dir, class_names, count=vis_count
        )
        import logging

        logging.getLogger("mx_rcnn_tpu").info(
            "wrote %d visualization(s) to %s", n, vis_dir
        )
    return evaluate_detections(
        per_image, roidb, num_classes, style, class_names, use_07_metric
    )
