"""The evaluation loop: model → detections → dataset metric.

Replaces ``rcnn/core/tester.py::pred_eval`` (Predictor loop, per-class NMS,
all_boxes accumulation, ``imdb.evaluate_detections``).  NMS and score
thresholding already happened in-graph (``forward_inference``); here we only
un-letterbox boxes back to original image coordinates (the reference's
``/ im_scale``) and feed the evaluator.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np

from mx_rcnn_tpu.data.loader import DetectionLoader
from mx_rcnn_tpu.evalutil.coco_eval import CocoEvaluator
from mx_rcnn_tpu.evalutil.detections import save_detections
from mx_rcnn_tpu.evalutil.voc_eval import voc_mean_ap


def device_eval_batches(loader: DetectionLoader, mesh=None):
    """Yield (device-ready batch, records) from an eval loader.

    Multi-process: the loader yields each host's slice of a global batch;
    ``shard_batch`` assembles the global array over ``mesh`` (single
    process feeds numpy straight to the jitted step's in_shardings).
    Shared by the detection eval loop and the proposal dump."""
    multiproc = jax.process_count() > 1
    if multiproc and mesh is None:
        raise ValueError("multi-process eval needs the mesh for shard_batch")
    for batch, recs in loader:
        batch = jax.tree_util.tree_map(np.asarray, batch)
        if multiproc:
            from mx_rcnn_tpu.parallel.mesh import shard_batch

            batch = shard_batch(batch, mesh)
        yield batch, recs


def collect_detections(
    eval_step: Callable,
    variables,
    loader: DetectionLoader,
    progress: Optional[Callable[[int], None]] = None,
    mesh=None,
) -> dict[str, dict]:
    """Run inference over the loader; → image_id → original-coord results."""
    from mx_rcnn_tpu.evalutil.postprocess import unletterbox_detections

    out: dict[str, dict] = {}
    done = 0
    for batch, recs in device_eval_batches(loader, mesh):
        dets = jax.device_get(eval_step(variables, batch))
        for i, rec in enumerate(recs):
            out[rec.image_id] = unletterbox_detections(
                dets.boxes[i], dets.scores[i], dets.classes[i], dets.valid[i],
                loader.record_scale(rec), rec.height, rec.width,
                masks=dets.masks[i] if dets.masks is not None else None,
                encode_rle=True,
            )
            done += 1
            if progress:
                progress(done)
    return out


def evaluate_detections(
    per_image: dict[str, dict],
    roidb,
    num_classes: int,
    style: str = "coco",
    class_names: Optional[tuple] = None,
    use_07_metric: bool = False,
) -> dict[str, float]:
    """Score cached detections against roidb gt (reeval parity: callable on
    loaded detections with no model)."""
    if style == "coco":
        ev = CocoEvaluator(num_classes)
        have_masks = any("masks" in d for d in per_image.values())
        seg_ev = CocoEvaluator(num_classes, iou_type="segm") if have_masks else None
        if seg_ev is not None:
            from mx_rcnn_tpu.evalutil.masks import gt_record_rles
        for rec in roidb:
            d = per_image.get(
                rec.image_id,
                {"boxes": np.zeros((0, 4)), "scores": np.zeros(0), "classes": np.zeros(0)},
            )
            ev.add_image(
                rec.image_id, d["boxes"], d["scores"], d["classes"],
                rec.boxes, rec.gt_classes,
                gt_crowd=rec.ignore_flags,
            )
            if seg_ev is not None:
                # An image entry without masks (e.g. merged dumps) contributes
                # its gt as misses rather than crashing on mask lookup.
                has_m = "masks" in d
                z = np.zeros(0)
                seg_ev.add_image(
                    rec.image_id,
                    d["boxes"] if has_m else np.zeros((0, 4)),
                    d["scores"] if has_m else z,
                    d["classes"] if has_m else z,
                    rec.boxes, rec.gt_classes,
                    det_masks=d.get("masks", []),
                    gt_masks=gt_record_rles(rec),
                    gt_crowd=rec.ignore_flags,
                )
        metrics = ev.summarize()
        if seg_ev is not None:
            metrics.update(
                {f"segm/{k}": v for k, v in seg_ev.summarize().items()}
            )
        return metrics
    if style == "voc":
        all_dets: dict[int, dict] = {c: {} for c in range(1, num_classes)}
        all_gt: dict[int, dict] = {c: {} for c in range(1, num_classes)}
        for rec in roidb:
            d = per_image.get(rec.image_id)
            for c in range(1, num_classes):
                if d is not None:
                    m = d["classes"] == c
                    if m.any():
                        all_dets[c][rec.image_id] = np.concatenate(
                            [d["boxes"][m], d["scores"][m, None]], axis=1
                        )
                gm = rec.gt_classes == c
                if gm.any():
                    # Difficult objects stay in the gt with their flag so
                    # voc_eval's ignore-matching fires (reference voc_eval
                    # semantics: matched-to-difficult is neither tp nor fp).
                    all_gt[c][rec.image_id] = {
                        "boxes": rec.boxes[gm],
                        "difficult": rec.ignore_flags[gm],
                    }
        names = class_names or tuple(str(i) for i in range(num_classes))
        return voc_mean_ap(all_dets, all_gt, names, use_07_metric=use_07_metric)
    raise ValueError(f"unknown eval style {style!r}")


def visualize_detections(
    per_image: dict[str, dict],
    roidb,
    out_dir: str,
    class_names: Optional[tuple] = None,
    count: int = 10,
    threshold: float = 0.5,
) -> int:
    """Draw the first ``count`` evaluated images with their detections
    (reference ``pred_eval(vis=True)`` / ``vis_all_detection`` parity,
    written to files instead of shown).  Returns images written."""
    import os
    import re

    from mx_rcnn_tpu.data import load_image
    from mx_rcnn_tpu.evalutil.masks import rle_decode
    from mx_rcnn_tpu.evalutil.vis import draw_detections

    os.makedirs(out_dir, exist_ok=True)
    written = 0
    for rec in roidb:
        if written >= count:
            break
        d = per_image.get(rec.image_id)
        if d is None:
            continue
        image = load_image(rec)
        masks = None
        if "masks" in d:
            masks = [
                rle_decode(m).astype(bool) if isinstance(m, dict) else m
                for m in d["masks"]
            ]
        name = re.sub(r"[^A-Za-z0-9_.-]", "_", str(rec.image_id))
        draw_detections(
            image, d["boxes"], d["scores"], d["classes"], class_names,
            os.path.join(out_dir, f"{name}.png"), threshold=threshold,
            masks=masks,
        )
        written += 1
    return written


def pred_eval(
    eval_step: Callable,
    variables,
    loader: DetectionLoader,
    roidb,
    num_classes: int,
    style: str = "coco",
    class_names: Optional[tuple] = None,
    use_07_metric: bool = False,
    dump_path: Optional[str] = None,
    vis_dir: Optional[str] = None,
    vis_count: int = 10,
    mesh=None,
    coco_results_path: Optional[str] = None,
    label_to_cat=None,
    voc_dets_dir: Optional[str] = None,
    voc_imageset: str = "test",
) -> dict[str, float]:
    """``coco_results_path`` / ``voc_dets_dir`` additionally write the
    official interchange artifacts (COCO results json in ORIGINAL sparse
    category ids via ``label_to_cat``; VOC comp4 det files) — the
    reference's ``evaluate_detections`` side-effect outputs that external
    tools and the eval servers consume (SURVEY.md §3.6)."""
    per_image = collect_detections(eval_step, variables, loader, mesh=mesh)
    # Multi-host: every host holds the full (gathered) detections and
    # computes identical metrics; artifacts are written once, by process 0.
    if dump_path and jax.process_index() == 0:
        save_detections(dump_path, per_image)
    if (coco_results_path or voc_dets_dir) and jax.process_index() == 0:
        from mx_rcnn_tpu.evalutil.submission import write_submission_artifacts

        write_submission_artifacts(
            per_image,
            coco_results_path=coco_results_path,
            label_to_cat=label_to_cat,
            voc_dets_dir=voc_dets_dir,
            class_names=class_names or (),
            voc_imageset=voc_imageset,
        )
    if vis_dir and jax.process_index() == 0:
        n = visualize_detections(
            per_image, roidb, vis_dir, class_names, count=vis_count
        )
        import logging

        logging.getLogger("mx_rcnn_tpu").info(
            "wrote %d visualization(s) to %s", n, vis_dir
        )
    return evaluate_detections(
        per_image, roidb, num_classes, style, class_names, use_07_metric
    )
