"""Interchange / submission output formats (VERDICT r4 #3).

The reference's ``evaluate_detections`` writes artifacts OTHER tools
consume, not just an in-memory metric:

- a COCO results json in ORIGINAL (sparse, 91-space) category ids — the
  format the COCO evaluation server and stock pycocotools ``loadRes``
  score (reference: ``rcnn/dataset/coco.py :: evaluate_detections`` →
  ``_write_coco_results`` per SURVEY.md §3.6);
- PASCAL VOC "comp4" per-class detection files — the devkit's official
  submission format (reference: ``rcnn/dataset/pascal_voc.py`` det-file
  writer, SURVEY.md §3.6).

This module converts between those wire formats and the framework's
internal per-image dict (``evalutil.detections``).  Both writers are the
exact inverses of the dataset readers' coordinate conventions
(``data/datasets.py``): COCO xywh ↔ internal inclusive xyxy via
``w = x2 - x1 + 1``; VOC 1-based pixel coords ↔ internal 0-based via
``+1``.  Round-trip tests in tests/test_eval.py assert write→read is
metric-identical through the internal evaluator.
"""

from __future__ import annotations

import json
import os
from typing import Mapping, Optional, Sequence

import numpy as np


def _coco_image_id(image_id: str):
    """COCO image ids are ints; the internal roidb stringifies them.
    Convert back only when the round-trip is lossless — ``int("000005")``
    is 5, and a gt json keyed by the zero-padded string would then never
    match a single result entry.  Non-numeric and non-canonical ids pass
    through as strings — stock pycocotools indexes results by whatever id
    type the gt json used."""
    try:
        as_int = int(image_id)
    except ValueError:
        return image_id
    return as_int if str(as_int) == image_id else image_id


def write_coco_results(
    path: str,
    per_image: Mapping[str, dict],
    label_to_cat: Optional[Mapping[int, int]] = None,
) -> int:
    """Write a COCO results json (detection + optional segmentation).

    ``label_to_cat`` maps the contiguous internal labels (1..80) back to
    the ORIGINAL sparse category ids (``CocoDataset.label_to_cat``); None
    is the identity (synthetic / custom datasets whose ids are already
    dense).  Boxes convert from internal inclusive xyxy to COCO
    ``[x, y, w, h]``.  Masks (when present) ride as uncompressed
    column-major RLE — ``{"size": [h, w], "counts": [ints]}`` — which
    stock pycocotools ``loadRes`` ingests via ``frUncompressedRLE``.

    Returns the number of result entries written.
    """
    results = []
    for image_id, d in per_image.items():
        iid = _coco_image_id(image_id)
        boxes = np.asarray(d["boxes"], np.float64).reshape(-1, 4)
        scores = np.asarray(d["scores"], np.float64).reshape(-1)
        classes = np.asarray(d["classes"], np.int64).reshape(-1)
        masks = d.get("masks")
        for j in range(boxes.shape[0]):
            x1, y1, x2, y2 = boxes[j]
            cat = int(classes[j])
            if label_to_cat is not None:
                cat = int(label_to_cat[cat])
            entry = {
                "image_id": iid,
                "category_id": cat,
                "bbox": [
                    round(float(x1), 2),
                    round(float(y1), 2),
                    round(float(x2 - x1 + 1), 2),
                    round(float(y2 - y1 + 1), 2),
                ],
                "score": round(float(scores[j]), 5),
            }
            if masks is not None:
                m = masks[j]
                entry["segmentation"] = {
                    "size": [int(m["size"][0]), int(m["size"][1])],
                    "counts": np.asarray(m["counts"]).astype(int).tolist(),
                }
            results.append(entry)
    with open(path, "w") as f:
        json.dump(results, f)
    return len(results)


def read_coco_results(
    path: str,
    cat_to_label: Optional[Mapping[int, int]] = None,
) -> dict[str, dict]:
    """Inverse of :func:`write_coco_results`: results json → internal
    per-image dict (contiguous labels, inclusive xyxy), fit for
    ``evaluate_detections`` / ``save_detections``.  Used by the reeval
    path to score a submission file and by the round-trip tests."""
    with open(path) as f:
        results = json.load(f)
    grouped: dict[str, dict] = {}
    for r in results:
        g = grouped.setdefault(
            str(r["image_id"]),
            {"boxes": [], "scores": [], "classes": [], "masks": []},
        )
        x, y, w, h = r["bbox"]
        g["boxes"].append([x, y, x + w - 1, y + h - 1])
        g["scores"].append(r["score"])
        label = int(r["category_id"])
        if cat_to_label is not None:
            label = int(cat_to_label[label])
        g["classes"].append(label)
        if "segmentation" in r:
            seg = r["segmentation"]
            g["masks"].append(
                {
                    "size": tuple(seg["size"]),
                    "counts": np.asarray(seg["counts"], np.uint32),
                }
            )
    out = {}
    for k, g in grouped.items():
        if g["masks"] and len(g["masks"]) != len(g["boxes"]):
            # The internal "masks" list is positionally aligned with
            # boxes; a file where only SOME of an image's entries carry a
            # segmentation would silently pair masks with the wrong
            # detections downstream.  Reject rather than misalign.
            raise ValueError(
                f"image {k}: {len(g['masks'])} of {len(g['boxes'])} result "
                "entries carry a 'segmentation' — mixed box/segm entries "
                "within one image are not representable; score the file "
                "as box-only (strip segmentations) or complete them"
            )
        entry = {
            "boxes": np.asarray(g["boxes"], np.float32).reshape(-1, 4),
            "scores": np.asarray(g["scores"], np.float32),
            "classes": np.asarray(g["classes"], np.int32),
        }
        if g["masks"]:
            entry["masks"] = g["masks"]
        out[k] = entry
    return out


def write_voc_dets(
    out_dir: str,
    per_image: Mapping[str, dict],
    class_names: Sequence[str],
    imageset: str = "test",
    competition: str = "comp4",
) -> list[str]:
    """Write PASCAL VOC per-class detection files.

    One ``<competition>_det_<imageset>_<class>.txt`` per foreground
    class, each line ``image_id score x1 y1 x2 y2`` with 1-BASED pixel
    coordinates (the devkit convention; ``VocDataset._parse`` subtracts
    the same 1 on read).  Classes with zero detections still get an
    (empty) file — the devkit requires every class file to exist.

    Returns the written paths in class order.
    """
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for cls_idx, cls_name in enumerate(class_names):
        if cls_idx == 0:  # __background__
            continue
        path = os.path.join(
            out_dir, f"{competition}_det_{imageset}_{cls_name}.txt"
        )
        with open(path, "w") as f:
            for image_id, d in per_image.items():
                classes = np.asarray(d["classes"]).reshape(-1)
                sel = np.flatnonzero(classes == cls_idx)
                if sel.size == 0:
                    continue
                boxes = np.asarray(d["boxes"], np.float64).reshape(-1, 4)
                scores = np.asarray(d["scores"], np.float64).reshape(-1)
                for j in sel:
                    x1, y1, x2, y2 = boxes[j]
                    f.write(
                        f"{image_id} {scores[j]:.3f} {x1 + 1:.1f} "
                        f"{y1 + 1:.1f} {x2 + 1:.1f} {y2 + 1:.1f}\n"
                    )
        paths.append(path)
    return paths


def write_submission_artifacts(
    per_image: Mapping[str, dict],
    coco_results_path: Optional[str] = None,
    label_to_cat: Optional[Mapping[int, int]] = None,
    voc_dets_dir: Optional[str] = None,
    class_names: Sequence[str] = (),
    voc_imageset: str = "test",
) -> None:
    """The shared export block behind ``eval --dump-coco/--dump-voc`` and
    the reeval CLI's model-free re-export — one implementation so the two
    drivers can't drift on format or naming."""
    import logging

    log = logging.getLogger("mx_rcnn_tpu")
    if coco_results_path:
        n = write_coco_results(coco_results_path, per_image, label_to_cat)
        log.info("wrote %d COCO result entries to %s", n, coco_results_path)
    if voc_dets_dir:
        if len(class_names) <= 1:
            # write_voc_dets over an empty/background-only name tuple is a
            # silent no-op — the user asked for det files and must hear
            # why none appeared.
            raise ValueError(
                "--dump-voc needs foreground class names; the dataset "
                f"exposes {tuple(class_names)!r} — comp4 det files are "
                "per-class-NAME"
            )
        paths = write_voc_dets(
            voc_dets_dir, per_image, class_names, imageset=voc_imageset
        )
        log.info(
            "wrote %d comp4 det files to %s", len(paths), voc_dets_dir
        )


def read_voc_dets(
    out_dir: str,
    class_names: Sequence[str],
    imageset: str = "test",
    competition: str = "comp4",
) -> dict[str, dict]:
    """Inverse of :func:`write_voc_dets` (round-trip testing / scoring a
    foreign comp4 submission with the internal evaluator)."""
    grouped: dict[str, dict] = {}
    for cls_idx, cls_name in enumerate(class_names):
        if cls_idx == 0:
            continue
        path = os.path.join(
            out_dir, f"{competition}_det_{imageset}_{cls_name}.txt"
        )
        if not os.path.exists(path):
            continue
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                image_id, score = parts[0], float(parts[1])
                x1, y1, x2, y2 = (float(v) - 1 for v in parts[2:6])
                g = grouped.setdefault(
                    image_id, {"boxes": [], "scores": [], "classes": []}
                )
                g["boxes"].append([x1, y1, x2, y2])
                g["scores"].append(score)
                g["classes"].append(cls_idx)
    return {
        k: {
            "boxes": np.asarray(g["boxes"], np.float32).reshape(-1, 4),
            "scores": np.asarray(g["scores"], np.float32),
            "classes": np.asarray(g["classes"], np.int32),
        }
        for k, g in grouped.items()
    }
