"""Detection visualization (``vis_all_detection`` parity, headless).

Lives in evalutil so the eval loop can draw without importing the CLI
layer (cli -> evalutil is the only allowed direction).
"""

from __future__ import annotations

import numpy as np


def draw_detections(
    image: np.ndarray,
    boxes: np.ndarray,
    scores: np.ndarray,
    classes: np.ndarray,
    class_names,
    out_path: str,
    threshold: float = 0.5,
    masks=None,
) -> int:
    """Matplotlib box (+ instance mask) overlay — vis_all_detection parity,
    saved not shown."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(1, figsize=(12, 12 * image.shape[0] / max(image.shape[1], 1)))
    ax.imshow(image.astype(np.uint8))
    ax.axis("off")
    cmap = plt.get_cmap("hsv")
    shown = 0
    for i, (box, score, cls) in enumerate(zip(boxes, scores, classes)):
        if score < threshold:
            continue
        color = cmap((int(cls) * 37 % 256) / 256.0)
        if masks is not None and i < len(masks) and masks[i] is not None:
            overlay = np.zeros((*masks[i].shape, 4), np.float32)
            overlay[masks[i]] = (*color[:3], 0.4)
            ax.imshow(overlay)
        x1, y1, x2, y2 = box
        ax.add_patch(
            plt.Rectangle((x1, y1), x2 - x1, y2 - y1, fill=False,
                          edgecolor=color, linewidth=2)
        )
        name = class_names[int(cls)] if class_names else str(int(cls))
        ax.text(x1, max(y1 - 3, 0), f"{name} {score:.2f}", fontsize=9,
                color="white", bbox=dict(facecolor=color, alpha=0.7, pad=1))
        shown += 1
    fig.savefig(out_path, bbox_inches="tight", dpi=120)
    plt.close(fig)
    return shown
