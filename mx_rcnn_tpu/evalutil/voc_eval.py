"""PASCAL VOC detection AP.

Port of the metric in ``rcnn/dataset/pascal_voc_eval.py::voc_eval`` (itself
the standard Girshick eval): greedy score-ordered matching at IoU≥0.5,
difficult gts ignored, both the 11-point (``use_07_metric``) and the
every-point (area-under-PR) AP.  Input is in-memory detections instead of
the reference's comp4 det files — file round-trips add nothing here.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


def voc_ap(rec: np.ndarray, prec: np.ndarray, use_07_metric: bool = False) -> float:
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = np.max(prec[rec >= t]) if np.any(rec >= t) else 0.0
            ap += p / 11.0
        return float(ap)
    mrec = np.concatenate(([0.0], rec, [1.0]))
    mpre = np.concatenate(([0.0], prec, [0.0]))
    for i in range(mpre.size - 1, 0, -1):
        mpre[i - 1] = np.maximum(mpre[i - 1], mpre[i])
    i = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[i + 1] - mrec[i]) * mpre[i + 1]))


@dataclass
class _ClassGt:
    boxes: np.ndarray
    difficult: np.ndarray
    matched: np.ndarray = field(init=False)

    def __post_init__(self):
        self.matched = np.zeros(len(self.boxes), bool)


def _iou_one_to_many(box: np.ndarray, boxes: np.ndarray) -> np.ndarray:
    ix1 = np.maximum(box[0], boxes[:, 0])
    iy1 = np.maximum(box[1], boxes[:, 1])
    ix2 = np.minimum(box[2], boxes[:, 2])
    iy2 = np.minimum(box[3], boxes[:, 3])
    iw = np.maximum(ix2 - ix1 + 1.0, 0.0)
    ih = np.maximum(iy2 - iy1 + 1.0, 0.0)
    inter = iw * ih
    a = (box[2] - box[0] + 1.0) * (box[3] - box[1] + 1.0)
    b = (boxes[:, 2] - boxes[:, 0] + 1.0) * (boxes[:, 3] - boxes[:, 1] + 1.0)
    return inter / np.maximum(a + b - inter, 1e-10)


def voc_eval(
    detections: dict[str, np.ndarray],
    gt: dict[str, dict],
    iou_threshold: float = 0.5,
    use_07_metric: bool = False,
) -> tuple[float, np.ndarray, np.ndarray]:
    """AP for one class.

    detections: image_id → (n, 5) [x1 y1 x2 y2 score].
    gt: image_id → {"boxes": (m, 4), "difficult": (m,) bool}.
    Returns (ap, recall_curve, precision_curve).
    """
    gts = {
        k: _ClassGt(np.asarray(v["boxes"], float).reshape(-1, 4),
                    np.asarray(v.get("difficult", np.zeros(len(v["boxes"]), bool)), bool))
        for k, v in gt.items()
    }
    npos = sum(int((~g.difficult).sum()) for g in gts.values())

    rows = []
    for img_id, dets in detections.items():
        for d in np.asarray(dets, float).reshape(-1, 5):
            rows.append((float(d[4]), img_id, d[:4]))
    if not rows or npos == 0:
        return 0.0, np.zeros(0), np.zeros(0)
    rows.sort(key=lambda r: -r[0])

    tp = np.zeros(len(rows))
    fp = np.zeros(len(rows))
    for i, (_, img_id, box) in enumerate(rows):
        g = gts.get(img_id)
        if g is None or len(g.boxes) == 0:
            fp[i] = 1
            continue
        ious = _iou_one_to_many(box, g.boxes)
        j = int(np.argmax(ious))
        if ious[j] >= iou_threshold:
            if g.difficult[j]:
                continue  # ignored, neither tp nor fp
            if not g.matched[j]:
                tp[i] = 1
                g.matched[j] = True
            else:
                fp[i] = 1  # duplicate detection
        else:
            fp[i] = 1

    tp_cum = np.cumsum(tp)
    fp_cum = np.cumsum(fp)
    rec = tp_cum / npos
    prec = tp_cum / np.maximum(tp_cum + fp_cum, np.finfo(np.float64).eps)
    return voc_ap(rec, prec, use_07_metric), rec, prec


def voc_mean_ap(
    all_detections: dict[int, dict[str, np.ndarray]],
    all_gt: dict[int, dict[str, dict]],
    class_names: tuple[str, ...],
    iou_threshold: float = 0.5,
    use_07_metric: bool = False,
) -> dict[str, float]:
    """Per-class AP + mAP.  Keys of the outer dicts are class labels
    (1-based foreground)."""
    aps = {}
    for c, dets in all_detections.items():
        ap, _, _ = voc_eval(dets, all_gt.get(c, {}), iou_threshold, use_07_metric)
        aps[class_names[c]] = ap
    aps["mAP"] = float(np.mean([v for k, v in aps.items() if k != "mAP"])) if aps else 0.0
    return aps
