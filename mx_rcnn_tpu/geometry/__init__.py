from mx_rcnn_tpu.geometry.boxes import (
    area,
    clip_boxes,
    decode_boxes,
    encode_boxes,
    ioa_matrix,
    iou_matrix,
    snap,
    valid_box_mask,
)
from mx_rcnn_tpu.geometry.anchors import (
    generate_base_anchors,
    shifted_anchors,
    shifted_anchors_np,
)
from mx_rcnn_tpu.geometry.losses import (
    huber_loss,
    masked_softmax_cross_entropy,
    smooth_l1,
    weighted_smooth_l1,
)

__all__ = [
    "area",
    "clip_boxes",
    "decode_boxes",
    "encode_boxes",
    "ioa_matrix",
    "iou_matrix",
    "snap",
    "valid_box_mask",
    "generate_base_anchors",
    "shifted_anchors",
    "shifted_anchors_np",
    "huber_loss",
    "masked_softmax_cross_entropy",
    "smooth_l1",
    "weighted_smooth_l1",
]
