"""Anchor generation.

Replaces ``rcnn/processing/generate_anchor.py::generate_anchors`` (the k base
anchors) and the per-feature-map shift enumeration done inside the reference
Proposal custom op (``rcnn/symbol/proposal.py``) and ``rcnn/io/rpn.py::
assign_anchor``.  All shapes are static given (stride, H, W), so under jit
the whole anchor grid constant-folds into the compiled executable — the
O(H*W*k) host-side numpy enumeration the reference pays every iteration
disappears entirely.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def generate_base_anchors(
    base_size: int = 16,
    ratios=(0.5, 1.0, 2.0),
    scales=(8, 16, 32),
    legacy_plus_one: bool = False,
) -> np.ndarray:
    """The k = len(ratios)*len(scales) base anchors, centered on a base cell.

    Numerically matches the reference's ``generate_anchors`` (which produces
    e.g. the canonical [-84, -40, 99, 55] style anchors for base 16) when
    ``legacy_plus_one=True``; the modern convention centers at base_size/2.
    Returned as numpy: this is config-time, not trace-time, work.
    """
    ratios = np.asarray(ratios, dtype=np.float64)
    scales = np.asarray(scales, dtype=np.float64)
    if legacy_plus_one:
        w = h = float(base_size)
        cx = cy = 0.5 * (base_size - 1)
        size = w * h
        size_ratios = size / ratios
        ws = np.round(np.sqrt(size_ratios))
        hs = np.round(ws * ratios)
        ws = (ws[:, None] * scales[None, :]).reshape(-1)
        hs = (hs[:, None] * scales[None, :]).reshape(-1)
        return np.stack(
            [
                cx - 0.5 * (ws - 1),
                cy - 0.5 * (hs - 1),
                cx + 0.5 * (ws - 1),
                cy + 0.5 * (hs - 1),
            ],
            axis=1,
        ).astype(np.float32)
    # Modern: exact sqrt areas, no rounding, centered at base/2.
    cx = cy = 0.5 * base_size
    size = float(base_size * base_size)
    ws = np.sqrt(size / ratios)
    hs = ws * ratios
    ws = (ws[:, None] * scales[None, :]).reshape(-1)
    hs = (hs[:, None] * scales[None, :]).reshape(-1)
    return np.stack(
        [cx - 0.5 * ws, cy - 0.5 * hs, cx + 0.5 * ws, cy + 0.5 * hs], axis=1
    ).astype(np.float32)


def shifted_anchors(base_anchors, stride: int, height: int, width: int):
    """Tile base anchors over an H x W feature grid.

    Returns (H*W*k, 4) anchors in input-image coordinates, ordered so that
    the anchor axis unrolls as (row-major spatial, then k) — matching how a
    (H, W, k*4) conv output reshapes to (H*W*k, 4).

    Computed in host numpy and embedded as a literal constant: shapes are
    static, so there is nothing to trace — and keeping the iota/meshgrid
    subgraph out of the compiled program guarantees every compilation of a
    step (pure-DP, spatially partitioned, different layout forms) consumes
    bit-identical anchors instead of re-deriving them under whatever
    partitioning XLA picks for the constant-folded grid.
    """
    return jnp.asarray(shifted_anchors_np(base_anchors, stride, height, width))


def shifted_anchors_np(base_anchors, stride: int, height: int, width: int):
    """:func:`shifted_anchors` as pure host numpy (no device transfer).

    Callers that memoize the grid across traces (detection/graph.py::
    _cached_level_anchor) must cache the numpy form: a jnp array produced
    while tracing is a tracer, and returning it from a cache into a later
    trace is a leak."""
    base = np.asarray(base_anchors, dtype=np.float32)
    shift_x = np.arange(width, dtype=np.float32) * stride
    shift_y = np.arange(height, dtype=np.float32) * stride
    sx, sy = np.meshgrid(shift_x, shift_y)  # (H, W)
    shifts = np.stack([sx, sy, sx, sy], axis=-1)  # (H, W, 4)
    out = shifts[:, :, None, :] + base[None, None, :, :]  # (H, W, k, 4)
    return out.reshape(-1, 4)
