"""Pure-JAX box geometry.

TPU-native replacement for the reference's host-side geometry stack:
``rcnn/processing/bbox_transform.py`` (bbox_overlaps, nonlinear_transform,
nonlinear_pred, clip_boxes) and the Cython hot kernel
``rcnn/cython/bbox.pyx`` (O(N*K) IoU matrix).  Everything here is
vectorized, jit-safe, static-shape, and differentiable where meaningful.

Box convention: ``(x1, y1, x2, y2)`` corner format, matching the
reference.  Like the reference, widths/heights are computed with a
``+ 1`` offset OFF by default — the reference uses the legacy
``x2 - x1 + 1.0`` convention everywhere; we expose it via ``legacy_plus_one``
so parity tests can check both, but the framework default is the modern
convention (used by FPN-era recipes that the BASELINE north star targets).
"""

from __future__ import annotations

import jax.numpy as jnp

# Matches the reference's bbox clamp on dw/dh before exp() so decoded boxes
# cannot overflow float32 (np.log(1000.0 / 16.0) in modern detectors).
BBOX_XFORM_CLIP = 4.135166556742356

# Grid spacing 2**-16 ~ 1.5e-5: orders of magnitude above cross-compilation
# ulp noise, orders of magnitude below any IoU/score difference that could
# matter to matching or ranking.
SNAP_BITS = 16


def snap(x: jnp.ndarray, bits: int = SNAP_BITS) -> jnp.ndarray:
    """Round onto the exact ``2**-bits`` grid — bit-stable across programs.

    Differently-partitioned (or differently laid-out) compilations of the
    same graph make different fusion/FMA-contraction choices, leaving float
    intermediates a few ulps apart.  Continuous consumers don't care, but
    *discrete* ones — threshold compares, argmax ties, top-k ranking, NMS
    suppression — flip, so the same batch trains on a different anchor/roi
    sample purely because of how the program was sharded.  Snapping the
    values feeding those comparisons makes them bit-identical across
    compilations: the power-of-two scale, ``round``, and the scale back are
    each exact in float32, so the only residual risk is an input sitting
    within ulps of a grid midpoint.  Infinities pass through unchanged
    (``-inf`` score masks survive).
    """
    scale = 2.0 ** bits
    return jnp.round(x * scale) * (1.0 / scale)


def _wh(boxes: jnp.ndarray, legacy_plus_one: bool = False):
    off = 1.0 if legacy_plus_one else 0.0
    w = boxes[..., 2] - boxes[..., 0] + off
    h = boxes[..., 3] - boxes[..., 1] + off
    return w, h


def area(boxes: jnp.ndarray, legacy_plus_one: bool = False) -> jnp.ndarray:
    """Box areas. boxes: (..., 4)."""
    w, h = _wh(boxes, legacy_plus_one)
    return jnp.maximum(w, 0.0) * jnp.maximum(h, 0.0)


def iou_matrix(
    boxes: jnp.ndarray,
    query: jnp.ndarray,
    legacy_plus_one: bool = False,
) -> jnp.ndarray:
    """Pairwise IoU between two box sets.

    Replaces ``rcnn/cython/bbox.pyx::bbox_overlaps`` (and the pure-python
    fallback in ``rcnn/processing/bbox_transform.py``): the O(N*K) loop
    becomes one broadcasted computation that XLA tiles onto the VPU.

    Args:
      boxes: (N, 4).
      query: (K, 4).
    Returns:
      (N, K) IoU matrix.  Degenerate (zero-area) boxes produce 0 rows/cols.
    """
    off = 1.0 if legacy_plus_one else 0.0
    lt = jnp.maximum(boxes[:, None, :2], query[None, :, :2])  # (N, K, 2)
    rb = jnp.minimum(boxes[:, None, 2:], query[None, :, 2:])  # (N, K, 2)
    wh = jnp.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    a1 = area(boxes, legacy_plus_one)[:, None]
    a2 = area(query, legacy_plus_one)[None, :]
    union = a1 + a2 - inter
    return jnp.where(union > 0.0, inter / jnp.where(union > 0.0, union, 1.0), 0.0)


def ioa_matrix(
    boxes: jnp.ndarray,
    query: jnp.ndarray,
    legacy_plus_one: bool = False,
) -> jnp.ndarray:
    """Pairwise intersection-over-area of ``boxes`` (first argument).

    The crowd/ignore overlap measure: a small anchor fully inside a huge
    crowd region has tiny IoU but IoA 1.0.  Used to exclude anchors/rois
    overlapping ignore regions from negative sampling and, det-normalized,
    for COCO crowd-ignore matching (pycocotools ``iou(..., iscrowd=1)``).

    Args:
      boxes: (N, 4) — the area in the denominator.
      query: (K, 4).
    Returns:
      (N, K); zero-area ``boxes`` rows are 0.
    """
    off = 1.0 if legacy_plus_one else 0.0
    lt = jnp.maximum(boxes[:, None, :2], query[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], query[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    a = area(boxes, legacy_plus_one)[:, None]
    return jnp.where(a > 0.0, inter / jnp.where(a > 0.0, a, 1.0), 0.0)


def _center(boxes: jnp.ndarray, legacy_plus_one: bool = False):
    """(w, h, cx, cy) of boxes under the chosen width convention."""
    off = 1.0 if legacy_plus_one else 0.0
    w, h = _wh(boxes, legacy_plus_one)
    cx = boxes[..., 0] + 0.5 * (w - off)
    cy = boxes[..., 1] + 0.5 * (h - off)
    return w, h, cx, cy


def encode_boxes(
    boxes: jnp.ndarray,
    anchors: jnp.ndarray,
    weights: tuple[float, float, float, float] = (1.0, 1.0, 1.0, 1.0),
    legacy_plus_one: bool = False,
) -> jnp.ndarray:
    """Encode target ``boxes`` relative to ``anchors`` as (dx, dy, dw, dh).

    Replaces ``rcnn/processing/bbox_transform.py::nonlinear_transform``.
    ``weights`` play the role of the reference's ``BBOX_STDS`` division
    (targets are multiplied by the weights; the reference divides by stds —
    weights = 1/std).
    """
    aw, ah, ax, ay = _center(anchors, legacy_plus_one)
    gw, gh, gx, gy = _center(boxes, legacy_plus_one)

    aw = jnp.maximum(aw, 1e-6)
    ah = jnp.maximum(ah, 1e-6)
    wx, wy, ww, wh_ = weights
    dx = wx * (gx - ax) / aw
    dy = wy * (gy - ay) / ah
    dw = ww * jnp.log(jnp.maximum(gw, 1e-6) / aw)
    dh = wh_ * jnp.log(jnp.maximum(gh, 1e-6) / ah)
    return jnp.stack([dx, dy, dw, dh], axis=-1)


def decode_boxes(
    deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    weights: tuple[float, float, float, float] = (1.0, 1.0, 1.0, 1.0),
    legacy_plus_one: bool = False,
) -> jnp.ndarray:
    """Apply regression ``deltas`` to ``anchors`` -> boxes.

    Replaces ``rcnn/processing/bbox_transform.py::nonlinear_pred`` (used by
    the Proposal custom op forward and by test-time ``im_detect``).
    """
    aw, ah, ax, ay = _center(anchors, legacy_plus_one)

    wx, wy, ww, wh_ = weights
    dx = deltas[..., 0] / wx
    dy = deltas[..., 1] / wy
    dw = jnp.clip(deltas[..., 2] / ww, max=BBOX_XFORM_CLIP)
    dh = jnp.clip(deltas[..., 3] / wh_, max=BBOX_XFORM_CLIP)

    cx = dx * aw + ax
    cy = dy * ah + ay
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah

    off = 1.0 if legacy_plus_one else 0.0
    x1 = cx - 0.5 * (w - off)
    y1 = cy - 0.5 * (h - off)
    x2 = cx + 0.5 * (w - off)
    y2 = cy + 0.5 * (h - off)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def clip_boxes(
    boxes: jnp.ndarray, height, width, legacy_plus_one: bool = False
) -> jnp.ndarray:
    """Clip boxes to image bounds.

    Replaces ``rcnn/processing/bbox_transform.py::clip_boxes``.  ``height``
    and ``width`` may be traced scalars (per-image true sizes inside a padded
    batch).
    """
    off = 1.0 if legacy_plus_one else 0.0
    x1 = jnp.clip(boxes[..., 0], 0.0, width - off)
    y1 = jnp.clip(boxes[..., 1], 0.0, height - off)
    x2 = jnp.clip(boxes[..., 2], 0.0, width - off)
    y2 = jnp.clip(boxes[..., 3], 0.0, height - off)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def valid_box_mask(
    boxes: jnp.ndarray, min_size: float = 0.0, legacy_plus_one: bool = False
) -> jnp.ndarray:
    """Mask of boxes at least min_size wide and tall.

    Replaces the min-size filter inside the reference Proposal op
    (``rcnn/symbol/proposal.py``: ``_filter_boxes``).  Returns a boolean mask
    instead of compacting — static shapes; padded entries are masked, never
    removed.  ``>=`` matches the reference's ``ws >= min_size``; at
    ``min_size == 0`` degenerate zero-extent boxes are still rejected.
    """
    w, h = _wh(boxes, legacy_plus_one)
    if min_size <= 0.0:
        return (w > 0.0) & (h > 0.0)
    return (w >= min_size) & (h >= min_size)
