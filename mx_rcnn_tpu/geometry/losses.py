"""Detection losses.

TPU-native replacements for the loss operators the reference pulls from the
MXNet engine (SURVEY.md section 3.5 "engine-side native ops"):

- ``SoftmaxOutput(ignore_label=-1, use_ignore=True, normalization='valid')``
  -> :func:`masked_softmax_cross_entropy` — an explicit masked CE with
  valid-count normalization, instead of a fused op with baked-in gradient.
- ``mx.symbol.smooth_l1(scalar=sigma)`` with in-graph inside/outside weight
  tensors -> :func:`weighted_smooth_l1` / :func:`huber_loss`.

All functions are shape-polymorphic over leading axes and jit/grad-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_softmax_cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    valid_mask: jnp.ndarray,
    normalize_by_valid: bool = True,
) -> jnp.ndarray:
    """Softmax CE over the last axis, ignoring entries where ``valid_mask`` is 0.

    ``labels`` are int class ids; entries with ``valid_mask == 0`` contribute
    zero loss and zero gradient (the reference marks them with label -1 and
    ``use_ignore``).  Normalization is by the number of valid entries
    (``normalization='valid'``), never by the padded total.
    """
    valid = valid_mask.astype(logits.dtype)
    safe_labels = jnp.clip(labels, 0, logits.shape[-1] - 1)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    ce = ce * valid
    if normalize_by_valid:
        return jnp.sum(ce) / jnp.maximum(jnp.sum(valid), 1.0)
    return jnp.sum(ce)


def huber_loss(pred: jnp.ndarray, target: jnp.ndarray, delta: float = 1.0) -> jnp.ndarray:
    """Standard elementwise Huber (optax/torch convention):
    ``0.5*d^2`` for |d| <= delta, else ``delta*(|d| - 0.5*delta)``.
    At delta=1 this equals ``smooth_l1(pred - target, sigma=1)``; for other
    deltas the two families differ in scale — use :func:`smooth_l1` for the
    reference's sigma parameterization."""
    diff = jnp.abs(pred - target)
    quad = 0.5 * diff * diff
    lin = delta * (diff - 0.5 * delta)
    return jnp.where(diff <= delta, quad, lin)


def smooth_l1(x: jnp.ndarray, sigma: float = 1.0) -> jnp.ndarray:
    """The reference's exact smooth_l1 parameterization (sigma form):
    0.5*(sigma*x)^2 if |x| < 1/sigma^2 else |x| - 0.5/sigma^2."""
    s2 = sigma * sigma
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2)


def weighted_smooth_l1(
    pred: jnp.ndarray,
    target: jnp.ndarray,
    inside_weight: jnp.ndarray,
    outside_weight: jnp.ndarray | None = None,
    sigma: float = 1.0,
    normalizer: jnp.ndarray | float = 1.0,
) -> jnp.ndarray:
    """Reference-style bbox regression loss.

    Mirrors the train-graph pattern in ``rcnn/symbol/symbol_vgg.py``:
    ``smooth_l1((pred - target) * inside_w) * outside_w``, summed and divided
    by a normalizer (RPN: batch anchors; RCNN: sampled rois).
    """
    diff = (pred - target) * inside_weight
    loss = smooth_l1(diff, sigma=sigma)
    if outside_weight is not None:
        loss = loss * outside_weight
    return jnp.sum(loss) / jnp.maximum(normalizer, 1.0)
