from mx_rcnn_tpu.models.resnet import ResNet
from mx_rcnn_tpu.models.vgg import VGG16
from mx_rcnn_tpu.models.fpn import FPN
from mx_rcnn_tpu.models.heads import RPNHead, BoxHead, MaskHead
from mx_rcnn_tpu.models.build import build_backbone

__all__ = ["ResNet", "VGG16", "FPN", "RPNHead", "BoxHead", "MaskHead", "build_backbone"]
