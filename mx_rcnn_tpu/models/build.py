"""Backbone factory: BackboneConfig -> flax module + metadata."""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from mx_rcnn_tpu.config import BackboneConfig
from mx_rcnn_tpu.models.resnet import ResNet, STAGE_BLOCKS
from mx_rcnn_tpu.models.vgg import VGG16

_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}


def build_backbone(
    cfg: BackboneConfig,
    out_levels: tuple[int, ...] = (2, 3, 4, 5),
    dtype: jnp.dtype | None = None,
) -> nn.Module:
    """``dtype`` overrides the config knob — the detector passes the
    resolved precision policy's compute dtype so a ``"float32"`` policy
    really forces the whole model to f32, backbone included."""
    dtype = _DTYPES[cfg.dtype] if dtype is None else dtype
    if cfg.name in STAGE_BLOCKS:
        return ResNet(blocks=STAGE_BLOCKS[cfg.name], norm=cfg.norm, dtype=dtype,
                      out_levels=out_levels, remat=cfg.remat,
                      stem_s2d=cfg.stem_s2d, stem_pool_fold=cfg.stem_pool_fold,
                      pad_small_ch=cfg.c2_pad, fold_bn=cfg.fold_frozen_bn,
                      name="backbone")
    if cfg.name == "vgg16":
        if cfg.stem_s2d:
            raise ValueError(
                "backbone.stem_s2d is ResNet-only (VGG's stem is a 3x3/1 "
                "conv stack with no strided RGB conv to rewrite)"
            )
        return VGG16(dtype=dtype, remat=cfg.remat, name="backbone")
    raise ValueError(f"unknown backbone {cfg.name!r}")
