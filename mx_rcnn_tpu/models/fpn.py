"""Feature Pyramid Network neck (Lin et al. 2017).

Not present in the reference (its R-CNN head reads a single C4 feature) but
required by the BASELINE north star (>=37 COCO mAP) and anticipated by
BASELINE config #4.  Standard top-down pathway: 1x1 lateral projections,
nearest-neighbor upsample + add, 3x3 output convs, plus P6 via stride-2
max-pool of P5 for RPN anchors at stride 64.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class FPN(nn.Module):
    channels: int = 256
    min_level: int = 2
    max_level: int = 6
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, feats: dict[int, jnp.ndarray]) -> dict[int, jnp.ndarray]:
        backbone_levels = sorted(k for k in feats if self.min_level <= k)
        laterals = {
            lvl: nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                         name=f"lateral{lvl}")(feats[lvl])
            for lvl in backbone_levels
        }
        top = max(backbone_levels)
        merged = {top: laterals[top]}
        with jax.named_scope("fpn_topdown"):
            for lvl in sorted(backbone_levels[:-1], reverse=True):
                up = merged[lvl + 1]
                b, h, w, c = up.shape
                up = jax.image.resize(
                    up, (b, h * 2, w * 2, c), method="nearest"
                )
                merged[lvl] = laterals[lvl] + up
        out = {
            lvl: nn.Conv(self.channels, (3, 3), padding=[(1, 1), (1, 1)],
                         dtype=self.dtype, name=f"output{lvl}")(merged[lvl])
            for lvl in backbone_levels
        }
        for lvl in range(top + 1, self.max_level + 1):
            # "Max-pool" with a 1x1 window IS stride-2 subsampling; the
            # strided slice says so directly instead of emitting a
            # reduce_window over P5 (identical output, trivially fusible).
            out[lvl] = out[lvl - 1][:, ::2, ::2, :]
        return out
