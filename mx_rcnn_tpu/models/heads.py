"""Detection heads: RPN, box (R-CNN), mask.

Rebuilds the head graphs of ``rcnn/symbol/symbol_vgg.py`` /
``symbol_resnet.py``:

- RPN head: 3x3 conv + ReLU, then 1x1 objectness (k logits, sigmoid — the
  reference uses a 2k-channel softmax; sigmoid is the numerically identical
  modern form) and 1x1 regression (4k).  One head shared across FPN levels
  (weight sharing per the FPN paper); the C4 recipe calls it on one level.
- Box head: flattened ROI features -> fc -> fc -> {cls_score (C),
  bbox_pred (4C or 4)} — the reference's fc6/fc7 (VGG) generalized.
- Mask head: 4x conv + deconv + 1x1 (Mask R-CNN), for BASELINE config #5.

Initialization follows the reference's train drivers: Normal(0.01) for cls
weights, Normal(0.001) for bbox_pred (it uses 0.01/0.001 via
``mx.init.Normal``), zeros for biases.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

_init01 = nn.initializers.normal(0.01)
_init001 = nn.initializers.normal(0.001)


class RPNHead(nn.Module):
    """Weight-shared RPN head with two execution forms.

    ``__call__`` applies the head to ONE level.  ``packed`` applies it to a
    whole FPN pyramid as a single computation: the per-level feature maps
    are packed into one canvas (stacked along H, right-padded to the widest
    level's W, one zero separator row between levels) and the 3x3 conv +
    objectness/delta 1x1s run ONCE over it instead of once per level — the
    five sequential small-spatial head dispatches (P2 alone measured
    6.6 ms/step) become three convs over one well-shaped tensor.  The
    packing is exact: a 3x3 SAME conv reads at most one row/col past a
    level's edge, and that row/col is zero both per-level (SAME padding)
    and in the canvas (separator row / W pad / canvas edge); outputs at
    separator/pad positions are sliced away.  Cost: the pad region adds
    ~40% head FLOPs at the recipe pyramid — bought back by issuing one
    large conv instead of five boundary-dominated small ones.

    Param tree ("conv"/"objectness"/"deltas") is identical for both forms;
    checkpoints are execution-form independent.
    """

    num_anchors: int
    channels: int = 256
    dtype: jnp.dtype = jnp.bfloat16
    # Dtype the head EMITS across the model/detection boundary.  f32 (the
    # historical "widen" contract) or the compute dtype (the "mixed"
    # policy — utils/precision.py); the detector wires it from the
    # resolved policy so heads never hard-code an upcast.
    out_dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.conv = nn.Conv(self.channels, (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype, kernel_init=_init01, name="conv")
        self.objectness = nn.Conv(self.num_anchors, (1, 1), dtype=self.dtype,
                                  kernel_init=_init01, name="objectness")
        self.deltas = nn.Conv(self.num_anchors * 4, (1, 1), dtype=self.dtype,
                              kernel_init=_init001, name="deltas")

    def _heads(self, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        y = nn.relu(self.conv(x))
        return self.objectness(y), self.deltas(y)

    def __call__(self, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        """x: (B, H, W, C) -> logits (B, H*W*A), deltas (B, H*W*A, 4).

        Flattening order is (H, W, A) row-major — anchor generation
        (geometry/anchors.py::shifted_anchors) must match.
        """
        logits, deltas = self._heads(x)
        b = x.shape[0]
        return (
            logits.reshape(b, -1).astype(self.out_dtype),
            deltas.reshape(b, -1, 4).astype(self.out_dtype),
        )

    def packed(
        self, feats: dict[int, jnp.ndarray]
    ) -> dict[int, tuple[jnp.ndarray, jnp.ndarray]]:
        """All levels through one packed head application; per-level
        outputs (same contract/flattening as looping ``__call__``)."""
        levels = sorted(feats)
        if len(levels) == 1:
            return {levels[0]: self(feats[levels[0]])}
        b, _, _, c = feats[levels[0]].shape
        wmax = max(feats[lvl].shape[2] for lvl in levels)
        zero_row = jnp.zeros((b, 1, wmax, c), feats[levels[0]].dtype)
        parts, offsets, row = [], {}, 0
        for i, lvl in enumerate(levels):
            f = feats[lvl]
            offsets[lvl] = row
            parts.append(
                jnp.pad(f, ((0, 0), (0, 0), (0, wmax - f.shape[2]), (0, 0)))
            )
            row += f.shape[1]
            if i + 1 < len(levels):
                parts.append(zero_row)
                row += 1
        logits, deltas = self._heads(jnp.concatenate(parts, axis=1))
        out = {}
        for lvl in levels:
            h, w = feats[lvl].shape[1], feats[lvl].shape[2]
            r0 = offsets[lvl]
            out[lvl] = (
                logits[:, r0:r0 + h, :w, :].reshape(b, -1).astype(self.out_dtype),
                deltas[:, r0:r0 + h, :w, :].reshape(b, -1, 4).astype(self.out_dtype),
            )
        return out


class BoxHead(nn.Module):
    num_classes: int  # includes background class 0
    hidden_dim: int = 1024
    class_agnostic: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    out_dtype: jnp.dtype = jnp.float32  # see RPNHead.out_dtype

    @nn.compact
    def __call__(self, rois: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        """rois: (R, S, S, C) pooled features -> (R, num_classes) logits,
        (R, num_classes (or 1), 4) box deltas."""
        r = rois.shape[0]
        x = rois.reshape(r, -1).astype(self.dtype)
        x = nn.relu(nn.Dense(self.hidden_dim, dtype=self.dtype, name="fc6")(x))
        x = nn.relu(nn.Dense(self.hidden_dim, dtype=self.dtype, name="fc7")(x))
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          kernel_init=_init01, name="cls_score")(x)
        n_reg = 1 if self.class_agnostic else self.num_classes
        deltas = nn.Dense(n_reg * 4, dtype=self.dtype,
                          kernel_init=_init001, name="bbox_pred")(x)
        return (
            logits.astype(self.out_dtype),
            deltas.reshape(r, n_reg, 4).astype(self.out_dtype),
        )


class MaskHead(nn.Module):
    num_classes: int
    channels: int = 256
    num_convs: int = 4
    dtype: jnp.dtype = jnp.bfloat16
    out_dtype: jnp.dtype = jnp.float32  # see RPNHead.out_dtype

    @nn.compact
    def __call__(self, rois: jnp.ndarray) -> jnp.ndarray:
        """rois: (R, S, S, C) -> (R, 2S, 2S, num_classes) mask logits."""
        x = rois.astype(self.dtype)
        for i in range(self.num_convs):
            x = nn.Conv(self.channels, (3, 3), padding=[(1, 1), (1, 1)],
                        dtype=self.dtype, kernel_init=_init01,
                        name=f"conv{i + 1}")(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(self.channels, (2, 2), strides=(2, 2),
                             dtype=self.dtype, kernel_init=_init01,
                             name="deconv")(x)
        x = nn.relu(x)
        x = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                    kernel_init=_init01, name="mask_logits")(x)
        return x.astype(self.out_dtype)
