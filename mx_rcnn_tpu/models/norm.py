"""Normalization layers.

The reference runs every BatchNorm with ``use_global_stats=True`` and frozen
gamma/beta (``rcnn/symbol/symbol_resnet.py``: BN params in fixed_param /
aux states never updated) — detection fine-tuning with per-GPU batch 1 makes
live BN statistics useless.  :class:`FrozenBatchNorm` reproduces that as a
pure affine transform whose four tensors live in a dedicated, non-trainable
``constants`` collection, so the optimizer never sees them and pretrained
ImageNet statistics pass through untouched.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn


class FrozenBatchNorm(nn.Module):
    """y = (x - mean) / sqrt(var + eps) * scale + bias, all four frozen."""

    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.variable("constants", "scale", nn.initializers.ones, None, (c,))
        bias = self.variable("constants", "bias", nn.initializers.zeros, None, (c,))
        mean = self.variable("constants", "mean", nn.initializers.zeros, None, (c,))
        var = self.variable("constants", "var", nn.initializers.ones, None, (c,))
        # One multiply-add over the activation map.  Measured r4 (R101
        # trunk, recipe shapes, fwd+bwd): this costs +1.4 ms vs an
        # identity norm — XLA does NOT fuse all of it into the convs.
        # backbone.fold_frozen_bn removes it by folding s/t into the conv
        # weights instead (models/resnet.py::Bottleneck.fold_bn).
        mul = (scale.value / jnp.sqrt(var.value + self.eps)).astype(self.dtype)
        add = (bias.value - mean.value * scale.value / jnp.sqrt(var.value + self.eps)).astype(self.dtype)
        return x * mul + add


class Identity(nn.Module):
    """No-op norm ("none"): the timing control for the FrozenBN-fusion A/B
    (tools/perf_breakdown.py --backbone) and a building block for norm-free
    experiments.  Parameterless."""

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return x


def make_norm(kind: str, dtype: jnp.dtype, name: str | None = None) -> nn.Module:
    if kind == "frozen_bn":
        return FrozenBatchNorm(dtype=dtype, name=name)
    if kind == "gn":
        return nn.GroupNorm(num_groups=32, dtype=dtype, name=name)
    if kind == "bn":
        # Live BN is only sound with large per-device batches; exposed for
        # from-scratch recipes (SURVEY.md section 8 hard part #3).
        return nn.BatchNorm(use_running_average=True, dtype=dtype, name=name)
    if kind == "none":
        return Identity(name=name)
    raise ValueError(f"unknown norm {kind!r}")
