"""ResNet backbones (50/101), NHWC, detection-flavored.

TPU-native rebuild of ``rcnn/symbol/symbol_resnet.py``'s residual-unit
builder (``residual_unit`` / ``get_resnet_conv``): same topology
(bottleneck-v1, stride-2 downsampling in the 3x3 conv per the torchvision
convention, frozen BN), expressed as flax modules emitting an NHWC feature
pyramid ``{2: C2, 3: C3, 4: C4, 5: C5}`` instead of a single symbolic C4
blob — both the C4 single-level recipe and FPN consume it.

TPU notes: convolutions run in ``dtype`` (bfloat16 by default) with float32
params; XLA tiles NHWC convs onto the MXU directly.  Stage freezing is done
by the optimizer mask (train/optim.py), not in-graph, so one compiled graph
serves all freeze policies.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from mx_rcnn_tpu.models.norm import FrozenBatchNorm, make_norm

STAGE_BLOCKS = {
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
    "resnet152": (3, 8, 36, 3),
}


# The folded path must use the SAME eps as the unfused FrozenBatchNorm or
# fold_bn silently stops being an exact reparameterization.
_BN_EPS = FrozenBatchNorm.eps


class _FrozenBNConsts(nn.Module):
    """Declares FrozenBatchNorm's four constant tensors WITHOUT applying
    them — the folded-conv path reads them to scale its kernel instead.
    Same names, shapes, and "constants" collection as FrozenBatchNorm, so
    checkpoints and the torchvision import are identical either way."""

    @nn.compact
    def __call__(self, c: int):
        scale = self.variable("constants", "scale", nn.initializers.ones, None, (c,))
        bias = self.variable("constants", "bias", nn.initializers.zeros, None, (c,))
        mean = self.variable("constants", "mean", nn.initializers.zeros, None, (c,))
        var = self.variable("constants", "var", nn.initializers.ones, None, (c,))
        mul = scale.value / jnp.sqrt(var.value + _BN_EPS)
        add = bias.value - mean.value * mul
        return mul, add


class _ConvKernel(nn.Module):
    """Bare conv kernel parameter under the same ``<name>/kernel`` path
    nn.Conv(use_bias=False) would create (the folded path applies the
    convolution itself so it can scale the kernel first)."""

    shape: tuple[int, int, int, int]

    @nn.compact
    def __call__(self) -> jnp.ndarray:
        return self.param(
            "kernel", nn.initializers.lecun_normal(), self.shape, jnp.float32
        )


class StemConv(nn.Module):
    """The 7x7/stride-2 RGB stem, optionally in space-to-depth form.

    A 3-input-channel conv is the worst case for the MXU: the contraction
    dimension (7*7*3 taps im2col'd, or 3 channels natively) is padded to the
    128-wide systolic array, so most of the hardware does zero work.  The
    standard TPU rewrite (MLPerf ResNet submissions) is exact: pad the 7x7
    kernel to 8x8 with one zero row/column at the top/left, space-to-depth
    both the image and the kernel by 2, and run the resulting 4x4x12 kernel
    at stride 1 — same output, 4x denser contraction.

    The parameter keeps the canonical ``(7, 7, 3, 64)`` layout under
    ``conv1/kernel`` (identical pytree to ``nn.Conv(name="conv1")``), so
    checkpoints and the torchvision import are layout-independent of the
    execution form; the rearrangement is a free in-graph reshape of a
    frozen weight.
    """

    s2d: bool = False
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, kscale=None) -> jnp.ndarray:
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (7, 7, 3, 64),
            jnp.float32,
        )
        if kscale is not None:
            # Folded frozen BN: scale the output channels in float32
            # before the compute-dtype cast (see Bottleneck.fold_bn).
            kernel = kernel * kscale
        kernel = kernel.astype(self.dtype)
        if not self.s2d:
            return jax.lax.conv_general_dilated(
                x, kernel, window_strides=(2, 2),
                padding=[(3, 3), (3, 3)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        n, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(f"s2d stem needs even canvas, got {h}x{w}")
        # z[p, q, (r, s, :)] = x[2p+r, 2q+s, :]
        z = x.reshape(n, h // 2, 2, w // 2, 2, c)
        z = z.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
        # Output row i of the original conv reads input rows 2i-3..2i+3; in
        # s2d coordinates a 4x4 stride-1 window at offset -2 reads rows
        # 2i-4..2i+3, so pad the kernel to 8x8 with a zero row/col at the
        # top/left (tap -4 is the zero) and space-to-depth it the same way.
        kp = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        kz = kp.reshape(4, 2, 4, 2, c, 64)
        kz = kz.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, 64)
        return jax.lax.conv_general_dilated(
            z, kz, window_strides=(1, 1),
            padding=[(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


def _maxpool3x3s2_slices(x: jnp.ndarray) -> jnp.ndarray:
    """The stem's 3x3/stride-2 SAME max-pool as an elementwise max of 9
    strided slices — numerically exact (both forms pad with -inf), but
    expressed as shifts+maximum instead of a ``reduce_window`` over the
    half-resolution 64-channel stem output, the worst-laid-out tensor in
    the network (64 channels = half the 128-wide vector lanes, huge
    spatial).  Strided slices fuse into the surrounding elementwise graph;
    the windowed reduction does not.  Requires even H and W (callers fall
    back to ``nn.max_pool`` otherwise)."""
    n, h, w, c = x.shape
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), constant_values=neg)
    out = None
    for dr in range(3):
        for ds in range(3):
            part = jax.lax.slice(
                xp, (0, dr, ds, 0), (n, dr + h - 1, ds + w - 1, c),
                (1, 2, 2, 1),
            )
            out = part if out is None else jnp.maximum(out, part)
    return out


class Bottleneck(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1(x4) with projection shortcut on shape change.

    ``fold_bn`` (frozen_bn only): apply each conv as conv(x, W * s) + t
    with s/t precomputed from the frozen BN constants — algebraically the
    same affine, but the multiply rides the params-sized f32->bf16 weight
    cast the unfused path already pays, instead of a separate multiply-add
    over the activation map.  Measured on the chip: the activation-side
    FrozenBN costs +1.4 ms across an R101 trunk at recipe shapes (it does
    NOT all fuse into the convs, contrary to this file's earlier claim);
    folding removes it.  Param tree identical to the unfused form.

    ``pad_small_ch``: zero-pad sub-128 contraction dims (all of C2's
    64-wide convs) to the MXU's 128 lanes.  Exact — the padded input
    channels are zero, so they contribute nothing whatever the padded
    kernel rows hold — and the lanes were already wasted; padding just
    makes the layout explicit instead of leaving XLA to re-derive it per
    fusion.  Params keep their canonical (k, k, 64, ch) shapes; the pad is
    an in-graph widening of the cast weight.
    """

    channels: int  # bottleneck width; output is channels * 4
    stride: int = 1
    norm: str = "frozen_bn"
    dtype: jnp.dtype = jnp.bfloat16
    fold_bn: bool = False
    pad_small_ch: bool = False

    def _conv_bn(self, x, ch, k, s, cname, bname):
        fold = self.fold_bn and self.norm == "frozen_bn"
        pad = self.pad_small_ch and x.shape[-1] < 128
        if not (fold or pad):
            y = nn.Conv(
                ch, (k, k), strides=(s, s), padding=[(k // 2, k // 2)] * 2,
                use_bias=False, dtype=self.dtype, name=cname,
            )(x)
            return make_norm(self.norm, self.dtype, bname)(y)
        kernel = _ConvKernel((k, k, x.shape[-1], ch), name=cname)()
        add = None
        if fold:
            mul, add = _FrozenBNConsts(name=bname)(ch)
            kernel = kernel * mul
        kernel = kernel.astype(self.dtype)
        if pad:
            extra = 128 - x.shape[-1]
            x = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, extra)))
            kernel = jnp.pad(kernel, ((0, 0), (0, 0), (0, extra), (0, 0)))
        y = jax.lax.conv_general_dilated(
            x, kernel,
            window_strides=(s, s), padding=[(k // 2, k // 2)] * 2,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if fold:
            return y + add.astype(self.dtype)
        return make_norm(self.norm, self.dtype, bname)(y)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        out_ch = self.channels * 4
        residual = x
        y = nn.relu(self._conv_bn(x, self.channels, 1, 1, "conv1", "bn1"))
        y = nn.relu(
            self._conv_bn(y, self.channels, 3, self.stride, "conv2", "bn2")
        )
        y = self._conv_bn(y, out_ch, 1, 1, "conv3", "bn3")
        if residual.shape[-1] != out_ch or self.stride != 1:
            residual = self._conv_bn(
                x, out_ch, 1, self.stride, "downsample_conv", "downsample_bn"
            )
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Returns {2: C2, 3: C3, 4: C4, 5: C5} (strides 4/8/16/32), NHWC."""

    blocks: Sequence[int] = STAGE_BLOCKS["resnet50"]
    norm: str = "frozen_bn"
    dtype: jnp.dtype = jnp.bfloat16
    out_levels: tuple[int, ...] = (2, 3, 4, 5)
    # Checkpoint each bottleneck: its activations are recomputed during the
    # backward pass instead of living in HBM across it.  The stage outputs
    # (the pyramid) are still saved, so FPN/heads see no recompute.
    remat: bool = False
    # Space-to-depth execution of the stem conv (see StemConv).
    stem_s2d: bool = False
    # Execute the stem's 3x3/2 max-pool as strided slices + maximum
    # instead of a reduce_window (see _maxpool3x3s2_slices).  Exact;
    # silently falls back on odd stem-output dims.
    stem_pool_fold: bool = False
    # Fold frozen-BN affines into the conv weights (see Bottleneck).
    fold_bn: bool = False
    # Zero-pad C2's 64-wide contractions to the 128 MXU lanes (see
    # Bottleneck.pad_small_ch).  Self-limiting: stages >= C3 are 128+ wide.
    pad_small_ch: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> dict[int, jnp.ndarray]:
        fold = self.fold_bn and self.norm == "frozen_bn"
        block_cls = (
            nn.remat(Bottleneck, prevent_cse=False) if self.remat else Bottleneck
        )
        x = x.astype(self.dtype)
        stem = StemConv(s2d=self.stem_s2d, dtype=self.dtype, name="conv1")
        if fold:
            mul, add = _FrozenBNConsts(name="bn1")(64)
            x = stem(x, kscale=mul) + add.astype(self.dtype)
        else:
            x = stem(x)
            x = make_norm(self.norm, self.dtype, "bn1")(x)
        x = nn.relu(x)
        if self.stem_pool_fold and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            x = _maxpool3x3s2_slices(x)
        else:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])

        feats: dict[int, jnp.ndarray] = {}
        widths = (64, 128, 256, 512)
        for i, (n_blocks, width) in enumerate(zip(self.blocks, widths)):
            stride = 1 if i == 0 else 2
            for b in range(n_blocks):
                x = block_cls(
                    channels=width,
                    stride=stride if b == 0 else 1,
                    norm=self.norm,
                    dtype=self.dtype,
                    fold_bn=fold,
                    pad_small_ch=self.pad_small_ch,
                    name=f"layer{i + 1}_block{b}",
                )(x)
            level = i + 2
            if level in self.out_levels:
                feats[level] = x
        return feats
