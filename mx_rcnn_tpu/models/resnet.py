"""ResNet backbones (50/101), NHWC, detection-flavored.

TPU-native rebuild of ``rcnn/symbol/symbol_resnet.py``'s residual-unit
builder (``residual_unit`` / ``get_resnet_conv``): same topology
(bottleneck-v1, stride-2 downsampling in the 3x3 conv per the torchvision
convention, frozen BN), expressed as flax modules emitting an NHWC feature
pyramid ``{2: C2, 3: C3, 4: C4, 5: C5}`` instead of a single symbolic C4
blob — both the C4 single-level recipe and FPN consume it.

TPU notes: convolutions run in ``dtype`` (bfloat16 by default) with float32
params; XLA tiles NHWC convs onto the MXU directly.  Stage freezing is done
by the optimizer mask (train/optim.py), not in-graph, so one compiled graph
serves all freeze policies.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from mx_rcnn_tpu.models.norm import make_norm

STAGE_BLOCKS = {
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
    "resnet152": (3, 8, 36, 3),
}


class Bottleneck(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1(x4) with projection shortcut on shape change."""

    channels: int  # bottleneck width; output is channels * 4
    stride: int = 1
    norm: str = "frozen_bn"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        out_ch = self.channels * 4
        conv = lambda c, k, s, name: nn.Conv(  # noqa: E731
            c, (k, k), strides=(s, s), padding=[(k // 2, k // 2)] * 2,
            use_bias=False, dtype=self.dtype, name=name,
        )
        residual = x
        y = conv(self.channels, 1, 1, "conv1")(x)
        y = make_norm(self.norm, self.dtype, "bn1")(y)
        y = nn.relu(y)
        y = conv(self.channels, 3, self.stride, "conv2")(y)
        y = make_norm(self.norm, self.dtype, "bn2")(y)
        y = nn.relu(y)
        y = conv(out_ch, 1, 1, "conv3")(y)
        y = make_norm(self.norm, self.dtype, "bn3")(y)
        if residual.shape[-1] != out_ch or self.stride != 1:
            residual = conv(out_ch, 1, self.stride, "downsample_conv")(x)
            residual = make_norm(self.norm, self.dtype, "downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Returns {2: C2, 3: C3, 4: C4, 5: C5} (strides 4/8/16/32), NHWC."""

    blocks: Sequence[int] = STAGE_BLOCKS["resnet50"]
    norm: str = "frozen_bn"
    dtype: jnp.dtype = jnp.bfloat16
    out_levels: tuple[int, ...] = (2, 3, 4, 5)
    # Checkpoint each bottleneck: its activations are recomputed during the
    # backward pass instead of living in HBM across it.  The stage outputs
    # (the pyramid) are still saved, so FPN/heads see no recompute.
    remat: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> dict[int, jnp.ndarray]:
        block_cls = (
            nn.remat(Bottleneck, prevent_cse=False) if self.remat else Bottleneck
        )
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="conv1")(x)
        x = make_norm(self.norm, self.dtype, "bn1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])

        feats: dict[int, jnp.ndarray] = {}
        widths = (64, 128, 256, 512)
        for i, (n_blocks, width) in enumerate(zip(self.blocks, widths)):
            stride = 1 if i == 0 else 2
            for b in range(n_blocks):
                x = block_cls(
                    channels=width,
                    stride=stride if b == 0 else 1,
                    norm=self.norm,
                    dtype=self.dtype,
                    name=f"layer{i + 1}_block{b}",
                )(x)
            level = i + 2
            if level in self.out_levels:
                feats[level] = x
        return feats
