"""VGG-16 trunk (conv1_1..conv5_3), NHWC.

Rebuild of ``rcnn/symbol/symbol_vgg.py::get_vgg_conv``: 13 conv layers in 5
groups with 2x2 max-pools after groups 1-4 (the reference drops the pool5,
leaving stride 16 for the RPN/ROI features).  Emitted as a one-entry pyramid
dict for interface parity with ResNet, keyed by log2(stride): conv5_3 sits
after 4 pools (stride 16), so it is level 4 — the same key as ResNet's C4 —
and the C4-recipe code path is backbone-agnostic.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

VGG16_GROUPS = ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3))


class VGG16(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> dict[int, jnp.ndarray]:
        x = x.astype(self.dtype)
        feats: dict[int, jnp.ndarray] = {}
        for g, (ch, n_convs) in enumerate(VGG16_GROUPS):
            for c in range(n_convs):
                x = nn.Conv(ch, (3, 3), padding=[(1, 1), (1, 1)], dtype=self.dtype,
                            name=f"conv{g + 1}_{c + 1}")(x)
                x = nn.relu(x)
            if g < 4:  # no pool5 (reference keeps stride 16)
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            feats[g + 1] = x
        return {4: feats[5]}  # stride 16 == 2**4
