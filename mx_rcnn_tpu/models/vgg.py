"""VGG-16 trunk (conv1_1..conv5_3), NHWC.

Rebuild of ``rcnn/symbol/symbol_vgg.py::get_vgg_conv``: 13 conv layers in 5
groups with 2x2 max-pools after groups 1-4 (the reference drops the pool5,
leaving stride 16 for the RPN/ROI features).  Emitted as a one-entry pyramid
dict for interface parity with ResNet, keyed by log2(stride): conv5_3 sits
after 4 pools (stride 16), so it is level 4 — the same key as ResNet's C4 —
and the C4-recipe code path is backbone-agnostic.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

VGG16_GROUPS = ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3))


class _ConvGroup(nn.Module):
    """One VGG group: n_convs 3x3 convs + relu (pooling stays outside)."""

    group: int
    channels: int
    n_convs: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for c in range(self.n_convs):
            x = nn.Conv(
                self.channels, (3, 3), padding=[(1, 1), (1, 1)],
                dtype=self.dtype, name=f"conv{self.group}_{c + 1}",
            )(x)
            x = nn.relu(x)
        return x


class VGG16(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16
    # Recompute each conv group's intermediates on the backward pass.
    remat: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> dict[int, jnp.ndarray]:
        group_cls = (
            nn.remat(_ConvGroup, prevent_cse=False) if self.remat else _ConvGroup
        )
        x = x.astype(self.dtype)
        feats: dict[int, jnp.ndarray] = {}
        for g, (ch, n_convs) in enumerate(VGG16_GROUPS):
            x = group_cls(group=g + 1, channels=ch, n_convs=n_convs,
                          dtype=self.dtype, name=f"group{g + 1}")(x)
            if g < 4:  # no pool5 (reference keeps stride 16)
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            feats[g + 1] = x
        return {4: feats[5]}  # stride 16 == 2**4
