"""Native (C++) host-side runtime components.

The reference keeps its host-side hot loops in compiled code — Cython
``bbox.pyx``/``cpu_nms.pyx``, the vendored COCO ``maskApi.c``, and the CUDA
``nms_kernel.cu`` (SURVEY.md §3.5).  On TPU the device-side equivalents are
XLA/Pallas; what remains on the host — image letterboxing in the input
pipeline, RLE mask arithmetic in evaluation, greedy NMS as a test oracle —
is implemented here in C++ (``src/native.cc``) behind a ctypes interface.

Build: ``python -m mx_rcnn_tpu.native.build`` (direct g++, no setuptools);
every entry point falls back to the numpy implementation when the shared
library is absent, so the package works un-built.
"""

from mx_rcnn_tpu.native.lib import (
    available,
    cpu_nms,
    letterbox_normalize,
    rle_encode_native,
    rle_iou_native,
)

__all__ = [
    "available",
    "cpu_nms",
    "letterbox_normalize",
    "rle_encode_native",
    "rle_iou_native",
]
