"""Build the native shared library with g++ (no setuptools, no pybind11).

Usage: ``python -m mx_rcnn_tpu.native.build``; the test suite and package
import both tolerate an un-built tree (numpy fallbacks take over).
"""

from __future__ import annotations

import os
import subprocess
import sys

PKG_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(PKG_DIR, "src", "native.cc")
OUT = os.path.join(PKG_DIR, "_native.so")


def build(verbose: bool = True) -> str:
    # Portable ISA (no -march=native): the .so may be built once and used
    # from a shared filesystem on heterogeneous hosts; a SIGILL in the data
    # loader is worse than a few percent of scalar-loop speed.
    # Compile to a temp path + atomic rename so concurrent builders
    # (multi-process loaders, parallel test workers) never dlopen a
    # half-written file.
    tmp = f"{OUT}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", SRC, "-o", tmp,
    ]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    try:
        subprocess.run(cmd, check=True)
        os.replace(tmp, OUT)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return OUT


if __name__ == "__main__":
    build()
