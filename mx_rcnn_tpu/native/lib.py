"""ctypes interface to the native library, with numpy fallbacks.

Every function here has identical semantics built or un-built; tests
compare the two directly (SURVEY.md §5: native kernels validated against
the pure-python oracles, the inverse of the reference which shipped the
Cython/C versions untested).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB = None  # None = not attempted; False = failed (don't retry); CDLL = loaded
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native.so")


def _load() -> Optional[ctypes.CDLL]:
    global _LIB
    if _LIB is not None:
        return _LIB or None  # False (cached failure) -> None
    if not os.path.exists(_SO):
        # Build lazily when a toolchain is present (dev/CI convenience).
        try:
            from mx_rcnn_tpu.native.build import build

            build(verbose=False)
        except Exception:
            # Cache the failure: these entry points sit on the per-image
            # loader hot path — one g++ attempt per process, not per call.
            _LIB = False
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        _LIB = False
        return None
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    lib.cpu_nms.restype = ctypes.c_int
    lib.cpu_nms.argtypes = [
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_float,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
    ]
    lib.rle_encode.restype = ctypes.c_int
    lib.rle_encode.argtypes = [
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_int, u32p,
    ]
    lib.rle_iou.restype = None
    lib.rle_iou.argtypes = [
        u32p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_int,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
    ]
    lib.letterbox_normalize.restype = None
    lib.letterbox_normalize.argtypes = [
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_int,
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_float,
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
    ]
    _LIB = lib
    return lib


def available() -> bool:
    return _load() is not None


def cpu_nms(boxes: np.ndarray, scores: np.ndarray, threshold: float) -> np.ndarray:
    """Greedy NMS; returns kept indices in score order.  Semantics of the
    reference's ``cpu_nms.pyx`` (+1 pixel areas)."""
    boxes = np.ascontiguousarray(boxes, np.float32)
    order = np.argsort(-np.asarray(scores), kind="mergesort").astype(np.int32)
    n = len(boxes)
    lib = _load()
    if lib is None or n == 0:
        return _py_nms(boxes, order, threshold)
    keep = np.empty(n, np.int32)
    kept = lib.cpu_nms(boxes, order, n, float(threshold), keep)
    return keep[:kept].copy()


def _py_nms(boxes: np.ndarray, order: np.ndarray, threshold: float) -> np.ndarray:
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = np.maximum(0, x2 - x1 + 1) * np.maximum(0, y2 - y1 + 1)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(int(i))
        xx1 = np.maximum(x1[i], x1[order])
        yy1 = np.maximum(y1[i], y1[order])
        xx2 = np.minimum(x2[i], x2[order])
        yy2 = np.minimum(y2[i], y2[order])
        inter = np.maximum(0, xx2 - xx1 + 1) * np.maximum(0, yy2 - yy1 + 1)
        iou = inter / (areas[i] + areas[order] - inter)
        suppressed[order[iou > threshold]] = True
    return np.asarray(keep, np.int32)


def rle_encode_native(binary: np.ndarray) -> Optional[dict]:
    """COCO column-major RLE via C++; None when the library is unavailable
    (callers fall back to evalutil.masks.rle_encode)."""
    lib = _load()
    if lib is None:
        return None
    m = np.ascontiguousarray(binary, np.uint8)
    h, w = m.shape
    counts = np.empty(h * w + 1, np.uint32)
    n = lib.rle_encode(m, h, w, counts)
    return {"size": (h, w), "counts": counts[:n].copy()}


def rle_iou_native(dts: list, gts: list) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    alls = list(dts) + list(gts)
    lengths = np.asarray([len(r["counts"]) for r in alls], np.int32)
    offsets = np.zeros(len(alls), np.int64)
    if len(alls) > 1:
        offsets[1:] = np.cumsum(lengths[:-1])
    flat = (
        np.concatenate([np.asarray(r["counts"], np.uint32) for r in alls])
        if alls else np.zeros(0, np.uint32)
    )
    out = np.zeros((len(dts), len(gts)), np.float64)
    if len(dts) and len(gts):
        lib.rle_iou(
            np.ascontiguousarray(flat), offsets, lengths, len(dts), len(gts), out
        )
    return out


def letterbox_normalize(
    image: np.ndarray,
    canvas_hw: tuple[int, int],
    nh: int,
    nw: int,
    scale: float,
    mean: tuple[float, float, float],
    std: tuple[float, float, float],
) -> Optional[np.ndarray]:
    """Fused resize-into-canvas + normalize for uint8 RGB inputs; None when
    the native library is unavailable."""
    lib = _load()
    if lib is None or image.dtype != np.uint8 or image.ndim != 3:
        return None
    sh, sw = image.shape[:2]
    dh, dw = canvas_hw
    dst = np.empty((dh, dw, 3), np.float32)
    lib.letterbox_normalize(
        np.ascontiguousarray(image), sh, sw, dst, dh, dw, int(nh), int(nw),
        float(scale),
        np.asarray(mean, np.float32), np.asarray(std, np.float32),
    )
    return dst
