// Host-side runtime kernels for mx_rcnn_tpu (C++17, no dependencies).
//
// TPU-native replacements for the reference's compiled host code
// (SURVEY.md §3.5): the Cython cpu_nms, the COCO maskApi RLE routines, and
// the input pipeline's resize+normalize inner loop (the reference leaned on
// OpenCV there; this removes that dependency from the hot path).  All
// entry points are extern "C" and operate on caller-owned buffers so the
// Python side is a thin ctypes wrapper.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Greedy NMS (reference: rcnn/cython/cpu_nms.pyx).
//
// boxes: (n, 4) float32 x1,y1,x2,y2 sorted by caller or not — order is
// taken from `order` (descending score indices).  keep_out receives the
// kept indices; returns the number kept.
int cpu_nms(const float* boxes, const int* order, int n, float thresh,
            int* keep_out) {
  std::vector<char> suppressed(n, 0);
  std::vector<float> areas(n);
  for (int i = 0; i < n; ++i) {
    const float* b = boxes + 4 * i;
    areas[i] = std::max(0.f, b[2] - b[0] + 1.f) * std::max(0.f, b[3] - b[1] + 1.f);
  }
  int kept = 0;
  for (int oi = 0; oi < n; ++oi) {
    int i = order[oi];
    if (suppressed[i]) continue;
    keep_out[kept++] = i;
    const float* bi = boxes + 4 * i;
    for (int oj = oi + 1; oj < n; ++oj) {
      int j = order[oj];
      if (suppressed[j]) continue;
      const float* bj = boxes + 4 * j;
      float xx1 = std::max(bi[0], bj[0]);
      float yy1 = std::max(bi[1], bj[1]);
      float xx2 = std::min(bi[2], bj[2]);
      float yy2 = std::min(bi[3], bj[3]);
      float w = std::max(0.f, xx2 - xx1 + 1.f);
      float h = std::max(0.f, yy2 - yy1 + 1.f);
      float inter = w * h;
      float iou = inter / (areas[i] + areas[j] - inter);
      if (iou > thresh) suppressed[j] = 1;
    }
  }
  return kept;
}

// ---------------------------------------------------------------------------
// RLE mask routines (reference: rcnn/pycocotools/maskApi.c contract —
// column-major alternating 0/1 run lengths, first run counts zeros).

// Encode a (h, w) uint8 mask (row-major in memory) into counts_out
// (caller-allocated, capacity h*w+1).  Returns the number of runs.
int rle_encode(const uint8_t* mask, int h, int w, uint32_t* counts_out) {
  int n_runs = 0;
  uint8_t cur = 0;  // runs start with zeros
  uint32_t run = 0;
  for (int x = 0; x < w; ++x) {
    for (int y = 0; y < h; ++y) {  // column-major scan
      uint8_t v = mask[(size_t)y * w + x] ? 1 : 0;
      if (v == cur) {
        ++run;
      } else {
        counts_out[n_runs++] = run;
        cur = v;
        run = 1;
      }
    }
  }
  counts_out[n_runs++] = run;
  return n_runs;
}

// Intersection of two RLEs in run space (no decode).
static int64_t rle_intersection(const uint32_t* a, int na, const uint32_t* b,
                                int nb) {
  int64_t inter = 0;
  int ia = 0, ib = 0;
  int64_t ea = a[0], eb = b[0];  // current run end positions
  int64_t pos = 0;
  while (ia < na && ib < nb) {
    int64_t end = std::min(ea, eb);
    if ((ia & 1) && (ib & 1)) inter += end - pos;
    pos = end;
    if (ea == end && ++ia < na) ea += a[ia];
    if (eb == end && ++ib < nb) eb += b[ib];
  }
  return inter;
}

int64_t rle_area(const uint32_t* counts, int n) {
  int64_t area = 0;
  for (int i = 1; i < n; i += 2) area += counts[i];
  return area;
}

// IoU matrix between n_d and n_g RLEs.  Flattened inputs: counts_flat holds
// all runs back to back, offsets/lengths index them (dts first, then gts).
void rle_iou(const uint32_t* counts_flat, const int64_t* offsets,
             const int32_t* lengths, int n_d, int n_g, double* iou_out) {
  std::vector<int64_t> areas(n_d + n_g);
  for (int i = 0; i < n_d + n_g; ++i)
    areas[i] = rle_area(counts_flat + offsets[i], lengths[i]);
  for (int i = 0; i < n_d; ++i) {
    for (int j = 0; j < n_g; ++j) {
      int64_t inter =
          rle_intersection(counts_flat + offsets[i], lengths[i],
                           counts_flat + offsets[n_d + j], lengths[n_d + j]);
      int64_t uni = areas[i] + areas[n_d + j] - inter;
      iou_out[(size_t)i * n_g + j] = uni > 0 ? (double)inter / (double)uni : 0.0;
    }
  }
}

// ---------------------------------------------------------------------------
// Input pipeline: bilinear resize into a zero-padded canvas + channelwise
// normalize, fused (reference: rcnn/io/image.py resize + transform, done
// via OpenCV + numpy in two passes).
//
// src: (sh, sw, 3) uint8 RGB.  dst: (dh, dw, 3) float32 canvas, fully
// overwritten (resized region top-left, rest zeros... normalized zeros).
// scale maps dst pixel -> src pixel (same factor both axes); nh/nw is the
// resized extent.  mean/std are per-channel.
void letterbox_normalize(const uint8_t* src, int sh, int sw, float* dst,
                         int dh, int dw, int nh, int nw, float scale,
                         const float* mean, const float* std_) {
  (void)scale;  // boxes use it; pixels use cv2's per-axis ratios below
  float inv_std[3] = {1.f / std_[0], 1.f / std_[1], 1.f / std_[2]};
  float pad[3] = {-mean[0] * inv_std[0], -mean[1] * inv_std[1],
                  -mean[2] * inv_std[2]};
  // cv2.resize convention: per-axis ratio src_extent / dst_extent (nh/nw
  // are rounded, so these differ slightly from 1/scale per axis).
  float ratio_y = (float)sh / (float)(nh > 0 ? nh : 1);
  float ratio_x = (float)sw / (float)(nw > 0 ? nw : 1);
  for (int y = 0; y < dh; ++y) {
    float* row = dst + (size_t)y * dw * 3;
    if (y >= nh) {
      for (int x = 0; x < dw; ++x)
        for (int c = 0; c < 3; ++c) row[3 * x + c] = pad[c];
      continue;
    }
    // cv2.INTER_LINEAR convention: src = (dst + 0.5) * inv_scale - 0.5.
    float sy = (y + 0.5f) * ratio_y - 0.5f;
    sy = std::max(0.f, std::min(sy, (float)sh - 1));
    int y0 = (int)sy;
    int y1 = std::min(y0 + 1, sh - 1);
    float ly = sy - y0;
    const uint8_t* r0 = src + (size_t)y0 * sw * 3;
    const uint8_t* r1 = src + (size_t)y1 * sw * 3;
    for (int x = 0; x < dw; ++x) {
      if (x >= nw) {
        for (int c = 0; c < 3; ++c) row[3 * x + c] = pad[c];
        continue;
      }
      float sx = (x + 0.5f) * ratio_x - 0.5f;
      sx = std::max(0.f, std::min(sx, (float)sw - 1));
      int x0 = (int)sx;
      int x1 = std::min(x0 + 1, sw - 1);
      float lx = sx - x0;
      for (int c = 0; c < 3; ++c) {
        float v = (1 - ly) * ((1 - lx) * r0[3 * x0 + c] + lx * r0[3 * x1 + c]) +
                  ly * ((1 - lx) * r1[3 * x0 + c] + lx * r1[3 * x1 + c]);
        row[3 * x + c] = (v - mean[c]) * inv_std[c];
      }
    }
  }
}

}  // extern "C"
