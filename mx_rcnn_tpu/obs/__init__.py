"""mx_rcnn_tpu.obs — the unified observability plane.

One host-side module for the four telemetry surfaces the runtime grew
across PRs 3-9 but recorded as scattered log strings:

* **journal**  — crash-safe typed JSONL event log (obs/journal.py)
* **metrics**  — process-wide registry + /metrics endpoint (obs/metrics.py,
  obs/endpoint.py)
* **spans**    — request/step tracing -> Chrome-trace JSON (obs/tracing.py)
* **flight**   — bounded ring dumped on death (obs/flight.py)

The plane is a process-wide singleton with two modes:

* **Unconfigured** (the default — every existing test and tool): events
  still derive their log lines (obs/events.py) and land in the flight
  ring; metrics still count in-process; nothing touches the filesystem
  and no endpoint binds.  Steady-state cost is a dict append.
* **Configured** (``obs.configure(out_dir=...)`` — wired from the train
  loop via ``cfg.obs``, from ``tools/loadgen.py`` via ``--obs-dir``, and
  from chaos children): events append to ``<out_dir>/journal.jsonl``,
  finished spans to ``<out_dir>/spans.jsonl``, flight dumps to
  ``<out_dir>/flight_*.json``, and an optional ``/metrics`` HTTP
  endpoint serves the registry.

HARD RULE (enforced by tpulint TPU007): nothing in this package may be
imported from jit-traced modules.  Observability reads the world from
the host side; it must never enter the compiled graph.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
import time
import uuid
from typing import Callable, Optional

from . import events as _events
from .flight import FlightRecorder
from .journal import Journal, read_journal
from .metrics import (
    DEFAULT_LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from .tracing import Span, Tracer, new_trace_id

__all__ = [
    "configure", "close", "reset", "is_configured", "out_dir", "run_id",
    "emit", "counter", "gauge", "histogram", "registry", "render_metrics",
    "span", "tracer", "new_trace_id", "spans_enabled",
    "flight_dump", "flight", "install_crash_handler",
    "register_status", "unregister_status", "metrics_port",
    "Journal", "read_journal", "Registry", "Counter", "Gauge", "Histogram",
    "Span", "Tracer", "FlightRecorder", "DEFAULT_LATENCY_BUCKETS_S",
]

log = logging.getLogger(__name__)

_lock = threading.RLock()
_registry = Registry()
_flight = FlightRecorder()
_tracer = Tracer()
_journal: Optional[Journal] = None
_server = None  # MetricsServer | None (lazy import keeps http out of cold path)
_run_id: str = "-"
_out_dir: Optional[str] = None
_spans_fd: Optional[int] = None
_spans_on = True
_flush_thread: Optional[threading.Thread] = None
_flush_stop = threading.Event()
# Status providers survive endpoint off: /statusz needs a server, but the
# journal flush and flight dumps can still snapshot them.
_status_providers: dict[str, Callable[[], dict]] = {}


def _span_sink(s: Span) -> None:
    rec = s.to_chrome()
    _flight.record({"type": "span", **rec})
    fd = _spans_fd
    if fd is not None and _spans_on:
        import json

        try:
            os.write(fd, (json.dumps(rec, default=str) + "\n").encode())
        except OSError:
            pass


_tracer.set_sink(_span_sink)


# -- lifecycle ----------------------------------------------------------------


def configure(
    out_dir: str,
    run_id: Optional[str] = None,
    metrics_port: Optional[int] = None,
    spans: bool = True,
    flight_size: int = 512,
    flush_s: float = 0.0,
) -> str:
    """Turn on the durable surfaces.  Idempotent per process (a second
    call re-points the plane at the new directory).

    ``metrics_port``: None = no endpoint, 0 = ephemeral port (read it
    back via :func:`metrics_port`).  ``flush_s`` > 0 starts a background
    thread writing a ``metrics_flush`` journal event every period, so
    headless runs keep the registry's history.  Returns the run id.
    """
    global _journal, _server, _run_id, _out_dir, _spans_fd, _spans_on
    global _flight, _flush_thread
    with _lock:
        close()
        _run_id = run_id or (
            time.strftime("%Y%m%d-%H%M%S") + "-" + uuid.uuid4().hex[:6]
        )
        _out_dir = os.path.abspath(out_dir)
        os.makedirs(_out_dir, exist_ok=True)
        _journal = Journal(os.path.join(_out_dir, "journal.jsonl"), _run_id)
        _spans_on = bool(spans)
        _spans_fd = os.open(
            os.path.join(_out_dir, "spans.jsonl"),
            os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644,
        )
        new_ring = FlightRecorder(flight_size)
        for entry in _flight.entries():  # keep pre-configure history
            new_ring.record(entry)
        new_ring.out_dir = _out_dir
        new_ring.run_id = _run_id
        _flight = new_ring
        if metrics_port is not None and metrics_port >= 0:
            from .endpoint import MetricsServer

            _server = MetricsServer(_registry, port=metrics_port).start()
            for name, fn in _status_providers.items():
                _server.register_status(name, fn)
        if flush_s and flush_s > 0:
            _flush_stop.clear()
            _flush_thread = threading.Thread(
                target=_flush_loop, args=(float(flush_s),),
                name="obs-metrics-flush", daemon=True,
            )
            _flush_thread.start()
        emit("obs", "configured", {
            "out_dir": _out_dir,
            "metrics_port": metrics_port if _server is None else _server.port,
            "spans": _spans_on, "flush_s": flush_s,
        })
        return _run_id


def _flush_loop(period_s: float) -> None:
    while not _flush_stop.wait(period_s):
        flush_metrics()


def flush_metrics() -> None:
    """Write one metrics_flush event carrying the registry snapshot."""
    emit("obs", "metrics_flush", {"snapshot": _registry.snapshot()})


def close() -> None:
    """Flush + close every durable surface (leaves the in-memory ring,
    registry and status providers intact)."""
    global _journal, _server, _spans_fd, _out_dir, _flush_thread
    with _lock:
        _flush_stop.set()
        if _flush_thread is not None:
            _flush_thread.join(timeout=2.0)
            _flush_thread = None
        if _journal is not None:
            flush_metrics()
            _journal.close()
            _journal = None
        if _server is not None:
            _server.close()
            _server = None
        if _spans_fd is not None:
            try:
                os.close(_spans_fd)
            except OSError:
                pass
            _spans_fd = None
        _flight.out_dir = None
        _out_dir = None


def reset() -> None:
    """Test hook: close + fresh registry/ring/run-id (providers cleared)."""
    global _registry, _flight, _run_id
    with _lock:
        close()
        _registry = Registry()
        _flight = FlightRecorder()
        _run_id = "-"
        _status_providers.clear()


atexit.register(close)


def is_configured() -> bool:
    return _journal is not None


def out_dir() -> Optional[str]:
    return _out_dir


def run_id() -> str:
    return _run_id


def metrics_port() -> Optional[int]:
    s = _server
    return None if s is None else s.port


# -- events -------------------------------------------------------------------


def emit(
    subsystem: str,
    kind: str,
    payload: Optional[dict] = None,
    *,
    logger: Optional[logging.Logger] = None,
) -> dict:
    """Emit one typed event: flight ring always, journal when configured,
    and the derived log line (obs/events.py) through ``logger`` (or the
    obs logger).  Returns the event record.  Never raises."""
    payload = payload or {}
    rec = {
        "type": "event",
        "run_id": _run_id,
        "ts": round(time.time(), 3),
        "ts_mono_ns": time.monotonic_ns(),
        "pid": os.getpid(),
        "subsystem": subsystem,
        "kind": kind,
        "payload": payload,
    }
    try:
        _flight.record(rec)
        j = _journal
        if j is not None:
            j.write({k: v for k, v in rec.items() if k != "type"})
        lvl, line = _events.render(subsystem, kind, payload)
        lg = logger or log
        if lg.isEnabledFor(lvl):
            lg.log(lvl, "%s", line)
        _registry.counter(
            "obs_events_total", "typed events emitted",
        ).inc(subsystem=subsystem, kind=kind)
    except Exception:  # noqa: BLE001 - telemetry must never hurt the host
        pass
    return rec


# -- metrics ------------------------------------------------------------------


def registry() -> Registry:
    return _registry


def counter(name: str, help: str = "") -> Counter:
    return _registry.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _registry.gauge(name, help)


def histogram(name: str, help: str = "", buckets=DEFAULT_LATENCY_BUCKETS_S
              ) -> Histogram:
    return _registry.histogram(name, help, buckets)


def render_metrics() -> str:
    return _registry.render()


def register_status(name: str, fn: Callable[[], dict]) -> None:
    """Expose a snapshot callable on /statusz (+ /healthz liveness when it
    reports an ``alive`` field).  Safe before or after configure()."""
    with _lock:
        _status_providers[name] = fn
        if _server is not None:
            _server.register_status(name, fn)


def unregister_status(name: str) -> None:
    with _lock:
        _status_providers.pop(name, None)
        if _server is not None:
            _server.unregister_status(name)


# -- spans --------------------------------------------------------------------


def tracer() -> Tracer:
    return _tracer


def spans_enabled() -> bool:
    return _spans_fd is not None and _spans_on


def span(name: str, *, subsystem: str = "app",
         trace_id: Optional[str] = None, parent_id: Optional[str] = None,
         attrs: Optional[dict] = None) -> Span:
    return _tracer.span(
        name, subsystem=subsystem, trace_id=trace_id, parent_id=parent_id,
        attrs=attrs,
    )


# -- flight recorder ----------------------------------------------------------


def flight() -> FlightRecorder:
    return _flight


def flight_dump(trigger: str, extra: Optional[dict] = None) -> Optional[str]:
    """Dump the ring; returns the artifact path (None when unconfigured)."""
    path = _flight.dump(trigger, extra)
    if path is not None:
        counter("obs_flight_dumps_total", "flight recorder dumps").inc(
            trigger=trigger
        )
        j = _journal
        if j is not None:
            j.write({
                "subsystem": "obs", "kind": "flight_dump",
                "payload": {"trigger": trigger, "path": path},
            })
    return path


def install_crash_handler() -> None:
    _flight.install_crash_handler()
