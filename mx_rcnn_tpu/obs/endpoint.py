"""Stdlib-only HTTP endpoint: /metrics, /healthz, /readyz, /statusz.

A ``ThreadingHTTPServer`` on a daemon thread — no new dependencies, no
interference with process exit.  Port 0 binds an ephemeral port
(``server.port`` reports the real one), which is what tests and the CI
obs_smoke job use.

``/healthz`` and ``/statusz`` ride registered *status providers*:
callables returning a JSON-able dict (the serving stack registers
``engine.stats()`` / ``fleet.stats()``, which already wrap
``serve/health.py``'s snapshot).  ``/healthz`` returns 200 when every
provider that reports an ``alive`` field says True (503 otherwise);
``/statusz`` returns the full merged snapshot as JSON.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .metrics import Registry

__all__ = ["MetricsServer"]

log = logging.getLogger(__name__)


class MetricsServer:
    """Daemon-thread HTTP server exposing one registry + status providers."""

    def __init__(self, registry: Registry, port: int = 0,
                 host: str = "127.0.0.1") -> None:
        self.registry = registry
        self._providers: dict[str, Callable[[], dict]] = {}
        self._plock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a) -> None:  # no stderr per scrape
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "text/plain; charset=utf-8") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 - http.server API
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200, outer.registry.render().encode("utf-8"),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        ok, status = outer.health()
                        self._send(
                            200 if ok else 503,
                            (json.dumps(status) + "\n").encode("utf-8"),
                            "application/json",
                        )
                    elif path == "/readyz":
                        ok, status = outer.readiness()
                        self._send(
                            200 if ok else 503,
                            (json.dumps(status) + "\n").encode("utf-8"),
                            "application/json",
                        )
                    elif path == "/statusz":
                        self._send(
                            200,
                            (json.dumps(outer.status(), default=str,
                                        indent=2) + "\n").encode("utf-8"),
                            "application/json",
                        )
                    else:
                        self._send(404, b"not found\n")
                except Exception as e:  # noqa: BLE001 - scrape must not kill
                    try:
                        self._send(500, f"{type(e).__name__}: {e}\n".encode())
                    except OSError:
                        pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="obs-metrics-http",
            daemon=True,
        )

    def start(self) -> "MetricsServer":
        self._thread.start()
        log.info("obs: /metrics endpoint on 127.0.0.1:%d", self.port)
        return self

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass

    # -- status providers --------------------------------------------------

    def register_status(self, name: str, fn: Callable[[], dict]) -> None:
        """Register (or replace) a named snapshot provider for /statusz."""
        with self._plock:
            self._providers[name] = fn

    def unregister_status(self, name: str) -> None:
        with self._plock:
            self._providers.pop(name, None)

    def status(self) -> dict:
        with self._plock:
            providers = dict(self._providers)
        out = {}
        for name, fn in providers.items():
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 - one bad provider != 500
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def health(self) -> tuple[bool, dict]:
        """(all-alive, per-provider alive map).  Providers that don't
        report ``alive`` count as healthy (they're stats, not liveness)."""
        status = self.status()
        alive = {
            name: bool(snap.get("alive", True))
            for name, snap in status.items()
            if isinstance(snap, dict)
        }
        ok = all(alive.values()) if alive else True
        return ok, {"ok": ok, "providers": alive}

    def readiness(self) -> tuple[bool, dict]:
        """Routability, distinct from liveness: 503 the moment any
        provider reports ``draining`` True or ``ready`` False, so an
        external balancer stops sending work while the fleet's exit-75
        drain completes — the process is still *alive* the whole time."""
        status = self.status()
        ready = {
            name: (
                bool(snap.get("ready", True))
                and not bool(snap.get("draining", False))
                and bool(snap.get("alive", True))
            )
            for name, snap in status.items()
            if isinstance(snap, dict)
        }
        ok = all(ready.values()) if ready else True
        return ok, {"ok": ok, "providers": ready}
