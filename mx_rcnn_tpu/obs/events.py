"""Typed event schema: one table of event kinds -> (level, log line).

Satellite contract ("one source of truth"): the critical-path log lines
that the chaos harness and operators grep for are DERIVED from the typed
event payload here, not hand-formatted at the call site.  A call site
does::

    obs.emit("data", "worker_death", {"service": name, "worker": wid,
                                      "why": why, ...}, logger=log)

and gets (a) a journal record, (b) a flight-recorder ring entry, and
(c) the exact log line the harness asserts on (e.g. the literal
``"respawning"`` / ``"falling back to in-process synchronous assembly"``
substrings in ``tools/chaos.py``).  Changing a line here changes it
everywhere — and the typed payload survives even if the prose drifts.

Unknown kinds are legal (the plane is open-vocabulary): they render as
``"<subsystem>: <kind> <payload>"`` at INFO.
"""

from __future__ import annotations

import logging
from typing import Callable

__all__ = ["EVENTS", "render"]


def _fmt_worker_death(p: dict) -> str:
    return (
        "{service}: worker {worker} {why}; reassigning {lost} in-flight "
        "batch(es) {indices}; respawning ({respawns_left} respawn(s) left)"
    ).format(**p)


def _fmt_worker_retired(p: dict) -> str:
    return (
        "{service}: worker {worker} {why}; respawn budget exhausted — "
        "slot retired ({lost} in-flight batch(es) reassigned)"
    ).format(**p)


def _fmt_worker_wedged(p: dict) -> str:
    return (
        "{service}: worker {worker} wedged (no heartbeat for "
        "{heartbeat_age_s:.1f}s); killing"
    ).format(**p)


def _fmt_service_fallback(p: dict) -> str:
    return (
        "{service}: all workers dead, respawn budget exhausted "
        "({deaths} deaths); falling back to in-process synchronous "
        "assembly — the run continues degraded"
    ).format(**p)


def _fmt_cache_quarantine(p: dict) -> str:
    return (
        "tensor cache: corrupt blob for image {image_id!r} ({error}) at "
        "{path}; quarantined + rebuilding from source"
    ).format(**p)


def _fmt_shm_quarantine(p: dict) -> str:
    return (
        "shm slot quarantined: batch {batch_index} slot {slot} "
        "({reason}) — index reassigned"
    ).format(**p)


def _fmt_cache_evict(p: dict) -> str:
    return (
        "cache evict: {evicted} blob(s), {freed_bytes}B freed "
        "({used_bytes}B/{max_bytes}B after)"
    ).format(**p)


def _fmt_guardian_rollback(p: dict) -> str:
    return (
        "guardian: {reason} at step {step} — rolling back to the last "
        "good checkpoint and skipping the offending data window "
        "(attempt {attempt}/{max_attempts})"
    ).format(**p)


def _fmt_rollback_restored(p: dict) -> str:
    return (
        "guardian rollback: restored step {restored_step}, skipping "
        "{skipped} batch(es) of the data schedule (total skipped: "
        "{total_skipped})"
    ).format(**p)


def _fmt_loss_spike(p: dict) -> str:
    return (
        "guardian: loss spike at step {step} — {loss:.4f} is "
        "{sigma:.1f} sigma above the trailing-window mean {mean:.4f} "
        "(watching for divergence)"
    ).format(**p)


def _fmt_fleet_quarantine(p: dict) -> str:
    return "fleet: quarantining replica {replica}: {reason}".format(**p)


def _fmt_fleet_reinstate(p: dict) -> str:
    return "fleet: replica {replica} reinstated".format(**p)


def _fmt_fleet_retire(p: dict) -> str:
    return (
        "fleet: replica {replica} exhausted its rebuild budget "
        "({rebuilds}); retiring it"
    ).format(**p)


def _fmt_weight_swap(p: dict) -> str:
    return (
        "fleet: weight swap -> generation {generation} "
        "({replicas} replica(s) rolled)"
    ).format(**p)


def _fmt_engine_dead(p: dict) -> str:
    return (
        "watchdog: {reason} — failing {queued} queued request(s)"
    ).format(**p)


def _fmt_engine_killed(p: dict) -> str:
    return "engine killed: {reason}".format(**p)


def _fmt_shed(p: dict) -> str:
    return (
        "shed: queue full ({queue_depth}/{max_queue}), request rejected"
    ).format(**p)


def _fmt_breaker(p: dict) -> str:
    return (
        "circuit breaker {level}: {old_state} -> {new_state}"
    ).format(**p)


def _fmt_ladder(p: dict) -> str:
    return (
        "degradation ladder: level {old_level} -> {new_level}"
    ).format(**p)


def _fmt_ckpt_saved(p: dict) -> str:
    return "checkpoint saved at step {step}".format(**p)


def _fmt_ckpt_restored(p: dict) -> str:
    return "checkpoint restored at step {step}".format(**p)


def _fmt_preempt(p: dict) -> str:
    return (
        "preemption drain at step {step}: emergency checkpoint written, "
        "exiting resumable"
    ).format(**p)


def _fmt_metrics_flush(p: dict) -> str:
    return "metrics flush ({metrics} series)".format(
        metrics=len(p.get("snapshot", {}))
    )


def _fmt_configured(p: dict) -> str:
    return (
        "observability plane up: dir={out_dir} metrics_port="
        "{metrics_port} spans={spans}"
    ).format(**p)


def _fmt_flight_dump(p: dict) -> str:
    return "flight recorder dump ({trigger}) -> {path}".format(**p)


def _fmt_training_diverged(p: dict) -> str:
    return (
        "guardian: training diverged at step {step} ({reason}) after "
        "{rollbacks} rollback(s) — aborting the run"
    ).format(**p)


def _fmt_lock_order_violation(p: dict) -> str:
    return (
        "lockcheck: lock-order cycle closing edge {edge} in thread "
        "{thread} (held: {held})"
    ).format(**p)


def _fmt_held_lock_blocked_call(p: dict) -> str:
    return (
        "lockcheck: blocking call {call} while thread {thread} holds "
        "{held}"
    ).format(**p)


def _fmt_tenant_quota_exceeded(p: dict) -> str:
    return (
        "quota: tenant {tenant} over its admission budget at the "
        "{layer} layer — request rejected with Retry-After"
    ).format(**p)


def _fmt_tenant_quota_tightened(p: dict) -> str:
    return (
        "quota governor: tightening tenant {tenant} to {factor:.0%} of "
        "its configured rate (burn on slo {slo})"
    ).format(**p)


def _fmt_tenant_quota_restored(p: dict) -> str:
    return (
        "quota governor: tenant {tenant} restored to full rate "
        "(burn cleared on slo {slo})"
    ).format(**p)


def _fmt_slo_burn_start(p: dict) -> str:
    return (
        "slo {slo}: burn-rate alert START — {burn_fast:.1f}x over "
        "{fast_s:.0f}s and {burn_slow:.1f}x over {slow_s:.0f}s "
        "(budget remaining {budget_remaining:.1%})"
    ).format(**p)


def _fmt_slo_burn_stop(p: dict) -> str:
    return (
        "slo {slo}: burn-rate alert STOP after {active_s:.1f}s "
        "(budget remaining {budget_remaining:.1%})"
    ).format(**p)


def _fmt_fleet_scale_up(p: dict) -> str:
    return (
        "autoscaler: scale up {size} -> {target} ({reason})"
    ).format(**p)


def _fmt_deploy_candidate(p: dict) -> str:
    return (
        "deploy: candidate step {step} manifest "
        "{status} ({reason})"
    ).format(status="ok" if p.get("valid") else "REJECTED", **p)


def _fmt_deploy_shadow_start(p: dict) -> str:
    return (
        "deploy: step {step} entering shadow as generation {generation} "
        "(mirror rate {mirror_rate})"
    ).format(**p)


def _fmt_deploy_shadow_verdict(p: dict) -> str:
    return (
        "deploy: step {step} shadow verdict {verdict} ({reason}) — "
        "{mirrored} mirrored, {mismatched}/{compared} bitwise mismatches, "
        "{level_mismatch} level-mismatched, mAP live={map_live} "
        "shadow={map_shadow}, shadow SLO {slo}"
    ).format(slo="held" if p.get("slo_ok") else "VIOLATED", **p)


def _fmt_deploy_promote(p: dict) -> str:
    return (
        "deploy: step {step} PROMOTED generation {from_generation} -> "
        "{generation}; watching burn for {watch_window_s:.0f}s"
    ).format(**p)


def _fmt_deploy_reject(p: dict) -> str:
    return "deploy: step {step} rejected ({reason})".format(**p)


def _fmt_deploy_rollback(p: dict) -> str:
    return (
        "deploy: ROLLBACK {from_generation} -> {to_generation} "
        "(restores generation {restored_generation} weights; "
        "burn on slo {slo})"
    ).format(**p)


def _fmt_deploy_resume(p: dict) -> str:
    return (
        "deploy: journal recovery for step {step}: {action}"
    ).format(**p)


def _fmt_fleet_scale_down(p: dict) -> str:
    return (
        "autoscaler: scale down {size} -> {target} after {dwell} "
        "comfortable evaluation(s) ({reason})"
    ).format(**p)


def _fmt_fleet_replica_added(p: dict) -> str:
    return (
        "fleet: replica {replica} added (generation {generation})"
    ).format(**p)


def _fmt_fleet_replica_retired(p: dict) -> str:
    return (
        "fleet: replica {replica} retired after drain ({reason})"
    ).format(**p)


def _fmt_peer_suspect(p: dict) -> str:
    return (
        "gossip: peer {peer} suspect (incarnation {incarnation}, "
        "heartbeat {heartbeat})"
    ).format(**p)


def _fmt_peer_dead(p: dict) -> str:
    return (
        "gossip: peer {peer} dead (incarnation {incarnation}, "
        "heartbeat {heartbeat})"
    ).format(**p)


def _fmt_peer_alive(p: dict) -> str:
    return (
        "gossip: peer {peer} alive (incarnation {incarnation}, "
        "heartbeat {heartbeat}, was {was})"
    ).format(**p)


def _fmt_gateway_quarantine(p: dict) -> str:
    return "gateway: quarantining host {host}: {reason}".format(**p)


def _fmt_gateway_reinstate(p: dict) -> str:
    return (
        "gateway: host {host} reinstated (generation {generation})"
    ).format(**p)


def _fmt_gateway_weight_roll(p: dict) -> str:
    return (
        "gateway: weight roll -> generation {generation} "
        "({hosts}/{of} host(s) rolled)"
    ).format(**p)


# kind -> (logging level, payload -> line).  Level is the default; emit()
# callers cannot override the line, only the destination logger.
EVENTS: dict[str, tuple[int, Callable[[dict], str]]] = {
    # data service / cache
    "worker_death": (logging.WARNING, _fmt_worker_death),
    "worker_retired": (logging.ERROR, _fmt_worker_retired),
    "worker_wedged": (logging.WARNING, _fmt_worker_wedged),
    "service_fallback": (logging.ERROR, _fmt_service_fallback),
    "cache_quarantine": (logging.ERROR, _fmt_cache_quarantine),
    "shm_quarantine": (logging.ERROR, _fmt_shm_quarantine),
    "cache_evict": (logging.INFO, _fmt_cache_evict),
    # train loop / guardian
    "guardian_rollback": (logging.ERROR, _fmt_guardian_rollback),
    "rollback_restored": (logging.WARNING, _fmt_rollback_restored),
    "guardian_loss_spike": (logging.WARNING, _fmt_loss_spike),
    "checkpoint_saved": (logging.INFO, _fmt_ckpt_saved),
    "checkpoint_restored": (logging.INFO, _fmt_ckpt_restored),
    "preempt_drain": (logging.WARNING, _fmt_preempt),
    # serving engine / fleet
    "engine_dead": (logging.ERROR, _fmt_engine_dead),
    "engine_killed": (logging.WARNING, _fmt_engine_killed),
    "shed": (logging.DEBUG, _fmt_shed),
    "breaker_transition": (logging.INFO, _fmt_breaker),
    "ladder_transition": (logging.INFO, _fmt_ladder),
    "fleet_quarantine": (logging.WARNING, _fmt_fleet_quarantine),
    "fleet_reinstate": (logging.INFO, _fmt_fleet_reinstate),
    "fleet_retire": (logging.ERROR, _fmt_fleet_retire),
    "weight_swap": (logging.INFO, _fmt_weight_swap),
    "fleet_replica_added": (logging.INFO, _fmt_fleet_replica_added),
    "fleet_replica_retired": (logging.INFO, _fmt_fleet_replica_retired),
    # multi-tenancy (serve/tenancy.py, serve/fleet.py, serve/engine.py)
    "tenant_quota_exceeded": (logging.DEBUG, _fmt_tenant_quota_exceeded),
    "tenant_quota_tightened": (logging.WARNING, _fmt_tenant_quota_tightened),
    "tenant_quota_restored": (logging.INFO, _fmt_tenant_quota_restored),
    # control plane (mx_rcnn_tpu/ctrl/)
    "slo_burn_start": (logging.WARNING, _fmt_slo_burn_start),
    "slo_burn_stop": (logging.INFO, _fmt_slo_burn_stop),
    "fleet_scale_up": (logging.WARNING, _fmt_fleet_scale_up),
    "fleet_scale_down": (logging.INFO, _fmt_fleet_scale_down),
    # continuous deployment (ctrl/deploy.py)
    "deploy_candidate": (logging.INFO, _fmt_deploy_candidate),
    "deploy_shadow_start": (logging.INFO, _fmt_deploy_shadow_start),
    "deploy_shadow_verdict": (logging.INFO, _fmt_deploy_shadow_verdict),
    "deploy_promote": (logging.WARNING, _fmt_deploy_promote),
    "deploy_reject": (logging.WARNING, _fmt_deploy_reject),
    "deploy_rollback": (logging.ERROR, _fmt_deploy_rollback),
    "deploy_resume": (logging.WARNING, _fmt_deploy_resume),
    # cross-host fabric (serve/gossip.py, serve/gateway.py)
    "peer_suspect": (logging.WARNING, _fmt_peer_suspect),
    "peer_dead": (logging.ERROR, _fmt_peer_dead),
    "peer_alive": (logging.INFO, _fmt_peer_alive),
    "gateway_quarantine": (logging.WARNING, _fmt_gateway_quarantine),
    "gateway_reinstate": (logging.INFO, _fmt_gateway_reinstate),
    "gateway_weight_roll": (logging.INFO, _fmt_gateway_weight_roll),
    # train loop / guardian (terminal)
    "training_diverged": (logging.ERROR, _fmt_training_diverged),
    # plane-internal
    "metrics_flush": (logging.DEBUG, _fmt_metrics_flush),
    "configured": (logging.INFO, _fmt_configured),
    "flight_dump": (logging.WARNING, _fmt_flight_dump),
    # runtime lock-order sanitizer (mx_rcnn_tpu/analysis/lockcheck.py)
    "lock_order_violation": (logging.ERROR, _fmt_lock_order_violation),
    "held_lock_blocked_call": (logging.ERROR, _fmt_held_lock_blocked_call),
}


def render(subsystem: str, kind: str, payload: dict) -> tuple[int, str]:
    """(level, derived log line) for an event; open-vocabulary fallback."""
    entry = EVENTS.get(kind)
    if entry is None:
        return logging.INFO, f"{subsystem}: {kind} {payload}"
    level, fmt = entry
    try:
        return level, fmt(payload)
    except (KeyError, ValueError, IndexError) as e:
        # A malformed payload must never take down the emitting subsystem.
        return level, f"{subsystem}: {kind} {payload} (template error: {e})"
