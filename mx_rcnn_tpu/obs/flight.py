"""Flight recorder: a bounded in-memory ring of recent events + spans,
dumped to a postmortem artifact when something dies.

Every event emitted through the plane (configured or not) and every
finished span lands in the ring — a fixed-size ``collections.deque``,
so steady-state cost is one dict append and old entries fall off the
back.  On a trigger (engine watchdog fire, ``engine.kill``, fleet
replica retirement, guardian ``TrainingDiverged``, or an unhandled
exception via the installed crash handler) the ring is written out as
``flight_<trigger>_<pid>_<n>.json`` under the configured obs dir: the
last-N-things-that-happened record a human (or ``tools/obs_report.py``)
reads first in a postmortem.

Dumps are best-effort by design: the recorder must never turn a dying
process's last breath into a second crash.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import traceback
from typing import Optional

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Bounded ring + dump-on-trigger.  Thread-safe."""

    def __init__(self, size: int = 512) -> None:
        self._ring: collections.deque[dict] = collections.deque(maxlen=size)
        self._lock = threading.Lock()
        self._dumps = 0
        self.out_dir: Optional[str] = None
        self.run_id: str = "-"

    def record(self, entry: dict) -> None:
        with self._lock:
            self._ring.append(entry)

    def entries(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def dump(self, trigger: str, extra: Optional[dict] = None
             ) -> Optional[str]:
        """Write the ring to ``flight_<trigger>_<pid>_<n>.json``; returns
        the path, or None when no obs dir is configured (the ring is
        still intact for a later trigger).  Never raises."""
        try:
            out_dir = self.out_dir
            if not out_dir:
                return None
            with self._lock:
                entries = list(self._ring)
                n = self._dumps
                self._dumps += 1
            safe = "".join(
                c if (c.isalnum() or c in "-_") else "_" for c in trigger
            )
            path = os.path.join(
                out_dir, f"flight_{safe}_{os.getpid()}_{n}.json"
            )
            payload = {
                "run_id": self.run_id,
                "trigger": trigger,
                "ts": round(time.time(), 3),
                "ts_mono_ns": time.monotonic_ns(),
                "pid": os.getpid(),
                "entries": entries,
            }
            if extra:
                payload["extra"] = extra
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 - postmortems must not re-crash
            return None

    # -- crash handler -----------------------------------------------------

    def install_crash_handler(self) -> None:
        """Chain onto sys.excepthook + threading.excepthook: an unhandled
        exception dumps the ring (trigger "crash") before the normal
        traceback machinery runs."""
        import sys

        prev_hook = sys.excepthook
        prev_thread_hook = threading.excepthook

        def _dump_exc(exc_type, exc, tb, where: str) -> None:
            self.record({
                "type": "event", "subsystem": "crash",
                "kind": "unhandled_exception",
                "ts": round(time.time(), 3),
                "ts_mono_ns": time.monotonic_ns(),
                "payload": {
                    "where": where,
                    "exc_type": getattr(exc_type, "__name__", str(exc_type)),
                    "message": str(exc),
                    "traceback": "".join(
                        traceback.format_exception(exc_type, exc, tb)
                    )[-4000:],
                },
            })
            self.dump("crash")

        def hook(exc_type, exc, tb):
            _dump_exc(exc_type, exc, tb, "main")
            prev_hook(exc_type, exc, tb)

        def thread_hook(args):
            _dump_exc(
                args.exc_type, args.exc_value, args.exc_traceback,
                getattr(args.thread, "name", "thread"),
            )
            prev_thread_hook(args)

        sys.excepthook = hook
        threading.excepthook = thread_hook
