"""Crash-safe typed event journal (JSONL, one ``write(2)`` per record).

The journal is the one durable record of a run's lifecycle: every
checkpoint save/restore, guardian rollback, worker death, cache
quarantine, fleet quarantine/reinstate, weight swap and breaker flip
lands here as ONE appended line.  The write discipline is the proven
``quarantine_append`` pattern from ``data/cache.py``:

* the fd is opened ``O_WRONLY|O_CREAT|O_APPEND`` and each record is a
  SINGLE ``os.write`` of one newline-terminated JSON line — on a crash
  (SIGKILL included) at most the final line is torn, never an earlier
  one;
* the reader (:func:`read_journal`) skips unparseable lines, so a torn
  tail or a foreign line degrades to "one record lost", not "journal
  unreadable".

Records carry ``run_id``, wall-clock ``ts`` (epoch seconds, for
cross-process ordering), ``ts_mono_ns`` (monotonic, for intra-process
ordering and durations), a per-writer ``seq`` and ``pid``.  Multiple
processes may append to the same file: ``O_APPEND`` makes each line
atomic at these sizes on every filesystem we run on.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Iterator, Optional

__all__ = ["Journal", "read_journal"]


class Journal:
    """Append-only JSONL writer; thread-safe; crash-tears at most 1 line."""

    def __init__(self, path: str, run_id: str) -> None:
        self.path = path
        self.run_id = run_id
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fd: Optional[int] = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        self._lock = threading.Lock()
        self._seq = 0

    def write(self, record: dict) -> None:
        """Append one record.  Stamps run_id/ts/ts_mono_ns/seq/pid unless
        the caller already set them (replayed records keep their stamps)."""
        rec = dict(record)
        rec.setdefault("run_id", self.run_id)
        rec.setdefault("ts", round(time.time(), 3))
        rec.setdefault("ts_mono_ns", time.monotonic_ns())
        rec.setdefault("pid", os.getpid())
        with self._lock:
            if self._fd is None:
                return
            rec.setdefault("seq", self._seq)
            self._seq += 1
            line = json.dumps(rec, sort_keys=True, default=str) + "\n"
            os.write(self._fd, line.encode("utf-8"))

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _iter_lines(path: str) -> Iterator[str]:
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            yield from f
    except OSError:
        return


def read_journal(path: str) -> list[dict]:
    """Read every parseable record; torn/corrupt lines are skipped (the
    crash-safety contract: a kill mid-write loses at most that line)."""
    out: list[dict] = []
    for line in _iter_lines(path):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out
