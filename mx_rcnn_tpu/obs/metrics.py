"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Stdlib-only and host-side by construction (tpulint TPU007 keeps it out of
traced modules).  Everything is thread-safe: hot paths touch one
``threading.Lock`` per metric family and do integer/float arithmetic —
no allocation beyond the first observation of a label set.

Rendering follows the Prometheus text exposition format 0.0.4, so the
``/metrics`` endpoint (obs/endpoint.py) can be scraped by a stock
Prometheus server; :meth:`Registry.snapshot` produces the same data as a
JSON-able dict for the periodic journal flush (headless runs keep the
numbers even with no scraper attached).

Histograms use FIXED buckets chosen at creation: cumulative bucket
counts + ``_sum``/``_count``, which is exactly what p50/p99 recording
rules need.  The default buckets cover serving latencies from 1 ms to
60 s.
"""

from __future__ import annotations

import re
import threading
from typing import Iterable, Optional, Sequence

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "SnapshotWindow",
    "snapshot_delta", "parse_labels", "percentile_from_counts",
    "DEFAULT_LATENCY_BUCKETS_S",
]

# 1ms .. 60s, roughly log-spaced: serving device calls sit mid-range,
# queue waits at the bottom, rebuild-shadowed tails at the top.
DEFAULT_LATENCY_BUCKETS_S: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class _Metric:
    """Shared name/help/label-children plumbing for one metric family."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def _header(self) -> list[str]:
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        return out


class Counter(_Metric):
    """Monotonic counter, optionally labelled via ``inc(**labels)``."""

    kind = "counter"

    def __init__(self, name: str, help: str = "") -> None:
        super().__init__(name, help)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = self._header()
        for key, v in items or [((), 0.0)]:
            out.append(f"{self.name}{_label_str(key)} {v:g}")
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {_label_str(k) or "": v for k, v in self._values.items()}


class Gauge(_Metric):
    """Settable point-in-time value (queue depth, worker count, ...)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "") -> None:
        super().__init__(name, help)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = self._header()
        for key, v in items or [((), 0.0)]:
            out.append(f"{self.name}{_label_str(key)} {v:g}")
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {_label_str(k) or "": v for k, v in self._values.items()}


class Histogram(_Metric):
    """Fixed-bucket histogram (cumulative counts + sum/count per labels)."""

    kind = "histogram"

    def __init__(
        self, name: str, help: str = "",
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
    ) -> None:
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one finite bucket")
        # per label-key: ([per-bucket counts...], count, sum)
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [[0] * len(self.buckets), 0, 0.0]
            counts, _, _ = s
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            s[1] += 1
            s[2] += value

    def percentile(self, q: float, **labels) -> Optional[float]:
        """Bucket-upper-bound estimate of the q-quantile (0..1); None when
        the series is empty.  Good enough for journal flushes — Prometheus
        recording rules do the real interpolation server-side."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None or s[1] == 0:
                return None
            counts, total = list(s[0]), s[1]
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c:
                return self.buckets[i]
        return float("inf")

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(
                (k, (list(s[0]), s[1], s[2]))
                for k, s in self._series.items()
            )
        out = self._header()
        for key, (counts, count, total) in items:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                lk = _label_str(key + (("le", f"{b:g}"),))
                out.append(f"{self.name}_bucket{lk} {cum}")
            lk = _label_str(key + (("le", "+Inf"),))
            out.append(f"{self.name}_bucket{lk} {count}")
            out.append(f"{self.name}_sum{_label_str(key)} {total:g}")
            out.append(f"{self.name}_count{_label_str(key)} {count}")
        return out

    def snapshot(self) -> dict:
        out = {}
        with self._lock:
            items = [(k, (list(s[0]), s[1], s[2]))
                     for k, s in self._series.items()]
        for key, (counts, count, total) in items:
            out[_label_str(key) or ""] = {
                "count": count,
                "sum": total,
                "p50": self.percentile(0.50, **dict(key)),
                "p99": self.percentile(0.99, **dict(key)),
                # Raw per-bucket counts + upper bounds: what windowed
                # deltas (snapshot_delta) need to rebuild a percentile
                # over just the window, not the whole run.
                "le": list(self.buckets),
                "buckets": counts,
            }
        return out


class Registry:
    """Name -> metric family; idempotent getters create on first use."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "",
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
    ) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def families(self) -> Iterable[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        """Prometheus text exposition (0.0.4) of every family."""
        lines: list[str] = []
        for m in sorted(self.families(), key=lambda m: m.name):
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able {name: {labelstr: value|hist-summary}} for the
        periodic journal flush."""
        return {m.name: m.snapshot() for m in self.families()}


# ---------------------------------------------------------------------------
# Windowed snapshot deltas (burn-rate / autoscaler math without
# re-scraping Prometheus text)
# ---------------------------------------------------------------------------

_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def parse_labels(labelstr: str) -> dict:
    """``'{level="full",replica="0"}'`` -> ``{"level": "full", ...}``."""
    return dict(_LABEL_RE.findall(labelstr or ""))


def percentile_from_counts(
    le: Sequence[float], counts: Sequence[int], q: float
) -> Optional[float]:
    """Bucket-upper-bound q-quantile over raw (non-cumulative) bucket
    counts — same estimator as :meth:`Histogram.percentile`, usable on a
    windowed delta.  None when the counts are empty; +inf when the rank
    falls past the last finite bucket."""
    total = sum(counts)
    if total == 0:
        return None
    rank = q * total
    cum = 0
    for b, c in zip(le, counts):
        cum += c
        if cum >= rank and c:
            return b
    return float("inf")


def _series_delta(older, newer):
    """Delta of one series value (counter float or histogram summary).
    Counter resets (newer < older) clamp to the newer value, the usual
    rate() convention."""
    if isinstance(newer, dict):
        old = older if isinstance(older, dict) else {}
        oc = old.get("buckets") or []
        nc = newer.get("buckets") or []
        if len(oc) != len(nc):
            oc = [0] * len(nc)
        counts = [max(0, n - o) for n, o in zip(nc, oc)]
        le = newer.get("le") or []
        dcount = newer.get("count", 0) - old.get("count", 0)
        if dcount < 0:
            dcount, counts = newer.get("count", 0), list(nc)
        return {
            "count": dcount,
            "sum": newer.get("sum", 0.0) - old.get("sum", 0.0),
            "le": list(le),
            "buckets": counts,
            "p50": percentile_from_counts(le, counts, 0.50),
            "p99": percentile_from_counts(le, counts, 0.99),
        }
    new = float(newer)
    old = float(older) if isinstance(older, (int, float)) else 0.0
    return new if new < old else new - old


def snapshot_delta(older: dict, newer: dict) -> dict:
    """Per-series difference between two :meth:`Registry.snapshot`
    dicts: counters become increments over the interval, histogram
    summaries become windowed count/sum/buckets with percentiles
    recomputed over just the window.  Gauges are point-in-time, so a
    delta is meaningless — callers should read gauges from ``newer``
    directly; here they fall through the counter rule (delta of the
    stored value), which is still the honest interval change."""
    older = older or {}
    out: dict = {}
    for name, series in newer.items():
        old_series = older.get(name, {})
        out[name] = {
            label: _series_delta(old_series.get(label), value)
            for label, value in series.items()
        }
    return out


class SnapshotWindow:
    """Rolling ``(t, Registry.snapshot())`` pairs with rate/delta reads.

    The SLO engine and autoscaler (mx_rcnn_tpu/ctrl/) call
    :meth:`observe` once per evaluation period and read
    :meth:`delta_over` / :meth:`rate` instead of re-scraping the
    Prometheus text endpoint.  Thread-safe; bounded by ``horizon_s``
    (entries older than the horizon are dropped on observe).
    """

    def __init__(
        self,
        registry: Optional[Registry] = None,
        horizon_s: float = 4000.0,
    ) -> None:
        self._registry = registry
        self.horizon_s = float(horizon_s)
        self._lock = threading.Lock()
        self._entries: list[tuple[float, dict]] = []

    def observe(self, t: float, snapshot: Optional[dict] = None) -> dict:
        """Record one snapshot at time ``t`` (monotonic or epoch — any
        clock, as long as it is THE clock for this window).  Taken from
        the attached registry when not given."""
        if snapshot is None:
            if self._registry is None:
                raise ValueError("no snapshot given and no registry attached")
            snapshot = self._registry.snapshot()
        with self._lock:
            self._entries.append((float(t), snapshot))
            floor = float(t) - self.horizon_s
            while len(self._entries) > 1 and self._entries[0][0] < floor:
                self._entries.pop(0)
        return snapshot

    def latest(self) -> Optional[tuple[float, dict]]:
        with self._lock:
            return self._entries[-1] if self._entries else None

    def span_s(self) -> float:
        """Seconds between the oldest and newest recorded snapshots."""
        with self._lock:
            if len(self._entries) < 2:
                return 0.0
            return self._entries[-1][0] - self._entries[0][0]

    def delta_over(self, window_s: float) -> tuple[float, dict]:
        """(actual seconds covered, snapshot_delta) between the newest
        entry and the newest entry at least ``window_s`` older — or the
        oldest available when the window has not filled yet.  ``(0.0,
        {})`` with fewer than two entries."""
        with self._lock:
            if len(self._entries) < 2:
                return 0.0, {}
            t_new, newest = self._entries[-1]
            base = self._entries[0]
            for entry in reversed(self._entries[:-1]):
                if t_new - entry[0] >= window_s:
                    base = entry
                    break
            t_old, oldest = base
        return t_new - t_old, snapshot_delta(oldest, newest)

    def rate(self, name: str, label: str = "",
             window_s: float = 60.0) -> Optional[float]:
        """Per-second increase of counter ``name``/``label`` over the
        last ``window_s`` (labels summed when ``label`` is "" and the
        series is labelled).  None before two snapshots exist."""
        dt, delta = self.delta_over(window_s)
        if dt <= 0:
            return None
        series = delta.get(name)
        if not series:
            return 0.0
        if label in series and not isinstance(series[label], dict):
            return series[label] / dt
        total = sum(
            v for v in series.values() if isinstance(v, (int, float))
        )
        return total / dt
