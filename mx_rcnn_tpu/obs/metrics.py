"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Stdlib-only and host-side by construction (tpulint TPU007 keeps it out of
traced modules).  Everything is thread-safe: hot paths touch one
``threading.Lock`` per metric family and do integer/float arithmetic —
no allocation beyond the first observation of a label set.

Rendering follows the Prometheus text exposition format 0.0.4, so the
``/metrics`` endpoint (obs/endpoint.py) can be scraped by a stock
Prometheus server; :meth:`Registry.snapshot` produces the same data as a
JSON-able dict for the periodic journal flush (headless runs keep the
numbers even with no scraper attached).

Histograms use FIXED buckets chosen at creation: cumulative bucket
counts + ``_sum``/``_count``, which is exactly what p50/p99 recording
rules need.  The default buckets cover serving latencies from 1 ms to
60 s.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional, Sequence

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "DEFAULT_LATENCY_BUCKETS_S",
]

# 1ms .. 60s, roughly log-spaced: serving device calls sit mid-range,
# queue waits at the bottom, rebuild-shadowed tails at the top.
DEFAULT_LATENCY_BUCKETS_S: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class _Metric:
    """Shared name/help/label-children plumbing for one metric family."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def _header(self) -> list[str]:
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        return out


class Counter(_Metric):
    """Monotonic counter, optionally labelled via ``inc(**labels)``."""

    kind = "counter"

    def __init__(self, name: str, help: str = "") -> None:
        super().__init__(name, help)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = self._header()
        for key, v in items or [((), 0.0)]:
            out.append(f"{self.name}{_label_str(key)} {v:g}")
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {_label_str(k) or "": v for k, v in self._values.items()}


class Gauge(_Metric):
    """Settable point-in-time value (queue depth, worker count, ...)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "") -> None:
        super().__init__(name, help)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = self._header()
        for key, v in items or [((), 0.0)]:
            out.append(f"{self.name}{_label_str(key)} {v:g}")
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {_label_str(k) or "": v for k, v in self._values.items()}


class Histogram(_Metric):
    """Fixed-bucket histogram (cumulative counts + sum/count per labels)."""

    kind = "histogram"

    def __init__(
        self, name: str, help: str = "",
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
    ) -> None:
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one finite bucket")
        # per label-key: ([per-bucket counts...], count, sum)
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [[0] * len(self.buckets), 0, 0.0]
            counts, _, _ = s
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            s[1] += 1
            s[2] += value

    def percentile(self, q: float, **labels) -> Optional[float]:
        """Bucket-upper-bound estimate of the q-quantile (0..1); None when
        the series is empty.  Good enough for journal flushes — Prometheus
        recording rules do the real interpolation server-side."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None or s[1] == 0:
                return None
            counts, total = list(s[0]), s[1]
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c:
                return self.buckets[i]
        return float("inf")

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(
                (k, (list(s[0]), s[1], s[2]))
                for k, s in self._series.items()
            )
        out = self._header()
        for key, (counts, count, total) in items:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                lk = _label_str(key + (("le", f"{b:g}"),))
                out.append(f"{self.name}_bucket{lk} {cum}")
            lk = _label_str(key + (("le", "+Inf"),))
            out.append(f"{self.name}_bucket{lk} {count}")
            out.append(f"{self.name}_sum{_label_str(key)} {total:g}")
            out.append(f"{self.name}_count{_label_str(key)} {count}")
        return out

    def snapshot(self) -> dict:
        out = {}
        with self._lock:
            items = list(self._series.items())
        for key, (counts, count, total) in items:
            out[_label_str(key) or ""] = {
                "count": count,
                "sum": total,
                "p50": self.percentile(0.50, **dict(key)),
                "p99": self.percentile(0.99, **dict(key)),
            }
        return out


class Registry:
    """Name -> metric family; idempotent getters create on first use."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "",
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
    ) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def families(self) -> Iterable[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        """Prometheus text exposition (0.0.4) of every family."""
        lines: list[str] = []
        for m in sorted(self.families(), key=lambda m: m.name):
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able {name: {labelstr: value|hist-summary}} for the
        periodic journal flush."""
        return {m.name: m.snapshot() for m in self.families()}
