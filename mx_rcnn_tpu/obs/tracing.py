"""Request/step span tracing -> Chrome-trace (Perfetto-loadable) events.

Spans are explicit host-side begin/end windows with ids:

* ``trace_id``   — one per request (or train step); hedged fleet
  attempts share their request's trace_id, so the whole request tree is
  one query away.
* ``span_id`` / ``parent_id`` — parent/child integrity (an attempt span
  is a child of the fleet request span; the engine's queue/device spans
  are children of the attempt).

Finished spans are appended to ``spans.jsonl`` — one Chrome-trace
complete event (``"ph": "X"``, ts/dur in microseconds) per line, via the
same single-``write(2)`` crash-safe discipline as the journal.  Load a
run in Perfetto/chrome://tracing by wrapping the lines in a JSON array
(``tools/obs_report.py`` emits exactly that), where they sit beside the
``jax.profiler`` XPlane dumps from ``utils/profiling.py``.

When the plane is unconfigured, spans still flow into the in-memory
flight ring (cheap dict append) so a crash dump carries the last
requests' timings even if nobody asked for a trace file.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Callable, Optional

__all__ = ["Span", "Tracer", "new_trace_id"]


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One explicit begin/end window.  Context-manager or manual end()."""

    __slots__ = (
        "name", "subsystem", "trace_id", "span_id", "parent_id",
        "attrs", "_t0_ns", "dur_ns", "_tracer", "_ended", "_ts_wall",
    )

    def __init__(self, tracer: "Tracer", name: str, subsystem: str,
                 trace_id: Optional[str], parent_id: Optional[str],
                 attrs: Optional[dict]) -> None:
        self.name = name
        self.subsystem = subsystem
        self.trace_id = trace_id or new_trace_id()
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.attrs = dict(attrs or {})
        self._tracer = tracer
        self._t0_ns = time.monotonic_ns()
        self._ts_wall = round(time.time(), 3)
        self.dur_ns = 0
        self._ended = False

    def child(self, name: str, attrs: Optional[dict] = None) -> "Span":
        return self._tracer.span(
            name, subsystem=self.subsystem, trace_id=self.trace_id,
            parent_id=self.span_id, attrs=attrs,
        )

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def end(self, **attrs) -> None:
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        self.dur_ns = time.monotonic_ns() - self._t0_ns
        self._tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.end()

    def to_chrome(self) -> dict:
        """Chrome-trace "complete" event; ts/dur in microseconds on the
        process monotonic clock (one timeline per pid)."""
        return {
            "ph": "X",
            "name": self.name,
            "cat": self.subsystem,
            "ts": self._t0_ns / 1e3,
            "dur": self.dur_ns / 1e3,
            "pid": os.getpid(),
            "tid": threading.get_ident() % 2**31,
            "args": {
                "trace_id": self.trace_id,
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                "ts_wall": self._ts_wall,
                **self.attrs,
            },
        }


class Tracer:
    """Span factory; routes finished spans to a sink (plane-installed)."""

    def __init__(self, sink: Optional[Callable[[Span], None]] = None) -> None:
        self._sink = sink

    def set_sink(self, sink: Optional[Callable[[Span], None]]) -> None:
        self._sink = sink

    def span(self, name: str, *, subsystem: str = "app",
             trace_id: Optional[str] = None,
             parent_id: Optional[str] = None,
             attrs: Optional[dict] = None) -> Span:
        return Span(self, name, subsystem, trace_id, parent_id, attrs)

    def _finish(self, span: Span) -> None:
        sink = self._sink
        if sink is not None:
            try:
                sink(span)
            except Exception:  # noqa: BLE001 - tracing must never throw up
                pass
