from mx_rcnn_tpu.ops.nms import batched_nms, nms_mask
from mx_rcnn_tpu.ops.roi_align import roi_align, multilevel_roi_align
from mx_rcnn_tpu.ops.proposals import generate_proposals
from mx_rcnn_tpu.ops.sampling import sample_rois, assign_anchors
from mx_rcnn_tpu.ops.topk import hierarchical_top_k

__all__ = [
    "batched_nms",
    "nms_mask",
    "roi_align",
    "multilevel_roi_align",
    "generate_proposals",
    "sample_rois",
    "assign_anchors",
    "hierarchical_top_k",
]
