"""Static-shape non-maximum suppression, fully in-graph.

TPU-native replacement for the reference's three NMS backends
(``rcnn/processing/nms.py``: py_nms / cpu_nms / gpu_nms and the CUDA
bitmask kernel ``rcnn/cython/nms_kernel.cu``).  The reference runs NMS on
the host (or a CUDA kernel) with a device round-trip inside the Proposal
custom op; here NMS stays inside the jitted step.

Algorithm: score-sort, build the O(N^2) IoU "suppression" matrix (strictly
upper-triangular: an earlier box can suppress a later one), then iterate

    keep[i] <- not OR_{j<i} (keep[j] AND iou[j, i] > thresh)

to a fixed point with ``lax.while_loop``.  Any fixed point of this map is
exactly the greedy-NMS solution (induction over i), and the iteration
finalizes at least one undecided box per sweep, so it terminates in at most
N sweeps — in practice a handful, each an O(N^2) VPU-friendly masked
reduction, with no host sync and no dynamic shapes.

Measured alternative (v5e, honest chained timing): a Detectron-style
64-box blocked-greedy lax.scan has a FIXED O(N^2/B) cost, but its ~2N/B
sequential tiny steps serialize poorly on TPU — 116 ms vs this
implementation's 91 ms even on the adversarial case (2000 iid random
boxes, where the sweep count is worst-case), and it loses ~0.8 img/s on
the full train-step bench (where RPN's score-sorted boxes converge in a
few sweeps).  The data-dependent sweep count is the better trade here.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from mx_rcnn_tpu.geometry import iou_matrix, snap


def nms_mask(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float,
    valid: jnp.ndarray | None = None,
    sweep_cap: int = 0,
) -> jnp.ndarray:
    """Greedy NMS as a boolean keep-mask in *input* order.

    Args:
      boxes: (N, 4).
      scores: (N,) — padded/invalid entries should carry ``-inf`` or use
        ``valid``.
      iou_threshold: suppression threshold (reference default 0.7 for RPN
        proposals, 0.3 at test time).
      valid: optional (N,) bool; invalid entries never keep nor suppress.
      sweep_cap: 0 (default) iterates the fixed point to convergence —
        exact greedy NMS.  > 0 bounds the while_loop to that many sweeps:
        each sweep finalizes at least one undecided box, so any cap >= N
        is still exact, and score-sorted RPN boxes converge in a handful
        of sweeps regardless; a small cap trades exactness on adversarial
        inputs for a hard latency bound (the batched per-level lane then
        pays a bounded worst case instead of the slowest lane's
        data-dependent sweep count).  Opt-in via ``RPNConfig.nms_sweep_cap``.

    Returns:
      (N,) bool keep mask.
    """
    n = boxes.shape[0]
    if valid is None:
        valid = jnp.isfinite(scores)
    else:
        valid = valid & jnp.isfinite(scores)

    order = jnp.argsort(-scores)  # descending; stable for ties
    sboxes = jnp.take(boxes, order, axis=0)
    svalid = jnp.take(valid, order)

    # snap(): the > threshold suppression decision must not flip on
    # cross-compilation ulp noise (see geometry.boxes.snap); one flipped
    # suppression cascades through the whole greedy chain.
    iou = snap(iou_matrix(sboxes, sboxes))
    upper = jnp.triu(jnp.ones((n, n), dtype=bool), k=1)
    suppress = (iou > iou_threshold) & upper & svalid[:, None] & svalid[None, :]

    if sweep_cap and sweep_cap > 0:
        # Bounded variant: identical iteration, with a sweep counter in
        # the carry.  Convergence before the cap gives the exact greedy
        # fixed point; hitting the cap returns the current iterate.
        def cond(state):
            keep, prev, it = state
            return jnp.any(keep != prev) & (it < sweep_cap)

        def body(state):
            keep, _, it = state
            new_keep = svalid & ~jnp.any(suppress & keep[:, None], axis=0)
            return new_keep, keep, it + 1

        init = (svalid, jnp.zeros(n, dtype=bool), jnp.asarray(0, jnp.int32))
        keep_sorted, _, _ = lax.while_loop(cond, body, init)
    else:
        def cond(state):
            keep, prev = state
            return jnp.any(keep != prev)

        def body(state):
            keep, _ = state
            new_keep = svalid & ~jnp.any(suppress & keep[:, None], axis=0)
            return new_keep, keep

        init = (svalid, jnp.zeros(n, dtype=bool))
        keep_sorted, _ = lax.while_loop(cond, body, init)

    return jnp.zeros(n, dtype=bool).at[order].set(keep_sorted)


def rank_keep(keep: jnp.ndarray, scores: jnp.ndarray, max_outputs: int):
    """Rank a keep mask by score into up to ``max_outputs`` indices.

    The back half of :func:`nms_indices`, shared with the fused middle
    (``ops/pallas/middle.py`` computes the keep mask in-kernel and hands
    it here): kept entries first, best score first, padded slots index 0
    with ``out_valid`` False.
    """
    n = keep.shape[0]
    neg = jnp.where(keep, -scores, jnp.inf)
    order = jnp.argsort(neg)  # kept entries first, best score first
    k = min(n, max_outputs)
    idx = order[:k]
    kept = jnp.take(keep, idx)
    if k < max_outputs:
        pad = max_outputs - k
        idx = jnp.concatenate([idx, jnp.zeros(pad, idx.dtype)])
        kept = jnp.concatenate([kept, jnp.zeros(pad, bool)])
    out_valid = kept & (jnp.arange(max_outputs) < jnp.sum(keep))
    return jnp.where(out_valid, idx, 0), out_valid


@partial(
    jax.jit,
    static_argnums=(2, 3),
    static_argnames=("sweep_cap", "nms_impl", "interpret"),
)
def nms_indices(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float,
    max_outputs: int,
    valid: jnp.ndarray | None = None,
    sweep_cap: int = 0,
    nms_impl: str = "xla",
    interpret: bool = False,
):
    """NMS returning up to ``max_outputs`` kept indices, score-descending.

    Static output shape: ``(indices (max_outputs,), out_valid (max_outputs,))``.
    Padded slots hold index 0 with ``out_valid`` False — the static-shape
    replacement for the reference Proposal op's pad-with-repeats
    (``rcnn/symbol/proposal.py`` pads rois to RPN_POST_NMS_TOP_N).

    ``nms_impl`` selects the keep-mask backend: ``"xla"`` (default) is the
    batched while-loop fixed point above; ``"pallas"`` routes through the
    VMEM-resident greedy sweep (``ops/pallas/nms.py::nms_mask_pallas``,
    bit-identical keep bits — it snaps IoU on the same 2**-16 grid before
    the threshold compare).  The pallas sweep is always-exact greedy, so
    ``sweep_cap`` does not apply to it (the cap exists to bound the XLA
    fixed point's data-dependent sweep count).  ``interpret`` runs the
    pallas kernel in interpret mode (CPU CI).
    """
    if nms_impl == "pallas":
        from mx_rcnn_tpu.ops.pallas.nms import nms_mask_pallas

        keep = nms_mask_pallas(
            boxes, scores, iou_threshold, valid, interpret=interpret
        )
    elif nms_impl == "xla":
        keep = nms_mask(
            boxes, scores, iou_threshold, valid, sweep_cap=sweep_cap
        )
    else:
        raise ValueError(f"nms_impl must be 'xla' or 'pallas', got {nms_impl!r}")
    return rank_keep(keep, scores, max_outputs)


def batched_nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    classes: jnp.ndarray,
    iou_threshold: float,
    valid: jnp.ndarray | None = None,
    sweep_cap: int = 0,
) -> jnp.ndarray:
    """Per-class NMS in one shot via the coordinate-offset trick.

    Boxes of different classes are translated to disjoint regions so they
    can never overlap; one NMS pass then equals independent per-class NMS.
    Replaces the reference's per-class python loop in
    ``rcnn/core/tester.py::pred_eval``.
    """
    span = jnp.max(boxes) - jnp.min(boxes) + 1.0
    offset = classes.astype(boxes.dtype)[:, None] * span
    return nms_mask(boxes + offset, scores, iou_threshold, valid,
                    sweep_cap=sweep_cap)
