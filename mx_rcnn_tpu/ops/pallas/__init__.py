"""Pallas TPU kernels — the performance path for the detection hot ops.

Each kernel has a pure-XLA reference implementation in :mod:`mx_rcnn_tpu.ops`
(the correctness oracle, SURVEY.md §5: Pallas kernels validated vs XLA
reference impls in tests).  Kernels run in interpret mode on CPU, so the
same tests cover both backends.
"""

from mx_rcnn_tpu.ops.pallas.nms import nms_mask_pallas
from mx_rcnn_tpu.ops.pallas.roi_align import (
    multilevel_roi_align_fast,
    multilevel_roi_align_pallas,
)

__all__ = [
    "multilevel_roi_align_fast",
    "multilevel_roi_align_pallas",
    "nms_mask_pallas",
]
