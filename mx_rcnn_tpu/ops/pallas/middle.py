"""Pallas TPU fused proposal middle: decode -> clip -> snap -> NMS in VMEM.

The proposal "middle" — everything between the RPN head's raw outputs and
the ranked roi set — historically ran as a string of small XLA programs
(``ops/proposals.py`` decode/clip, ``geometry/boxes.py`` snapping,
``ops/nms.py`` suppression), each round-tripping its (k, 4)/(k,) operands
through HBM.  This kernel keeps the per-level candidate tiles VMEM-resident
across the whole chain: one launch per proposal call (grid over FPN
levels) reads the gathered (anchors, deltas, scores) rows and writes
decoded/clipped/snapped boxes, masked scores, and the greedy-NMS keep mask.

Exactness contract (asserted bitwise in tests/test_fused_middle.py):

- Decode/clip replicate ``geometry.boxes.decode_boxes``/``clip_boxes`` to
  the operation (weights (1,1,1,1), modern width convention, the same
  ``BBOX_XFORM_CLIP`` bound), and the results ride the same 1/256-px
  coordinate snap the dense path applies — so the few ulps any backend
  reassociation could introduce round away exactly as they do there.
- IoU uses ``geometry.boxes.iou_matrix``'s formula (clamped areas,
  zero-union guard) snapped on the 2**-16 grid before the threshold
  compare, matching ``ops/nms.py::nms_mask``.
- NMS runs greedily in POSITIONAL order.  That equals the oracle's
  argsort order bit-for-bit because the kernel's inputs come from top-k:
  scores are positionally descending with index-ascending tie-breaks, so
  the oracle's stable ``argsort(-scores)`` is the identity on valid lanes,
  and ``-inf`` lanes (min-size-rejected or padding) neither keep nor
  suppress under either order.

The top-k front half stays in XLA (``ops/topk.py``'s blocked reduction is
already one fused program) — the kernel takes over exactly where the HBM
round-trips began.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mx_rcnn_tpu.geometry.boxes import BBOX_XFORM_CLIP


def _snap(x, bits: int):
    """In-kernel twin of geometry.boxes.snap (power-of-two grid round)."""
    scale = 2.0 ** bits
    return jnp.round(x * scale) * (1.0 / scale)


def _middle_kernel(data_ref, hw_ref, out_ref, *, n: int,
                   min_size: float, thresh: float):
    # data rows: 0-3 anchors (x1, y1, x2, y2); 4-7 deltas (dx, dy, dw, dh);
    # 8 snapped top-k scores; 9-15 zero pad.  Everything (1, N) f32.
    ax1 = data_ref[0, 0:1, :]
    ay1 = data_ref[0, 1:2, :]
    ax2 = data_ref[0, 2:3, :]
    ay2 = data_ref[0, 3:4, :]
    d_x = data_ref[0, 4:5, :]
    d_y = data_ref[0, 5:6, :]
    d_w = data_ref[0, 6:7, :]
    d_h = data_ref[0, 7:8, :]
    score = data_ref[0, 8:9, :]
    img_h = hw_ref[0, 0]
    img_w = hw_ref[0, 1]

    # decode_boxes (weights (1,1,1,1), modern convention).
    aw = ax2 - ax1
    ah = ay2 - ay1
    acx = ax1 + 0.5 * aw
    acy = ay1 + 0.5 * ah
    dw = jnp.minimum(d_w, BBOX_XFORM_CLIP)
    dh = jnp.minimum(d_h, BBOX_XFORM_CLIP)
    cx = d_x * aw + acx
    cy = d_y * ah + acy
    bw = jnp.exp(dw) * aw
    bh = jnp.exp(dh) * ah
    x1 = cx - 0.5 * bw
    y1 = cy - 0.5 * bh
    x2 = cx + 0.5 * bw
    y2 = cy + 0.5 * bh

    # clip_boxes + the dense path's 1/256-px coordinate snap.
    x1 = _snap(jnp.clip(x1, 0.0, img_w), 8)
    y1 = _snap(jnp.clip(y1, 0.0, img_h), 8)
    x2 = _snap(jnp.clip(x2, 0.0, img_w), 8)
    y2 = _snap(jnp.clip(y2, 0.0, img_h), 8)

    # valid_box_mask + score masking (ops/proposals.py::_pre_nms_candidates).
    w = x2 - x1
    h = y2 - y1
    if min_size <= 0.0:
        ok = (w > 0.0) & (h > 0.0)
    else:
        ok = (w >= min_size) & (h >= min_size)
    masked = jnp.where(ok, score, -jnp.inf)
    valid = ok & jnp.isfinite(score)

    # Greedy NMS in positional (= score) order; same recurrence as
    # ops/pallas/nms.py::_nms_kernel.  Scalars come out by masked
    # reduction (no dynamic lane extraction in Mosaic); alive is f32
    # 1.0/0.0 (i1 carries don't legalize through scf.for).
    area = jnp.maximum(w, 0.0) * jnp.maximum(h, 0.0)
    col = lax.broadcasted_iota(jnp.int32, (1, n), 1)

    def body(i, alive):
        sel = (col == i).astype(jnp.float32)
        bx1 = jnp.sum(x1 * sel)
        by1 = jnp.sum(y1 * sel)
        bx2 = jnp.sum(x2 * sel)
        by2 = jnp.sum(y2 * sel)
        b_area = jnp.sum(area * sel)
        ai = jnp.sum(alive * sel)

        iw = jnp.maximum(jnp.minimum(x2, bx2) - jnp.maximum(x1, bx1), 0.0)
        ih = jnp.maximum(jnp.minimum(y2, by2) - jnp.maximum(y1, by1), 0.0)
        inter = iw * ih
        union = area + b_area - inter
        iou = jnp.where(
            union > 0.0, inter / jnp.where(union > 0.0, union, 1.0), 0.0
        )
        # The oracle compares snap(iou) > thresh — identical grid here.
        iou = _snap(iou, 16)
        suppress = jnp.where((iou > thresh) & (col > i), ai, 0.0)
        return alive * (1.0 - suppress)

    alive = lax.fori_loop(0, n, body, valid.astype(jnp.float32))

    out_ref[0, 0:1, :] = x1
    out_ref[0, 1:2, :] = y1
    out_ref[0, 2:3, :] = x2
    out_ref[0, 3:4, :] = y2
    out_ref[0, 4:5, :] = masked
    out_ref[0, 5:6, :] = alive
    out_ref[0, 6:7, :] = jnp.zeros((1, n), jnp.float32)
    out_ref[0, 7:8, :] = jnp.zeros((1, n), jnp.float32)


@functools.partial(
    jax.jit,
    static_argnames=("min_size", "iou_threshold", "interpret"),
)
def fused_middle_levels(
    anchors: jnp.ndarray,
    deltas: jnp.ndarray,
    scores: jnp.ndarray,
    image_height,
    image_width,
    min_size: float = 0.0,
    iou_threshold: float = 0.7,
    interpret: bool = False,
):
    """Run the fused middle over stacked per-level top-k candidates.

    Args:
      anchors: (L, k, 4) gathered anchor boxes in top-k score order
        (zero rows on lanes past a level's true k).
      deltas: (L, k, 4) gathered RPN deltas (zero rows on pad lanes).
      scores: (L, k) snapped top-k scores, ``-inf`` on pad lanes.
      image_height / image_width: true image extent (may be traced).
      min_size / iou_threshold: RPNConfig.min_size / nms_threshold.
      interpret: run the kernel in interpret mode (CPU CI).

    Returns:
      (boxes (L, k, 4), masked_scores (L, k), keep (L, k) bool) — the
      decoded/clipped/snapped candidates, their ``-inf``-masked scores,
      and the greedy-NMS keep mask, each bit-identical to the dense path
      through ``_pre_nms_candidates`` + ``nms_mask``.
    """
    lvls, k = scores.shape
    n_pad = -(-k // 128) * 128
    pad = n_pad - k
    if pad:
        anchors = jnp.pad(anchors, ((0, 0), (0, pad), (0, 0)))
        deltas = jnp.pad(deltas, ((0, 0), (0, pad), (0, 0)))
        scores = jnp.pad(scores, ((0, 0), (0, pad)),
                         constant_values=-jnp.inf)

    # (L, 16, N): anchor rows, delta rows, score row, zero pad rows —
    # one contiguous VMEM block per level.
    data = jnp.concatenate(
        [
            jnp.swapaxes(anchors, 1, 2),                    # (L, 4, N)
            jnp.swapaxes(deltas, 1, 2),                     # (L, 4, N)
            scores[:, None, :],                             # (L, 1, N)
            jnp.zeros((lvls, 7, n_pad), jnp.float32),       # (L, 7, N)
        ],
        axis=1,
    ).astype(jnp.float32)
    hw = jnp.stack(
        [jnp.asarray(image_height, jnp.float32),
         jnp.asarray(image_width, jnp.float32)]
    ).reshape(1, 2)

    out = pl.pallas_call(
        functools.partial(
            _middle_kernel,
            n=n_pad,
            # Static kwargs (static_argnames above) — plain Python floats
            # at trace time, never tracers.
            min_size=min_size,
            thresh=iou_threshold,
        ),
        grid=(lvls,),
        in_specs=[
            pl.BlockSpec((1, 16, n_pad), lambda l: (l, 0, 0)),
            pl.BlockSpec((1, 2), lambda l: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, 8, n_pad), lambda l: (l, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((lvls, 8, n_pad), jnp.float32),
        interpret=interpret,
    )(data, hw)

    boxes = jnp.swapaxes(out[:, 0:4, :k], 1, 2)             # (L, k, 4)
    masked_scores = out[:, 4, :k]                           # (L, k)
    keep = out[:, 5, :k] > 0.0                              # (L, k)
    return boxes, masked_scores, keep
