"""Pallas TPU greedy NMS.

Replaces the reference's CUDA bitmask kernel (``rcnn/cython/nms_kernel.cu``
— the repo's only hand-written GPU kernel, SURVEY.md §3.5) inside the
jitted step.  The XLA fallback (:func:`mx_rcnn_tpu.ops.nms.nms_mask`)
materializes the full N×N IoU matrix in HBM and sweeps it to a fixed point
(O(sweeps·N²) HBM traffic); this kernel keeps everything VMEM-resident and
does the exact greedy recurrence in one pass:

    for i in score order:  alive[j>i] &= ~(alive[i] & iou(i, j) > t)

Per iteration it extracts box i's scalars by masked reduction and does one
N-wide VPU suppression update — O(N) VMEM traffic per step, no HBM round
trips, and bit-identical keep decisions to the greedy definition.

Measured on a v5e at N=2000: 9.7ms vs the XLA path's 2.3ms — the XLA
fixed-point converges in a handful of N² sweeps while this kernel always
pays N sequential iterations, so **the XLA implementation remains the
production path**; this kernel is kept as the latency-predictable
alternative (worst-case XLA sweeps = suppression-chain depth) and as the
in-graph replacement story for the reference's CUDA bitmask kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _nms_kernel(data_ref, keep_ref, *, n: int, thresh: float):
    x1 = data_ref[0:1, :]     # (1, N)
    y1 = data_ref[1:2, :]
    x2 = data_ref[2:3, :]
    y2 = data_ref[3:4, :]
    areas = data_ref[4:5, :]
    valid = data_ref[5:6, :] > 0.0

    col = lax.broadcasted_iota(jnp.int32, (1, n), 1)

    def body(i, alive):  # alive: (1, N) float32 1.0/0.0 (i1 carries don't
        # legalize through Mosaic's scf.for).  All per-box scalars come out
        # as masked reductions — Mosaic has neither dynamic lane extraction
        # from vectors nor room in SMEM for an N-row scalar table.
        sel = (col == i).astype(jnp.float32)
        bx1 = jnp.sum(x1 * sel)
        by1 = jnp.sum(y1 * sel)
        bx2 = jnp.sum(x2 * sel)
        by2 = jnp.sum(y2 * sel)
        b_area = (bx2 - bx1) * (by2 - by1)
        ai = jnp.sum(alive * sel)

        iw = jnp.maximum(jnp.minimum(x2, bx2) - jnp.maximum(x1, bx1), 0.0)
        ih = jnp.maximum(jnp.minimum(y2, by2) - jnp.maximum(y1, by1), 0.0)
        inter = iw * ih
        union = areas + b_area - inter
        iou = jnp.where(union > 0.0, inter / jnp.where(union > 0.0, union, 1.0), 0.0)
        # Same 2**-16 IoU snap as the XLA oracle (ops/nms.py::nms_mask):
        # the > threshold compare must make the identical decision on both
        # backends, including inputs sitting ulps from the threshold.
        iou = jnp.round(iou * 65536.0) * (1.0 / 65536.0)

        suppress = jnp.where((iou > thresh) & (col > i), ai, 0.0)
        return alive * (1.0 - suppress)

    alive = lax.fori_loop(0, n, body, valid.astype(jnp.float32))
    keep_ref[:, :] = (alive > 0.0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("iou_threshold", "interpret"))
def nms_mask_pallas(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float,
    valid: jnp.ndarray | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in for :func:`mx_rcnn_tpu.ops.nms.nms_mask` (same contract:
    keep mask in input order; invalid/-inf entries neither keep nor
    suppress).  Pads N to a lane multiple internally."""
    n = boxes.shape[0]
    if valid is None:
        valid = jnp.isfinite(scores)
    else:
        valid = valid & jnp.isfinite(scores)

    order = jnp.argsort(-scores)
    sboxes = jnp.take(boxes, order, axis=0)
    svalid = jnp.take(valid, order)

    n_pad = -(-n // 128) * 128
    pad = n_pad - n
    if pad:
        sboxes = jnp.concatenate([sboxes, jnp.zeros((pad, 4), sboxes.dtype)])
        svalid = jnp.concatenate([svalid, jnp.zeros(pad, bool)])

    area = (sboxes[:, 2] - sboxes[:, 0]) * (sboxes[:, 3] - sboxes[:, 1])
    data = jnp.stack(
        [sboxes[:, 0], sboxes[:, 1], sboxes[:, 2], sboxes[:, 3],
         area, svalid.astype(sboxes.dtype),
         jnp.zeros(n_pad, sboxes.dtype), jnp.zeros(n_pad, sboxes.dtype)],
    ).astype(jnp.float32)                               # (8, N)

    keep_sorted = pl.pallas_call(
        functools.partial(_nms_kernel, n=n_pad, thresh=float(iou_threshold)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        interpret=interpret,
    )(data)[0, :n] > 0

    return jnp.zeros(n, dtype=bool).at[order].set(keep_sorted)
