"""Pallas TPU ROIAlign over an FPN pyramid.

The TPU-native replacement for the reference's engine-side ROIPooling CUDA
kernel (``mx.symbol.ROIPooling``; SURVEY.md §3.5 "engine-side native ops"),
upgraded to ROIAlign.  The XLA fallback (:mod:`mx_rcnn_tpu.ops.roi_align`)
pools every roi from every pyramid level and masks (4x redundant compute,
gather-bound); this kernel does one pass:

- grid = one step per roi, across the WHOLE batch (B*R steps — batching
  is a column of the per-roi parameter block, not a loop of kernel calls);
- each roi's parameter row (geometry + assigned level + window origin +
  batch index) streams in as a tiny per-step SMEM block — NOT a
  scalar-prefetch table, which costs ~512 B of smem per row and cannot
  hold a batched-eval grid (see _kernel);
- the roi's assigned level selects which HBM feature map a ``(T, T, C)``
  window is DMA'd from — only the window travels over HBM, never a whole
  pyramid level per roi;
- bilinear interpolation is expressed as two small matmuls with sparse
  interpolation matrices ``pooled = mean_pool(Wy @ window @ Wx^T)`` — the
  MXU-friendly formulation of "gather 4 corners per sample" (each Wy/Wx row
  holds the two bilinear taps of one sample coordinate);
- bin-averaging folds into the same reshape.

The window size T (default 40) bounds the roi extent in feature cells at
its assigned level: :func:`fpn_level_assignment` is extent-aware (rois
whose span would exceed T-2 cells are bumped to a coarser level), so the
kernel is exact whenever the coarsest map fits the window — canvases up to
(T-2) * 2^max_level px, i.e. 1216px at P5 with the default T.  Beyond
that, samples past the window clamp to its edge (only for rois spanning
more than T-2 cells at the coarsest level).

Numerics match the XLA reference: samples outside (-1, H) x (-1, W)
contribute zero; in-range samples clamp to the [0, H-1] cell range
(Detectron ROIAlign semantics).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mx_rcnn_tpu.ops.roi_align import fpn_level_assignment

# Default roi window in feature cells — the single knob every entry point
# below defaults to.  MUST stay 10 above ops.roi_align.MAX_EXTENT_CELLS so
# the XLA and Pallas paths assign rois to identical levels (see there);
# detection/graph.py threads this SAME constant into both the single-chip
# and shard_map'd call sites so the two can never silently diverge.
POOL_WINDOW = 48


def window_classes(t: int) -> tuple[tuple[int, int], ...]:
    """Per-roi (Ty, Tx) window classes, smallest first; the last is the
    full (t, t) fallback whose clamp semantics define exactness.

    The kernels are window-DMA-bound (cost tracks Ty*Tx*C), and the FPN
    level assignment targets ~7-20 cells of roi extent, so most rois need
    far less than the worst-case window.  The r4 eval-shape distribution
    probe (random-weight proposals, recipe canvas): y-need p50/p90 =
    10/20 cells, x-need (which carries the origin's 8-alignment slack,
    up to +7) p50/p90 = 21/25 — (16, 24) fits 72% of rois and (24, 32)
    fits 100%, where the single 32-corner class shipped 1024 cells for
    every one of them.  Ty is unconstrained (H is the untiled dim); Tx
    must be a multiple of 8 (Mosaic sublane slicing).
    """
    base = [(ty, tx) for ty, tx in ((16, 24), (24, 32)) if ty < t and tx < t]
    return tuple(base) + ((t, t),)


def _interp_matrix(start, bin_size, num_bins, sr, extent, origin, t):
    """Rows = P = num_bins*sr sample coords; cols = T window cells.

    Row p holds the two bilinear taps of sample p, zeroed when the sample
    falls outside (-1, extent); both taps merge on the edge cell when the
    sample clamps to extent-1 (weights sum to 1, matching the XLA path).
    """
    p = num_bins * sr
    pid_i = jax.lax.broadcasted_iota(jnp.int32, (p, 1), 0)  # (P, 1)
    s = (pid_i // sr).astype(jnp.float32)
    frac = ((pid_i % sr).astype(jnp.float32) + 0.5) / sr
    coord = start + (s + frac) * bin_size                    # absolute cells
    inside = (coord > -1.0) & (coord < extent)
    c = jnp.clip(coord, 0.0, extent - 1.0)
    c0 = jnp.floor(c)
    lc = c - c0
    # Window-relative taps.  Negative is impossible (the origin sits one
    # cell below the roi start); > t-1 only for rois spanning more than the
    # window — those clamp to the window edge (see module docstring).
    c0i = jnp.clip(c0.astype(jnp.int32) - origin, 0, t - 1)
    c1i = jnp.clip(
        jnp.minimum(c0i + 1, (extent - 1.0).astype(jnp.int32) - origin), 0, t - 1
    )
    cells = jax.lax.broadcasted_iota(jnp.int32, (p, t), 1)
    w = jnp.where(cells == c0i, 1.0 - lc, 0.0) + jnp.where(cells == c1i, lc, 0.0)
    return w * inside.astype(jnp.float32)                    # (P, T)


def _interp_matrix_avg(start, bin_size, num_bins, sr, extent, origin, t):
    """(S, T) interpolation matrix with the sr-subsample bin mean BAKED IN.

    Row i = (1/sr) * sum of the sr bilinear-tap rows of bin i, i.e. the
    mean over subsamples folded into the weights (mean of linear maps =
    linear map).  Halving the matmul row count this way took the kernel's
    x-interpolation matmul — measured as its LARGEST compute component at
    eval shapes (N = P*C with P = S*sr) — down by 2x with no semantics
    change beyond f32 summation order (weights are computed in f32; /sr is
    exact for the power-of-two default)."""
    w = _interp_matrix(start, bin_size, num_bins, sr, extent, origin, t)
    return w.reshape(num_bins, sr, t).sum(axis=1) / sr       # (S, T)


def _dot_q(a, b, dn, interpret):
    """dot_general of already-quantized low-precision operands, f32 accum.

    On TPU the operands dot natively (full-rate bf16 MXU passes, f32
    accumulation).  Under ``interpret`` (the CPU emulation used by tests
    and the multichip dryrun) the same VALUES dot in f32 instead — the CPU
    runtime has no BF16xBF16=F32 dot thunk.  The two are value-equivalent
    up to f32 summation order: each bf16 product is exact in f32, but the
    backends may reduce in different orders, so interpret-mode tests are
    an up-to-rounding oracle for the TPU path, NOT a bitwise one (the
    on-TPU parity check lives in tests/test_overfit_tpu.py)."""
    if interpret:
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
    return jax.lax.dot_general(
        a, b, dimension_numbers=dn, preferred_element_type=jnp.float32
    )


def _kernel(
    roi_ref,       # SMEM block (G, 1, 9+2K) f32, G rois per grid step:
                   # [x1, y1, bin_w, bin_h, H, W, level_idx, batch,
                   #  (oy_c, ox_c) x K classes, cls]
                   # Streamed per step, NOT scalar-prefetched: a prefetch
                   # table costs ~512 B of smem PER ROW, so an N = B*R
                   # batched-eval grid (8000 rois) would need 4 MB of the
                   # 1 MB smem.  The indices ride as f32 (exact < 2^24).
    *rest,
    num_levels: int,
    t: int,
    output_size: int,
    sampling_ratio: int,
    group: int,
    interpret: bool = False,
):
    feat_refs = rest[:num_levels]
    out_ref = rest[num_levels]
    win = rest[num_levels + 1]     # (G, T, T, C) VMEM scratch
    sem = rest[num_levels + 2]     # DMA sems, shape (G,)
    classes = window_classes(t)

    # Phase 1: start ALL G window DMAs, then wait — the copies fly
    # concurrently, amortizing HBM latency across the group (a 1-roi-per-
    # step grid serializes fetch->compute->fetch and measured ~10 ms for
    # 1024 train rois; grouped fetches overlap).  Each roi copies only its
    # CLASS window corner (see _prep); cells beyond it hold stale finite
    # scratch that every interpolation weight zeroes — which needs the
    # scratch to START finite: uninitialized VMEM can hold NaN and 0 * NaN
    # poisons the matmul, so step 0 memsets all windows once (later steps
    # inherit real features or these zeros).
    @pl.when(pl.program_id(0) == 0)
    def _():
        for g in range(group):
            win[g] = jnp.zeros((t, t, win.shape[-1]), win.dtype)

    # (Cells a DMA never reaches — undersized levels, class corners —
    # need no per-step re-zeroing: the extent/corner masking in the interp
    # matrices gives them exactly-zero weight, and the step-0 memset keeps
    # them finite for the whole grid.)
    cls_col = 8 + 2 * len(classes)
    for phase in ("start", "wait"):
        for g in range(group):
            level = roi_ref[g, 0, 6].astype(jnp.int32)
            bi = roi_ref[g, 0, 7].astype(jnp.int32)
            cls = roi_ref[g, 0, cls_col].astype(jnp.int32)
            for ci, (ty, tx) in enumerate(classes):
                oy_c = roi_ref[g, 0, 8 + 2 * ci].astype(jnp.int32)
                ox_c = pl.multiple_of(
                    roi_ref[g, 0, 9 + 2 * ci].astype(jnp.int32), 8
                )
                for i, f in enumerate(feat_refs):
                    th = min(ty, f.shape[1])
                    tw = min(tx, f.shape[2])

                    @pl.when((level == i) & (cls == ci))
                    def _(g=g, f=f, th=th, tw=tw, oy_c=oy_c, ox_c=ox_c,
                          bi=bi, phase=phase):
                        getattr(pltpu.make_async_copy(
                            f.at[bi, pl.ds(oy_c, th), pl.ds(ox_c, tw), :],
                            win.at[g, pl.ds(0, th), pl.ds(0, tw), :],
                            sem.at[g],
                        ), phase)()

    # Phase 2: interpolate each roi's window — per CLASS, at the class's
    # static (Ty, Tx) widths: the matmul cost tracks Ty*Tx*C exactly like
    # the DMA does, so a (16, 24)-class roi runs 1/6 the full-window
    # matmul FLOPs, not just 1/6 the copy bytes.  The sr x sr bin mean is
    # baked into the interpolation matrices (see _interp_matrix_avg).
    s, sr = output_size, sampling_ratio
    c = win.shape[-1]
    for g in range(group):
        x1 = roi_ref[g, 0, 0]
        y1 = roi_ref[g, 0, 1]
        bin_w = roi_ref[g, 0, 2]
        bin_h = roi_ref[g, 0, 3]
        hl = roi_ref[g, 0, 4]
        wl = roi_ref[g, 0, 5]
        cls = roi_ref[g, 0, cls_col].astype(jnp.int32)
        for ci, (ty, tx) in enumerate(classes):
            # The interpolation origin must match whichever class window
            # was DMA'd; each roi matches exactly one class branch, so
            # out_ref[g] is written exactly once.
            oy_c = roi_ref[g, 0, 8 + 2 * ci].astype(jnp.int32)
            ox_c = roi_ref[g, 0, 9 + 2 * ci].astype(jnp.int32)

            @pl.when(cls == ci)
            def _(g=g, ty=ty, tx=tx, oy_c=oy_c, ox_c=ox_c):
                wy = _interp_matrix_avg(y1, bin_h, s, sr, hl, oy_c, ty)
                wx = _interp_matrix_avg(x1, bin_w, s, sr, wl, ox_c, tx)

                # rows: (S, Ty) @ (Ty, Tx*C) -> (S, Tx, C).
                #
                # Precision, by feature dtype:
                # - f32 windows (CPU-recipe tests, goldens): HIGHEST with
                #   exact f32 weights — bit-stable vs the XLA oracle at
                #   atol 1e-4.
                # - bf16 windows (the production train/eval graphs): the
                #   old path upcast the whole window to f32 just so a
                #   same-dtype HIGHEST dot could run (6 MXU passes).  The
                #   r4c cost probe showed that cast + those passes were
                #   the kernel's single largest compute component (first
                #   dot ~9.8 of 28.8 ms at batch-8 eval), so bf16 windows
                #   now dot DIRECTLY against hi/lo SPLIT bf16 weights:
                #   w = w_hi + w_lo reconstructs the f32 weight to ~2^-17
                #   relative, so the geometric concern that forbids plain
                #   bf16 weights (a ~2^-8 shift of where features are
                #   sampled) does not arise — two full-rate bf16 passes
                #   with f32 accumulation replace six.  The intermediate
                #   rows then take ONE bf16 quantization (~2^-8, the same
                #   granularity as the bf16 output itself) before the x
                #   dot, also split.  Measured (r4c, same-session A/B):
                #   standalone fwd kernel 8.0 -> 5.9 ms at train shapes,
                #   27.1 -> 25.6 ms at batch-8 eval.  A THREE-dot exact
                #   split of the x dot was probed and is SLOWER than the
                #   f32 path (42 ms eval): per-dot issue overhead, not
                #   pass count, prices each extra dot (~0.7 us/roi), so
                #   the one-quantization two-dot form is the optimum.
                sub = win[g, pl.ds(0, ty), pl.ds(0, tx), :]
                if win.dtype == jnp.bfloat16:
                    wy_hi = wy.astype(jnp.bfloat16)
                    wy_lo = (wy - wy_hi.astype(jnp.float32)).astype(jnp.bfloat16)
                    wx_hi = wx.astype(jnp.bfloat16)
                    wx_lo = (wx - wx_hi.astype(jnp.float32)).astype(jnp.bfloat16)
                    sub_b = sub.reshape(ty, tx * c)
                    dn = (((1,), (0,)), ((), ()))
                    rows = (
                        _dot_q(wy_hi, sub_b, dn, interpret)
                        + _dot_q(wy_lo, sub_b, dn, interpret)
                    ).reshape(s, tx, c).astype(jnp.bfloat16)
                    dn2 = (((1,), (1,)), ((), ()))
                    qpc = (
                        _dot_q(wx_hi, rows, dn2, interpret)
                        + _dot_q(wx_lo, rows, dn2, interpret)
                    )                                             # (Sx, Sy, C)
                else:
                    rows = jax.lax.dot_general(
                        wy, sub.astype(jnp.float32).reshape(ty, tx * c),
                        dimension_numbers=(((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST,
                    ).reshape(s, tx, c)
                    qpc = jax.lax.dot_general(
                        wx, rows,
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST,
                    )                                             # (Sx, Sy, C)
                out_ref[g] = jnp.swapaxes(qpc, 0, 1).astype(out_ref.dtype)


def _prep(feature_pyramid, rois, output_size, window):
    """Shared forward/backward preprocessing: pad level widths to the
    Mosaic sublane multiple and build the per-roi parameter table.

    Returns (levels, feats (padded, batched), ws_true, roi_params, b,
    r_per, batched).  Forward and backward MUST agree on every field here
    (level assignment, window origins), so it is factored out."""
    levels = sorted(feature_pyramid.keys())
    batched = rois.ndim == 3
    if not batched:
        feature_pyramid = {l: f[None] for l, f in feature_pyramid.items()}
        rois = rois[None]
    feats = [feature_pyramid[l] for l in levels]
    b, r_per = rois.shape[:2]
    flat = rois.reshape(-1, 4)
    t = window
    # Mosaic's HBM window slice needs the sublane (W) dim to be a multiple
    # of 8; recipe canvases (800x1344) give odd widths at coarse levels
    # (84/42/21 cells).  Pad those levels' W with zeros — geometry and
    # extent masking below keep using the TRUE widths, so padded cells get
    # zero interpolation weight and the result is unchanged.  The pads copy
    # only the small coarse maps (P4+), nothing at P2/P3 scale.
    ws_true = [f.shape[2] for f in feats]
    feats = [
        jnp.pad(f, ((0, 0), (0, 0), (0, -f.shape[2] % 8), (0, 0)))
        if f.shape[2] % 8
        else f
        for f in feats
    ]

    assignment = fpn_level_assignment(
        flat, min_level=levels[0], max_level=levels[-1],
        max_extent_cells=window - 10,
    )
    level_idx = assignment - levels[0]                         # 0-based

    # Per-roi geometry in its level's cell units (gather per-level consts).
    scale = jnp.asarray([1.0 / (1 << l) for l in levels], jnp.float32)[level_idx]
    hs = jnp.asarray([f.shape[1] for f in feats], jnp.float32)[level_idx]
    ws = jnp.asarray(ws_true, jnp.float32)[level_idx]
    ws_pad = jnp.asarray([f.shape[2] for f in feats], jnp.float32)[level_idx]
    x1 = flat[:, 0] * scale
    y1 = flat[:, 1] * scale
    rw = jnp.maximum(flat[:, 2] * scale - x1, 1.0)
    rh = jnp.maximum(flat[:, 3] * scale - y1, 1.0)
    roi_geom = [x1, y1, rw / output_size, rh / output_size, hs, ws]

    bidx = jnp.repeat(jnp.arange(b, dtype=jnp.int32), r_per)

    # Window classes (smallest first; the last is the (t, t) fallback —
    # see window_classes).  Per class: origin with one cell of bilinear
    # margin, clamped into the map; ox floors to a multiple of 8 (Mosaic
    # requires provable sublane alignment for HBM slices in the tiled
    # second-to-last dim; the up-to-7-cell loss is budgeted both in
    # max_extent_cells and in each class's fit test).  A roi takes the
    # SMALLEST class whose every nonzero tap fits the class window at its
    # clamped origin; cells beyond the DMA'd corner hold stale scratch
    # with exactly-zero interpolation weight (finite garbage x 0).
    #
    # Highest cell any sample can tap: floor of the largest clipped sample
    # coordinate, +1 for the second bilinear tap, +1 more as f32 slack (the
    # kernel recomputes coords as y1 + k*(rh/S), which can exceed y1 + rh
    # by an ULP — the slack makes the bound robustly conservative).
    classes = window_classes(t)
    y_hi = jnp.minimum(
        jnp.floor(jnp.clip(y1 + rh, 0.0, hs - 1.0)) + 2.0, hs - 1.0
    )
    x_hi = jnp.minimum(
        jnp.floor(jnp.clip(x1 + rw, 0.0, ws - 1.0)) + 2.0, ws - 1.0
    )
    origin_cols = []
    cls = jnp.full(x1.shape, len(classes) - 1, jnp.int32)
    for ci in reversed(range(len(classes))):
        ty, tx = classes[ci]
        oy_c = jnp.clip(
            jnp.floor(y1) - 1, 0, jnp.maximum(hs - ty, 0)
        ).astype(jnp.int32)
        ox_c = jnp.clip(
            jnp.floor(x1) - 1, 0, jnp.maximum(ws_pad - tx, 0)
        ).astype(jnp.int32)
        ox_c = (ox_c // 8) * 8
        if ci < len(classes) - 1:
            fits = (
                (y_hi - oy_c.astype(jnp.float32) <= ty - 1)
                & (x_hi - ox_c.astype(jnp.float32) <= tx - 1)
            )
            cls = jnp.where(fits, ci, cls)
        origin_cols = [oy_c.astype(jnp.float32), ox_c.astype(jnp.float32)] + origin_cols

    # Indices ride the same f32 table as the geometry (exact for values
    # < 2^24; feature maps are nowhere near that) — see _kernel docstring.
    roi_params = jnp.stack(
        roi_geom
        + [level_idx.astype(jnp.float32), bidx.astype(jnp.float32)]
        + origin_cols
        + [cls.astype(jnp.float32)],
        axis=1,
    ).astype(jnp.float32)[:, None, :]              # (N, 1, 9 + 2K)
    # 3-D so the SMEM block's last two dims equal the array's (Mosaic's
    # block-shape divisibility rule exempts full-extent dims).
    return levels, feats, ws_true, roi_params, b, r_per, batched


@functools.partial(
    jax.jit,
    static_argnames=("output_size", "sampling_ratio", "window", "interpret", "group"),
)
def multilevel_roi_align_pallas(
    feature_pyramid: dict[int, jnp.ndarray],
    rois: jnp.ndarray,
    output_size: int = 7,
    sampling_ratio: int = 2,
    window: int = POOL_WINDOW,
    interpret: bool = False,
    group: int = 8,
) -> jnp.ndarray:
    """Drop-in replacement for :func:`multilevel_roi_align`.

    Accepts the per-image contract — pyramid {level: (H_l, W_l, C)},
    rois (R, 4) → (R, S, S, C) — or the batched one: {level: (B, H_l, W_l,
    C)}, rois (B, R, 4) → (B, R, S, S, C).  The batch folds into the
    kernel grid (one step per ``group`` rois across ALL images), so a
    batched call is ONE pallas_call, not B.  ``group`` rois per step issue
    their window DMAs together (concurrent fetches — measured ~3x over the
    1-roi-per-step grid at train shapes); the roi count is padded to a
    multiple of ``group`` with row-0 copies whose outputs are sliced off.
    """
    levels, feats, ws_true, roi_params, b, r_per, batched = _prep(
        feature_pyramid, rois, output_size, window
    )
    n = b * r_per
    c = feats[0].shape[-1]
    t = window
    # The (G, T, T, C) window scratch must fit scoped VMEM (16 MB budget,
    # shared with the out block): G=8 bf16 windows at T=48/C=256 are
    # 9.4 MB, but an f32 pyramid (the tiny CPU-recipe configs) doubles
    # that past the limit — shrink the group to fit ~12 MB of scratch.
    itemsize = jnp.dtype(feats[0].dtype).itemsize
    budget = max(1, (12 * 1024 * 1024) // (t * t * c * itemsize))
    grp = max(1, min(group, budget, n))
    n_pad = -n % grp
    nf = roi_params.shape[-1]
    if n_pad:
        roi_params = jnp.concatenate(
            [roi_params, jnp.broadcast_to(roi_params[:1], (n_pad, 1, nf))]
        )

    kernel = functools.partial(
        _kernel,
        num_levels=len(levels),
        t=t,
        output_size=output_size,
        sampling_ratio=sampling_ratio,
        group=grp,
        interpret=interpret,
    )
    out = pl.pallas_call(
        kernel,
        grid=((n + n_pad) // grp,),
        in_specs=[
            pl.BlockSpec(
                (grp, 1, nf), lambda r: (r, 0, 0), memory_space=pltpu.SMEM
            )
        ] + [pl.BlockSpec(memory_space=pl.ANY) for _ in levels],
        out_specs=pl.BlockSpec(
            (grp, output_size, output_size, c),
            lambda r: (r, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((grp, t, t, c), feats[0].dtype),
            pltpu.SemaphoreType.DMA((grp,)),
        ],
        out_shape=jax.ShapeDtypeStruct(
            (n + n_pad, output_size, output_size, c), feats[0].dtype
        ),
        interpret=interpret,
    )(roi_params, *feats)
    out = out[:n].reshape(b, r_per, output_size, output_size, c)
    return out if batched else out[0]


def _bwd_kernel(
    roi_ref,       # SMEM (1, 1, 9+2K) f32 — same fields as the forward.
    g_ref,         # VMEM (1, S, S, C) — cotangent of this roi's pooled out.
    *rest,
    num_levels: int,
    t: int,
    output_size: int,
    sampling_ratio: int,
    interpret: bool = False,
):
    """Transpose of :func:`_kernel`, accumulated by read-modify-write.

    The forward is two interpolation matmuls of a DMA'd window; its exact
    transpose is two transposed matmuls producing a (T, T, C) window
    gradient, ADDED into the roi's window slice of its level's gradient
    buffer.  The XLA autodiff of the gather formulation instead emits an
    HBM scatter-add with ~16 duplicate-index rows per bin, which the TPU
    serializes — measured 18-19 ms/step at train shapes (b2 x 512 rois,
    R101-FPN) vs ~3 ms for this kernel.

    Correctness of the accumulation: the TPU grid is sequential on a core,
    and each step's read-DMA waits before the add and the write-DMA waits
    before the step ends, so overlapping windows of different rois
    serialize cleanly (no lost updates).  The buffers accumulate in f32 —
    strictly tighter than the XLA path's feature-dtype (bf16 in the train
    graph) scatter accumulation.
    """
    # rest: [grad_level ANY ×L (in, aliased)] + [grad_level ANY ×L (out)] +
    # scratch [win2 (T,T,C) f32 VMEM, sem].  The aliased inputs are not
    # read through their input refs — RMW goes through the OUTPUT refs,
    # which point at the same buffers.
    out_refs = rest[num_levels: 2 * num_levels]
    win2 = rest[2 * num_levels]
    sem = rest[2 * num_levels + 1]

    classes = window_classes(t)
    cls_col = 8 + 2 * len(classes)
    level = roi_ref[0, 0, 6].astype(jnp.int32)
    bi = roi_ref[0, 0, 7].astype(jnp.int32)
    x1 = roi_ref[0, 0, 0]
    y1 = roi_ref[0, 0, 1]
    bin_w = roi_ref[0, 0, 2]
    bin_h = roi_ref[0, 0, 3]
    hl = roi_ref[0, 0, 4]
    wl = roi_ref[0, 0, 5]
    # Window classes (see _prep/_kernel): the RMW traffic — 2x window
    # bytes per roi — AND the transposed matmuls shrink with the class,
    # exactly like the forward.  The interp origins must match the window
    # actually read back.
    cls = roi_ref[0, 0, cls_col].astype(jnp.int32)
    s, sr = output_size, sampling_ratio
    c = win2.shape[-1]

    # d_out (S_y, S_x, C) -> d_qpc (S_x, S_y, C): just the transpose of the
    # forward's (x, y) -> (y, x) swap — the sr x sr subsample mean lives in
    # the averaged interpolation matrices (forward and backward MUST use
    # the same baked form; _interp_matrix_avg), so the old /sr^2 scale and
    # subsample broadcast are gone.  Stays in the cotangent's NATIVE dtype
    # (bf16 in the train graph).
    g = g_ref[0]                                               # (S, S, C)
    d_qpc = jnp.swapaxes(g, 0, 1)                              # (S_x, S_y, C)

    # Precision of the two transposed matmuls: bf16 cotangents (the train
    # graph) take DEFAULT — one MXU pass with f32 accumulation.  The
    # operands' information content is already bf16 (the cotangent arrives
    # in the graph's compute dtype), so truncating the exact-f32 weights
    # costs ~2^-8 relative.  The SECOND dot additionally truncates the f32
    # intermediate d_rows_t: each of its rows is a <=2-tap combination
    # (weights summing <=1) of bf16-valued cotangent entries, so that
    # rounding is one more independent ~2^-8 relative error — no
    # amplification, still below the cotangent's own quantization and
    # strictly tighter than the bf16-ACCUMULATING XLA scatter-add this
    # kernel replaced (hundreds of bf16 += per P2 cell).  On-chip check
    # (the off-TPU interpret tests can't see MXU truncation): max
    # |pallas - xla-autodiff| feature-grad diff at R101 train shapes is
    # within bf16 output granularity — gated by the opt-in
    # RUN_POOL_BWD_TPU=1 test (tests/test_pool_bwd_tpu.py; r5 recorded
    # worst_rel 0.0092 ~ 2.4 ulps).  Measured 10.7 -> 6.1 ms at R101
    # train shapes vs HIGHEST.  f32 cotangents (CPU-recipe tests, golden
    # paths) keep the exact HIGHEST dot.  The FORWARD stays HIGHEST always:
    # weight truncation there shifts where features are SAMPLED (a
    # systematic geometric error, not gradient noise) and its measured win
    # was only ~1.5 ms.
    bf16_cot = g.dtype == jnp.bfloat16
    for ci, (ty, tx) in enumerate(classes):
        oy_c = roi_ref[0, 0, 8 + 2 * ci].astype(jnp.int32)
        ox_c = pl.multiple_of(roi_ref[0, 0, 9 + 2 * ci].astype(jnp.int32), 8)

        @pl.when(cls == ci)
        def _(ty=ty, tx=tx, oy_c=oy_c, ox_c=ox_c):
            wy = _interp_matrix_avg(y1, bin_h, s, sr, hl, oy_c, ty)  # (S, Ty)
            wx = _interp_matrix_avg(x1, bin_w, s, sr, wl, ox_c, tx)  # (S, Tx)
            # d_rows_T[tx, sy, c] = sum_sx wx[sx, tx] * d_qpc[sx, sy, c] —
            # the SMALL matmul (N = S*C), against the native cotangent.
            # bf16 cotangents dot DIRECTLY as bf16 operands with
            # single-bf16 weights (no f32 upcast of the cotangent): the
            # ~2^-8 weight truncation is plain gradient noise here, below
            # the cotangent's own quantization (the precision note above);
            # the geometric-exactness argument that makes the FORWARD use
            # hi/lo split weights does not apply to a backward.
            dn1 = (((0,), (0,)), ((), ()))
            dn2 = (((0,), (1,)), ((), ()))
            if bf16_cot:
                d_rows_t = _dot_q(
                    wx.astype(g.dtype), d_qpc.reshape(s, s * c), dn1, interpret
                ).reshape(tx, s, c)                            # (Tx, Sy, C)
                d_window = _dot_q(
                    wy.astype(g.dtype), d_rows_t.astype(g.dtype), dn2, interpret
                )                                              # (Ty, Tx, C)
            else:
                d_rows_t = jax.lax.dot_general(
                    wx, d_qpc.astype(jnp.float32).reshape(s, s * c),
                    dimension_numbers=dn1,
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                ).reshape(tx, s, c)                            # (Tx, Sy, C)
                d_window = jax.lax.dot_general(
                    wy, d_rows_t,
                    dimension_numbers=dn2,
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                )                                              # (Ty, Tx, C)

            for i, gl in enumerate(out_refs):
                th = min(ty, gl.shape[1])
                tw = min(tx, gl.shape[2])

                @pl.when(level == i)
                def _(gl=gl, th=th, tw=tw, d_window=d_window):
                    # Read-modify-write of the roi's class-window slice.
                    # Taps beyond the level's true extent (and beyond the
                    # class corner) carry zero weight in the interp
                    # matrices, so adding the [:th, :tw] corner is exact.
                    rd = pltpu.make_async_copy(
                        gl.at[bi, pl.ds(oy_c, th), pl.ds(ox_c, tw), :],
                        win2.at[pl.ds(0, th), pl.ds(0, tw), :],
                        sem,
                    )
                    rd.start()
                    rd.wait()
                    win2[:th, :tw, :] = (
                        win2[:th, :tw, :] + d_window[:th, :tw, :]
                    )
                    wr = pltpu.make_async_copy(
                        win2.at[pl.ds(0, th), pl.ds(0, tw), :],
                        gl.at[bi, pl.ds(oy_c, th), pl.ds(ox_c, tw), :],
                        sem,
                    )
                    wr.start()
                    wr.wait()


@functools.partial(
    jax.jit, static_argnames=("output_size", "sampling_ratio", "window", "interpret")
)
def multilevel_roi_align_bwd_pallas(
    feature_pyramid: dict[int, jnp.ndarray],
    rois: jnp.ndarray,
    g: jnp.ndarray,
    output_size: int = 7,
    sampling_ratio: int = 2,
    window: int = POOL_WINDOW,
    interpret: bool = False,
) -> dict[int, jnp.ndarray]:
    """Feature-pyramid gradient of :func:`multilevel_roi_align_pallas`.

    ``g``: cotangent of the pooled output — (R, S, S, C) or batched
    (B, R, S, S, C).  Returns a pyramid-shaped dict of gradients in the
    features' dtype.  Accumulation is f32 via per-roi window RMW
    (see :func:`_bwd_kernel`)."""
    levels, feats, ws_true, roi_params, b, r_per, batched = _prep(
        feature_pyramid, rois, output_size, window
    )
    n = b * r_per
    c = feats[0].shape[-1]
    t = window
    s = output_size
    g2 = g.reshape(n, s, s, c)
    zeros = [jnp.zeros(f.shape, jnp.float32) for f in feats]

    kernel = functools.partial(
        _bwd_kernel,
        num_levels=len(levels),
        t=t,
        output_size=s,
        sampling_ratio=sampling_ratio,
        interpret=interpret,
    )
    grads = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec(
                (1, 1, roi_params.shape[-1]), lambda r: (r, 0, 0),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(
                (1, s, s, c), lambda r: (r, 0, 0, 0), memory_space=pltpu.VMEM
            ),
        ] + [pl.BlockSpec(memory_space=pl.ANY) for _ in levels],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY) for _ in levels],
        scratch_shapes=[
            pltpu.VMEM((t, t, c), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(f.shape, jnp.float32) for f in feats
        ],
        input_output_aliases={2 + i: i for i in range(len(levels))},
        interpret=interpret,
    )(roi_params, g2, *zeros)

    out = {}
    for i, l in enumerate(levels):
        gl = grads[i][:, :, : ws_true[i], :].astype(feature_pyramid[l].dtype)
        out[l] = gl if batched else gl[0]
    return out


def pallas_supported(feature_pyramid: dict, window: int = POOL_WINDOW) -> bool:
    """Static check that every level's layout is Mosaic-DMA-sliceable:
    channels must be a multiple of 128 (lane dim).  The x (sublane-tiled)
    dim, which the window copy slices, is zero-padded to a multiple of 8
    inside the kernel wrapper, so odd widths (recipe canvases) are fine.
    Single-level (C4) pyramids use the XLA path (their roi extent is
    unbounded by level reassignment)."""
    for f in feature_pyramid.values():
        if f.shape[-1] % 128 != 0:
            return False
    return len(feature_pyramid) > 1


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6)
)
def multilevel_roi_align_fast(
    feature_pyramid: dict[int, jnp.ndarray],
    rois: jnp.ndarray,
    output_size: int = 7,
    sampling_ratio: int = 2,
    window: int = POOL_WINDOW,
    interpret: bool = False,
    bwd_impl: str = "pallas",
) -> jnp.ndarray:
    """Pallas forward + selectable backward.

    Forward runs the kernel above; ``bwd_impl`` picks the VJP — "pallas"
    (default) is the window-RMW scatter-accumulate kernel
    (:func:`multilevel_roi_align_bwd_pallas`), "xla" differentiates the
    XLA implementation of the same function (:func:`multilevel_roi_align`
    with the matching extent-aware level assignment), which is exact
    because both compute identical outputs.  The config spelling is
    ``rcnn.roi_align_bwd_impl``; the MX_RCNN_POOL_BWD env var overrides
    either at trace time (A/B without touching the config).  Roi
    coordinates get no gradient (the reference's Proposal/ProposalTarget
    custom ops are forward-only too — SURVEY.md §4.1).  ``interpret``
    runs the kernel's pure-JAX emulation (CPU fake-mesh tests and the
    driver's multichip dryrun)."""
    return multilevel_roi_align_pallas(
        feature_pyramid, rois, output_size=output_size,
        sampling_ratio=sampling_ratio, window=window, interpret=interpret,
    )


def _fast_fwd(feature_pyramid, rois, output_size, sampling_ratio, window,
              interpret, bwd_impl):
    out = multilevel_roi_align_fast(
        feature_pyramid, rois, output_size, sampling_ratio, window, interpret,
        bwd_impl,
    )
    return out, (feature_pyramid, rois)


def _fast_bwd(output_size, sampling_ratio, window, interpret, bwd_impl, res, g):
    import os

    feature_pyramid, rois = res

    # Pallas window-RMW backward by default (the XLA autodiff backward is
    # a duplicate-index HBM scatter-add the TPU serializes: 18-19 ms/step
    # at R101-FPN train shapes vs ~3 ms for the kernel — see _bwd_kernel).
    # rcnn.roi_align_bwd_impl="xla" (or MX_RCNN_POOL_BWD=xla, which wins)
    # restores the old path for A/B and debugging.
    if os.environ.get("MX_RCNN_POOL_BWD", bwd_impl) != "xla":
        grad_pyramid = multilevel_roi_align_bwd_pallas(
            feature_pyramid, rois, g, output_size=output_size,
            sampling_ratio=sampling_ratio, window=window, interpret=interpret,
        )
        return grad_pyramid, jnp.zeros_like(rois)

    from mx_rcnn_tpu.ops.roi_align import multilevel_roi_align

    def ref(p, rr):
        return multilevel_roi_align(
            p, rr, output_size=output_size, sampling_ratio=sampling_ratio,
            max_extent_cells=window - 10,
        )

    if rois.ndim == 3:  # batched: vmap the XLA reference over images
        fn = lambda p: jax.vmap(ref)(p, rois)  # noqa: E731
    else:
        fn = lambda p: ref(p, rois)  # noqa: E731
    _, vjp = jax.vjp(fn, feature_pyramid)
    (grad_pyramid,) = vjp(g)
    return grad_pyramid, jnp.zeros_like(rois)


multilevel_roi_align_fast.defvjp(_fast_fwd, _fast_bwd)


def sharded_multilevel_roi_align(
    feature_pyramid: dict[int, jnp.ndarray],
    rois: jnp.ndarray,
    output_size: int,
    sampling_ratio: int,
    mesh,
    data_axis: str,
    window: int = POOL_WINDOW,
    interpret: bool = False,
    bwd_impl: str = "pallas",
) -> jnp.ndarray:
    """The kernel's multi-chip form: :func:`multilevel_roi_align_fast`
    per data-axis shard via ``jax.shard_map``.

    The batched kernel contract is already per-shard exact — each shard
    holds whole images (pyramid (B/n, H, W, C) + rois (B/n, R, 4)) and
    batch indices are computed from local shapes — so the wrap needs no
    collectives; it only stops GSPMD from replicating the opaque kernel
    call (gathering every image's pyramid to every chip), which is what a
    bare pallas_call under a sharded jit would get.  Axes other than
    ``data_axis`` stay under GSPMD (partial-manual shard_map).
    ``check_vma=False``: the pallas out_shape carries no varying-mesh-axes
    annotation.  The custom_vjp rides inside, so the backward (the Pallas
    window-RMW kernel by default since r3; autodiff-of-XLA under
    ``bwd_impl="xla"`` or MX_RCNN_POOL_BWD=xla) is per-shard too."""
    from jax.sharding import PartitionSpec as P

    # Positional call: custom_vjp nondiff_argnums forbid keywords.
    def fn(pyramid, shard_rois):
        return multilevel_roi_align_fast(
            pyramid, shard_rois, output_size, sampling_ratio, window, interpret,
            bwd_impl,
        )

    if hasattr(jax, "shard_map"):
        wrapped = jax.shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(data_axis), P(data_axis)),
            out_specs=P(data_axis),
            axis_names={data_axis},
            check_vma=False,
        )
    else:
        # jax < 0.6: shard_map lives in jax.experimental; "manual over
        # data_axis only" is spelled as auto=<every other axis>, and the
        # vma check is the old check_rep flag.
        from jax.experimental.shard_map import shard_map as _shard_map

        wrapped = _shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(data_axis), P(data_axis)),
            out_specs=P(data_axis),
            auto=frozenset(mesh.axis_names) - {data_axis},
            check_rep=False,
        )
    return wrapped(feature_pyramid, rois)
