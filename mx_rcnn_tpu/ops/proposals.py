"""In-graph RPN proposal generation.

Replaces the reference Proposal custom op (``rcnn/symbol/proposal.py``,
and the engine's ``mx.contrib.symbol.Proposal`` behind CXX_PROPOSAL):
decode RPN outputs into scored boxes, pre-NMS top-k, NMS, and emit a fixed
``post_nms_top_n`` roi set — with zero host interaction.  The reference
pays a device->host->device round-trip plus a CUDA NMS here every
iteration (SURVEY.md section 4.5); this version is one fused XLA region.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from mx_rcnn_tpu.geometry import clip_boxes, decode_boxes, snap, valid_box_mask
from mx_rcnn_tpu.ops.nms import nms_indices, rank_keep
from mx_rcnn_tpu.ops.topk import hierarchical_top_k


class Proposals(NamedTuple):
    rois: jnp.ndarray    # (post_nms_top_n, 4)
    scores: jnp.ndarray  # (post_nms_top_n,)
    valid: jnp.ndarray   # (post_nms_top_n,) bool


def generate_proposals(
    scores: jnp.ndarray,
    deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    image_height,
    image_width,
    pre_nms_top_n: int = 6000,
    post_nms_top_n: int = 300,
    nms_threshold: float = 0.7,
    min_size: float = 0.0,
    topk_impl: str = "hier",
    topk_recall: float = 0.95,
    topk_block: int = 32768,
    nms_sweep_cap: int = 0,
    nms_impl: str = "xla",
    fused_middle: bool = False,
    pallas_interpret: bool = False,
) -> Proposals:
    """Single-level proposal generation.

    Args:
      scores: (A,) objectness probabilities (post-sigmoid/softmax-fg).
      deltas: (A, 4) RPN regression output.
      anchors: (A, 4) matching anchor boxes.
      image_height/image_width: true (unpadded) image extent, may be traced.
      pre_nms_top_n / post_nms_top_n / nms_threshold / min_size: the
        reference's RPN_PRE_NMS_TOP_N / RPN_POST_NMS_TOP_N /
        config.TRAIN.RPN_NMS_THRESH / RPN_MIN_SIZE.
      topk_impl / topk_recall / topk_block: pre-NMS selection operator —
        see ``RPNConfig.topk_impl`` (config.py) for the semantics/parity
        argument.  ``"hier"`` (default) is the blocked exact top-k
        (bit-identical to ``"exact"``, see ``ops/topk.py``); only the
        strict-subset case (k < A) can go approx; k == A is a plain sort
        either way.
      nms_sweep_cap: 0 (default) runs the NMS fixed point to convergence
        (exact); > 0 bounds the sweep count (see ``ops/nms.py``).
      nms_impl: keep-mask backend for the non-fused path — ``"xla"``
        (default, the oracle) or ``"pallas"`` (see ``ops/nms.py``).
      fused_middle: run decode->clip->snap->NMS as ONE Pallas kernel
        (``ops/pallas/middle.py``), bit-identical to the dense chain.
        When set, ``nms_impl``/``nms_sweep_cap`` don't apply (the kernel
        IS the exact greedy NMS).
      pallas_interpret: run any Pallas kernel in interpret mode (CPU CI).

    Returns:
      Fixed-size Proposals; invalid slots carry zeros.
    """
    if fused_middle:
        from mx_rcnn_tpu.ops.pallas.middle import fused_middle_levels

        with jax.named_scope("fused_middle"):
            top_scores, top_deltas, top_anchors = _topk_candidates(
                scores, deltas, anchors,
                pre_nms_top_n, topk_impl, topk_recall, topk_block,
            )
            bx, msc, keep = fused_middle_levels(
                top_anchors[None], top_deltas[None], top_scores[None],
                image_height, image_width,
                min_size=min_size, iou_threshold=nms_threshold,
                interpret=pallas_interpret,
            )
            boxes, masked_scores = bx[0], msc[0]
            keep_idx, keep_valid = rank_keep(
                keep[0], masked_scores, post_nms_top_n
            )
    else:
        boxes, masked_scores = _pre_nms_candidates(
            scores, deltas, anchors, image_height, image_width,
            pre_nms_top_n, min_size, topk_impl, topk_recall, topk_block,
        )
        keep_idx, keep_valid = nms_indices(
            boxes, masked_scores, nms_threshold, post_nms_top_n,
            sweep_cap=nms_sweep_cap, nms_impl=nms_impl,
            interpret=pallas_interpret,
        )
    rois = jnp.take(boxes, keep_idx, axis=0) * keep_valid[:, None]
    out_scores = jnp.where(keep_valid, jnp.take(masked_scores, keep_idx), 0.0)
    return Proposals(rois=rois, scores=out_scores, valid=keep_valid)


def _topk_candidates(
    scores, deltas, anchors,
    pre_nms_top_n: int, topk_impl: str, topk_recall: float,
    topk_block: int = 32768,
):
    """Score snap + pre-NMS top-k + candidate gather.

    The front half shared by the dense chain (:func:`_pre_nms_candidates`)
    and the fused middle (``ops/pallas/middle.py`` takes over from here).
    Returns ``(top_scores (k,), deltas (k, 4), anchors (k, 4))`` in
    score-descending, index-ascending-tie order.
    """
    a = scores.shape[0]
    k = min(pre_nms_top_n, a)
    # snap(): top-k ranking and the NMS visit order are discrete in the
    # scores; snapped scores + index-stable tie-breaks (lax.top_k and
    # argsort both prefer the lower index) give the same candidate ordering
    # in every compilation of this graph (see geometry.boxes.snap).
    scores = snap(scores)

    if topk_impl == "approx" and k < a:
        top_scores, top_idx = lax.approx_max_k(
            scores, k, recall_target=topk_recall
        )
    elif topk_impl == "hier":
        # Blocked exact top-k — bit-identical to lax.top_k including the
        # snapped-score index-stable tie-breaks (proof in ops/topk.py).
        top_scores, top_idx = hierarchical_top_k(scores, k, block=topk_block)
    elif topk_impl in ("exact", "approx"):
        top_scores, top_idx = lax.top_k(scores, k)
    else:
        raise ValueError(
            f"topk_impl must be 'hier', 'exact' or 'approx', got {topk_impl!r}"
        )
    return (
        top_scores,
        jnp.take(deltas, top_idx, axis=0),
        jnp.take(anchors, top_idx, axis=0),
    )


def _pre_nms_candidates(
    scores, deltas, anchors, image_height, image_width,
    pre_nms_top_n: int, min_size: float, topk_impl: str, topk_recall: float,
    topk_block: int = 32768,
):
    """Shared pre-NMS front half: top-k by objectness, decode, clip, and
    min-size masking.  Returns (boxes (k, 4), masked_scores (k,)) with
    suppressed/invalid candidates at ``-inf`` score."""
    top_scores, top_deltas, top_anchors = _topk_candidates(
        scores, deltas, anchors, pre_nms_top_n, topk_impl, topk_recall,
        topk_block,
    )
    boxes = decode_boxes(top_deltas, top_anchors)
    boxes = clip_boxes(boxes, image_height, image_width)
    # snap to a 1/256-px grid: decode/clip arithmetic carries a few ulps of
    # cross-compilation noise at coordinate scale (~1e-5 px), which is the
    # same magnitude as the fine IoU snap grid downstream — snapping the
    # coordinates themselves makes every IoU consumer (NMS here, roi
    # sampling later) see bit-identical boxes.  1/256 px is far below
    # anything detection quality can notice.
    boxes = snap(boxes, bits=8)

    ok = valid_box_mask(boxes, min_size=min_size)
    masked_scores = jnp.where(ok, top_scores, -jnp.inf)
    return boxes, masked_scores


def generate_fpn_proposals(
    level_scores: dict[int, jnp.ndarray],
    level_deltas: dict[int, jnp.ndarray],
    level_anchors: dict[int, jnp.ndarray],
    image_height,
    image_width,
    pre_nms_top_n: int = 2000,
    post_nms_top_n: int = 1000,
    nms_threshold: float = 0.7,
    min_size: float = 0.0,
    topk_impl: str = "hier",
    topk_recall: float = 0.95,
    topk_block: int = 32768,
    nms_sweep_cap: int = 0,
    nms_impl: str = "xla",
    fused_middle: bool = False,
    pallas_interpret: bool = False,
) -> Proposals:
    """FPN-style proposals: per-level top-k + NMS, then global top-k by score.

    (Detectron recipe: PRE_NMS_TOPK per level, POST_NMS_TOPK across the
    union — the configuration the BASELINE north star's >=37 mAP requires.)

    The per-level NMS runs as ONE vmapped fixed point over the level axis
    (short levels padded to the widest k with ``-inf`` scores — padding
    never keeps nor suppresses, so each lane equals its standalone NMS
    bit-for-bit, tested).  L sequential while-loops would pay L
    convergence latencies back-to-back; one batched loop pays the
    worst lane's.  r4 A/B on the train step: see BASELINE.md.

    ``fused_middle`` replaces the decode->clip->snap->NMS chain with one
    Pallas launch gridded over the level axis (``ops/pallas/middle.py``)
    — bit-identical outputs, no HBM round-trips between the stages.
    ``nms_impl`` selects the keep-mask backend on the non-fused path
    ("pallas" runs one kernel launch per level — vmapping the sequential
    sweep would serialize anyway).
    """
    # Detectron recipe: each level may keep up to post_nms_top_n proposals;
    # the global top-k over the union then trims to post_nms_top_n total.
    levels = sorted(level_scores.keys())
    if fused_middle:
        from mx_rcnn_tpu.ops.pallas.middle import fused_middle_levels

        with jax.named_scope("fused_middle"):
            cand = [
                _topk_candidates(
                    level_scores[lvl], level_deltas[lvl], level_anchors[lvl],
                    pre_nms_top_n, topk_impl, topk_recall, topk_block,
                )
                for lvl in levels
            ]
            kmax = max(s.shape[0] for s, _, _ in cand)
            sc_k = jnp.stack(
                [
                    jnp.pad(s, (0, kmax - s.shape[0]),
                            constant_values=-jnp.inf)
                    for s, _, _ in cand
                ]
            )                                               # (L, kmax)
            dl_k = jnp.stack(
                [jnp.pad(d, ((0, kmax - d.shape[0]), (0, 0)))
                 for _, d, _ in cand]
            )                                               # (L, kmax, 4)
            an_k = jnp.stack(
                [jnp.pad(a, ((0, kmax - a.shape[0]), (0, 0)))
                 for _, _, a in cand]
            )                                               # (L, kmax, 4)
            bx, sc, keep = fused_middle_levels(
                an_k, dl_k, sc_k, image_height, image_width,
                min_size=min_size, iou_threshold=nms_threshold,
                interpret=pallas_interpret,
            )
            keep_idx, keep_valid = jax.vmap(
                lambda k_, s_: rank_keep(k_, s_, post_nms_top_n)
            )(keep, sc)                                     # (L, post) x2
    else:
        cand = [
            _pre_nms_candidates(
                level_scores[lvl], level_deltas[lvl], level_anchors[lvl],
                image_height, image_width,
                pre_nms_top_n, min_size, topk_impl, topk_recall, topk_block,
            )
            for lvl in levels
        ]
        kmax = max(b.shape[0] for b, _ in cand)
        bx = jnp.stack(
            [jnp.pad(b, ((0, kmax - b.shape[0]), (0, 0))) for b, _ in cand]
        )                                                   # (L, kmax, 4)
        sc = jnp.stack(
            [
                jnp.pad(s, (0, kmax - s.shape[0]), constant_values=-jnp.inf)
                for _, s in cand
            ]
        )                                                   # (L, kmax)

        if nms_impl == "pallas":
            # One sequential-sweep kernel launch per level; the sweeps
            # would serialize under vmap regardless.
            per_level = [
                nms_indices(
                    bx[l], sc[l], nms_threshold, post_nms_top_n,
                    nms_impl="pallas", interpret=pallas_interpret,
                )
                for l in range(len(levels))
            ]
            keep_idx = jnp.stack([i for i, _ in per_level])
            keep_valid = jnp.stack([v for _, v in per_level])
        else:
            keep_idx, keep_valid = jax.vmap(
                lambda b, s: nms_indices(
                    b, s, nms_threshold, post_nms_top_n,
                    sweep_cap=nms_sweep_cap,
                )
            )(bx, sc)                                       # (L, post) x2
    rois_l = jnp.take_along_axis(
        bx, keep_idx[..., None], axis=1
    ) * keep_valid[..., None]
    scores_l = jnp.where(
        keep_valid, jnp.take_along_axis(sc, keep_idx, axis=1), 0.0
    )

    rois = rois_l.reshape(-1, 4)
    scores = scores_l.reshape(-1)
    valid = keep_valid.reshape(-1)

    masked = jnp.where(valid, scores, -jnp.inf)
    k = min(post_nms_top_n, rois.shape[0])
    top_scores, top_idx = lax.top_k(masked, k)
    out_valid = jnp.isfinite(top_scores)
    out_rois = jnp.take(rois, top_idx, axis=0) * out_valid[:, None]
    return Proposals(
        rois=out_rois,
        scores=jnp.where(out_valid, top_scores, 0.0),
        valid=out_valid,
    )
