"""In-graph RPN proposal generation.

Replaces the reference Proposal custom op (``rcnn/symbol/proposal.py``,
and the engine's ``mx.contrib.symbol.Proposal`` behind CXX_PROPOSAL):
decode RPN outputs into scored boxes, pre-NMS top-k, NMS, and emit a fixed
``post_nms_top_n`` roi set — with zero host interaction.  The reference
pays a device->host->device round-trip plus a CUDA NMS here every
iteration (SURVEY.md section 4.5); this version is one fused XLA region.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from mx_rcnn_tpu.geometry import clip_boxes, decode_boxes, valid_box_mask
from mx_rcnn_tpu.ops.nms import nms_indices


class Proposals(NamedTuple):
    rois: jnp.ndarray    # (post_nms_top_n, 4)
    scores: jnp.ndarray  # (post_nms_top_n,)
    valid: jnp.ndarray   # (post_nms_top_n,) bool


def generate_proposals(
    scores: jnp.ndarray,
    deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    image_height,
    image_width,
    pre_nms_top_n: int = 6000,
    post_nms_top_n: int = 300,
    nms_threshold: float = 0.7,
    min_size: float = 0.0,
) -> Proposals:
    """Single-level proposal generation.

    Args:
      scores: (A,) objectness probabilities (post-sigmoid/softmax-fg).
      deltas: (A, 4) RPN regression output.
      anchors: (A, 4) matching anchor boxes.
      image_height/image_width: true (unpadded) image extent, may be traced.
      pre_nms_top_n / post_nms_top_n / nms_threshold / min_size: the
        reference's RPN_PRE_NMS_TOP_N / RPN_POST_NMS_TOP_N /
        config.TRAIN.RPN_NMS_THRESH / RPN_MIN_SIZE.

    Returns:
      Fixed-size Proposals; invalid slots carry zeros.
    """
    a = scores.shape[0]
    k = min(pre_nms_top_n, a)

    top_scores, top_idx = lax.top_k(scores, k)
    boxes = decode_boxes(
        jnp.take(deltas, top_idx, axis=0), jnp.take(anchors, top_idx, axis=0)
    )
    boxes = clip_boxes(boxes, image_height, image_width)

    ok = valid_box_mask(boxes, min_size=min_size)
    masked_scores = jnp.where(ok, top_scores, -jnp.inf)

    keep_idx, keep_valid = nms_indices(
        boxes, masked_scores, nms_threshold, post_nms_top_n
    )
    rois = jnp.take(boxes, keep_idx, axis=0) * keep_valid[:, None]
    out_scores = jnp.where(keep_valid, jnp.take(masked_scores, keep_idx), 0.0)
    return Proposals(rois=rois, scores=out_scores, valid=keep_valid)


def generate_fpn_proposals(
    level_scores: dict[int, jnp.ndarray],
    level_deltas: dict[int, jnp.ndarray],
    level_anchors: dict[int, jnp.ndarray],
    image_height,
    image_width,
    pre_nms_top_n: int = 2000,
    post_nms_top_n: int = 1000,
    nms_threshold: float = 0.7,
    min_size: float = 0.0,
) -> Proposals:
    """FPN-style proposals: per-level top-k + NMS, then global top-k by score.

    (Detectron recipe: PRE_NMS_TOPK per level, POST_NMS_TOPK across the
    union — the configuration the BASELINE north star's >=37 mAP requires.)
    """
    per_level = []
    # Detectron recipe: each level may keep up to post_nms_top_n proposals;
    # the global top-k over the union then trims to post_nms_top_n total.
    for lvl in sorted(level_scores.keys()):
        p = generate_proposals(
            level_scores[lvl],
            level_deltas[lvl],
            level_anchors[lvl],
            image_height,
            image_width,
            pre_nms_top_n=pre_nms_top_n,
            post_nms_top_n=post_nms_top_n,
            nms_threshold=nms_threshold,
            min_size=min_size,
        )
        per_level.append(p)

    rois = jnp.concatenate([p.rois for p in per_level], axis=0)
    scores = jnp.concatenate([p.scores for p in per_level], axis=0)
    valid = jnp.concatenate([p.valid for p in per_level], axis=0)

    masked = jnp.where(valid, scores, -jnp.inf)
    k = min(post_nms_top_n, rois.shape[0])
    top_scores, top_idx = lax.top_k(masked, k)
    out_valid = jnp.isfinite(top_scores)
    out_rois = jnp.take(rois, top_idx, axis=0) * out_valid[:, None]
    return Proposals(
        rois=out_rois,
        scores=jnp.where(out_valid, top_scores, 0.0),
        valid=out_valid,
    )
