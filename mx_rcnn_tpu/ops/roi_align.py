"""ROIAlign, XLA-native (gather + bilinear), with multilevel FPN dispatch.

Replaces the engine-side ``mx.symbol.ROIPooling`` CUDA op the reference's
R-CNN head depends on (SURVEY.md section 3.5), upgraded to ROIAlign per the
BASELINE north star.  Design notes for TPU:

- All shapes static: (R rois) x (S x S bins) x (sr x sr samples/bin).
- The bilinear gather is expressed as 4 corner gathers from the flattened
  (H*W, C) feature map with computed flat indices — XLA lowers this to
  dynamic-gather, which is the memory-bound but correct baseline; the
  Pallas kernel (ops/pallas/roi_align.py) is the performance path.
- Sample points are accumulated one at a time (sr*sr iterations, unrolled
  at trace time) so the peak intermediate is (R, S, S, C), not
  (R, S*sr, S*sr, C).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(2, 3, 4))
def roi_align(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: int = 7,
    spatial_scale: float = 1.0 / 16.0,
    sampling_ratio: int = 2,
) -> jnp.ndarray:
    """ROIAlign on a single feature map.

    Args:
      features: (H, W, C) feature map.
      rois: (R, 4) boxes in input-image coordinates (x1, y1, x2, y2).
      output_size: S — pooled bins per side (7 for box head, 14 for mask).
      spatial_scale: 1/stride of this feature map.
      sampling_ratio: sr — bilinear samples per bin side.

    Returns:
      (R, S, S, C) pooled features.
    """
    h, w, c = features.shape
    flat = features.reshape(h * w, c)

    scaled = rois * spatial_scale
    x1, y1 = scaled[:, 0], scaled[:, 1]
    rw = jnp.maximum(scaled[:, 2] - x1, 1.0)
    rh = jnp.maximum(scaled[:, 3] - y1, 1.0)
    bin_w = rw / output_size  # (R,)
    bin_h = rh / output_size

    bins = jnp.arange(output_size, dtype=jnp.float32)  # (S,)

    out = jnp.zeros((rois.shape[0], output_size, output_size, c), jnp.float32)
    for iy in range(sampling_ratio):
        fy = (iy + 0.5) / sampling_ratio
        # (R, S): absolute y of this sample row in every bin
        sy = y1[:, None] + (bins[None, :] + fy) * bin_h[:, None]
        for ix in range(sampling_ratio):
            fx = (ix + 0.5) / sampling_ratio
            sx = x1[:, None] + (bins[None, :] + fx) * bin_w[:, None]
            out = out + _bilinear_gather(flat, h, w, sy, sx)
    # f32 interpolation arithmetic, result back in the features' dtype
    # (keeps the Pallas kernel and this reference bit-for-bit interchangeable
    # inside a bf16 train graph, including cotangent dtypes in custom_vjp).
    return (out / (sampling_ratio * sampling_ratio)).astype(features.dtype)


def _bilinear_gather(flat, h, w, sy, sx):
    """Bilinear sample at (sy (R,S), sx (R,S)) -> (R, S, S, C).

    Out-of-range samples (beyond one pixel outside the map, matching
    Detectron ROIAlign semantics) contribute zero.  The single-map case of
    ``_bilinear_gather_flat`` with constant per-roi extents.
    """
    r = sy.shape[0]
    ones = jnp.ones((r,), jnp.float32)
    return _bilinear_gather_flat(
        flat,
        h * ones,
        w * ones,
        jnp.full((r,), w, jnp.int32),
        jnp.zeros((r,), jnp.int32),
        sy,
        sx,
    )


# Default bound on a roi's extent in feature cells at its assigned level.
# MUST equal the Pallas kernel's window - 10 (ops/pallas/roi_align.py,
# default T=48: 1 cell of bilinear margin per side + up to 7 cells lost to
# the 8-aligned x-origin + 1 tap) so the XLA and Pallas paths assign rois
# to identical levels.  Rois whose span would exceed it (pathologically
# thin-and-long boxes — small area, huge extent — that the area heuristic
# sends to a fine level) are bumped to a coarser level where they fit.
MAX_EXTENT_CELLS = 38


def fpn_level_assignment(
    rois: jnp.ndarray,
    min_level: int = 2,
    max_level: int = 5,
    canonical_scale: float = 224.0,
    canonical_level: int = 4,
    max_extent_cells: int | None = MAX_EXTENT_CELLS,
) -> jnp.ndarray:
    """FPN paper eq. 1: level k = k0 + log2(sqrt(area)/224), clamped; plus
    the extent bound above (pass ``max_extent_cells=None`` for the pure
    paper heuristic)."""
    w = jnp.maximum(rois[:, 2] - rois[:, 0], 1e-6)
    h = jnp.maximum(rois[:, 3] - rois[:, 1], 1e-6)
    k = canonical_level + jnp.log2(jnp.sqrt(w * h) / canonical_scale)
    k = jnp.floor(k).astype(jnp.int32)
    if max_extent_cells is not None:
        extent = jnp.maximum(w, h)
        k_fit = jnp.ceil(jnp.log2(extent / max_extent_cells)).astype(jnp.int32)
        k = jnp.maximum(k, k_fit)
    return jnp.clip(k, min_level, max_level)


def multilevel_roi_align(
    feature_pyramid: dict[int, jnp.ndarray],
    rois: jnp.ndarray,
    output_size: int = 7,
    sampling_ratio: int = 2,
    max_extent_cells: int | None = MAX_EXTENT_CELLS,
) -> jnp.ndarray:
    """ROIAlign over an FPN pyramid with per-roi level assignment.

    ``feature_pyramid`` maps level -> (H_l, W_l, C); stride of level l is
    2**l.  The levels are flattened and concatenated into ONE (sum H_l*W_l,
    C) buffer and each roi gathers through a per-roi base offset into it —
    one bilinear gather pass total (and one scatter-add in the backward),
    versus pooling every roi at every level and masking (4x the gather and
    scatter volume; kept as ``_multilevel_roi_align_dense``, the oracle).
    All shapes static, no host interaction.
    """
    levels = sorted(feature_pyramid.keys())
    c = feature_pyramid[levels[0]].shape[-1]
    flat = jnp.concatenate(
        [feature_pyramid[l].reshape(-1, c) for l in levels], axis=0
    )
    hs, ws, bases = [], [], []
    off = 0
    for l in levels:
        h, w, _ = feature_pyramid[l].shape
        hs.append(h)
        ws.append(w)
        bases.append(off)
        off += h * w
    hs = jnp.asarray(hs, jnp.float32)
    ws_f = jnp.asarray(ws, jnp.float32)
    ws_i = jnp.asarray(ws, jnp.int32)
    bases = jnp.asarray(bases, jnp.int32)

    assignment = fpn_level_assignment(
        rois, min_level=levels[0], max_level=levels[-1],
        max_extent_cells=max_extent_cells,
    )
    li = assignment - levels[0]                       # (R,) index into arrays
    scale = 2.0 ** (-assignment.astype(jnp.float32))  # (R,) 1/stride per roi
    h_r = jnp.take(hs, li)                            # (R,) float
    w_r = jnp.take(ws_f, li)
    wi_r = jnp.take(ws_i, li)                         # (R,) int row pitch
    base_r = jnp.take(bases, li)                      # (R,) int

    scaled = rois * scale[:, None]
    x1, y1 = scaled[:, 0], scaled[:, 1]
    rw = jnp.maximum(scaled[:, 2] - x1, 1.0)
    rh = jnp.maximum(scaled[:, 3] - y1, 1.0)
    bin_w = rw / output_size
    bin_h = rh / output_size
    bins = jnp.arange(output_size, dtype=jnp.float32)

    out = jnp.zeros((rois.shape[0], output_size, output_size, c), jnp.float32)
    for iy in range(sampling_ratio):
        fy = (iy + 0.5) / sampling_ratio
        sy = y1[:, None] + (bins[None, :] + fy) * bin_h[:, None]  # (R, S)
        for ix in range(sampling_ratio):
            fx = (ix + 0.5) / sampling_ratio
            sx = x1[:, None] + (bins[None, :] + fx) * bin_w[:, None]
            out = out + _bilinear_gather_flat(
                flat, h_r, w_r, wi_r, base_r, sy, sx
            )
    return (out / (sampling_ratio * sampling_ratio)).astype(flat.dtype)


def _bilinear_gather_flat(flat, h_r, w_r, wi_r, base_r, sy, sx):
    """Per-roi-extent bilinear sample into a concatenated pyramid buffer.

    Same semantics as ``_bilinear_gather`` with the map bounds (h_r, w_r),
    row pitch (wi_r) and flat-index base (base_r) varying per roi.
    """
    inside = (
        (sy[:, :, None] > -1.0)
        & (sy[:, :, None] < h_r[:, None, None])
        & (sx[:, None, :] > -1.0)
        & (sx[:, None, :] < w_r[:, None, None])
    )  # (R, S, S)

    y = jnp.clip(sy, 0.0, h_r[:, None] - 1)  # (R, S)
    x = jnp.clip(sx, 0.0, w_r[:, None] - 1)
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    ly = y - y0
    lx = x - x0
    y0i = y0.astype(jnp.int32)
    x0i = x0.astype(jnp.int32)
    y1i = jnp.minimum(y0i + 1, h_r[:, None].astype(jnp.int32) - 1)
    x1i = jnp.minimum(x0i + 1, w_r[:, None].astype(jnp.int32) - 1)

    def gather(yi, xi):  # yi (R,S), xi (R,S) -> (R, S, S, C)
        idx = base_r[:, None, None] + yi[:, :, None] * wi_r[:, None, None] + xi[:, None, :]
        return jnp.take(flat, idx.reshape(-1), axis=0).reshape(*idx.shape, -1)

    wy0 = (1.0 - ly)[:, :, None, None]
    wy1 = ly[:, :, None, None]
    wx0 = (1.0 - lx)[:, None, :, None]
    wx1 = lx[:, None, :, None]

    val = (
        gather(y0i, x0i) * wy0 * wx0
        + gather(y0i, x1i) * wy0 * wx1
        + gather(y1i, x0i) * wy1 * wx0
        + gather(y1i, x1i) * wy1 * wx1
    )
    return val * inside[..., None]


def _multilevel_roi_align_dense(
    feature_pyramid: dict[int, jnp.ndarray],
    rois: jnp.ndarray,
    output_size: int = 7,
    sampling_ratio: int = 2,
    max_extent_cells: int | None = MAX_EXTENT_CELLS,
) -> jnp.ndarray:
    """Oracle: pool every roi at every level, mask-select by assignment.

    4x the gather volume of ``multilevel_roi_align`` — kept for tests (the
    two must agree exactly) and as the reference semantics."""
    levels = sorted(feature_pyramid.keys())
    assignment = fpn_level_assignment(
        rois, min_level=levels[0], max_level=levels[-1],
        max_extent_cells=max_extent_cells,
    )
    out = None
    for lvl in levels:
        pooled = roi_align(
            feature_pyramid[lvl],
            rois,
            output_size=output_size,
            spatial_scale=1.0 / (2**lvl),
            sampling_ratio=sampling_ratio,
        )
        sel = (assignment == lvl).astype(pooled.dtype)[:, None, None, None]
        out = pooled * sel if out is None else out + pooled * sel
    return out
