"""In-graph target assignment and sampling.

Replaces two host-side components of the reference with static-shape,
rng-keyed, jit-safe functions:

- ``rcnn/io/rpn.py::assign_anchor`` (RPN anchor labeling + subsampling,
  run on the host by the data loader every batch) -> :func:`assign_anchors`.
- ``rcnn/symbol/proposal_target.py::ProposalTargetOperator`` +
  ``rcnn/io/rcnn.py::sample_rois`` (the device->host->device CustomOp in
  the middle of the train graph) -> :func:`sample_rois`.

Random subsampling with *fixed output shapes* uses the randomized-rank
trick: candidates get iid uniform priorities; "choose n of k" becomes
"rank < n" over the priorities, where n is a traced scalar.  No dynamic
shapes, no host RNG, reproducible from a jax PRNG key.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from mx_rcnn_tpu.geometry import encode_boxes, ioa_matrix, iou_matrix, snap
from mx_rcnn_tpu.ops.topk import hierarchical_top_k


def _ignore_overlap_mask(
    boxes: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_ignore: jnp.ndarray | None,
    threshold: float,
) -> jnp.ndarray:
    """(N,) bool: box has IoA >= threshold with some ignore/crowd region.

    Reference parity: the upstream loader drops crowd annotations entirely,
    silently letting anchors inside crowds train as negatives
    (``rcnn/dataset/coco.py`` skips iscrowd); Detectron-lineage crowd
    filtering (intersection-over-box-area, not IoU — a small anchor inside
    a huge crowd has tiny IoU) is the behavior real COCO training needs.
    """
    if gt_ignore is None:
        return jnp.zeros(boxes.shape[0], bool)
    # snap(): the >= threshold compare must not flip on cross-compilation
    # ulp noise (see geometry.boxes.snap).
    ioa = snap(ioa_matrix(boxes, gt_boxes)) * gt_ignore[None, :].astype(boxes.dtype)
    return jnp.max(ioa, axis=1) >= threshold


def _random_rank(key: jax.Array, candidate: jnp.ndarray) -> jnp.ndarray:
    """Rank of each element among candidates under a random permutation.

    Non-candidates rank after all candidates.  rank is 0-based: selecting
    ``rank < n`` picks n uniform-random candidates.  O(N log N) sort plus an
    O(N) scatter — fine at proposal scale (N ~ 2k in :func:`sample_rois`);
    use :func:`_select_random` for anchor-scale N (~262k at 1024x1024),
    where the full sort + scatter dominate the whole assignment.
    """
    pri = jax.random.uniform(key, candidate.shape)
    pri = jnp.where(candidate, pri, 2.0)  # non-candidates sort last
    order = jnp.argsort(pri)
    ranks = jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))
    return ranks


def _select_random(
    key: jax.Array,
    candidate: jnp.ndarray,
    n,
    quota: int,
    block: int = 0,
    with_indices: bool = False,
):
    """Uniform-random boolean selection of ``n`` (traced, <= static
    ``quota``) of the candidates.

    top_k of random priorities over the ``quota`` best replaces the full
    argsort-rank: the sort shrinks from O(N log N) to O(N log quota) and
    the scatter from N-wide to quota-wide.  Exact — ties are broken inside
    top_k by index, and exactly ``min(n, #candidates)`` entries come back
    True (callers pass ``n <= #candidates``).

    ``block`` > 0 routes the top_k through the blocked exact reduction
    (``ops/topk.py`` — bit-identical, avoids the full 268k-anchor sort).
    ``with_indices`` additionally returns ``(idx (quota,), take (quota,))``
    — the selected anchor rows and their active-slot mask — so callers
    can run losses on the compact selected set instead of the full
    anchor axis (``RPNConfig.loss_impl == "compact"``).
    """
    a = candidate.shape[0]
    n = jnp.minimum(n, jnp.sum(candidate))  # total: never select non-candidates
    pri = jax.random.uniform(key, (a,))
    pri = jnp.where(candidate, pri, -1.0)  # non-candidates last under max
    k = min(quota, a)
    if block and block > 0:
        _, idx = hierarchical_top_k(pri, k, block=block)
    else:
        _, idx = jax.lax.top_k(pri, k)  # quota most-prior candidates
    take = jnp.arange(idx.shape[0]) < n
    mask = jnp.zeros((a,), bool).at[idx].set(take)
    if with_indices:
        return mask, idx, take
    return mask


class AnchorTargets(NamedTuple):
    labels: jnp.ndarray        # (A,) int32: 1 fg, 0 bg, -1 ignore
    bbox_targets: jnp.ndarray  # (A, 4) encode of matched gt (fg rows only meaningful)
    fg_mask: jnp.ndarray       # (A,) bool
    valid_mask: jnp.ndarray    # (A,) bool: labels != -1 (loss-contributing)
    # Compact view of the sampled minibatch (fg quota block then bg quota
    # block): the anchor rows the losses actually touch.  Lets the RPN
    # loss gather Q = fg_quota + batch_size rows instead of reducing over
    # all A anchors (``RPNConfig.loss_impl == "compact"``).  Inactive
    # slots have sel_take False (their sel_idx is an arbitrary row).
    sel_idx: jnp.ndarray | None = None   # (Q,) int32 anchor rows
    sel_take: jnp.ndarray | None = None  # (Q,) bool: slot is a real sample
    sel_fg: jnp.ndarray | None = None    # (Q,) bool: slot is a fg sample


def _per_anchor_stats_dense(
    anchors, gt_boxes, gt_valid, gt_ignore,
    image_height, image_width, allowed_border, ignore_ioa,
):
    """Single-pass (A, G) reduction: the original assign_anchors middle.

    Returns per-anchor ``(inside, max_iou, argmax_gt, is_gt_best,
    in_ignore)`` plus the per-gt best IoU vector.
    """
    inside = (
        (anchors[:, 0] >= -allowed_border)
        & (anchors[:, 1] >= -allowed_border)
        & (anchors[:, 2] < image_width + allowed_border)
        & (anchors[:, 3] < image_height + allowed_border)
    )

    # snap(): fg/bg labeling is all discrete decisions (thresholds, per-gt
    # best ties) on these IoUs; snapping makes them bit-identical across
    # differently-partitioned compilations (see geometry.boxes.snap).
    iou = snap(iou_matrix(anchors, gt_boxes))  # (A, G)
    iou = iou * gt_valid[None, :].astype(iou.dtype)
    max_iou = jnp.max(iou, axis=1)
    argmax_gt = jnp.argmax(iou, axis=1)

    # Per-gt best anchors (with ties, like the reference's gt_argmax trick).
    # Restricted to INSIDE anchors — the reference filters to inside anchors
    # before the gt-argmax step, so a gt near the border still gets its best
    # in-bounds anchor as a positive.
    iou_inside = iou * inside[:, None].astype(iou.dtype)
    gt_best = jnp.max(iou_inside, axis=0)  # (G,)
    # Exact == is safe here because the IoUs are snapped to a coarse grid:
    # ties are true ties in every compilation of this graph.
    is_gt_best = jnp.any(
        (iou_inside == gt_best[None, :]) & gt_valid[None, :] & (gt_best[None, :] > 0.0),
        axis=1,
    )
    in_ignore = _ignore_overlap_mask(anchors, gt_boxes, gt_ignore, ignore_ioa)
    return inside, max_iou, argmax_gt, is_gt_best, in_ignore


def _per_anchor_stats_blocked(
    anchors, gt_boxes, gt_valid, gt_ignore,
    image_height, image_width, allowed_border, ignore_ioa, block,
):
    """Tiled equivalent of :func:`_per_anchor_stats_dense` — bit-identical.

    The (A, G) IoU matrix (34 MB at the 268k-anchor recipe canvas) never
    materializes: a ``lax.scan`` over ``block``-anchor tiles computes each
    tile's IoU in VMEM, reduces it to the per-anchor stats in the same
    fusion, and carries only the (G,) per-gt running best.  A second
    sweep recomputes each tile's IoU (arithmetically the exact same
    elementwise values — ~86 MFLOP, noise) to test the snapped-IoU
    equality against the now-final ``gt_best``.

    Bitwise parity with the dense pass (asserted exactly in
    tests/test_detection_middle.py): elementwise IoU/IoA/threshold math is identical
    per anchor regardless of tiling, and f32 ``max`` is associative and
    commutative exactly, so the blockwise per-gt maximum equals the
    global one bit for bit.
    """
    a = anchors.shape[0]
    nb = -(-a // block)
    pad = nb * block - a
    apad = (
        jnp.concatenate([anchors, jnp.zeros((pad, 4), anchors.dtype)])
        if pad
        else anchors
    )
    tiles = apad.reshape(nb, block, 4)
    real = (jnp.arange(nb * block) < a).reshape(nb, block)
    gvf = gt_valid.astype(anchors.dtype)

    def tile_stats(ab, rb):
        inside = (
            rb
            & (ab[:, 0] >= -allowed_border)
            & (ab[:, 1] >= -allowed_border)
            & (ab[:, 2] < image_width + allowed_border)
            & (ab[:, 3] < image_height + allowed_border)
        )
        iou = snap(iou_matrix(ab, gt_boxes)) * gvf[None, :]
        return inside, iou * inside[:, None].astype(iou.dtype), iou

    def pass1(gt_best, xs):
        ab, rb = xs
        inside, iou_inside, iou = tile_stats(ab, rb)
        max_iou = jnp.max(iou, axis=1)
        argmax_gt = jnp.argmax(iou, axis=1)
        gt_best = jnp.maximum(gt_best, jnp.max(iou_inside, axis=0))
        if gt_ignore is None:
            in_ignore = jnp.zeros(ab.shape[0], bool)
        else:
            ioa = snap(ioa_matrix(ab, gt_boxes)) * gt_ignore[None, :].astype(
                ab.dtype
            )
            in_ignore = jnp.max(ioa, axis=1) >= ignore_ioa
        return gt_best, (inside, max_iou, argmax_gt, in_ignore)

    gt_best0 = jnp.zeros(gt_boxes.shape[0], anchors.dtype)
    gt_best, (inside, max_iou, argmax_gt, in_ignore) = lax.scan(
        pass1, gt_best0, (tiles, real)
    )

    def pass2(carry, xs):
        ab, rb = xs
        _, iou_inside, _ = tile_stats(ab, rb)
        is_best = jnp.any(
            (iou_inside == gt_best[None, :])
            & gt_valid[None, :]
            & (gt_best[None, :] > 0.0),
            axis=1,
        )
        return carry, is_best

    _, is_gt_best = lax.scan(pass2, 0, (tiles, real))

    def flat(x):
        return x.reshape(nb * block)[:a]

    return (
        flat(inside), flat(max_iou), flat(argmax_gt), flat(is_gt_best),
        flat(in_ignore),
    )


def _per_row_stats_blocked(
    boxes, row_valid, gt_boxes, gt_valid, gt_ignore, ignore_ioa, block,
    iou_bits,
):
    """Tiled per-row IoU stats for ROI sampling — the :func:`sample_rois`
    sibling of :func:`_per_anchor_stats_blocked`'s pass 1.

    One ``lax.scan`` over ``block``-row tiles computes each tile's
    (block, G) IoU/IoA in VMEM and reduces it to ``(max_iou, argmax_gt,
    in_ignore)`` in the same fusion; the full (N, G) matrices never
    materialize.  Bit-identical to the dense pass for the same reason the
    anchor variant is: the elementwise IoU/IoA values don't depend on the
    tiling, and the max/argmax reductions are per ROW, so they never
    cross a tile boundary at all.  No second sweep is needed — ROI
    sampling has no cross-row ``gt_best`` coupling.
    """
    n = boxes.shape[0]
    nb = -(-n // block)
    pad = nb * block - n
    bpad = (
        jnp.concatenate([boxes, jnp.zeros((pad, 4), boxes.dtype)])
        if pad
        else boxes
    )
    vpad = (
        jnp.concatenate([row_valid, jnp.zeros(pad, bool)])
        if pad
        else row_valid
    )
    tiles = bpad.reshape(nb, block, 4)
    vtiles = vpad.reshape(nb, block)
    gvf = gt_valid.astype(boxes.dtype)

    def body(carry, xs):
        bb, vb = xs
        iou = snap(iou_matrix(bb, gt_boxes), bits=iou_bits) * gvf[None, :]
        max_iou = jnp.where(vb, jnp.max(iou, axis=1), -1.0)
        argmax_gt = jnp.argmax(iou, axis=1)
        if gt_ignore is None:
            in_ignore = jnp.zeros(bb.shape[0], bool)
        else:
            ioa = snap(ioa_matrix(bb, gt_boxes)) * gt_ignore[None, :].astype(
                bb.dtype
            )
            in_ignore = jnp.max(ioa, axis=1) >= ignore_ioa
        return carry, (max_iou, argmax_gt, in_ignore)

    _, (max_iou, argmax_gt, in_ignore) = lax.scan(
        body, 0, (tiles, vtiles)
    )

    def flat(x):
        return x.reshape(nb * block)[:n]

    return flat(max_iou), flat(argmax_gt), flat(in_ignore)


def assign_anchors(
    key: jax.Array,
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    image_height,
    image_width,
    batch_size: int = 256,
    fg_fraction: float = 0.5,
    positive_iou: float = 0.7,
    negative_iou: float = 0.3,
    allowed_border: float = 0.0,
    gt_ignore: jnp.ndarray | None = None,
    ignore_ioa: float = 0.5,
    assign_block: int = 16384,
    topk_block: int = 32768,
) -> AnchorTargets:
    """Label anchors for RPN training (reference assign_anchor semantics).

    - anchors crossing the image boundary (by more than ``allowed_border``)
      are ignored;
    - fg: IoU >= positive_iou with some gt, PLUS every gt's best anchor
      (so each gt gets at least one positive even below the threshold);
    - bg: max IoU < negative_iou;
    - subsample to ``batch_size`` with at most ``fg_fraction`` positives;
      leftover fg quota is given to bg (reference behavior).

    ``gt_boxes`` is padded to a static G with ``gt_valid`` masking; slots
    flagged in ``gt_ignore`` (COCO crowd / VOC difficult) are never fg
    matches, and anchors covering them (IoA >= ``ignore_ioa``) are excluded
    from bg so crowds don't train as negatives.

    ``assign_block`` > 0 tiles the anchor axis so the (A, G) IoU never
    materializes (``_per_anchor_stats_blocked`` — bit-identical to the
    dense pass, see its docstring); ``topk_block`` routes the two
    subsampling top_k's through the blocked exact reduction.  0 disables
    either (the original dense/global forms).
    """
    a = anchors.shape[0]
    if assign_block and 0 < assign_block < a:
        inside, max_iou, argmax_gt, is_gt_best, in_ignore = (
            _per_anchor_stats_blocked(
                anchors, gt_boxes, gt_valid, gt_ignore,
                image_height, image_width, allowed_border, ignore_ioa,
                assign_block,
            )
        )
    else:
        inside, max_iou, argmax_gt, is_gt_best, in_ignore = (
            _per_anchor_stats_dense(
                anchors, gt_boxes, gt_valid, gt_ignore,
                image_height, image_width, allowed_border, ignore_ioa,
            )
        )

    any_gt = jnp.any(gt_valid)
    fg_cand = inside & any_gt & ((max_iou >= positive_iou) | is_gt_best)
    bg_cand = inside & (max_iou < negative_iou) & ~fg_cand & ~in_ignore

    num_fg_quota = int(batch_size * fg_fraction)
    k_fg, k_bg = jax.random.split(key)
    n_fg = jnp.minimum(num_fg_quota, jnp.sum(fg_cand))
    fg, fg_idx, fg_take = _select_random(
        k_fg, fg_cand, n_fg, num_fg_quota, block=topk_block, with_indices=True
    )

    n_bg = jnp.minimum(batch_size - n_fg, jnp.sum(bg_cand))
    bg, bg_idx, bg_take = _select_random(
        k_bg, bg_cand, n_bg, batch_size, block=topk_block, with_indices=True
    )

    labels = jnp.full((a,), -1, dtype=jnp.int32)
    labels = jnp.where(bg, 0, labels)
    labels = jnp.where(fg, 1, labels)

    matched = jnp.take(gt_boxes, argmax_gt, axis=0)  # (A, 4)
    bbox_targets = encode_boxes(matched, anchors)
    bbox_targets = jnp.where(fg[:, None], bbox_targets, 0.0)

    return AnchorTargets(
        labels=labels,
        bbox_targets=bbox_targets,
        fg_mask=fg,
        valid_mask=labels >= 0,
        sel_idx=jnp.concatenate([fg_idx, bg_idx]).astype(jnp.int32),
        sel_take=jnp.concatenate([fg_take, bg_take]),
        sel_fg=jnp.concatenate([fg_take, jnp.zeros_like(bg_take)]),
    )


class RoiSamples(NamedTuple):
    rois: jnp.ndarray          # (B, 4)
    labels: jnp.ndarray        # (B,) int32 class ids (0 = background)
    label_weights: jnp.ndarray # (B,) 1.0 for real samples, 0.0 for padding
    bbox_targets: jnp.ndarray  # (B, 4) encoded vs the roi (fg rows only)
    fg_mask: jnp.ndarray       # (B,) bool
    gt_indices: jnp.ndarray    # (B,) int32 matched gt row (fg rows only
                               # meaningful; mask-target lookup)


def sample_rois(
    key: jax.Array,
    rois: jnp.ndarray,
    roi_valid: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_classes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    batch_size: int = 512,
    fg_fraction: float = 0.25,
    fg_iou: float = 0.5,
    bg_iou_hi: float = 0.5,
    bg_iou_lo: float = 0.0,
    bbox_weights: tuple[float, float, float, float] = (10.0, 10.0, 5.0, 5.0),
    gt_ignore: jnp.ndarray | None = None,
    ignore_ioa: float = 0.5,
    roi_block: int = 0,
) -> RoiSamples:
    """Sample proposals into a fixed R-CNN minibatch with targets.

    Mirrors ProposalTargetOperator: gt boxes are appended to the proposal
    set (guaranteeing clean positives early in training), rois are matched
    to gt by IoU, and a fixed-size batch is drawn at ``fg_fraction``.  Where
    the reference resamples with replacement to fill the quota, we emit
    zero-weight padding slots and normalize losses by the valid count —
    equivalent in expectation, shape-static, and bias-free.

    ``bbox_weights`` is 1/std of the reference's ``TRAIN.BBOX_NORMALIZATION``
    (targets scaled in-graph; the head's predictions are unscaled at decode).

    ``roi_block`` > 0 tiles the ROI axis so the (R+G, G) IoU/IoA matrices
    never materialize (:func:`_per_row_stats_blocked` — bit-identical to
    the dense pass, see its docstring); <= 0 keeps the dense form.
    """
    all_rois = jnp.concatenate([rois, gt_boxes], axis=0)  # (R+G, 4)
    all_valid = jnp.concatenate([roi_valid, gt_valid], axis=0)

    # snap() at bits=8 (IoU grid ~0.004, invisible next to the 0.5/0.3
    # thresholds): fg/bg thresholds and argmax matching below are discrete —
    # keep them bit-stable across compilations (see geometry.boxes.snap).
    # The rois here are network outputs, so per-program contraction noise
    # is broader than for constant anchor grids and needs the wider
    # midpoint margin.
    if roi_block and 0 < roi_block < all_rois.shape[0]:
        max_iou, argmax_gt, in_ignore = _per_row_stats_blocked(
            all_rois, all_valid, gt_boxes, gt_valid, gt_ignore, ignore_ioa,
            roi_block, iou_bits=8,
        )
    else:
        iou = snap(iou_matrix(all_rois, gt_boxes), bits=8) * gt_valid[None, :].astype(rois.dtype)
        max_iou = jnp.where(all_valid, jnp.max(iou, axis=1), -1.0)
        argmax_gt = jnp.argmax(iou, axis=1)
        in_ignore = _ignore_overlap_mask(
            all_rois, gt_boxes, gt_ignore, ignore_ioa
        )

    fg_cand = all_valid & (max_iou >= fg_iou)
    bg_cand = (
        all_valid
        & (max_iou < bg_iou_hi)
        & (max_iou >= bg_iou_lo)
        & ~fg_cand
        & ~in_ignore
    )

    num_fg_quota = int(batch_size * fg_fraction)
    k_fg, k_bg = jax.random.split(key)
    fg_rank = _random_rank(k_fg, fg_cand)
    n_fg = jnp.minimum(num_fg_quota, jnp.sum(fg_cand))
    fg_sel = fg_cand & (fg_rank < n_fg)

    bg_rank = _random_rank(k_bg, bg_cand)
    n_bg = jnp.minimum(batch_size - n_fg, jnp.sum(bg_cand))
    bg_sel = bg_cand & (bg_rank < n_bg)

    # Compact selected rois into the fixed batch: fg block, then bg block,
    # then zero-weight padding.  Selection priority is monotone-decreasing,
    # so one argsort produces the gather order.
    pri = jnp.where(fg_sel, 3.0e9 - fg_rank, jnp.where(bg_sel, 1.0e9 - bg_rank, -1.0))
    order = jnp.argsort(-pri)[:batch_size]
    picked = jnp.take(pri, order) > 0.0  # (B,) real sample?

    out_rois = jnp.take(all_rois, order, axis=0)
    out_fg = jnp.take(fg_sel, order)
    matched_gt = jnp.take(argmax_gt, order)
    cls = jnp.take(gt_classes, matched_gt)
    labels = jnp.where(out_fg, cls, 0).astype(jnp.int32)

    matched_boxes = jnp.take(gt_boxes, matched_gt, axis=0)
    targets = encode_boxes(matched_boxes, out_rois, weights=bbox_weights)
    targets = jnp.where(out_fg[:, None], targets, 0.0)

    return RoiSamples(
        rois=out_rois,
        labels=labels,
        label_weights=picked.astype(jnp.float32),
        bbox_targets=targets,
        fg_mask=out_fg,
        gt_indices=matched_gt.astype(jnp.int32),
    )
