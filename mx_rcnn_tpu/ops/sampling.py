"""In-graph target assignment and sampling.

Replaces two host-side components of the reference with static-shape,
rng-keyed, jit-safe functions:

- ``rcnn/io/rpn.py::assign_anchor`` (RPN anchor labeling + subsampling,
  run on the host by the data loader every batch) -> :func:`assign_anchors`.
- ``rcnn/symbol/proposal_target.py::ProposalTargetOperator`` +
  ``rcnn/io/rcnn.py::sample_rois`` (the device->host->device CustomOp in
  the middle of the train graph) -> :func:`sample_rois`.

Random subsampling with *fixed output shapes* uses the randomized-rank
trick: candidates get iid uniform priorities; "choose n of k" becomes
"rank < n" over the priorities, where n is a traced scalar.  No dynamic
shapes, no host RNG, reproducible from a jax PRNG key.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.geometry import encode_boxes, ioa_matrix, iou_matrix, snap


def _ignore_overlap_mask(
    boxes: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_ignore: jnp.ndarray | None,
    threshold: float,
) -> jnp.ndarray:
    """(N,) bool: box has IoA >= threshold with some ignore/crowd region.

    Reference parity: the upstream loader drops crowd annotations entirely,
    silently letting anchors inside crowds train as negatives
    (``rcnn/dataset/coco.py`` skips iscrowd); Detectron-lineage crowd
    filtering (intersection-over-box-area, not IoU — a small anchor inside
    a huge crowd has tiny IoU) is the behavior real COCO training needs.
    """
    if gt_ignore is None:
        return jnp.zeros(boxes.shape[0], bool)
    # snap(): the >= threshold compare must not flip on cross-compilation
    # ulp noise (see geometry.boxes.snap).
    ioa = snap(ioa_matrix(boxes, gt_boxes)) * gt_ignore[None, :].astype(boxes.dtype)
    return jnp.max(ioa, axis=1) >= threshold


def _random_rank(key: jax.Array, candidate: jnp.ndarray) -> jnp.ndarray:
    """Rank of each element among candidates under a random permutation.

    Non-candidates rank after all candidates.  rank is 0-based: selecting
    ``rank < n`` picks n uniform-random candidates.  O(N log N) sort plus an
    O(N) scatter — fine at proposal scale (N ~ 2k in :func:`sample_rois`);
    use :func:`_select_random` for anchor-scale N (~262k at 1024x1024),
    where the full sort + scatter dominate the whole assignment.
    """
    pri = jax.random.uniform(key, candidate.shape)
    pri = jnp.where(candidate, pri, 2.0)  # non-candidates sort last
    order = jnp.argsort(pri)
    ranks = jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))
    return ranks


def _select_random(
    key: jax.Array, candidate: jnp.ndarray, n, quota: int
) -> jnp.ndarray:
    """Uniform-random boolean selection of ``n`` (traced, <= static
    ``quota``) of the candidates.

    top_k of random priorities over the ``quota`` best replaces the full
    argsort-rank: the sort shrinks from O(N log N) to O(N log quota) and
    the scatter from N-wide to quota-wide.  Exact — ties are broken inside
    top_k by index, and exactly ``min(n, #candidates)`` entries come back
    True (callers pass ``n <= #candidates``).
    """
    a = candidate.shape[0]
    n = jnp.minimum(n, jnp.sum(candidate))  # total: never select non-candidates
    pri = jax.random.uniform(key, (a,))
    pri = jnp.where(candidate, pri, -1.0)  # non-candidates last under max
    _, idx = jax.lax.top_k(pri, min(quota, a))  # quota most-prior candidates
    take = jnp.arange(idx.shape[0]) < n
    return jnp.zeros((a,), bool).at[idx].set(take)


class AnchorTargets(NamedTuple):
    labels: jnp.ndarray        # (A,) int32: 1 fg, 0 bg, -1 ignore
    bbox_targets: jnp.ndarray  # (A, 4) encode of matched gt (fg rows only meaningful)
    fg_mask: jnp.ndarray       # (A,) bool
    valid_mask: jnp.ndarray    # (A,) bool: labels != -1 (loss-contributing)


def assign_anchors(
    key: jax.Array,
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    image_height,
    image_width,
    batch_size: int = 256,
    fg_fraction: float = 0.5,
    positive_iou: float = 0.7,
    negative_iou: float = 0.3,
    allowed_border: float = 0.0,
    gt_ignore: jnp.ndarray | None = None,
    ignore_ioa: float = 0.5,
) -> AnchorTargets:
    """Label anchors for RPN training (reference assign_anchor semantics).

    - anchors crossing the image boundary (by more than ``allowed_border``)
      are ignored;
    - fg: IoU >= positive_iou with some gt, PLUS every gt's best anchor
      (so each gt gets at least one positive even below the threshold);
    - bg: max IoU < negative_iou;
    - subsample to ``batch_size`` with at most ``fg_fraction`` positives;
      leftover fg quota is given to bg (reference behavior).

    ``gt_boxes`` is padded to a static G with ``gt_valid`` masking; slots
    flagged in ``gt_ignore`` (COCO crowd / VOC difficult) are never fg
    matches, and anchors covering them (IoA >= ``ignore_ioa``) are excluded
    from bg so crowds don't train as negatives.
    """
    a = anchors.shape[0]
    inside = (
        (anchors[:, 0] >= -allowed_border)
        & (anchors[:, 1] >= -allowed_border)
        & (anchors[:, 2] < image_width + allowed_border)
        & (anchors[:, 3] < image_height + allowed_border)
    )

    # snap(): fg/bg labeling is all discrete decisions (thresholds, per-gt
    # best ties) on these IoUs; snapping makes them bit-identical across
    # differently-partitioned compilations (see geometry.boxes.snap).
    iou = snap(iou_matrix(anchors, gt_boxes))  # (A, G)
    iou = iou * gt_valid[None, :].astype(iou.dtype)
    max_iou = jnp.max(iou, axis=1)
    argmax_gt = jnp.argmax(iou, axis=1)

    # Per-gt best anchors (with ties, like the reference's gt_argmax trick).
    # Restricted to INSIDE anchors — the reference filters to inside anchors
    # before the gt-argmax step, so a gt near the border still gets its best
    # in-bounds anchor as a positive.
    any_gt = jnp.any(gt_valid)
    iou_inside = iou * inside[:, None].astype(iou.dtype)
    gt_best = jnp.max(iou_inside, axis=0)  # (G,)
    # Exact == is safe here because the IoUs are snapped to a coarse grid:
    # ties are true ties in every compilation of this graph.
    is_gt_best = jnp.any(
        (iou_inside == gt_best[None, :]) & gt_valid[None, :] & (gt_best[None, :] > 0.0),
        axis=1,
    )

    fg_cand = inside & any_gt & ((max_iou >= positive_iou) | is_gt_best)
    in_ignore = _ignore_overlap_mask(anchors, gt_boxes, gt_ignore, ignore_ioa)
    bg_cand = inside & (max_iou < negative_iou) & ~fg_cand & ~in_ignore

    num_fg_quota = int(batch_size * fg_fraction)
    k_fg, k_bg = jax.random.split(key)
    n_fg = jnp.minimum(num_fg_quota, jnp.sum(fg_cand))
    fg = _select_random(k_fg, fg_cand, n_fg, num_fg_quota)

    n_bg = jnp.minimum(batch_size - n_fg, jnp.sum(bg_cand))
    bg = _select_random(k_bg, bg_cand, n_bg, batch_size)

    labels = jnp.full((a,), -1, dtype=jnp.int32)
    labels = jnp.where(bg, 0, labels)
    labels = jnp.where(fg, 1, labels)

    matched = jnp.take(gt_boxes, argmax_gt, axis=0)  # (A, 4)
    bbox_targets = encode_boxes(matched, anchors)
    bbox_targets = jnp.where(fg[:, None], bbox_targets, 0.0)

    return AnchorTargets(
        labels=labels,
        bbox_targets=bbox_targets,
        fg_mask=fg,
        valid_mask=labels >= 0,
    )


class RoiSamples(NamedTuple):
    rois: jnp.ndarray          # (B, 4)
    labels: jnp.ndarray        # (B,) int32 class ids (0 = background)
    label_weights: jnp.ndarray # (B,) 1.0 for real samples, 0.0 for padding
    bbox_targets: jnp.ndarray  # (B, 4) encoded vs the roi (fg rows only)
    fg_mask: jnp.ndarray       # (B,) bool
    gt_indices: jnp.ndarray    # (B,) int32 matched gt row (fg rows only
                               # meaningful; mask-target lookup)


def sample_rois(
    key: jax.Array,
    rois: jnp.ndarray,
    roi_valid: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_classes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    batch_size: int = 512,
    fg_fraction: float = 0.25,
    fg_iou: float = 0.5,
    bg_iou_hi: float = 0.5,
    bg_iou_lo: float = 0.0,
    bbox_weights: tuple[float, float, float, float] = (10.0, 10.0, 5.0, 5.0),
    gt_ignore: jnp.ndarray | None = None,
    ignore_ioa: float = 0.5,
) -> RoiSamples:
    """Sample proposals into a fixed R-CNN minibatch with targets.

    Mirrors ProposalTargetOperator: gt boxes are appended to the proposal
    set (guaranteeing clean positives early in training), rois are matched
    to gt by IoU, and a fixed-size batch is drawn at ``fg_fraction``.  Where
    the reference resamples with replacement to fill the quota, we emit
    zero-weight padding slots and normalize losses by the valid count —
    equivalent in expectation, shape-static, and bias-free.

    ``bbox_weights`` is 1/std of the reference's ``TRAIN.BBOX_NORMALIZATION``
    (targets scaled in-graph; the head's predictions are unscaled at decode).
    """
    all_rois = jnp.concatenate([rois, gt_boxes], axis=0)  # (R+G, 4)
    all_valid = jnp.concatenate([roi_valid, gt_valid], axis=0)

    # snap(): fg/bg thresholds and argmax matching below are discrete — keep
    # them bit-stable across compilations (see geometry.boxes.snap).  bits=8
    # (IoU grid ~0.004, invisible next to the 0.5/0.3 thresholds): the rois
    # here are network outputs, so per-program contraction noise is broader
    # than for constant anchor grids and needs the wider midpoint margin.
    iou = snap(iou_matrix(all_rois, gt_boxes), bits=8) * gt_valid[None, :].astype(rois.dtype)
    max_iou = jnp.where(all_valid, jnp.max(iou, axis=1), -1.0)
    argmax_gt = jnp.argmax(iou, axis=1)

    fg_cand = all_valid & (max_iou >= fg_iou)
    in_ignore = _ignore_overlap_mask(all_rois, gt_boxes, gt_ignore, ignore_ioa)
    bg_cand = (
        all_valid
        & (max_iou < bg_iou_hi)
        & (max_iou >= bg_iou_lo)
        & ~fg_cand
        & ~in_ignore
    )

    num_fg_quota = int(batch_size * fg_fraction)
    k_fg, k_bg = jax.random.split(key)
    fg_rank = _random_rank(k_fg, fg_cand)
    n_fg = jnp.minimum(num_fg_quota, jnp.sum(fg_cand))
    fg_sel = fg_cand & (fg_rank < n_fg)

    bg_rank = _random_rank(k_bg, bg_cand)
    n_bg = jnp.minimum(batch_size - n_fg, jnp.sum(bg_cand))
    bg_sel = bg_cand & (bg_rank < n_bg)

    # Compact selected rois into the fixed batch: fg block, then bg block,
    # then zero-weight padding.  Selection priority is monotone-decreasing,
    # so one argsort produces the gather order.
    pri = jnp.where(fg_sel, 3.0e9 - fg_rank, jnp.where(bg_sel, 1.0e9 - bg_rank, -1.0))
    order = jnp.argsort(-pri)[:batch_size]
    picked = jnp.take(pri, order) > 0.0  # (B,) real sample?

    out_rois = jnp.take(all_rois, order, axis=0)
    out_fg = jnp.take(fg_sel, order)
    matched_gt = jnp.take(argmax_gt, order)
    cls = jnp.take(gt_classes, matched_gt)
    labels = jnp.where(out_fg, cls, 0).astype(jnp.int32)

    matched_boxes = jnp.take(gt_boxes, matched_gt, axis=0)
    targets = encode_boxes(matched_boxes, out_rois, weights=bbox_weights)
    targets = jnp.where(out_fg[:, None], targets, 0.0)

    return RoiSamples(
        rois=out_rois,
        labels=labels,
        label_weights=picked.astype(jnp.float32),
        bbox_targets=targets,
        fg_mask=out_fg,
        gt_indices=matched_gt.astype(jnp.int32),
    )
