"""Blocked (hierarchical) exact top-k.

``lax.top_k`` over the full anchor set is the single hottest non-matmul
op in the train step (7.40 ms for the two 268,569-anchor images of the
recipe batch — ``tools/perf_breakdown.py`` micro-bench): XLA lowers it to
a full sort of the operand.  :func:`hierarchical_top_k` replaces the one
global sort with a two-stage reduction —

  1. reshape the operand into ``nb`` contiguous blocks and take a
     per-block ``top_k`` (one batched sort over ``block``-sized rows,
     VPU-friendly and parallel across blocks);
  2. merge: one final ``top_k`` over the ``nb * min(k, block)``
     survivors, then gather the surviving global indices.

EXACTNESS (bit-identical to ``lax.top_k``, including ties):

``lax.top_k`` orders by (value desc, index asc) — the lower index wins a
tie.  The blocked reduction preserves that total order end to end:

- Any element of the true global top-k has fewer than ``k`` elements
  ahead of it in that order *globally*, hence fewer than ``k`` ahead of
  it *within its own block*, so it survives stage 1 (which keeps
  ``min(k, block)`` per block).  The survivor set therefore contains the
  true top-k.
- Stage 1 emits survivors in (block asc, within-block rank asc) layout.
  Restricted to any fixed value, within-block rank asc == index asc
  (per-block ``top_k`` is index-stable) and blocks are index-contiguous,
  so survivor *position* order restricted to equal values equals global
  *index* order.  Stage 2's ``top_k`` breaks its ties by survivor
  position — i.e. by global index — exactly like the global sort.
- Padding (added to fill the last block) carries the dtype's minimum and
  sits at the highest indices of the last block, so it loses every tie
  against real entries; and since ``k <= a`` there are always at least
  ``k`` real survivors (any full block alone yields ``min(k, block)``
  of them), padding can never be selected.

Used by proposal generation (``ops/proposals.py``, ``topk_impl="hier"``,
the default) and anchor subsampling (``ops/sampling.py::_select_random``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _floor_value(dtype):
    """Value that sorts (weakly) below every element of ``dtype``.

    Static dtype dispatch on the host (numpy, not jnp — keeps the traced
    function free of python branches on jax expressions).
    """
    if np.issubdtype(np.dtype(dtype), np.inexact):
        return -np.inf
    return np.iinfo(np.dtype(dtype)).min


def hierarchical_top_k(scores: jnp.ndarray, k: int, block: int = 32768):
    """Exact ``lax.top_k(scores, k)`` via a blocked two-stage reduction.

    Bit-identical values AND indices (see the module docstring for the
    tie-break proof).  Falls back to the plain ``lax.top_k`` whenever
    blocking cannot help: ``a <= block`` (single block) or ``k >= block``
    (every block would survive whole).

    Args:
      scores: (A,) operand — 1-D; callers batch via ``vmap``.
      k: number of entries to keep (``k <= A``, as for ``lax.top_k``).
      block: stage-1 tile width.  Power-of-two multiples of the 128-lane
        VPU width keep the batched per-block sort layout-friendly.

    Returns:
      ``(values (k,), indices (k,))`` exactly as ``lax.top_k``.
    """
    if scores.ndim != 1:
        raise ValueError(f"hierarchical_top_k expects 1-D scores, got {scores.shape}")
    a = scores.shape[0]
    if k > a:
        raise ValueError(f"k={k} exceeds operand size {a}")
    if block <= 0 or a <= block or k >= block:
        return lax.top_k(scores, k)

    with jax.named_scope("topk_hier"):
        nb = -(-a // block)
        pad = nb * block - a
        if pad:
            scores = jnp.concatenate(
                [scores, jnp.full((pad,), _floor_value(scores.dtype), scores.dtype)]
            )
        tiles = scores.reshape(nb, block)
        kb = min(k, block)
        part_vals, part_idx = lax.top_k(tiles, kb)          # (nb, kb)
        gidx = part_idx + jnp.arange(nb, dtype=part_idx.dtype)[:, None] * block
        top_vals, pos = lax.top_k(part_vals.reshape(-1), k)
        return top_vals, jnp.take(gidx.reshape(-1), pos)
