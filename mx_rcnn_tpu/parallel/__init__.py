"""Device-mesh parallelism.

Replaces the reference's entire parallel stack (SURVEY.md §3.8): the
``Module(context=[mx.gpu(i)...])`` per-device batch slicing in
``rcnn/core/loader.py``, and the KVStore gradient aggregation
(``local``/``device`` single-host, ``dist_sync`` ps-lite multi-host).  On
TPU there is no parameter server and no push/pull: parameters are
replicated over a 1-D data mesh, batches are sharded along it, and XLA
inserts the gradient all-reduce over ICI (DCN across slices) when it
compiles the jitted train step.  Synchronous and deterministic — the
semantic equivalent of ``dist_sync`` + ``device`` aggregation with none of
the machinery.

Every compile goes through the :class:`~mx_rcnn_tpu.parallel.plan.
ExecutionPlan` (plan.py): regex partition rules over canonical state-leaf
names decide the layout once, for train, eval, serving, and
checkpoint-restore alike.
"""

from mx_rcnn_tpu.parallel.distributed import initialize, is_primary
from mx_rcnn_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
)
from mx_rcnn_tpu.parallel.plan import (
    ExecutionPlan,
    family_rules,
    match_partition_rules,
)
from mx_rcnn_tpu.parallel.prefetch import PrefetchStats, device_prefetch
from mx_rcnn_tpu.parallel.step import make_eval_step, make_train_step

__all__ = [
    "ExecutionPlan",
    "PrefetchStats",
    "batch_sharding",
    "device_prefetch",
    "family_rules",
    "initialize",
    "is_primary",
    "make_eval_step",
    "make_mesh",
    "make_train_step",
    "match_partition_rules",
    "replicated",
    "shard_batch",
]
