"""Multi-host (multi-process) runtime initialization.

The reference scales past one host with MXNet KVStore ``dist_sync`` on a
ps-lite parameter server: ``tools/launch.py`` spawns scheduler/server/worker
processes wired by env vars, workers push gradients and pull weights each
iteration (SURVEY.md §3.8).  The TPU-native equivalent has no server role
at all: every host runs the same program, :func:`initialize` wires them
into one jax runtime (coordination service + global device view), and the
gradient all-reduce is an XLA collective over ICI/DCN inside the jitted
step.  Synchronous and deterministic — ``dist_sync`` semantics with no
push/pull machinery.

Launch parity:

  reference: python tools/launch.py -n 4 ... python train_end2end.py --kv-store dist_sync
  here:      srun/gcloud per host: python train.py --config r101_coco
             (TPU pods: the runtime's env markers trigger autodetecting
             jax.distributed.initialize(); CPU/GPU clusters: pass
             coordinator/rank/count explicitly or via
             JAX_COORDINATOR_ADDRESS / JAX_PROCESS_ID / JAX_NUM_PROCESSES)

The data path is the GLOBAL-schedule design (data/loader.py): every host
keeps the full roidb, derives the identical global batch schedule
(shuffle order, orientation buckets, flip draws), and decodes only its
rank's rows of each global batch — lockstep per-step collectives by
construction, with no per-host roidb slicing to desync them.
:func:`mx_rcnn_tpu.parallel.shard_batch` then assembles each host's rows
into the global device array.  Together with this module that is the
complete multi-host story.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger("mx_rcnn_tpu")


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host runtime (no-op for single-process runs).

    On TPU pods all arguments autodetect from the TPU runtime metadata.
    Elsewhere pass them explicitly or via JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID.  Must run before the first device
    query in the process.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_n = os.environ.get("JAX_NUM_PROCESSES")
    n = num_processes if num_processes is not None else (
        int(env_n) if env_n else None
    )
    env_id = os.environ.get("JAX_PROCESS_ID")
    pid = process_id if process_id is not None else (
        int(env_id) if env_id else None
    )
    explicit = coordinator_address is not None or (n is not None and n > 1)
    # Multi-host TPU pods carry runtime metadata jax autodetects from; these
    # markers are how we know to join without explicit configuration.
    tpu_pod = any(
        os.environ.get(k)
        for k in ("TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS",
                  "CLOUD_TPU_TASK_ID")
    )
    if not explicit and not tpu_pod:
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=n,
            process_id=pid,
        )
    except ValueError as e:
        # Only the stale-marker case is benign: a dev box carrying garbage
        # TPU env markers that don't actually name multiple worker hosts.
        # On anything that looks like a real pod (several hostnames in
        # TPU_WORKER_HOSTNAMES) every failure must stay fatal: swallowing
        # it would split-brain the job into N independent "process 0" runs
        # clobbering one shared workdir.
        hosts = [
            h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
            if h.strip()
        ]
        if explicit or len(hosts) > 1 or "coordinator_address" not in str(e):
            raise
        log.warning(
            "TPU pod markers present but no coordinator address could be "
            "derived (%s); continuing single-process", e,
        )
        return
    log.info(
        "distributed runtime up: process %d/%d, %d local + %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def is_primary() -> bool:
    """True on the host that owns shared side effects (checkpoint
    writes, metric journals, progress logging).  Process 0 by
    convention — trivially True single-process, and stable for the
    life of the runtime once :func:`initialize` has run.  Call sites
    gate on this instead of comparing ``jax.process_index()`` inline
    so the convention lives in exactly one place."""
    return jax.process_index() == 0


def describe_plan(plan) -> str:
    """One-line placement summary for run-start logs (all hosts see the
    SAME plan by construction — it is a pure function of cfg + mesh, so
    logging it per host doubles as a cheap lockstep sanity check in
    multi-host stdouts)."""
    mesh = plan.mesh
    if mesh is None:
        return "plan: single-device (no mesh)"
    return (
        f"plan: mesh {dict(mesh.shape)} over {jax.process_count()} "
        f"process(es), {len(plan.rules)} partition rules, "
        f"accum_steps={plan.accum_steps}, "
        f"steps_per_call={plan.steps_per_call}, "
        f"spatial={plan.spatial}, "
        f"step={'shard_map' if plan.use_shard_map else 'jit+gspmd'}"
    )
