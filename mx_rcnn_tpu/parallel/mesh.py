"""Mesh construction and sharding specs.

The data axis is the only required axis for reference parity (it only ever
does data parallelism); the mesh is built (data, model) so tensor-parallel
shardings can be layered in without re-plumbing.  Multi-host: every process
calls :func:`make_mesh` over ``jax.devices()`` (global), and
:func:`shard_batch` builds global arrays from per-host shards.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None, model_parallel: int = 1
) -> Mesh:
    """(data, model) mesh over all devices; model_parallel=1 → pure DP.

    Adjacent device ids share the model axis so model-parallel collectives
    ride the shortest ICI hops.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh, stacked: bool = False) -> NamedSharding:
    """Leading (batch) dim split over the data axis, rest replicated.

    ``stacked``: the batch has a leading steps-per-call axis (K, B, ...)
    that stays replicated; the batch axis is then dim 1.
    """
    return NamedSharding(mesh, P(None, DATA_AXIS) if stacked else P(DATA_AXIS))


def spatial_sharding(mesh: Mesh, stacked: bool = False) -> NamedSharding:
    """Images (B, H, W, C): batch over data, height over the model axis.

    The CNN analog of sequence/context parallelism: convolutions over a
    spatially-sharded tensor are partitioned by XLA's SPMD pass with
    automatic halo exchange over ICI at stage boundaries — the detector's
    "long context" story (train resolutions whose activations exceed one
    chip's HBM), replacing nothing in the reference (it has no such mode).
    """
    spec = (
        P(None, DATA_AXIS, MODEL_AXIS) if stacked else P(DATA_AXIS, MODEL_AXIS)
    )
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh, spatial: bool = False, stacked: bool = False):
    """Place a host batch onto the mesh, batch dim over the data axis.

    ``spatial``: images additionally shard their height over the model
    axis (each device receives only its slice — no replicate-then-slice).
    ``stacked``: leaves carry a leading steps-per-call axis (K, B, ...).

    Single-process: a plain device_put with the named sharding.
    Multi-process: each host holds its local slice of the global batch and
    jax assembles the global array (the per-host input sharding the
    reference gets from per-worker KVStore ranks).
    """
    data = batch_sharding(mesh, stacked=stacked)
    img = spatial_sharding(mesh, stacked=stacked) if spatial else data

    def spec_for(path):
        name = getattr(path[-1], "name", None) if path else None
        return img if name == "images" else data

    if jax.process_count() == 1:
        return jax.tree_util.tree_map_with_path(
            lambda p, x: jax.device_put(x, spec_for(p)), batch
        )
    return jax.tree_util.tree_map_with_path(
        lambda p, x: jax.make_array_from_process_local_data(
            spec_for(p), np.asarray(x)
        ),
        batch,
    )
