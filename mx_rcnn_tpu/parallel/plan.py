"""The execution plan: one sharding + compilation policy for every step.

Train, eval, and serving used to each assemble their own ``jax.jit``
scaffolding (replicated-state broadcast, per-field batch shardings,
donation) inline in ``parallel/step.py`` and ``serve/engine.py``.  The
:class:`ExecutionPlan` centralizes all of it, GSPMD-style (Xu et al.
2021): the program is written once, and the plan annotates it —

- **Regex partition rules** over the canonical "/"-joined param-tree
  names (train/state.py::leaf_paths) resolve every state leaf to a
  ``PartitionSpec``.  Scalars are replicated automatically; a leaf no
  rule matches is a HARD error — new heads must extend the rule
  vocabulary (detector.py::param_families), never silently default.
  Param names recur inside optax wrapper paths (``.../trace/backbone/
  conv1/kernel``) and BN stats (``batch_stats/backbone/...``), so one
  family rule covers the parameter, its momentum, and its stats.
- **Compilation**: ``jit`` + ``NamedSharding`` when the program is a
  single global computation (the default — XLA's SPMD pass inserts the
  gradient all-reduce), ``shard_map`` when the rules require explicit
  per-shard control (gradient accumulation: grads accumulate LOCALLY
  across microbatches and all-reduce once, instead of once per scan
  iteration as GSPMD would schedule a replicated carry).
- **Placement**: state device layout (``shard_state``) and the
  checkpoint-restore target shardings (train/checkpoint.py) both come
  from the same rule match, so a restored pod run never round-trips
  through a host-replicated layout.

Today every rule resolves to ``P()`` (pure data parallelism — reference
parity); the machinery exists so tensor layouts can be introduced per
family by editing ONE rule, not re-plumbing three call sites.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mx_rcnn_tpu.detection.graph import Batch
from mx_rcnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from mx_rcnn_tpu.train.state import leaf_paths

# Non-model state the rules must always cover: the per-step folding base
# is a (2,) uint32 key — not a scalar, so the auto-replicate path does
# not catch it.
_STATE_RULES: tuple[tuple[str, P], ...] = ((r"(^|/)rng$", P()),)


def family_rules(families: Sequence[str]) -> tuple[tuple[str, P], ...]:
    """One replicate rule per param family — the pure-DP layout.

    Anchored on a path separator so ``rpn`` cannot accidentally match a
    hypothetical ``some_rpn_like`` family: the rule hits ``backbone/``,
    ``batch_stats/backbone/`` and ``.../trace/backbone/`` but never a
    name that merely contains the family as a substring.
    """
    return _STATE_RULES + tuple(
        (rf"(^|/){re.escape(f)}/", P()) for f in families
    )


def match_partition_rules(rules: Sequence[tuple[str, P]], tree):
    """Resolve every leaf of ``tree`` to a PartitionSpec.

    Scalars (and 1-element leaves — optax counters) replicate without
    consulting the rules; other leaves take the FIRST rule whose pattern
    ``re.search``-matches their "/"-joined path.  An unmatched leaf is a
    hard error listing the path and the rule vocabulary — the failure
    mode this guards against is a new parameter family training under
    an accidental default layout.
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def resolve(name: str, leaf) -> P:
        shape = getattr(leaf, "shape", ())
        size = 1
        for d in shape:
            size *= d
        if len(shape) == 0 or size == 1:
            return P()
        for pat, spec in compiled:
            if pat.search(name):
                return spec
        raise ValueError(
            f"no partition rule matches state leaf {name!r} "
            f"(shape {tuple(shape)}); known rules: "
            f"{[pat for pat, _ in rules]} — extend the plan's rule set "
            "(parallel/plan.py::family_rules / "
            "detector.py::param_families) for new parameter families"
        )

    named = leaf_paths(tree)
    specs = [resolve(name, leaf) for name, leaf in named]
    treedef = jax.tree_util.tree_structure(tree)
    return jax.tree_util.tree_unflatten(treedef, specs)


@dataclasses.dataclass(frozen=True)
class ExecutionPlan:
    """Mesh + partition rules + step-shape knobs, validated together.

    ``accum_steps``: microbatches accumulated per optimizer step
    (lax.scan, f32 accumulators).  ``steps_per_call``: optimizer steps
    scanned per dispatch.  Exactly one of the two may exceed 1 — both
    stack the batch's leading axis and composing them would need a
    (K, N, B, ...) layout nothing produces.  ``spatial``: image heights
    sharded over the mesh's model axis (big-image mode); incompatible
    with accumulation (the accumulation shard_map owns the data axis and
    would hide the model axis from XLA's conv partitioner).
    """

    mesh: Optional[Mesh] = None
    rules: tuple[tuple[str, P], ...] = ()
    spatial: bool = False
    accum_steps: int = 1
    steps_per_call: int = 1
    # Gradient-bucket size in MiB for the explicit all-reduce schedule
    # (parallel/step.py::_bucketed_pmean): grads are grouped in REVERSE
    # parameter order — the order backward produces them — into ~this
    # many MiB per bucket, and each bucket rides its own ``pmean`` so
    # early buckets' collectives overlap the rest of the backward pass.
    # 0 keeps the single whole-tree reduce (the plain GSPMD trace for
    # non-accumulated steps — PR 3's bit-exact-resume proofs apply
    # literally).  Exact either way: every leaf rides exactly one pmean.
    bucket_mb: int = 0
    # Replica-per-chip serving (serve/fleet.py): pin this plan's programs
    # to ONE device.  jit follows its committed operands, so placement
    # happens through ``place`` (params land on the replica's chip) and
    # the compiled programs execute there — no mesh, no resharding.
    device: Optional[object] = None

    def __post_init__(self):
        if self.device is not None and self.mesh is not None:
            raise ValueError(
                "device= pins a single-chip replica plan; a mesh plan "
                "places state through its partition rules instead — "
                "set one or the other"
            )
        if self.accum_steps < 1 or self.steps_per_call < 1:
            raise ValueError(
                f"accum_steps={self.accum_steps} / "
                f"steps_per_call={self.steps_per_call} must be >= 1"
            )
        if self.bucket_mb < 0:
            raise ValueError(
                f"bucket_mb={self.bucket_mb} must be >= 0 "
                "(0 = single whole-tree all-reduce)"
            )
        if self.bucket_mb and self.spatial:
            raise ValueError(
                "bucket_mb is incompatible with spatial partitioning "
                "(the overlapped step's shard_map owns the data axis; "
                "the model axis would be invisible to XLA's spatial "
                "conv partitioning)"
            )
        if self.accum_steps > 1 and self.steps_per_call > 1:
            raise ValueError(
                "accum_steps and steps_per_call both > 1: each stacks the "
                "batch's leading axis — pick one"
            )
        if self.spatial:
            if self.mesh is None:
                raise ValueError("spatial partitioning needs a device mesh")
            if self.accum_steps > 1:
                raise ValueError(
                    "spatial partitioning is incompatible with gradient "
                    "accumulation (the accumulation shard_map owns the "
                    "data axis; the model axis would be invisible to "
                    "XLA's spatial conv partitioning)"
                )

    # -- construction -----------------------------------------------------

    @classmethod
    def for_model(
        cls,
        model,
        mesh: Optional[Mesh] = None,
        spatial: bool = False,
        accum_steps: int = 1,
        steps_per_call: int = 1,
        bucket_mb: int = 0,
    ) -> "ExecutionPlan":
        """Rules from the model's own family vocabulary (pure DP)."""
        return cls(
            mesh=mesh,
            rules=family_rules(model.param_families()),
            spatial=spatial,
            accum_steps=accum_steps,
            steps_per_call=steps_per_call,
            bucket_mb=bucket_mb,
        )

    # -- properties -------------------------------------------------------

    @property
    def stacked(self) -> bool:
        """Batches carry a leading (K or N) axis."""
        return self.steps_per_call > 1 or self.accum_steps > 1

    @property
    def overlap_grads(self) -> bool:
        """The non-accumulated step issues its own bucketed all-reduce
        schedule (shard_map) instead of leaving the single gradient
        all-reduce to GSPMD — lets early buckets' collectives overlap
        the remaining backward computation."""
        return (
            self.bucket_mb > 0
            and self.mesh is not None
            and self.accum_steps == 1
            and self.steps_per_call == 1
        )

    @property
    def use_shard_map(self) -> bool:
        """The step body needs explicit per-shard control: gradient
        accumulation over a data mesh accumulates locally and
        all-reduces once (jit+GSPMD would all-reduce every microbatch
        of a replicated scan carry), and the bucketed-overlap step
        issues its own collective schedule."""
        return (
            self.accum_steps > 1 or self.overlap_grads
        ) and self.mesh is not None

    @property
    def data_shards(self) -> int:
        return 1 if self.mesh is None else self.mesh.shape[DATA_AXIS]

    # -- specs and shardings ---------------------------------------------

    def state_specs(self, state):
        """PartitionSpec pytree for a TrainState (hard error on an
        unmatched non-scalar leaf)."""
        return match_partition_rules(self.rules, state)

    def state_shardings(self, state):
        """NamedSharding pytree for ``state`` (None without a mesh)."""
        if self.mesh is None:
            return None
        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec),
            self.state_specs(state),
            is_leaf=lambda x: isinstance(x, P),
        )

    def shard_state(self, state):
        """Place ``state`` per the rules (plain device_put off-mesh)."""
        shardings = self.state_shardings(state)
        if shardings is None:
            return jax.device_put(state)
        return jax.device_put(state, shardings)

    def place(self, tree):
        """Place an inference-shaped pytree (replicated params, quantized
        trees) per the plan: onto ``device`` for a single-chip replica
        plan, the default device otherwise.  Mesh plans place state
        through :meth:`shard_state` (rule-matched layouts) instead."""
        if self.mesh is not None:
            raise ValueError(
                "place() is the single-device path; a mesh plan places "
                "state through shard_state() and its partition rules"
            )
        if self.device is None:
            return jax.device_put(tree)
        return jax.device_put(tree, self.device)

    def batch_specs(self) -> Batch:
        """Per-field PartitionSpec prefix tree for a train Batch."""
        lead = (None,) if self.stacked else ()
        data = P(*lead, DATA_AXIS)
        img = P(*lead, DATA_AXIS, MODEL_AXIS) if self.spatial else data
        return Batch(
            images=img,
            image_hw=data, gt_boxes=data, gt_classes=data, gt_valid=data,
            gt_masks=data, gt_ignore=data, ext_rois=data, ext_valid=data,
        )

    def batch_shardings(self) -> Optional[Batch]:
        if self.mesh is None:
            return None
        return Batch(*[
            NamedSharding(self.mesh, spec) for spec in self.batch_specs()
        ])

    # -- compilation ------------------------------------------------------

    def compile_step(self, fn, state_template=None):
        """Jit a ``step(state, batch)`` under the plan's shardings.

        State buffers are donated (params update in place in HBM).  With
        a ``state_template`` the in/out state shardings are the per-leaf
        rule match; without one, a broadcast replicated sharding — valid
        only while every rule resolves to ``P()``, which the template
        path would also produce today (identical compiled program).
        """
        if self.mesh is None:
            return jax.jit(fn, donate_argnums=(0,))
        rep = NamedSharding(self.mesh, P())
        state_sh = (
            self.state_shardings(state_template)
            if state_template is not None
            else rep
        )
        return jax.jit(
            fn,
            in_shardings=(state_sh, self.batch_shardings()),
            out_shardings=(state_sh, rep),
            donate_argnums=(0,),
        )

    def compile_infer(self, fn, gather_outputs: bool = False):
        """Jit an inference-shaped ``fn(variables, batch)``: replicated
        params, data-sharded batch.  ``gather_outputs`` replicates the
        outputs (multi-host eval: a host can only device_get what it
        addresses).  Off-mesh: plain jit — the serving engine's path;
        with ``device`` set, execution follows the ``place``-committed
        params onto that one chip (replica-per-chip fleets)."""
        if self.mesh is None:
            return jax.jit(fn)
        rep = NamedSharding(self.mesh, P())
        data = NamedSharding(self.mesh, P(DATA_AXIS))
        return jax.jit(
            fn,
            in_shardings=(rep, data),
            out_shardings=rep if gather_outputs else data,
        )
