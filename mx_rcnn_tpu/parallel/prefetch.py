"""Async host→device batch prefetch.

Two overlaps, two mechanisms:

- **Transfer overlap** — ``jax.device_put`` is asynchronous: issuing the
  transfer for batch k+1 while batch k's step runs hides the PCIe/ICI
  copy behind compute (the reference relies on MXNet's threaded DataIter
  + engine for the same overlap).  Keeping ``depth`` batches in flight
  bounds device memory.
- **Host-work overlap** — a plain generator pipeline still runs the host
  loader (decode, augment, letterbox, ``np.stack``) *synchronously in
  the consumer's thread* between steps: the device sits idle for exactly
  the loader's per-batch CPU time.  ``_HostPrefetcher`` moves the
  ``next(it)`` calls to a background thread with a bounded handoff queue
  (``host_depth`` batches read ahead — the one-step double buffer), so
  loader time overlaps device time instead of serializing with it.

Batch ORDER is unchanged by both (single producer, single consumer,
FIFO queue), so schedule determinism — quarantine substitution, chaos
bit-exact resume — is preserved.

:class:`PrefetchStats` measures what the overlap does NOT hide: the time
the consumer blocks waiting for a batch that is not ready (the queue ran
dry — the loader is slower than the step).  That stall is the
input-bound signal; it feeds the ``data_stall_ms`` entry of the
``train_stage_ms`` breakdown (bench.py, train/loop.py).
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from typing import Iterator, Optional

import jax

from mx_rcnn_tpu.parallel.mesh import shard_batch

log = logging.getLogger("mx_rcnn_tpu")


class PrefetchStats:
    """Consumer-side stall accounting for the host-prefetch stage.

    ``stall_s`` accumulates ONLY time the consumer spends blocked waiting
    for the producer (an empty handoff queue); a batch that is already
    buffered costs ~0 regardless of how long the loader took to build it
    — that work was hidden behind the device step, which is the point.
    ``take()`` returns-and-resets, so callers meter per interval (the
    training loop) or per timed window (bench) without seeding from a
    wall clock.
    """

    def __init__(self) -> None:
        self.stall_s = 0.0
        self.batches = 0

    def add(self, stall_s: float) -> None:
        self.stall_s += stall_s
        self.batches += 1

    def take(self) -> tuple[float, int]:
        """(accumulated stall seconds, batches) since the last take."""
        out = (self.stall_s, self.batches)
        self.stall_s = 0.0
        self.batches = 0
        return out


class _HostPrefetcher:
    """Background-thread stage: pulls from ``it`` ahead of the consumer.

    Exceptions raised by the source iterator are re-raised in the
    consumer at the position they occurred (the failure is part of the
    stream, not swallowed in the thread).  ``close()`` stops the thread
    promptly even if it is blocked on a full queue; iterating a closed
    prefetcher raises StopIteration.
    """

    _DONE = object()

    def __init__(
        self, it: Iterator, depth: int = 1,
        stats: Optional[PrefetchStats] = None,
    ):
        self._it = it
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._stats = stats
        self._thread = threading.Thread(
            target=self._run, args=(it,), name="host-prefetch", daemon=True
        )
        self._thread.start()

    def _run(self, it: Iterator) -> None:
        try:
            for item in it:
                while not self._stop.is_set():
                    try:
                        self._q.put((item, None), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
            payload = (self._DONE, None)
        except BaseException as exc:  # noqa: BLE001 — relayed to consumer
            payload = (self._DONE, exc)
        while not self._stop.is_set():
            try:
                self._q.put(payload, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self) -> "_HostPrefetcher":
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        if self._stats is None:
            item, exc = self._q.get()
        else:
            # Time ONLY the blocking wait: a non-empty queue short-circuits
            # through get_nowait with no clock reads on the hot path's
            # happy case beyond the two perf_counter calls.
            try:
                item, exc = self._q.get_nowait()
                self._stats.add(0.0)
            except queue.Empty:
                t0 = time.perf_counter()
                item, exc = self._q.get()
                self._stats.add(time.perf_counter() - t0)
        if item is self._DONE:
            self._stop.set()
            if exc is not None:
                raise exc
            raise StopIteration
        return item

    def close(
        self, raise_pending: bool = False
    ) -> Optional[BaseException]:
        """Stop and join the thread, close the source iterator, and
        surface any exception the producer hit that the consumer never
        pulled (it would otherwise vanish with the thread).  Returns the
        pending exception (or re-raises it with ``raise_pending``) so
        callers choose: the training loop logs it at teardown, the
        loader-side wrapper propagates it."""
        self._stop.set()
        pending: Optional[BaseException] = None

        def drain() -> None:
            nonlocal pending
            try:
                while True:
                    _, exc = self._q.get_nowait()
                    if exc is not None and pending is None:
                        pending = exc
            except queue.Empty:
                pass

        # Drain so a producer blocked on put() observes the stop event,
        # join, then drain again for anything it published while exiting.
        drain()
        self._thread.join(timeout=5.0)
        drain()
        # Close the source chain (generators propagate close to theirs) so
        # loader prefetch threads and input-service workers are reclaimed,
        # not leaked behind a dead consumer.  A pending exception the
        # source surfaces AT close (the loader's own prefetch wrapper does
        # this) folds into ours — teardown itself must not die on it.
        close = getattr(self._it, "close", None)
        if close is not None:
            try:
                close()
            except RuntimeError:
                pass  # generator already executing/closed
            except BaseException as exc:  # noqa: BLE001 — folded, not fatal
                if pending is None:
                    pending = exc
        if pending is not None and raise_pending:
            raise pending
        return pending


def _timed_pulls(it: Iterator, stats: PrefetchStats) -> Iterator:
    """host_depth=0 fallback: every pull is synchronous, so the whole
    ``next(it)`` is consumer-blocking stall by definition."""
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        stats.add(time.perf_counter() - t0)
        yield item


def device_prefetch(
    it: Iterator, mesh: Optional[jax.sharding.Mesh], depth: int = 2,
    spatial: bool = False, stacked: bool = False, host_depth: int = 1,
    stats: Optional[PrefetchStats] = None,
) -> Iterator:
    """Wrap a host batch iterator: batches come out device-resident (sharded
    over the mesh when given), ``depth`` transfers ahead of consumption.
    ``stacked``: batches carry a leading steps-per-call axis (K, B, ...).
    ``host_depth``: batches the background host-prefetch thread reads
    ahead of the device_put stage (0 = synchronous pulls in the consumer
    thread — the pre-r6 behavior, kept for strictly single-threaded
    debugging).  ``stats``: optional :class:`PrefetchStats` accumulating
    the consumer-side stall (data-starvation) time.  Closing the returned
    generator (``gen.close()``) stops the thread."""
    q: collections.deque = collections.deque()
    if host_depth <= 0:
        src: Iterator = it if stats is None else _timed_pulls(it, stats)
    else:
        src = _HostPrefetcher(it, host_depth, stats=stats)

    def put(batch):
        if mesh is not None:
            return shard_batch(batch, mesh, spatial=spatial, stacked=stacked)
        return jax.device_put(batch)

    try:
        for batch in src:
            q.append(put(batch))
            if len(q) > depth:
                yield q.popleft()
        while q:
            yield q.popleft()
    finally:
        if isinstance(src, _HostPrefetcher):
            pending = src.close()
            if pending is not None:
                # The consumer stopped before it would have seen this (a
                # loader failure mid-read-ahead during early close).  Log
                # rather than raise: teardown paths (rollback, shutdown)
                # must not die on a stream the run already abandoned.
                log.warning(
                    "host prefetch: source raised after consumer stopped: "
                    "%s: %s", type(pending).__name__, pending,
                )
        else:
            close = getattr(it, "close", None)
            if close is not None:
                close()
