"""Async host→device batch prefetch.

Two overlaps, two mechanisms:

- **Transfer overlap** — ``jax.device_put`` is asynchronous: issuing the
  transfer for batch k+1 while batch k's step runs hides the PCIe/ICI
  copy behind compute (the reference relies on MXNet's threaded DataIter
  + engine for the same overlap).  Keeping ``depth`` batches in flight
  bounds device memory.
- **Host-work overlap** — a plain generator pipeline still runs the host
  loader (decode, augment, letterbox, ``np.stack``) *synchronously in
  the consumer's thread* between steps: the device sits idle for exactly
  the loader's per-batch CPU time.  ``_HostPrefetcher`` moves the
  ``next(it)`` calls to a background thread with a bounded handoff queue
  (``host_depth`` batches read ahead — the one-step double buffer), so
  loader time overlaps device time instead of serializing with it.

Batch ORDER is unchanged by both (single producer, single consumer,
FIFO queue), so schedule determinism — quarantine substitution, chaos
bit-exact resume — is preserved.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Iterator, Optional

import jax

from mx_rcnn_tpu.parallel.mesh import shard_batch


class _HostPrefetcher:
    """Background-thread stage: pulls from ``it`` ahead of the consumer.

    Exceptions raised by the source iterator are re-raised in the
    consumer at the position they occurred (the failure is part of the
    stream, not swallowed in the thread).  ``close()`` stops the thread
    promptly even if it is blocked on a full queue; iterating a closed
    prefetcher raises StopIteration.
    """

    _DONE = object()

    def __init__(self, it: Iterator, depth: int = 1):
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(it,), name="host-prefetch", daemon=True
        )
        self._thread.start()

    def _run(self, it: Iterator) -> None:
        try:
            for item in it:
                while not self._stop.is_set():
                    try:
                        self._q.put((item, None), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
            payload = (self._DONE, None)
        except BaseException as exc:  # noqa: BLE001 — relayed to consumer
            payload = (self._DONE, exc)
        while not self._stop.is_set():
            try:
                self._q.put(payload, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self) -> "_HostPrefetcher":
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item, exc = self._q.get()
        if item is self._DONE:
            self._stop.set()
            if exc is not None:
                raise exc
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()
        # Drain so a producer blocked on put() observes the stop event.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)


def device_prefetch(
    it: Iterator, mesh: Optional[jax.sharding.Mesh], depth: int = 2,
    spatial: bool = False, stacked: bool = False, host_depth: int = 1,
) -> Iterator:
    """Wrap a host batch iterator: batches come out device-resident (sharded
    over the mesh when given), ``depth`` transfers ahead of consumption.
    ``stacked``: batches carry a leading steps-per-call axis (K, B, ...).
    ``host_depth``: batches the background host-prefetch thread reads
    ahead of the device_put stage (0 = synchronous pulls in the consumer
    thread — the pre-r6 behavior, kept for strictly single-threaded
    debugging).  Closing the returned generator (``gen.close()``) stops
    the thread."""
    q: collections.deque = collections.deque()
    src: Iterator = it if host_depth <= 0 else _HostPrefetcher(it, host_depth)

    def put(batch):
        if mesh is not None:
            return shard_batch(batch, mesh, spatial=spatial, stacked=stacked)
        return jax.device_put(batch)

    try:
        for batch in src:
            q.append(put(batch))
            if len(q) > depth:
                yield q.popleft()
        while q:
            yield q.popleft()
    finally:
        if isinstance(src, _HostPrefetcher):
            src.close()
