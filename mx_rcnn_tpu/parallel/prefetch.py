"""Async host→device batch prefetch.

``jax.device_put`` is asynchronous: issuing the transfer for batch k+1
while batch k's step runs hides the PCIe/ICI copy behind compute (the
reference relies on MXNet's threaded DataIter + engine for the same
overlap).  Keeping ``depth`` batches in flight bounds device memory.
"""

from __future__ import annotations

import collections
from typing import Iterator, Optional

import jax

from mx_rcnn_tpu.parallel.mesh import shard_batch


def device_prefetch(
    it: Iterator, mesh: Optional[jax.sharding.Mesh], depth: int = 2,
    spatial: bool = False, stacked: bool = False,
) -> Iterator:
    """Wrap a host batch iterator: batches come out device-resident (sharded
    over the mesh when given), ``depth`` transfers ahead of consumption.
    ``stacked``: batches carry a leading steps-per-call axis (K, B, ...)."""
    q: collections.deque = collections.deque()

    def put(batch):
        if mesh is not None:
            return shard_batch(batch, mesh, spatial=spatial, stacked=stacked)
        return jax.device_put(batch)

    for batch in it:
        q.append(put(batch))
        if len(q) > depth:
            yield q.popleft()
    while q:
        yield q.popleft()
