"""Jitted, sharded train and eval steps.

Replaces the reference's per-iteration runtime (SURVEY.md §4.1 hot loop):
``MutableModule.forward/backward/update`` + KVStore push/pull per parameter.
One compiled XLA program does forward, backward, gradient all-reduce (ICI)
and the optimizer update; there is no per-parameter communication schedule
to manage because XLA fuses the collectives.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from mx_rcnn_tpu.detection.detector import TwoStageDetector
from mx_rcnn_tpu.detection.graph import Batch, forward_inference, forward_train
from mx_rcnn_tpu.parallel.mesh import batch_sharding, replicated, spatial_sharding
from mx_rcnn_tpu.train.state import TrainState, state_variables


def make_train_step(
    model: TwoStageDetector,
    tx: optax.GradientTransformation,
    schedule=None,
    mesh: Optional[Mesh] = None,
    spatial: bool = False,
    trainable_mask=None,
    steps_per_call: int = 1,
    pixel_stats=None,
):
    """Build ``step(state, batch) -> (state, metrics)``.

    With a mesh: state replicated, batch sharded over the data axis; the
    gradient all-reduce is implicit in XLA's SPMD partitioning (grads of
    replicated params w.r.t. a sharded batch).  Without: plain single-device
    jit.  State buffers are donated — params update in place in HBM.

    ``spatial``: additionally shard the image height over the mesh's model
    axis (parallel/mesh.py::spatial_sharding) — XLA partitions the
    backbone convs with halo exchange; the detection head's flatten/top-k
    ops re-gather where profitable (XLA's choice).

    ``trainable_mask``: optional params-shaped bool pytree (True =
    trainable).  Frozen leaves enter the loss under ``stop_gradient`` so
    XLA deletes their whole backward computation — the reference likewise
    never runs backward for ``fixed_param`` layers; the optimizer's
    set_to_zero on the same mask alone would still compute (then discard)
    those gradients.  Freezing the stem+stage1 is ~40% of the R50
    backbone's forward FLOPs whose weight-gradient pass disappears.
    """
    stacked = steps_per_call > 1
    spatial_spec = (
        spatial_sharding(mesh) if spatial and mesh is not None else None
    )
    # The Pallas ROIAlign shard_map wrap needs the mesh at trace time.
    # Spatial partitioning shards feature heights over the model axis — a
    # layout the per-shard kernel contract doesn't cover — so those runs
    # keep mesh=None here and the XLA path (see mesh_safe_model_cfg).
    roi_mesh = mesh if (mesh is not None and not spatial) else None

    def step(state: TrainState, batch: Batch):
        if spatial_spec is not None:
            batch = batch._replace(
                images=jax.lax.with_sharding_constraint(
                    batch.images, spatial_spec
                )
            )
        rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            if trainable_mask is not None:
                params = jax.tree_util.tree_map(
                    lambda p, t: p if t else jax.lax.stop_gradient(p),
                    params,
                    trainable_mask,
                )
            variables = {"params": params, **state.model_state}
            total, metrics = forward_train(
                model, variables, rng, batch, mesh=roi_mesh,
                pixel_stats=pixel_stats,
            )
            return total, metrics

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
        with jax.named_scope("guardian"):
            # On-device finiteness reduction (train/guardian.py): ONE 0/1
            # scalar covering the gradient global norm (inf/NaN anywhere
            # in the grad tree makes the norm non-finite) and every loss
            # metric.  It rides the metric dict the loop already fetches
            # once per log interval — no per-step host sync is added, so
            # the hot loop stays transfer_guard-clean (tools/tpulint.py).
            finite = jnp.isfinite(optax.global_norm(grads))
            for key in sorted(metrics):
                finite &= jnp.all(jnp.isfinite(metrics[key]))
            nonfinite = 1.0 - finite.astype(jnp.float32)
        with jax.named_scope("optimizer"):
            new_state = state.apply_gradients(grads, tx)
        metrics = dict(metrics, nonfinite=nonfinite)
        if schedule is not None:
            metrics["lr"] = schedule(state.step)
        return new_state, metrics

    def multi_step(state: TrainState, batches: Batch):
        # The host-side step loop, moved on-device: scan over the leading
        # (K, B, ...) axis.  One dispatch per K optimizer steps — the
        # per-call host->device latency (tens of ms through a tunneled
        # runtime) amortizes K-fold.  rng/schedule stay per-step correct
        # because `step` keys everything off state.step.
        new_state, mets = jax.lax.scan(step, state, batches)
        # Per-call metrics: mean over the K steps (lr: the last step's).
        # The f32 cast is the metric-accumulation contract (a no-op today
        # — every loss/metric upcasts inside its accumulation scope — but
        # it pins the K-step mean to f32 even if a future metric leaf
        # arrives in bf16).
        metrics = jax.tree_util.tree_map(
            lambda m: jnp.mean(m.astype(jnp.float32), axis=0), mets
        )
        if schedule is not None:
            metrics["lr"] = mets["lr"][-1]
        return new_state, metrics

    fn = multi_step if stacked else step
    if mesh is None:
        return jax.jit(fn, donate_argnums=(0,))
    rep = replicated(mesh)
    data = batch_sharding(mesh, stacked=stacked)
    img = (
        spatial_sharding(mesh, stacked=stacked)
        if spatial_spec is not None
        else data
    )
    # Per-field batch shardings (a pytree prefix): images may be spatially
    # sharded; a prefix leaf over Batch's optional None fields applies to
    # zero leaves, which is fine.
    batch_shardings = Batch(
        images=img,
        image_hw=data, gt_boxes=data, gt_classes=data, gt_valid=data,
        gt_masks=data, gt_ignore=data, ext_rois=data, ext_valid=data,
    )
    return jax.jit(
        fn,
        in_shardings=(rep, batch_shardings),
        out_shardings=(rep, rep),
        donate_argnums=(0,),
    )


def mesh_safe_model_cfg(model_cfg, mesh, spatial: bool = False):
    """Model config adjusted for spatially-partitioned meshes.

    Pure data-parallel meshes run the Pallas ROIAlign per-shard via
    ``shard_map`` (graph.py::_pool_rois) — no downgrade.  Spatial
    partitioning (model axis > 1) shards feature-map heights across chips,
    which the per-shard kernel contract doesn't cover, so those runs use
    the XLA form (identical numerics — it is the kernel's oracle).

    The TPU layout forms revert to their dense equivalents under spatial
    partitioning for the same reason — each reshapes or concatenates along
    the sharded height axis (s2d stem halves H, the packed RPN head stacks
    levels along H), turning an exact local rewrite into a cross-shard
    shuffle.  All are exact either way, so only the compiled program
    changes.  C2 lane padding widens channels, not height, and stays.
    """
    if not (spatial and mesh is not None and mesh.size > 1):
        return model_cfg
    import dataclasses

    changed = {}
    if model_cfg.rcnn.roi_align_impl == "pallas":
        changed["rcnn"] = dataclasses.replace(
            model_cfg.rcnn, roi_align_impl="xla"
        )
    if model_cfg.rpn.packed_head:
        changed["rpn"] = dataclasses.replace(model_cfg.rpn, packed_head=False)
    bb = model_cfg.backbone
    if bb.stem_s2d or bb.stem_pool_fold:
        changed["backbone"] = dataclasses.replace(
            bb, stem_s2d=False, stem_pool_fold=False
        )
    return dataclasses.replace(model_cfg, **changed) if changed else model_cfg


def make_sharded_infer(
    fn, mesh: Optional[Mesh] = None, gather_outputs: bool = False
):
    """Jit an inference-shaped ``fn(variables, batch)`` for the mesh:
    replicated params, data-sharded batch.  The one scaffolding shared by
    eval, proposal dumps, and any future read-only pass.

    ``gather_outputs``: replicate the outputs across the mesh (an XLA
    all-gather at the step's end).  Multi-host runs need it — a host can
    only ``device_get`` what it addresses, and detection/proposal outputs
    are tiny next to the step's compute."""
    if mesh is None:
        return jax.jit(fn)
    rep, data = replicated(mesh), batch_sharding(mesh)
    # out_shardings is a single spec broadcast over the output pytree
    # (a tuple here would be matched structurally and fail).
    return jax.jit(
        fn,
        in_shardings=(rep, data),
        out_shardings=rep if gather_outputs else data,
    )


def make_eval_step(
    model: TwoStageDetector,
    mesh: Optional[Mesh] = None,
    gather_outputs: bool = False,
    pixel_stats=None,
):
    """Build ``eval_step(variables, batch) -> Detections`` (jitted)."""

    def step(variables, batch: Batch):
        return forward_inference(
            model, variables, batch, mesh=mesh, pixel_stats=pixel_stats
        )

    return make_sharded_infer(step, mesh, gather_outputs)


def eval_variables(state: TrainState) -> dict:
    """Inference variables from a train state (no weight folding needed —
    see train/checkpoint.py docstring)."""
    return state_variables(state)
