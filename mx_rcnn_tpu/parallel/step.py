"""Jitted, sharded train and eval steps, compiled through the execution plan.

Replaces the reference's per-iteration runtime (SURVEY.md §4.1 hot loop):
``MutableModule.forward/backward/update`` + KVStore push/pull per parameter.
One compiled XLA program does forward, backward, gradient all-reduce (ICI)
and the optimizer update; there is no per-parameter communication schedule
to manage because XLA fuses the collectives.

All sharding/donation decisions live in :class:`~mx_rcnn_tpu.parallel.plan.
ExecutionPlan` (parallel/plan.py) — train, eval, and serving compiles go
through the same plan.  This module owns only the step BODIES: the fused
fwd+bwd+update, the steps-per-call scan, and the gradient-accumulation
loop (``shard_map`` over the data axis: grads accumulate locally in f32
across microbatches and all-reduce ONCE per optimizer step).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mx_rcnn_tpu.detection.detector import TwoStageDetector
from mx_rcnn_tpu.detection.graph import Batch, forward_inference, forward_train
from mx_rcnn_tpu.parallel.mesh import DATA_AXIS, spatial_sharding
from mx_rcnn_tpu.parallel.plan import ExecutionPlan
from mx_rcnn_tpu.train.state import TrainState, state_variables
from mx_rcnn_tpu.utils.precision import policy_of


def _bucketed_pmean(grads, bucket_mb: int):
    """All-reduce a gradient pytree in ~``bucket_mb``-MiB buckets.

    Leaves are grouped in REVERSE flatten order — the backbone's deep
    layers flatten first and backward produces gradients output-to-input,
    so reversed order is (approximately) completion order.  Each bucket
    rides its own ``pmean``, so the scheduler can launch the first
    buckets' collectives while backward is still computing the last —
    the overlap a single whole-tree reduce structurally forbids (it
    depends on EVERY leaf).

    Exact: ``pmean`` over a list reduces each leaf independently, so a
    leaf's value is bit-identical whatever bucket it rides in —
    bucketed vs single differ only in schedule, never in numerics.
    ``bucket_mb <= 0`` is the single whole-tree reduce, literally the
    pre-bucketing trace.
    """
    if bucket_mb <= 0:
        return jax.lax.pmean(grads, DATA_AXIS)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    budget = bucket_mb * (1 << 20)
    buckets, cur, cur_bytes = [], [], 0
    for idx in reversed(range(len(leaves))):
        leaf = leaves[idx]
        nbytes = leaf.size * jnp.dtype(leaf.dtype).itemsize
        if cur and cur_bytes + nbytes > budget:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(idx)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    out = [None] * len(leaves)
    for bucket in buckets:
        reduced = jax.lax.pmean([leaves[i] for i in bucket], DATA_AXIS)
        for i, r in zip(bucket, reduced):
            out[i] = r
    return jax.tree_util.tree_unflatten(treedef, out)


def make_train_step(
    model: TwoStageDetector,
    tx: optax.GradientTransformation,
    schedule=None,
    mesh: Optional[Mesh] = None,
    spatial: bool = False,
    trainable_mask=None,
    steps_per_call: int = 1,
    pixel_stats=None,
    accum_steps: int = 1,
    plan: Optional[ExecutionPlan] = None,
    state_template: Optional[TrainState] = None,
):
    """Build ``step(state, batch) -> (state, metrics)``.

    With a mesh: state placed per the plan's partition rules (pure DP:
    replicated), batch sharded over the data axis; the gradient all-reduce
    is implicit in XLA's SPMD partitioning.  Without: plain single-device
    jit.  State buffers are donated — params update in place in HBM.

    ``spatial``: additionally shard the image height over the mesh's model
    axis (parallel/mesh.py::spatial_sharding) — XLA partitions the
    backbone convs with halo exchange; the detection head's flatten/top-k
    ops re-gather where profitable (XLA's choice).

    ``trainable_mask``: optional params-shaped bool pytree (True =
    trainable).  Frozen leaves enter the loss under ``stop_gradient`` so
    XLA deletes their whole backward computation — the reference likewise
    never runs backward for ``fixed_param`` layers; the optimizer's
    set_to_zero on the same mask alone would still compute (then discard)
    those gradients.  Freezing the stem+stage1 is ~40% of the R50
    backbone's forward FLOPs whose weight-gradient pass disappears.

    ``accum_steps`` > 1: the batch arrives STACKED (N, B, ...) and one
    optimizer step accumulates gradients over the N microbatches
    (``lax.scan``, f32 accumulators per utils/precision.py) — the
    large-minibatch lever (Goyal et al. 2017) when the target global
    batch exceeds what the chips hold.  ``accum_steps=1`` is bit-identical
    to the plain step (it IS the plain step — same trace), so the chaos
    harness's bit-exact-resume proof carries over unchanged.  Per-image
    rng keys are derived for the FULL (N*B) global batch and sliced per
    microbatch, so an accumulated step samples the same anchors/rois per
    image as one monolithic (N*B,) batch would — the parity oracle
    tests/test_plan.py asserts.

    ``plan`` / ``state_template``: an explicit ExecutionPlan (otherwise
    built from the model's family vocabulary) and a state whose structure
    resolves the per-leaf in/out shardings (otherwise a broadcast
    replicated spec — identical program while every rule is ``P()``).
    """
    if plan is None:
        plan = ExecutionPlan.for_model(
            model, mesh=mesh, spatial=spatial, accum_steps=accum_steps,
            steps_per_call=steps_per_call,
        )
    mesh, spatial = plan.mesh, plan.spatial
    spatial_spec = (
        spatial_sharding(mesh) if spatial and mesh is not None else None
    )
    # The Pallas ROIAlign shard_map wrap needs the mesh at trace time.
    # Spatial partitioning shards feature heights over the model axis — a
    # layout the per-shard kernel contract doesn't cover — so those runs
    # keep mesh=None here and the XLA path (see mesh_safe_model_cfg).
    # Inside the accumulation shard_map the step is ALREADY per-shard, so
    # the kernel runs its single-device form there too.
    roi_mesh = mesh if (mesh is not None and not spatial) else None

    def _finish(state: TrainState, grads, metrics):
        with jax.named_scope("guardian"):
            # On-device finiteness reduction (train/guardian.py): ONE 0/1
            # scalar covering the gradient global norm (inf/NaN anywhere
            # in the grad tree makes the norm non-finite) and every loss
            # metric.  It rides the metric dict the loop already fetches
            # once per log interval — no per-step host sync is added, so
            # the hot loop stays transfer_guard-clean (tools/tpulint.py).
            finite = jnp.isfinite(optax.global_norm(grads))
            for key in sorted(metrics):
                finite &= jnp.all(jnp.isfinite(metrics[key]))
            nonfinite = 1.0 - finite.astype(jnp.float32)
        with jax.named_scope("optimizer"):
            new_state = state.apply_gradients(grads, tx)
        metrics = dict(metrics, nonfinite=nonfinite)
        if schedule is not None:
            metrics["lr"] = schedule(state.step)
        return new_state, metrics

    def _masked(params):
        if trainable_mask is None:
            return params
        return jax.tree_util.tree_map(
            lambda p, t: p if t else jax.lax.stop_gradient(p),
            params,
            trainable_mask,
        )

    def step(state: TrainState, batch: Batch):
        if spatial_spec is not None:
            batch = batch._replace(
                images=jax.lax.with_sharding_constraint(
                    batch.images, spatial_spec
                )
            )
        rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            variables = {"params": _masked(params), **state.model_state}
            total, metrics = forward_train(
                model, variables, rng, batch, mesh=roi_mesh,
                pixel_stats=pixel_stats,
            )
            return total, metrics

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
        return _finish(state, grads, metrics)

    def multi_step(state: TrainState, batches: Batch):
        # The host-side step loop, moved on-device: scan over the leading
        # (K, B, ...) axis.  One dispatch per K optimizer steps — the
        # per-call host->device latency (tens of ms through a tunneled
        # runtime) amortizes K-fold.  rng/schedule stay per-step correct
        # because `step` keys everything off state.step.
        new_state, mets = jax.lax.scan(step, state, batches)
        # Per-call metrics: mean over the K steps (lr: the last step's).
        # The f32 cast is the metric-accumulation contract (a no-op today
        # — every loss/metric upcasts inside its accumulation scope — but
        # it pins the K-step mean to f32 even if a future metric leaf
        # arrives in bf16).
        metrics = jax.tree_util.tree_map(
            lambda m: jnp.mean(m.astype(jnp.float32), axis=0), mets
        )
        if schedule is not None:
            metrics["lr"] = mets["lr"][-1]
        return new_state, metrics

    # --- gradient accumulation (accum_steps > 1) -------------------------
    # f32 accumulators: grads are cast to the precision policy's accum
    # dtype before summing, divided by N, then cast back to the param
    # dtype for the optimizer (a no-op with f32 masters).
    acc_dtype = policy_of(model.cfg).accum_dtype

    def _accum_local(params, model_state, batches, a_keys, s_keys):
        """Mean grads/metrics over the N stacked microbatches.

        Runs per-shard inside the accumulation shard_map when a mesh is
        present (batches/keys then hold this shard's rows), or on the
        whole batch off-mesh.  Losses normalize by each microbatch's own
        sampled-anchor/roi count, so the mean over microbatches equals
        the monolithic big-batch loss exactly when every image meets its
        sampling quota (the usual case) and to normalizer-weighting
        round-off otherwise — the documented accumulation contract
        (docs/scaling.md).
        """
        n = batches.images.shape[0]

        def loss_fn(p, mb, ak, sk):
            variables = {"params": _masked(p), **model_state}
            return forward_train(
                model, variables, None, mb, mesh=None,
                pixel_stats=pixel_stats, rngs=(ak, sk),
            )

        def body(g_acc, xs):
            mb, ak, sk = xs
            grads, metrics = jax.grad(loss_fn, has_aux=True)(
                params, mb, ak, sk
            )
            g_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(acc_dtype), g_acc, grads
            )
            return g_acc, metrics

        g0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, acc_dtype), params
        )
        g_sum, mets = jax.lax.scan(body, g0, (batches, a_keys, s_keys))
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / n).astype(p.dtype), g_sum, params
        )
        metrics = jax.tree_util.tree_map(
            lambda m: jnp.mean(m.astype(jnp.float32), axis=0), mets
        )
        return grads, metrics

    def _accum_psum(params, model_state, batches, a_keys, s_keys):
        # Per-shard local means, ONE all-reduce pass per optimizer step
        # (bucketed when plan.bucket_mb > 0) — the reason this is
        # shard_map and not jit+GSPMD (which would all-reduce the
        # replicated scan carry every microbatch).
        grads, metrics = _accum_local(
            params, model_state, batches, a_keys, s_keys
        )
        grads = _bucketed_pmean(grads, plan.bucket_mb)
        metrics = jax.lax.pmean(metrics, DATA_AXIS)
        return grads, metrics

    def accum_step(state: TrainState, batches: Batch):
        rng = jax.random.fold_in(state.rng, state.step)
        rng_assign, rng_sample = jax.random.split(rng)
        n, b = batches.images.shape[0], batches.images.shape[1]
        if b % plan.data_shards:
            raise ValueError(
                f"microbatch size {b} not divisible by the data axis "
                f"({plan.data_shards} shards)"
            )
        # Keys for the FULL global batch, sliced (N, B): microbatch j gets
        # the rows a monolithic (N*B,) batch would hand images jB..jB+B-1.
        a_keys = jax.random.split(rng_assign, n * b).reshape(n, b, -1)
        s_keys = jax.random.split(rng_sample, n * b).reshape(n, b, -1)
        if mesh is None:
            grads, metrics = _accum_local(
                state.params, state.model_state, batches, a_keys, s_keys
            )
        else:
            kspec = P(None, DATA_AXIS)
            grads, metrics = shard_map(
                _accum_psum,
                mesh=mesh,
                in_specs=(P(), P(), plan.batch_specs(), kspec, kspec),
                out_specs=(P(), P()),
                check_rep=False,
            )(state.params, state.model_state, batches, a_keys, s_keys)
        return _finish(state, grads, metrics)

    # --- overlapped non-accumulated step (plan.bucket_mb > 0, mesh) -----
    # The plain jitted step leaves the gradient all-reduce to GSPMD: one
    # whole-tree collective that depends on every leaf, so nothing moves
    # over ICI until backward fully finishes.  This variant takes the
    # per-shard view explicitly (shard_map, like the accumulation path)
    # and issues _bucketed_pmean's schedule instead — the first buckets'
    # collectives overlap the rest of backward.  Keys are derived for the
    # FULL global batch exactly as forward_train's internal split would
    # (fold_in -> split -> per-image split) and handed in via the rngs
    # override, so every image samples identically to the plain step.

    def _overlap_psum(params, model_state, batch, a_keys, s_keys):
        def loss_fn(p):
            variables = {"params": _masked(p), **model_state}
            return forward_train(
                model, variables, None, batch, mesh=None,
                pixel_stats=pixel_stats, rngs=(a_keys, s_keys),
            )

        grads, metrics = jax.grad(loss_fn, has_aux=True)(params)
        grads = _bucketed_pmean(grads, plan.bucket_mb)
        metrics = jax.lax.pmean(metrics, DATA_AXIS)
        return grads, metrics

    def overlap_step(state: TrainState, batch: Batch):
        rng = jax.random.fold_in(state.rng, state.step)
        rng_assign, rng_sample = jax.random.split(rng)
        b = batch.images.shape[0]
        if b % plan.data_shards:
            raise ValueError(
                f"batch size {b} not divisible by the data axis "
                f"({plan.data_shards} shards)"
            )
        a_keys = jax.random.split(rng_assign, b)
        s_keys = jax.random.split(rng_sample, b)
        kspec = P(DATA_AXIS)
        grads, metrics = shard_map(
            _overlap_psum,
            mesh=mesh,
            in_specs=(P(), P(), plan.batch_specs(), kspec, kspec),
            out_specs=(P(), P()),
            check_rep=False,
        )(state.params, state.model_state, batch, a_keys, s_keys)
        return _finish(state, grads, metrics)

    if plan.accum_steps > 1:
        fn = accum_step
    elif plan.steps_per_call > 1:
        fn = multi_step
    elif plan.overlap_grads:
        fn = overlap_step
    else:
        fn = step
    return plan.compile_step(fn, state_template=state_template)


def mesh_safe_model_cfg(model_cfg, mesh, spatial: bool = False):
    """Model config adjusted for spatially-partitioned meshes.

    Pure data-parallel meshes run the Pallas ROIAlign per-shard via
    ``shard_map`` (graph.py::_pool_rois) — no downgrade.  Spatial
    partitioning (model axis > 1) shards feature-map heights across chips,
    which the per-shard kernel contract doesn't cover, so those runs use
    the XLA form (identical numerics — it is the kernel's oracle).

    The TPU layout forms revert to their dense equivalents under spatial
    partitioning for the same reason — each reshapes or concatenates along
    the sharded height axis (s2d stem halves H, the packed RPN head stacks
    levels along H), turning an exact local rewrite into a cross-shard
    shuffle.  All are exact either way, so only the compiled program
    changes.  C2 lane padding widens channels, not height, and stays.
    """
    if not (spatial and mesh is not None and mesh.size > 1):
        return model_cfg
    import dataclasses

    changed = {}
    if model_cfg.rcnn.roi_align_impl == "pallas":
        changed["rcnn"] = dataclasses.replace(
            model_cfg.rcnn, roi_align_impl="xla"
        )
    if model_cfg.rpn.packed_head:
        changed["rpn"] = dataclasses.replace(model_cfg.rpn, packed_head=False)
    bb = model_cfg.backbone
    if bb.stem_s2d or bb.stem_pool_fold:
        changed["backbone"] = dataclasses.replace(
            bb, stem_s2d=False, stem_pool_fold=False
        )
    return dataclasses.replace(model_cfg, **changed) if changed else model_cfg


def make_sharded_infer(
    fn, mesh: Optional[Mesh] = None, gather_outputs: bool = False,
    plan: Optional[ExecutionPlan] = None,
):
    """Jit an inference-shaped ``fn(variables, batch)`` for the mesh:
    replicated params, data-sharded batch.  The one scaffolding shared by
    eval, proposal dumps, and any future read-only pass — all via
    :meth:`ExecutionPlan.compile_infer`, the same plan the train step
    compiles through.

    ``gather_outputs``: replicate the outputs across the mesh (an XLA
    all-gather at the step's end).  Multi-host runs need it — a host can
    only ``device_get`` what it addresses, and detection/proposal outputs
    are tiny next to the step's compute."""
    if plan is None:
        plan = ExecutionPlan(mesh=mesh)
    return plan.compile_infer(fn, gather_outputs=gather_outputs)


def make_eval_step(
    model: TwoStageDetector,
    mesh: Optional[Mesh] = None,
    gather_outputs: bool = False,
    pixel_stats=None,
    plan: Optional[ExecutionPlan] = None,
):
    """Build ``eval_step(variables, batch) -> Detections`` (jitted)."""

    def step(variables, batch: Batch):
        return forward_inference(
            model, variables, batch, mesh=mesh, pixel_stats=pixel_stats
        )

    return make_sharded_infer(step, mesh, gather_outputs, plan=plan)


def eval_variables(state: TrainState) -> dict:
    """Inference variables from a train state (no weight folding needed —
    see train/checkpoint.py docstring)."""
    return state_variables(state)
