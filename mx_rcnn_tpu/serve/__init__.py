"""Serving-grade inference runtime (docs/serving.md).

``InferenceEngine`` wraps the jitted inference step with warmup
compilation over fixed shape buckets, bounded-queue admission control,
per-request deadlines, a degradation ladder, a circuit breaker, and a
hang watchdog; ``EngineHealth`` exposes the readiness/liveness state
machine and stats snapshot.
"""

from mx_rcnn_tpu.serve.degrade import (
    LEVELS,
    CircuitBreaker,
    LatencyEstimator,
    plan_level,
)
from mx_rcnn_tpu.serve.engine import (
    DeadlineExceeded,
    DetectorRunner,
    EngineUnavailable,
    InferenceEngine,
    InferenceRequest,
    Overloaded,
    Plan,
    ServeError,
    build_engine,
)
from mx_rcnn_tpu.serve.health import EngineHealth

__all__ = [
    "LEVELS",
    "CircuitBreaker",
    "LatencyEstimator",
    "plan_level",
    "DeadlineExceeded",
    "DetectorRunner",
    "EngineUnavailable",
    "InferenceEngine",
    "InferenceRequest",
    "Overloaded",
    "Plan",
    "ServeError",
    "build_engine",
    "EngineHealth",
]
