"""Serving-grade inference runtime (docs/serving.md).

``InferenceEngine`` wraps the jitted inference step with warmup
compilation over fixed shape buckets, bounded-queue admission control,
per-request deadlines, a degradation ladder, a circuit breaker, and a
hang watchdog; ``EngineHealth`` exposes the readiness/liveness state
machine and stats snapshot.  ``FleetRouter`` runs N replica engines as
independent failure domains: least-loaded routing, hedged retries,
quarantine/rebuild, zero-downtime weight swap, draining shutdown.
The cross-host fabric lifts the same shapes one layer up:
``HostRpcServer``/``RpcClient`` export a host's fleet over stdlib
HTTP/JSON, ``GossipNode`` exchanges peer health with incarnation-safe
merges, and ``GatewayRouter`` composes remote host-fleets into one
pod-wide serving surface with generation-consistent weight rolls.
"""

from mx_rcnn_tpu.serve.batcher import PackBuffer
from mx_rcnn_tpu.serve.degrade import (
    LEVELS,
    CircuitBreaker,
    HysteresisPlanner,
    LatencyEstimator,
    plan_level,
)
from mx_rcnn_tpu.serve.engine import (
    DeadlineExceeded,
    DetectorRunner,
    EngineUnavailable,
    InferenceEngine,
    InferenceRequest,
    Overloaded,
    Plan,
    QuotaExceeded,
    ServeError,
    build_engine,
)
from mx_rcnn_tpu.serve.fleet import FleetRequest, FleetRouter, build_fleet
from mx_rcnn_tpu.serve.gateway import (
    GatewayRequest,
    GatewayRouter,
    HostView,
    select_host,
)
from mx_rcnn_tpu.serve.gossip import (
    GossipNode,
    PeerState,
    merge_peer,
    merge_table,
)
from mx_rcnn_tpu.serve.health import EngineHealth
from mx_rcnn_tpu.serve.result_cache import ResultCache, content_key
from mx_rcnn_tpu.serve.rpc import HostRpcServer, HostUnreachable, RpcClient
from mx_rcnn_tpu.serve.tenancy import (
    QuotaGovernor,
    TenancyPolicy,
    TenantSpec,
)
from mx_rcnn_tpu.serve.router import (
    DEAD,
    DEGRADED,
    QUARANTINED,
    READY,
    RETIRING,
    ReplicaView,
    mean_load,
    routable_views,
    select_replica,
)

__all__ = [
    "PackBuffer",
    "LEVELS",
    "CircuitBreaker",
    "HysteresisPlanner",
    "LatencyEstimator",
    "plan_level",
    "DeadlineExceeded",
    "DetectorRunner",
    "EngineUnavailable",
    "InferenceEngine",
    "InferenceRequest",
    "Overloaded",
    "Plan",
    "QuotaExceeded",
    "ServeError",
    "build_engine",
    "FleetRequest",
    "FleetRouter",
    "build_fleet",
    "GatewayRequest",
    "GatewayRouter",
    "HostView",
    "select_host",
    "GossipNode",
    "PeerState",
    "merge_peer",
    "merge_table",
    "HostRpcServer",
    "HostUnreachable",
    "RpcClient",
    "QuotaGovernor",
    "TenancyPolicy",
    "TenantSpec",
    "EngineHealth",
    "ResultCache",
    "content_key",
    "DEAD",
    "DEGRADED",
    "QUARANTINED",
    "READY",
    "RETIRING",
    "ReplicaView",
    "mean_load",
    "routable_views",
    "select_replica",
]
