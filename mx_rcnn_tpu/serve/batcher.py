"""Continuous-batching pack policy for the serving engine.

The runner's micro-batch is a STATIC shape: every device call runs
``batch_size`` slots whether they hold one request or eight (the pad
rows are zeros the postprocess never reads).  Filling those slots with
requests from *different* callers is therefore free throughput — the
device call costs the same, the per-request latency only improves.
:class:`PackBuffer` is the policy half of that packer, deliberately
separated from the engine's queue/thread mechanics so it can be tested
standalone.

Packing rules (docs/serving.md):

* **One program per call.**  A pack shares one compiled program, i.e.
  one ``(mode, bucket)`` — the ``Plan`` minus its level name.  Mixing
  degrade levels that map to the same program (``full`` and ``small``
  never do; ``reduced`` requests always share the smallest bucket) is
  allowed and exercised by tests.
* **Deadline-aware ordering.**  The most urgent buffered request —
  earliest deadline, then earliest arrival; deadline-less requests sort
  last — picks the program, and its program-mates join it most-urgent
  first.  With no deadlines anywhere this degenerates to exact FIFO, so
  the packer composes with hedged retries (a hedge is just a second
  request, possibly landing in the same pack) and with the
  ``HysteresisPlanner`` ladder (whose per-request level choice already
  happened at plan time).
* **Anti-starvation aging.**  Deadline-first alone can starve: a
  deadline-less request on program B waits forever while deadlined
  program-A leads keep arriving.  Every request passed over by
  ``max_passovers`` consecutive packs is promoted to lead the next one,
  so FIFO degeneration is bounded — any buffered request reaches the
  device within ``max_passovers + 1`` packs of arriving
  (tests/test_tenancy.py pins the regression).
* **Weighted-fair tenant shares.**  With a :class:`TenancyPolicy`
  (serve/tenancy.py), the lead is chosen priority-class first (lower
  class drains earlier), and a tenant's slots in each pack are capped
  at its weight's share of ``batch_size`` — a flooding tenant cannot
  crowd program-mates out of the call.  Ordering *within* a tenant
  stays deadline-first, caps are work-conserving (unused share is
  refilled by urgency), and requests without a tenant fold to the
  default tenant so the single-tenant path is unchanged.
* **Bitwise identity.**  Rows in a padded micro-batch are independent
  through letterbox, the jitted graph, and per-row postprocess, so a
  request's de-interleaved response is bitwise identical whether it
  shared its device call with seven strangers or rode alone
  (tests/test_batcher.py proves this against the real runner).

The buffer never blocks and never touches the clock on its own: the
engine feeds it admitted (planned) requests, expires it with the
engine's clock, and asks for one pack per device call.
"""

from __future__ import annotations

import math
from typing import Optional


def urgency(req) -> tuple[float, float]:
    """Sort key: earliest deadline first, arrival order among equals;
    deadline-less requests pack after every deadlined one."""
    return (
        math.inf if req.deadline is None else req.deadline,
        req.enqueued_at,
    )


class PackBuffer:
    """Planned requests awaiting a device call, packed by program.

    The engine bounds how many requests it holds out of its admission
    queue (``2 * batch_size``), so shed semantics stay predictable; the
    buffer itself is just the ordered pool those requests wait in.
    """

    def __init__(self, tenancy=None, max_passovers: int = 4) -> None:
        self._items: list = []
        self._tenancy = tenancy
        # A request passed over by this many consecutive packs leads the
        # next one.  > 1 so one urgent newcomer can still jump the line
        # (deadline-first stays the common case).
        self._max_passovers = max(2, int(max_passovers))
        self._passovers: dict[int, int] = {}  # id(req) -> packs missed

    def __len__(self) -> int:
        return len(self._items)

    def add(self, req) -> None:
        """Admit one planned request (``req.plan`` must be set)."""
        assert req.plan is not None, "PackBuffer takes PLANNED requests"
        self._items.append(req)

    def expire(self, now: float) -> list:
        """Remove and return every request whose deadline has passed —
        the engine fails them exactly as the unpacked path does."""
        expired = [
            r for r in self._items
            if r.deadline is not None and now > r.deadline
        ]
        if expired:
            self._remove(expired)
        return expired

    def _remove(self, taken: list) -> None:
        dead = set(id(r) for r in taken)
        self._items = [r for r in self._items if id(r) not in dead]
        for rid in dead:
            self._passovers.pop(rid, None)

    def _tenant_of(self, req) -> str:
        t = getattr(req, "tenant", None)
        return self._tenancy.resolve(t) if self._tenancy is not None else ""

    def _pick_lead(self):
        """Aged request first (most-starved wins); else priority class +
        urgency when tenancy is on; else pure urgency."""
        aged = [
            r for r in self._items
            if self._passovers.get(id(r), 0) >= self._max_passovers
        ]
        if aged:
            return max(
                aged,
                key=lambda r: (self._passovers[id(r)],
                               tuple(-u for u in urgency(r))),
            )
        if self._tenancy is not None:
            return min(
                self._items,
                key=lambda r: (self._tenancy.priority(self._tenant_of(r)),
                               *urgency(r)),
            )
        return min(self._items, key=urgency)

    def _fill_fair(self, lead, mates: list, batch_size: int) -> list:
        """Weighted-fair pack composition: per-tenant slot caps from the
        tenant table, priority-class order across tenants, deadline-first
        within a tenant, work-conserving second pass."""
        by_tenant: dict[str, list] = {}
        for r in [lead] + mates:
            by_tenant.setdefault(self._tenant_of(r), []).append(r)
        weights = {
            t: self._tenancy.weight(t) for t in by_tenant
        }
        total_w = sum(weights.values())
        caps = {
            t: max(1, int(math.floor(batch_size * w / total_w)))
            for t, w in weights.items()
        }
        order = sorted(
            mates,
            key=lambda r: (self._tenancy.priority(self._tenant_of(r)),
                           *urgency(r)),
        )
        group = [lead]
        used = {self._tenant_of(lead): 1}
        leftovers = []
        for r in order:
            if len(group) >= batch_size:
                break
            t = self._tenant_of(r)
            if used.get(t, 0) >= caps[t]:
                leftovers.append(r)
                continue
            group.append(r)
            used[t] = used.get(t, 0) + 1
        # Work-conserving: unfilled slots go to whoever is most urgent,
        # caps ignored — fairness never costs occupancy.
        for r in leftovers:
            if len(group) >= batch_size:
                break
            group.append(r)
        return group

    def take(self, batch_size: int) -> Optional[list]:
        """One pack: the lead request plus up to ``batch_size - 1``
        program-mates.  None when empty."""
        if not self._items:
            return None
        lead = self._pick_lead()
        key = lead.plan[1:]  # (mode, bucket) — the compiled program
        mates = sorted(
            (r for r in self._items
             if r is not lead and r.plan[1:] == key),
            key=urgency,
        )
        if self._tenancy is not None and batch_size > 1:
            group = self._fill_fair(lead, mates, batch_size)
        else:
            group = [lead] + mates[:batch_size - 1]
        self._remove(group)
        for r in self._items:  # everyone left behind aged one pack
            rid = id(r)
            self._passovers[rid] = self._passovers.get(rid, 0) + 1
        return group

    def drain(self) -> list:
        """Remove and return everything (engine shutdown/failure path)."""
        items, self._items = self._items, []
        self._passovers.clear()
        return items
