"""Continuous-batching pack policy for the serving engine.

The runner's micro-batch is a STATIC shape: every device call runs
``batch_size`` slots whether they hold one request or eight (the pad
rows are zeros the postprocess never reads).  Filling those slots with
requests from *different* callers is therefore free throughput — the
device call costs the same, the per-request latency only improves.
:class:`PackBuffer` is the policy half of that packer, deliberately
separated from the engine's queue/thread mechanics so it can be tested
standalone.

Packing rules (docs/serving.md):

* **One program per call.**  A pack shares one compiled program, i.e.
  one ``(mode, bucket)`` — the ``Plan`` minus its level name.  Mixing
  degrade levels that map to the same program (``full`` and ``small``
  never do; ``reduced`` requests always share the smallest bucket) is
  allowed and exercised by tests.
* **Deadline-aware ordering.**  The most urgent buffered request —
  earliest deadline, then earliest arrival; deadline-less requests sort
  last — picks the program, and its program-mates join it most-urgent
  first.  With no deadlines anywhere this degenerates to exact FIFO, so
  the packer composes with hedged retries (a hedge is just a second
  request, possibly landing in the same pack) and with the
  ``HysteresisPlanner`` ladder (whose per-request level choice already
  happened at plan time).
* **Bitwise identity.**  Rows in a padded micro-batch are independent
  through letterbox, the jitted graph, and per-row postprocess, so a
  request's de-interleaved response is bitwise identical whether it
  shared its device call with seven strangers or rode alone
  (tests/test_batcher.py proves this against the real runner).

The buffer never blocks and never touches the clock on its own: the
engine feeds it admitted (planned) requests, expires it with the
engine's clock, and asks for one pack per device call.
"""

from __future__ import annotations

import math
from typing import Optional


def urgency(req) -> tuple[float, float]:
    """Sort key: earliest deadline first, arrival order among equals;
    deadline-less requests pack after every deadlined one."""
    return (
        math.inf if req.deadline is None else req.deadline,
        req.enqueued_at,
    )


class PackBuffer:
    """Planned requests awaiting a device call, packed by program.

    The engine bounds how many requests it holds out of its admission
    queue (``2 * batch_size``), so shed semantics stay predictable; the
    buffer itself is just the ordered pool those requests wait in.
    """

    def __init__(self) -> None:
        self._items: list = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, req) -> None:
        """Admit one planned request (``req.plan`` must be set)."""
        assert req.plan is not None, "PackBuffer takes PLANNED requests"
        self._items.append(req)

    def expire(self, now: float) -> list:
        """Remove and return every request whose deadline has passed —
        the engine fails them exactly as the unpacked path does."""
        expired = [
            r for r in self._items
            if r.deadline is not None and now > r.deadline
        ]
        if expired:
            dead = set(id(r) for r in expired)
            self._items = [r for r in self._items if id(r) not in dead]
        return expired

    def take(self, batch_size: int) -> Optional[list]:
        """One pack: the most urgent request plus up to ``batch_size - 1``
        program-mates, most urgent first.  None when empty."""
        if not self._items:
            return None
        lead = min(self._items, key=urgency)
        key = lead.plan[1:]  # (mode, bucket) — the compiled program
        group = sorted(
            (r for r in self._items if r.plan[1:] == key), key=urgency
        )[:batch_size]
        picked = set(id(r) for r in group)
        self._items = [r for r in self._items if id(r) not in picked]
        return group

    def drain(self) -> list:
        """Remove and return everything (engine shutdown/failure path)."""
        items, self._items = self._items, []
        return items
