"""Graceful degradation policy: quality ladder + circuit breaker.

Serving keeps a small set of pre-compiled programs (serve/engine.py) at
decreasing cost: the full detector at each resolution bucket, a
reduced-``max_detections`` variant, and an RPN-proposals-only variant.
Under pressure — a request deadline the full program's observed latency
cannot meet, or a circuit breaker opened by repeated full-path failures —
requests step DOWN this ladder instead of timing out or queueing forever:

    full  >  small (full quality at a smaller resolution bucket)
          >  full_q8 (int8/bf16 box head — serve/quantize.py; near-full
                      quality, cheaper head; present when the runner was
                      built with ``int8_head=True``)
          >  reduced (fewer max detections)
          >  proposals (RPN boxes only, class-agnostic)

Everything here is pure policy over injected clocks and observed latency
estimates; the engine owns the threads and the device.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Mapping, Optional, Sequence

from mx_rcnn_tpu import obs

# Quality-ordered serving levels, best first.  ``small`` reuses the FULL
# program of a smaller resolution bucket; ``full_q8`` (int8 box head),
# ``full_q8n`` (int8 whole network — cheaper, noisier), ``reduced`` and
# ``proposals`` are distinct compiled programs (engine warmup compiles
# them up front so degrading never pays a compile mid-incident).
LEVELS = ("full", "small", "full_q8", "full_q8n", "reduced", "proposals")

# Levels that run the full-quality pipeline; the circuit breaker guards
# these (a failing/overrunning full path should stop being probed at
# either resolution until it recovers).
FULL_QUALITY_LEVELS = frozenset({"full", "small"})


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probes.

    closed     normal operation; ``failure_threshold`` consecutive
               failures trip it open.
    open       the full-quality path is not attempted for ``cooldown``
               seconds; requests serve degraded.
    half-open  after the cooldown ONE request is allowed through as a
               probe: success closes the breaker, failure re-opens it
               for another cooldown.

    Thread-safe; the engine's worker calls ``allow_full`` when planning a
    request and reports the outcome with ``record_success`` /
    ``record_failure``.  ``cancel_probe`` returns an unused probe (the
    planner may consume one and then be forced to degrade anyway, e.g. by
    a tight deadline — that must not count as a probe outcome).
    """

    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self.trips = 0  # total times the breaker opened (stats)

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._clock() - self._opened_at >= self.cooldown:
            return "half_open"
        return "open"

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def allow_full(self) -> bool:
        """May this request take a full-quality level?  In half-open state
        this CONSUMES the single probe slot."""
        with self._lock:
            s = self._state_locked()
            if s == "closed":
                return True
            if s == "half_open" and not self._probing:
                self._probing = True
                return True
            return False

    def cancel_probe(self) -> None:
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        closed_from: Optional[str] = None
        with self._lock:
            self._consecutive = 0
            if self._opened_at is not None:
                # A success while open can only be the half-open probe.
                closed_from = self._state_locked()
                self._opened_at = None
            self._probing = False
        if closed_from is not None:
            obs.emit("serve", "breaker_transition", {
                "level": "full", "old_state": closed_from,
                "new_state": "closed",
            })

    def record_failure(self) -> None:
        opened_from: Optional[str] = None
        with self._lock:
            self._consecutive += 1
            if self._probing or self._consecutive >= self.failure_threshold:
                if self._opened_at is None or self._probing:
                    self.trips += 1
                    opened_from = self._state_locked()
                self._opened_at = self._clock()
                self._consecutive = 0
                self._probing = False
        if opened_from is not None:
            obs.emit("serve", "breaker_transition", {
                "level": "full", "old_state": opened_from,
                "new_state": "open",
            })


def plan_level(
    remaining: Optional[float],
    estimates: Mapping[str, float],
    full_allowed: bool,
    available: Sequence[str],
    headroom: float = 1.25,
) -> str:
    """Pick the serving level for one request.

    Args:
      remaining: seconds until the request's deadline (None = no deadline).
      estimates: observed latency estimate per level (seconds); a level
        with no estimate yet is assumed to fit (first requests must not
        degrade on zero information).
      full_allowed: circuit-breaker verdict for the full-quality path.
      available: subset of :data:`LEVELS` the engine actually compiled
        (e.g. ``small`` is absent with a single resolution bucket).
      headroom: a level is deemed to fit when ``estimate * headroom <=
        remaining`` — the margin absorbs queueing jitter.

    Returns the best available level that fits the deadline; if nothing
    fits, the cheapest available level (serving SOMETHING cheap beats a
    guaranteed deadline miss at a better level).
    """
    candidates = [lvl for lvl in LEVELS if lvl in available]
    if not candidates:
        raise ValueError("no serving levels available")
    if not full_allowed:
        candidates = [
            lvl for lvl in candidates if lvl not in FULL_QUALITY_LEVELS
        ] or candidates[-1:]
    if remaining is None:
        return candidates[0]
    for lvl in candidates:
        est = estimates.get(lvl)
        if est is None or est * headroom <= remaining:
            return lvl
    return candidates[-1]


class HysteresisPlanner:
    """Stateful :func:`plan_level` wrapper that damps upgrade thrash.

    A replica sitting at the boundary between two levels (e.g. ``full``
    vs ``full_q8`` when the full estimate hovers around the deadline)
    would otherwise alternate program families request-by-request —
    churning micro-batch grouping and making latency bimodal.  Policy:

    * **Downgrades are immediate** — pressure is never absorbed.
    * **Upgrades need margin and dwell** — moving to a better level
      requires ``up_dwell`` consecutive plans where that level fits the
      deadline with ``up_margin`` extra headroom (``estimate * headroom
      * up_margin <= remaining``); a single borderline reading resets
      the streak.  Requests without a deadline count toward the dwell
      (no pressure signal), so a cleared incident still recovers.

    Thread-safe; one instance per engine (the engine's worker is the
    only planner, but ``stats`` readers may race it).
    """

    def __init__(
        self,
        headroom: float = 1.25,
        up_margin: float = 1.5,
        up_dwell: int = 3,
    ) -> None:
        if up_dwell < 1:
            raise ValueError("up_dwell must be >= 1")
        self.headroom = headroom
        self.up_margin = up_margin
        self.up_dwell = up_dwell
        self._lock = threading.Lock()
        self._level: Optional[str] = None
        self._streak = 0

    @property
    def level(self) -> Optional[str]:
        with self._lock:
            return self._level

    def plan(
        self,
        remaining: Optional[float],
        estimates: Mapping[str, float],
        full_allowed: bool,
        available: Sequence[str],
    ) -> str:
        target = plan_level(
            remaining, estimates, full_allowed, available,
            headroom=self.headroom,
        )
        moved: Optional[tuple[str, str]] = None
        try:
            with self._lock:
                current = self._level
                if current is None or current not in available:
                    self._level, self._streak = target, 0
                    return target
                if LEVELS.index(target) >= LEVELS.index(current):
                    # Same or worse quality: follow plan_level immediately.
                    self._level, self._streak = target, 0
                    if target != current:
                        moved = (current, target)
                    return target
                # Upgrade candidate: count margin-clean plans before moving.
                est = estimates.get(target)
                comfortable = (
                    remaining is None
                    or est is None
                    or est * self.headroom * self.up_margin <= remaining
                )
                self._streak = self._streak + 1 if comfortable else 0
                if self._streak >= self.up_dwell:
                    self._level, self._streak = target, 0
                    moved = (current, target)
                    return target
                return current
        finally:
            if moved is not None:
                obs.emit("serve", "ladder_transition", {
                    "old_level": moved[0], "new_level": moved[1],
                })


class LatencyEstimator:
    """Per-level EWMA of observed serving latency (seconds)."""

    def __init__(self, alpha: float = 0.3) -> None:
        self.alpha = alpha
        self._est: dict[str, float] = {}
        self._lock = threading.Lock()

    def observe(self, level: str, seconds: float) -> None:
        with self._lock:
            prev = self._est.get(level)
            self._est[level] = (
                seconds
                if prev is None
                else (1 - self.alpha) * prev + self.alpha * seconds
            )

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._est)
