"""Robust inference runtime around the jitted inference step.

The jitted graphs (detection/graph.py) are fast but brittle to operate:
an unexpected image shape silently triggers a multi-second recompile, a
hung device call blocks forever, and a burst of requests queues without
bound.  :class:`InferenceEngine` wraps them with the serving behaviors a
production endpoint needs:

* **Startup warmup** — every (mode, resolution-bucket) program is
  compiled before the engine reports ready; a request can never pay a
  compile.
* **Bucketed pad-batching** — requests letterbox into a fixed set of
  resolution buckets and pad into static batch shapes, so arbitrary
  request sizes never create new programs (enforced, not hoped:
  :class:`DetectorRunner` refuses shapes outside the warmed set).
* **Admission control** — a bounded queue; when it is full the request
  is shed immediately with a typed :class:`Overloaded` instead of
  queueing into certain deadline death.
* **Per-request deadlines** — expired requests fail fast with
  :class:`DeadlineExceeded`; remaining budget drives the degradation
  ladder (serve/degrade.py) so tight deadlines get a cheaper program
  instead of a guaranteed miss.
* **Watchdog** — a monitor thread detects a device call that stopped
  returning (hung runtime, wedged tunnel) and fails the engine to DEAD
  so supervisors replace the process instead of black-holing traffic.

The engine is generic over a ``runner`` (anything with ``buckets``,
``levels()``, ``batch_size``, ``pick_bucket`` and ``run``); the real
JAX-backed implementation is :class:`DetectorRunner`, and tests drive the
same engine with deterministic fakes.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from typing import Callable, NamedTuple, Optional, Sequence

import numpy as np

from mx_rcnn_tpu.serve import health as health_mod
from mx_rcnn_tpu.serve.degrade import (
    FULL_QUALITY_LEVELS,
    CircuitBreaker,
    LatencyEstimator,
    plan_level,
)

log = logging.getLogger("mx_rcnn_tpu.serve")


class ServeError(RuntimeError):
    """Base class for typed serving failures."""


class Overloaded(ServeError):
    """Admission control shed this request: the queue is full."""


class DeadlineExceeded(ServeError):
    """The request's deadline passed before a result was produced."""


class EngineUnavailable(ServeError):
    """The engine cannot serve (not started, stopped, or declared dead)."""


class Plan(NamedTuple):
    level: str              # degrade.LEVELS entry
    mode: str               # program family: full | reduced | proposals
    bucket: tuple[int, int]  # compiled canvas (H, W)


class InferenceRequest:
    """A submitted request; ``result()`` blocks until served or failed."""

    __slots__ = ("image", "enqueued_at", "deadline", "_event", "_result",
                 "_error", "plan")

    def __init__(self, image: np.ndarray, enqueued_at: float,
                 deadline: Optional[float]) -> None:
        self.image = image
        self.enqueued_at = enqueued_at
        self.deadline = deadline
        self._event = threading.Event()
        self._result: Optional[dict] = None
        self._error: Optional[BaseException] = None
        self.plan: Optional[Plan] = None

    def _set_result(self, result: dict) -> None:
        self._result = result
        self._event.set()

    def _set_error(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> dict:
        """The served detections dict (boxes/scores/classes/level/...);
        raises the typed serving error on failure.  The watchdog bounds
        how long an un-timed wait can last."""
        if not self._event.wait(timeout):
            raise TimeoutError("request not complete")
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


class DetectorRunner:
    """JAX-backed runner: compiled programs over fixed shape buckets.

    Programs (all compiled at warmup, none ever added after):
      * ``("full", bucket)`` for EVERY bucket — the production detector.
      * ``("full_q8", smallest bucket)`` — int8/bf16 box head
        (serve/quantize.py), when built with ``int8_head=True``.
      * ``("reduced", smallest bucket)`` — ``reduced_max_detections``
        output slots (cheaper postprocess/NMS).
      * ``("proposals", smallest bucket)`` — RPN-only, class-agnostic.

    ``run`` letterboxes each request image into the plan's bucket, pads
    the micro-batch to the static ``batch_size``, executes, and maps
    boxes back to original image coordinates.  Any (mode, bucket) pair
    outside the warmed set is a hard error — the no-recompile guarantee
    is enforced here rather than discovered in a latency graph.
    """

    def __init__(
        self,
        cfg,
        variables,
        buckets: Optional[Sequence[tuple[int, int]]] = None,
        batch_size: int = 1,
        reduced_max_detections: Optional[int] = None,
        with_proposals: bool = True,
        int8_head: bool = False,
    ) -> None:
        import dataclasses

        import jax

        from mx_rcnn_tpu.detection import TwoStageDetector

        self.cfg = cfg
        self.batch_size = int(batch_size)
        bks = list(buckets) if buckets else [tuple(cfg.data.image_size)]
        # Ascending by area; pick_bucket takes the first that fits.
        self.buckets = sorted(
            (tuple(int(x) for x in b) for b in bks),
            key=lambda b: (b[0] * b[1], b),
        )
        if reduced_max_detections is None:
            reduced_max_detections = max(1, cfg.model.test.max_detections // 4)
        self.reduced_max_detections = int(reduced_max_detections)
        stats = (cfg.data.pixel_mean, cfg.data.pixel_std)

        model = TwoStageDetector(cfg=cfg.model)
        reduced_cfg = dataclasses.replace(
            cfg.model,
            test=dataclasses.replace(
                cfg.model.test,
                max_detections=self.reduced_max_detections,
                fused_top_k=min(
                    cfg.model.test.fused_top_k,
                    4 * self.reduced_max_detections,
                ),
            ),
        )
        reduced_model = TwoStageDetector(cfg=reduced_cfg)
        self._variables = jax.device_put(variables)

        from mx_rcnn_tpu.detection.graph import (
            forward_inference,
            forward_proposals,
        )

        # One jitted callable per MODE; buckets become distinct XLA
        # programs of the same callable (different static shapes).  All
        # compile through the execution plan (parallel/plan.py) — the
        # same scaffolding the train/eval steps use; serving runs the
        # plan's mesh-less form (plain jit) today, and a sharded server
        # is one ``mesh=`` away rather than a rewrite.
        from mx_rcnn_tpu.parallel.plan import ExecutionPlan

        plan = ExecutionPlan(mesh=None)
        self._steps = {
            "full": plan.compile_infer(
                lambda v, b: forward_inference(model, v, b, pixel_stats=stats)
            ),
            "reduced": plan.compile_infer(
                lambda v, b: forward_inference(
                    reduced_model, v, b, pixel_stats=stats
                )
            ),
            "proposals": plan.compile_infer(
                lambda v, b: forward_proposals(model, v, b, pixel_stats=stats)
            ),
        }
        self._program_keys = [("full", b) for b in self.buckets]
        if int8_head:
            from mx_rcnn_tpu.serve.quantize import (
                apply_box_head_q8,
                quantize_box_head,
            )

            # The quantized tree rides as a jit ARGUMENT (device buffers),
            # not a closure — same request-size reasoning as _variables.
            self._box_q8 = jax.device_put(quantize_box_head(variables))
            # Mesh-less plan compile == plain jit, so the extra quantized
            # operand is fine; a sharded plan would need its own spec.
            q8_step = plan.compile_infer(
                lambda v, q, b: forward_inference(
                    model, v, b, pixel_stats=stats,
                    box_head_apply=lambda pooled: apply_box_head_q8(
                        q, pooled
                    ),
                )
            )
            self._steps["full_q8"] = (
                lambda v, b: q8_step(v, self._box_q8, b)
            )
            # Like the other degrade programs, compiled for the smallest
            # bucket only (engine._plan routes non-full levels there).
            self._program_keys.append(("full_q8", self.buckets[0]))
        if with_proposals:
            self._program_keys += [
                ("reduced", self.buckets[0]),
                ("proposals", self.buckets[0]),
            ]
        else:
            self._program_keys += [("reduced", self.buckets[0])]
        self._warmed: set[tuple[str, tuple[int, int]]] = set()

    # -- engine-facing surface --------------------------------------------

    def levels(self) -> tuple[str, ...]:
        out = ["full"]
        if len(self.buckets) > 1:
            out.append("small")
        if any(m == "full_q8" for m, _ in self._program_keys):
            out.append("full_q8")
        out.append("reduced")
        if any(m == "proposals" for m, _ in self._program_keys):
            out.append("proposals")
        return tuple(out)

    def pick_bucket(self, height: int, width: int) -> tuple[int, int]:
        """Smallest bucket that holds the image without downscaling; the
        largest bucket otherwise (letterbox downscales into it)."""
        for b in self.buckets:
            if b[0] >= height and b[1] >= width:
                return b
        return self.buckets[-1]

    def smaller_bucket(
        self, bucket: tuple[int, int]
    ) -> Optional[tuple[int, int]]:
        i = self.buckets.index(bucket)
        return self.buckets[i - 1] if i > 0 else None

    def warmup(self) -> int:
        """Compile every program with a zero batch; returns program count."""
        for mode, bucket in self._program_keys:
            batch = self._make_batch(
                np.zeros((self.batch_size, *bucket, 3), np.float32),
                np.tile(
                    np.asarray([bucket], np.float32), (self.batch_size, 1)
                ),
            )
            out = self._steps[mode](self._variables, batch)
            import jax

            jax.block_until_ready(out)
            self._warmed.add((mode, bucket))
        return len(self._warmed)

    def run(self, mode: str, bucket: tuple[int, int],
            images: Sequence[np.ndarray]) -> list[dict]:
        if (mode, bucket) not in self._warmed:
            raise EngineUnavailable(
                f"program ({mode}, {bucket}) was never warmed — refusing "
                "to compile on the serving path"
            )
        if len(images) > self.batch_size:
            raise ValueError(
                f"micro-batch of {len(images)} exceeds batch_size "
                f"{self.batch_size}"
            )
        import jax

        from mx_rcnn_tpu.data.transforms import letterbox, normalize_image

        rows, hw, scales, orig = [], [], [], []
        for img in images:
            h, w = img.shape[:2]
            canvas, _, scale, (nh, nw) = letterbox(
                img.astype(np.float32),
                np.zeros((0, 4), np.float32),
                bucket,
                min(bucket),
                max(bucket),
            )
            rows.append(
                normalize_image(
                    canvas, self.cfg.data.pixel_mean, self.cfg.data.pixel_std
                )
            )
            hw.append([nh, nw])
            scales.append(scale)
            orig.append((h, w))
        pad = self.batch_size - len(rows)
        if pad:
            rows += [np.zeros_like(rows[0])] * pad
            hw += [list(bucket)] * pad
        batch = self._make_batch(
            np.stack(rows), np.asarray(hw, np.float32)
        )
        out = jax.device_get(self._steps[mode](self._variables, batch))
        return [
            self._postprocess(mode, out, i, scales[i], *orig[i])
            for i in range(len(images))
        ]

    # -- internals ---------------------------------------------------------

    def _make_batch(self, images: np.ndarray, image_hw: np.ndarray):
        from mx_rcnn_tpu.detection import Batch

        g = self.cfg.data.max_gt_boxes
        b = images.shape[0]
        return Batch(
            images=images,
            image_hw=image_hw,
            gt_boxes=np.zeros((b, g, 4), np.float32),
            gt_classes=np.zeros((b, g), np.int32),
            gt_valid=np.zeros((b, g), bool),
        )

    def _postprocess(self, mode, out, i, scale, height, width) -> dict:
        from mx_rcnn_tpu.evalutil.postprocess import unletterbox_detections

        if mode == "proposals":
            valid = np.asarray(out.valid[i])
            boxes = np.asarray(out.rois[i])[valid] / max(scale, 1e-12)
            boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, width - 1)
            boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, height - 1)
            return {
                "boxes": boxes.astype(np.float32),
                "scores": np.asarray(out.scores[i])[valid],
                "classes": np.zeros(int(valid.sum()), np.int32),
            }
        return unletterbox_detections(
            out.boxes[i], out.scores[i], out.classes[i], out.valid[i],
            scale, height, width,
            masks=out.masks[i] if getattr(out, "masks", None) is not None
            else None,
        )


class InferenceEngine:
    """Bounded-queue serving loop over a runner's compiled programs.

    Lifecycle: construct → ``start()`` (warms every program, then spawns
    the worker + watchdog threads and reports READY) → ``submit``/
    ``infer`` → ``stop()``.  Usable as a context manager.
    """

    _STOP = object()

    def __init__(
        self,
        runner,
        max_queue: int = 16,
        default_timeout: Optional[float] = None,
        hang_timeout: float = 60.0,
        watchdog_poll: float = 0.25,
        headroom: float = 1.25,
        breaker: Optional[CircuitBreaker] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.runner = runner
        self._clock = clock
        self.default_timeout = default_timeout
        self.hang_timeout = hang_timeout
        self.watchdog_poll = watchdog_poll
        self.headroom = headroom
        self.breaker = breaker or CircuitBreaker(clock=clock)
        self.estimates = LatencyEstimator()
        self.health = health_mod.EngineHealth(clock=clock)
        self._queue: queue_mod.Queue = queue_mod.Queue(maxsize=max_queue)
        self._carry: Optional[InferenceRequest] = None
        self._inflight_since: Optional[float] = None
        self._inflight_plan: Optional[Plan] = None
        self._inflight_reqs: list[InferenceRequest] = []
        self._lock = threading.Lock()
        self._started = False
        self._stopping = False
        self._worker: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "InferenceEngine":
        if self._started:
            return self
        try:
            n = self.runner.warmup()
        except Exception as e:
            self.health.transition(
                health_mod.DEAD, f"warmup failed: {type(e).__name__}: {e}"
            )
            raise
        log.info(
            "engine ready: %d compiled programs, buckets=%s, levels=%s",
            n, list(self.runner.buckets), list(self.runner.levels()),
        )
        self._started = True
        self.health.transition(health_mod.READY, "warmup complete")
        self._worker = threading.Thread(
            target=self._worker_loop, name="serve-worker", daemon=True
        )
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, name="serve-watchdog", daemon=True
        )
        self._worker.start()
        self._watchdog.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        if not self._started or self._stopping:
            return
        self._stopping = True
        try:
            self._queue.put_nowait(self._STOP)
        except queue_mod.Full:
            pass
        if self._worker is not None:
            self._worker.join(timeout)
        self._fail_pending(EngineUnavailable("engine stopped"))
        self.health.transition(health_mod.DEAD, "stopped")
        if self._watchdog is not None:
            self._watchdog.join(timeout)

    def __enter__(self) -> "InferenceEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client API --------------------------------------------------------

    def submit(
        self, image: np.ndarray, timeout: Optional[float] = None
    ) -> InferenceRequest:
        """Enqueue one image; returns immediately.  Raises
        :class:`Overloaded` when the queue is full, or
        :class:`EngineUnavailable` when the engine cannot serve."""
        if not self._started or self._stopping:
            raise EngineUnavailable("engine not started")
        if not self.health.alive():
            raise EngineUnavailable(
                f"engine is dead: {self.health.reason}"
            )
        now = self._clock()
        timeout = self.default_timeout if timeout is None else timeout
        req = InferenceRequest(
            image, now, None if timeout is None else now + timeout
        )
        try:
            self._queue.put_nowait(req)
        except queue_mod.Full:
            self.health.record_shed()
            self._note_pressure()
            raise Overloaded(
                f"queue full ({self._queue.maxsize} waiting); request shed"
            ) from None
        return req

    def infer(
        self, image: np.ndarray, timeout: Optional[float] = None
    ) -> dict:
        return self.submit(image, timeout).result()

    def stats(self) -> dict:
        with self._lock:
            inflight_age = (
                None
                if self._inflight_since is None
                else round(self._clock() - self._inflight_since, 3)
            )
        return self.health.snapshot(
            queue_depth=self._queue.qsize(),
            inflight_age_s=inflight_age,
            breaker=self.breaker.state,
            breaker_trips=self.breaker.trips,
            latency_estimates_s=self.estimates.snapshot(),
            buckets=[list(b) for b in self.runner.buckets],
        )

    # -- planning ----------------------------------------------------------

    def _plan(self, req: InferenceRequest) -> Plan:
        h, w = req.image.shape[:2]
        base = self.runner.pick_bucket(h, w)
        smaller = self.runner.smaller_bucket(base)
        available = [
            lvl for lvl in self.runner.levels()
            if lvl != "small" or smaller is not None
        ]
        remaining = (
            None if req.deadline is None else req.deadline - self._clock()
        )
        full_ok = self.breaker.allow_full()
        level = plan_level(
            remaining, self.estimates.snapshot(), full_ok, available,
            headroom=self.headroom,
        )
        if full_ok and level not in FULL_QUALITY_LEVELS:
            # Consumed a half-open probe but was forced to degrade anyway
            # (deadline pressure) — return it, this is not a probe outcome.
            self.breaker.cancel_probe()
        if level == "full":
            return Plan("full", "full", base)
        if level == "small":
            assert smaller is not None
            return Plan("small", "full", smaller)
        # reduced / proposals programs exist for the smallest bucket only.
        return Plan(level, level, self.runner.buckets[0])

    def _note_pressure(self) -> None:
        if self.health.state == health_mod.READY:
            self.health.transition(health_mod.DEGRADED, "load shedding")

    # -- worker ------------------------------------------------------------

    def _take_batch(self) -> Optional[list[InferenceRequest]]:
        """Next micro-batch: the first live request plus any immediately
        available requests with the SAME plan, up to the static batch."""
        while True:
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                try:
                    first = self._queue.get(timeout=0.1)
                except queue_mod.Empty:
                    return None
            if first is self._STOP:
                return []
            if (
                first.deadline is not None
                and self._clock() > first.deadline
            ):
                self.health.record_deadline_miss()
                self._note_pressure()
                first._set_error(
                    DeadlineExceeded("deadline passed while queued")
                )
                continue
            first.plan = self._plan(first)
            batch = [first]
            while len(batch) < self.runner.batch_size:
                try:
                    nxt = self._queue.get_nowait()
                except queue_mod.Empty:
                    break
                if nxt is self._STOP:
                    self._stopping = True
                    break
                if (
                    nxt.deadline is not None
                    and self._clock() > nxt.deadline
                ):
                    self.health.record_deadline_miss()
                    nxt._set_error(
                        DeadlineExceeded("deadline passed while queued")
                    )
                    continue
                nxt.plan = self._plan(nxt)
                if nxt.plan[1:] != first.plan[1:]:
                    self._carry = nxt  # different program; runs next
                    break
                batch.append(nxt)
            return batch

    def _worker_loop(self) -> None:
        while not self._stopping:
            batch = self._take_batch()
            if batch is None:
                continue
            if not batch:  # STOP
                break
            plan = batch[0].plan
            assert plan is not None
            start = self._clock()
            with self._lock:
                self._inflight_since = start
                self._inflight_plan = plan
                self._inflight_reqs = list(batch)
            try:
                results = self.runner.run(
                    plan.mode, plan.bucket, [r.image for r in batch]
                )
                err: Optional[BaseException] = None
            except BaseException as e:  # noqa: BLE001 - typed below
                results, err = None, e
            finally:
                with self._lock:
                    self._inflight_since = None
                    self._inflight_plan = None
                    self._inflight_reqs = []
            if not self.health.alive():
                # The watchdog declared us dead while this call was stuck;
                # its requests were already failed.  Drop the zombie result.
                break
            latency = self._clock() - start
            if err is not None:
                self.health.record_failure()
                if plan.level in FULL_QUALITY_LEVELS:
                    self.breaker.record_failure()
                self._note_pressure()
                for r in batch:
                    r._set_error(
                        ServeError(
                            f"inference failed at level {plan.level}: "
                            f"{type(err).__name__}: {err}"
                        )
                    )
                continue
            self.estimates.observe(plan.level, latency)
            late = [
                r for r in batch
                if r.deadline is not None and self._clock() > r.deadline
            ]
            if plan.level in FULL_QUALITY_LEVELS:
                # A full-path overrun that blew the deadline counts against
                # the breaker; an on-time full result heals it.
                if late:
                    self.breaker.record_failure()
                else:
                    self.breaker.record_success()
            for r, res in zip(batch, results):
                if r in late:
                    self.health.record_deadline_miss()
                    self._note_pressure()
                    r._set_error(
                        DeadlineExceeded(
                            f"served at level {plan.level} in "
                            f"{latency:.3f}s, past the deadline"
                        )
                    )
                else:
                    self.health.record_served(plan.level, latency)
                    res = dict(res)
                    res["level"] = plan.level
                    res["latency_s"] = latency
                    r._set_result(res)
            if (
                self.health.state == health_mod.DEGRADED
                and self.breaker.state == "closed"
                and not late
                and self._queue.qsize() < max(1, self._queue.maxsize // 2)
            ):
                self.health.transition(health_mod.READY, "pressure cleared")

    # -- watchdog ----------------------------------------------------------

    def _fail_pending(self, error: BaseException) -> None:
        if self._carry is not None:
            self._carry._set_error(error)
            self._carry = None
        while True:
            try:
                item = self._queue.get_nowait()
            except queue_mod.Empty:
                return
            if item is not self._STOP:
                item._set_error(error)

    def _watchdog_loop(self) -> None:
        while not self._stopping and self.health.alive():
            time.sleep(self.watchdog_poll)
            with self._lock:
                since = self._inflight_since
                plan = self._inflight_plan
            if since is None:
                continue
            age = self._clock() - since
            if age <= self.hang_timeout:
                continue
            self.health.hung += 1
            self.health.transition(
                health_mod.DEAD,
                f"device call hung for {age:.1f}s "
                f"(plan={plan}, hang_timeout={self.hang_timeout}s)",
            )
            log.error(
                "watchdog: %s — failing %d queued request(s)",
                self.health.reason, self._queue.qsize(),
            )
            error = EngineUnavailable(f"engine died: {self.health.reason}")
            with self._lock:
                stuck = list(self._inflight_reqs)
            for r in stuck:
                # The device call may never return; unblock its waiters.
                r._set_error(error)
            self._fail_pending(error)
            return


def build_engine(
    cfg,
    variables,
    buckets: Optional[Sequence[tuple[int, int]]] = None,
    batch_size: int = 1,
    int8_head: bool = False,
    **engine_kwargs,
) -> InferenceEngine:
    """Convenience: real runner + engine from a config and variables
    (checkpoint-restored or freshly initialized)."""
    runner = DetectorRunner(
        cfg, variables, buckets=buckets, batch_size=batch_size,
        int8_head=int8_head,
    )
    return InferenceEngine(runner, **engine_kwargs)
